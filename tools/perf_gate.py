#!/usr/bin/env python
"""CPU-bench perf gate (`make perf-gate`, ROADMAP item 5).

Runs the CPU proxy bench (`bench.py --measure cpu`) three times, takes the
MEDIAN samples/sec, and fails (exit 1) when it is more than `tolerance`
(default 15%) below the checked-in budget in
`bench_results/cpu_budget.json` — so a hot-path regression like the one
suspected in round 5 can never land silently again.  A median above budget
prints a note suggesting a re-baseline (ratchet upward, never down).

    python tools/perf_gate.py               # gate against the budget
    python tools/perf_gate.py --rebaseline  # measure + rewrite the budget

Background (ROADMAP item 5): the r05 203->82 samples/s "regression"
bisected to measurement noise — every commit PR2..PR5 measures within the
same 37-52 ms/step band on this box, and the pre-r06 single-6-step-slope
timing swings +/-30% run to run.  bench.py now uses best-of-three 12-step
slopes; this gate adds the regression tripwire on top.
"""
from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUDGET = os.path.join(ROOT, "bench_results", "cpu_budget.json")
RUNS = 3
TIMEOUT = 600


def measure_once() -> tuple:
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py"),
         "--measure", "cpu"],
        capture_output=True, text=True, timeout=TIMEOUT, cwd=ROOT)
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout).strip().splitlines()[-6:]
        raise RuntimeError("bench failed rc=%d: %s"
                           % (proc.returncode, " | ".join(tail)))
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            d = json.loads(line)
            mfu = (d["extras"].get("cost") or {}).get("mfu_estimate")
            return float(d["extras"]["samples_per_sec_per_chip"]), mfu
    raise RuntimeError("no JSON line in bench output")


def record_serve_extras() -> None:
    """RECORDED, never gated (like mfu_estimate): one `bench.py --serve
    --spec 4` round so the per-request decode tokens/s percentiles and
    the speculation accept rate ride every gate transcript — a decode
    fast-path regression is then visible in the round logs even though
    only the CPU train bench gates.  Skipped with --no-serve; any
    failure here is a warning, never a gate verdict."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "bench.py"),
             "--serve", "--spec", "4"],
            capture_output=True, text=True, timeout=TIMEOUT, cwd=ROOT)
        line = next(ln for ln in reversed(
            proc.stdout.strip().splitlines()) if ln.startswith("{"))
        d = json.loads(line)
        ex = d["extras"]
        rec = {
            "serve_tokens_per_sec": d["value"],
            "steps_per_token": ex.get("steps_per_token"),
            "decode_tok_s_p50": ex.get("decode_tok_s_p50"),
            "decode_tok_s_p99": ex.get("decode_tok_s_p99"),
            "spec_accept_rate": (ex.get("spec") or {}).get("accept_rate"),
            "prefix_hit_tokens": (ex.get("spec")
                                  or {}).get("prefix_hit_tokens"),
            "measured_at": time.strftime("%Y-%m-%d"),
        }
        out = os.path.join(ROOT, "bench_results", "perf_gate_serve.json")
        with open(out, "w") as f:
            json.dump(rec, f, indent=2)
            f.write("\n")
        print(f"perf-gate: serve extras (informational): "
              f"{rec['serve_tokens_per_sec']} tok/s, decode p50/p99 "
              f"{rec['decode_tok_s_p50']}/{rec['decode_tok_s_p99']} "
              f"tok/s, accept {rec['spec_accept_rate']}, "
              f"steps/token {rec['steps_per_token']} -> {out}")
    except Exception as e:   # noqa: BLE001 — never gate on this round
        print(f"perf-gate: serve extras round skipped ({e})",
              file=sys.stderr)


def record_procfleet_extras() -> None:
    """RECORDED, never gated: one process-transport fleet round with a
    mid-window worker SIGKILL (`bench.py --serve --replicas 2 --kill-at
    2 --kill-mode process`), so the failover loss window and respawn
    count ride every gate transcript — a ledger-failover or respawn
    regression shows up in the round logs without gating the merge."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "bench.py"),
             "--serve", "--replicas", "2", "--kill-at", "2",
             "--kill-mode", "process"],
            capture_output=True, text=True, timeout=TIMEOUT, cwd=ROOT)
        line = next(ln for ln in reversed(
            proc.stdout.strip().splitlines()) if ln.startswith("{"))
        d = json.loads(line)
        ex = d["extras"]
        rec = {
            "fleet_tokens_per_sec": d["value"],
            "kill_mode": ex.get("kill_mode"),
            "failover_loss_window_ms": ex.get("failover_loss_window_ms"),
            "deaths": ex.get("deaths"),
            "respawns": ex.get("respawns"),
            "failovers": ex.get("failovers"),
            "ttft_p99_ms": ex.get("ttft_p99_ms"),
            "measured_at": time.strftime("%Y-%m-%d"),
        }
        out = os.path.join(ROOT, "bench_results",
                           "perf_gate_procfleet.json")
        with open(out, "w") as f:
            json.dump(rec, f, indent=2)
            f.write("\n")
        print(f"perf-gate: procfleet extras (informational): "
              f"{rec['fleet_tokens_per_sec']} tok/s under SIGKILL, "
              f"loss window {rec['failover_loss_window_ms']} ms, "
              f"respawns {rec['respawns']} -> {out}")
    except Exception as e:   # noqa: BLE001 — never gate on this round
        print(f"perf-gate: procfleet extras round skipped ({e})",
              file=sys.stderr)


def record_disagg_extras() -> None:
    """RECORDED, never gated: one disaggregated-serving round
    (`bench.py --serve --disagg 1x2 --tp 2`) so the handoff latency
    percentiles, per-role utilization, and the independent-scaling
    check (aggregate tokens/s with one extra PREFILL replica, decode
    tier untouched) ride every gate transcript — a handoff or
    tp-sharding regression shows up in the round logs without gating
    the merge."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "bench.py"),
             "--serve", "--disagg", "1x2", "--tp", "2"],
            capture_output=True, text=True, timeout=TIMEOUT, cwd=ROOT)
        line = next(ln for ln in reversed(
            proc.stdout.strip().splitlines()) if ln.startswith("{"))
        d = json.loads(line)
        ex = d["extras"]
        scal = ex.get("prefill_scaling") or {}
        rec = {
            "disagg_tokens_per_sec": d["value"],
            "disagg": ex.get("disagg"),
            "tp": ex.get("tp"),
            "handoffs": ex.get("handoffs"),
            "handoff_failures": ex.get("handoff_failures"),
            "handoff_ms_p50": ex.get("handoff_ms_p50"),
            "handoff_ms_p99": ex.get("handoff_ms_p99"),
            "phases": ex.get("phases"),
            "prefill_scaling_improvement": scal.get("improvement"),
            "measured_at": time.strftime("%Y-%m-%d"),
        }
        out = os.path.join(ROOT, "bench_results",
                           "perf_gate_disagg.json")
        with open(out, "w") as f:
            json.dump(rec, f, indent=2)
            f.write("\n")
        print(f"perf-gate: disagg extras (informational): "
              f"{rec['disagg_tokens_per_sec']} tok/s at "
              f"{rec['disagg']} tp={rec['tp']}, handoff p50/p99 "
              f"{rec['handoff_ms_p50']}/{rec['handoff_ms_p99']} ms, "
              f"+1-prefill scaling x"
              f"{rec['prefill_scaling_improvement']} -> {out}")
    except Exception as e:   # noqa: BLE001 — never gate on this round
        print(f"perf-gate: disagg extras round skipped ({e})",
              file=sys.stderr)


def main() -> int:
    vals, mfus = [], []
    for i in range(RUNS):
        v, mfu = measure_once()
        vals.append(v)
        if mfu is not None:
            mfus.append(float(mfu))
        print(f"perf-gate: run {i + 1}/{RUNS}: {v:.2f} samples/s/chip"
              + (f"  (mfu_estimate {mfu:.3g}, projected peak)"
                 if mfu is not None else ""))
    med = statistics.median(vals)
    # RECORDED, never gated: the projected-MFU trajectory belongs in
    # BENCH_*.json / the gate transcript so the number is visible every
    # round while the TPU tunnel is down — it is a cost-model proxy,
    # not a CPU regression signal (docs/observability.md)
    med_mfu = statistics.median(mfus) if mfus else None
    if med_mfu is not None:
        print(f"perf-gate: mfu_estimate median {med_mfu:.4g} "
              f"(informational; from XLA cost_analysis flops)")

    if "--rebaseline" in sys.argv:
        budget = {
            "metric": "bert_base_pretrain_samples_per_sec",
            "samples_per_sec_per_chip": round(med, 1),
            "tolerance": 0.15,
            "measured_at": time.strftime("%Y-%m-%d"),
            # informational only — the gate never fails on it
            "mfu_estimate": med_mfu,
            "note": "re-baselined by tools/perf_gate.py --rebaseline "
                    "(median of %d runs: %s)" % (RUNS, vals),
        }
        with open(BUDGET, "w") as f:
            json.dump(budget, f, indent=2)
            f.write("\n")
        print(f"perf-gate: budget re-baselined to {med:.1f} -> {BUDGET}")
        return 0

    with open(BUDGET) as f:
        budget = json.load(f)
    target = float(budget["samples_per_sec_per_chip"])
    tol = float(budget.get("tolerance", 0.15))
    floor = target * (1.0 - tol)
    verdict = "PASS" if med >= floor else "FAIL"
    print(f"perf-gate: median {med:.2f} vs budget {target:.2f} "
          f"(floor {floor:.2f}, tolerance {tol:.0%}) -> {verdict}")
    if med < floor:
        print("perf-gate: CPU bench regressed beyond the budget — find "
              "the hot-path change (git bisect running THIS gate per "
              "commit) before merging; do NOT re-baseline downward.",
              file=sys.stderr)
        return 1
    if med > target * (1.0 + tol):
        print("perf-gate: median is >15% ABOVE budget — if a deliberate "
              "optimization landed, ratchet the budget up: "
              "python tools/perf_gate.py --rebaseline")
    if "--no-serve" not in sys.argv:
        record_serve_extras()
        record_procfleet_extras()
        record_disagg_extras()
    return 0


if __name__ == "__main__":
    sys.exit(main())
