#!/usr/bin/env python
"""Self-healing chaos smoke (`make chaos-smoke`, docs/resilience.md).

End-to-end proof of the anomaly→remediation ladder over the ENV wiring a
production run would use (``MXTPU_RECOVERY=1`` + ``MXTPU_FAULT_SPEC``),
pure CPU, well under 60 s.  Phase A runs a 40-step `ElasticLoop` +
`ShardedTrainStep` child that takes three injected hits:

1. **NaN batch** (``nan_batch@7``) → the in-graph tier-1 guard drops the
   update, the policy backs off the attached AMP loss scale, and a
   ``remediation kind=skip`` journal event lands at step 7;
2. **worker death** (``worker_exec@2:exit``) → the batches ride a
   supervised process-pool DataLoader whose worker is repeatedly
   hard-killed; supervision respawns + resubmits, order preserved;
3. **sustained divergence** (``diverge_batch@20,21,22``) → three
   consecutive grad-explosion/loss-spike steps trigger exactly ONE
   tier-2 rollback to the newest healthy-tagged checkpoint (step 18,
   since the step-24 save never happens / is tagged unhealthy), with the
   poison window fast-forwarded on replay;
4. a **mid-run SIGTERM** at step 30 → grace-deadline emergency
   checkpoint + resumable marker, exit status ``preempted``.

Phase B reruns the child with no faults armed: `ElasticLoop.run` honors
the resume marker, restores the verified emergency checkpoint at step 30,
and completes to 40.  Both phases assert ``trace_count == 1`` — the whole
recovery machinery adds zero retraces when idle.

Pure stdlib on the parent side; exits non-zero with a reason on failure.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STEPS = 40
SAVE_EVERY = 6
NAN_AT = 7            # fault hit N fires on loop attempt N-1 = step id N
DIVERGE_AT = (20, 21, 22)
SIGTERM_AT = 30
HEALTHY_CKPT = 18     # newest healthy-tagged save before the divergence
INIT_SCALE = 2.0 ** 16


class _ChaosDataset:
    """Deterministic picklable dataset for the spawn workers."""

    def __init__(self, n):
        self._n = n

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        import numpy as onp
        return onp.full((8,), float(i), onp.float32)


def _pull_epoch_through_loader():
    """The worker-death leg: one epoch through the supervised process
    pool while ``worker_exec@2:exit`` hard-kills every worker incarnation
    on its 2nd batch — supervision must respawn, resubmit, and hand the
    epoch out complete and in order."""
    import numpy as onp
    from mxnet_tpu.gluon.data import DataLoader

    dl = DataLoader(_ChaosDataset(16), batch_size=4, num_workers=1,
                    thread_pool=False, timeout=120, worker_respawns=16)
    batches = [onp.asarray(b.asnumpy()) for b in dl]
    dl._proc_pool.shutdown()
    assert len(batches) == 4, f"epoch short: {len(batches)} batches"
    flat = onp.concatenate(batches)[:, 0]
    assert list(flat) == [float(i) for i in range(16)], \
        "worker respawn broke batch order"
    return batches


def _child(phase: str, ckpt_dir: str) -> int:
    import jax
    import jax.numpy as jnp
    import numpy as onp

    import mxnet_tpu as mx  # noqa: F401 — env auto-enables the subsystems
    from mxnet_tpu import health, optimizer as opt, recovery, telemetry
    from mxnet_tpu.amp.loss_scaler import LossScaler
    from mxnet_tpu.elastic import ElasticLoop
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import make_mesh, make_sharded_train_step
    from mxnet_tpu.resilience import FaultInjected, fault_point

    assert telemetry.enabled(), "MXTPU_TELEMETRY env wiring broken"
    assert health.enabled(), "MXTPU_HEALTH implied by recovery"
    assert recovery.enabled(), "MXTPU_RECOVERY env wiring broken"

    if phase == "A":
        _pull_epoch_through_loader()

    net = nn.Dense(4, in_units=8)
    net.initialize()
    mesh = make_mesh({"dp": 1}, jax.devices()[:1])
    step = make_sharded_train_step(
        net, opt.SGD(learning_rate=1e-2),
        lambda out, x, y: jnp.mean((out - y) ** 2), mesh, num_model_args=1)
    assert step._skip_nonfinite, "in-graph skip guard not armed"
    rng = onp.random.RandomState(0)
    xs = rng.uniform(-1, 1, (8, 8)).astype("float32")
    ys = rng.uniform(-1, 1, (8, 4)).astype("float32")

    scaler = LossScaler(init_scale=INIT_SCALE)
    policy = recovery.RecoveryPolicy(scaler=scaler)
    loop = ElasticLoop(step, ckpt_dir, save_every=SAVE_EVERY, keep=4,
                       recovery=policy, preempt_grace=60.0)

    def step_fn(i):
        x = xs
        try:
            # timing from the armed registry, payload a poisoned batch —
            # how a bad record or corrupt H2D shows up for real
            fault_point("nan_batch")
        except FaultInjected:
            x = xs * float("nan")
        try:
            fault_point("diverge_batch")
        except FaultInjected:
            x = xs * 1e4   # grads explode ~1e8: spike + explosion rules
        return step.dispatch(x, ys)

    def on_step(i, _loss):
        if phase == "A" and i == SIGTERM_AT:
            os.kill(os.getpid(), signal.SIGTERM)

    out = loop.run(step_fn, total_steps=STEPS, on_step=on_step)
    step.drain()
    print(json.dumps({
        "phase": phase, "status": out["status"], "step": out["step"],
        "trace_count": step.trace_count, "skips": policy.skips,
        "rollbacks": policy.rollbacks, "loss_scale": scaler.loss_scale,
        "emergency": out.get("emergency"),
    }))
    return 0


def _read_journal(path):
    rows = []
    with open(path) as f:
        for line in f:
            if line.strip():
                rows.append(json.loads(line))
    return rows


def _fail(msg, extra=""):
    print(f"FAIL: {msg}", file=sys.stderr)
    if extra:
        print(extra[-4000:], file=sys.stderr)
    return 1


def main() -> int:
    if "--child" in sys.argv:
        return _child(sys.argv[sys.argv.index("--child") + 1],
                      sys.argv[sys.argv.index("--child") + 2])

    workdir = tempfile.mkdtemp(prefix="mxtpu-chaos-smoke-")
    ckpt_dir = os.path.join(workdir, "ckpt")
    here = os.path.abspath(__file__)
    base_env = {
        "JAX_PLATFORMS": "cpu",
        "MXTPU_RECOVERY": "1",
        "MXTPU_SKIP_BUDGET": "8",
        "MXTPU_ROLLBACK_BUDGET": "2",
        "MXTPU_PREEMPT_GRACE": "60",
        "MXTPU_CRASH_DIR": os.path.join(workdir, "crash"),
    }

    # ---- phase A: NaN skip, worker death, divergence rollback, SIGTERM
    journal_a = os.path.join(workdir, "journal_a.jsonl")
    env = dict(os.environ)
    env.update(base_env)
    env["MXTPU_TELEMETRY"] = journal_a
    env["MXTPU_FAULT_SPEC"] = (
        f"nan_batch@{NAN_AT},worker_exec@2:exit,"
        + ",".join(f"diverge_batch@{s}" for s in DIVERGE_AT))
    proc = subprocess.run(
        [sys.executable, here, "--child", "A", ckpt_dir],
        env=env, capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.dirname(here)))
    if proc.returncode != 0:
        return _fail(f"phase A child exited {proc.returncode}",
                     proc.stdout + proc.stderr)
    try:
        result_a = json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return _fail("phase A child produced no result json",
                     proc.stdout + proc.stderr)

    if result_a["status"] != "preempted":
        return _fail(f"phase A status {result_a['status']!r} != 'preempted'",
                     proc.stderr)
    if result_a["trace_count"] != 1:
        return _fail(f"recovery machinery caused retraces: "
                     f"trace_count={result_a['trace_count']}")
    if result_a["skips"] < 1:
        return _fail("tier-1 skip never fired")
    if result_a["rollbacks"] != 1:
        return _fail(f"expected exactly 1 rollback, got "
                     f"{result_a['rollbacks']}", proc.stderr)
    if not result_a["loss_scale"] < INIT_SCALE:
        return _fail(f"loss scale not backed off "
                     f"(still {result_a['loss_scale']})")
    emergency = result_a.get("emergency") or {}
    if not emergency.get("complete"):
        return _fail(f"emergency checkpoint incomplete: {emergency}")

    rows = _read_journal(journal_a)
    rem = [r for r in rows if r["event"] == "remediation"]
    skips = [r for r in rem if r.get("kind") == "skip"]
    if not skips or skips[0]["step"] != NAN_AT:
        return _fail(f"remediation skip event missing/misplaced: {skips}")
    if skips[0].get("loss_scale") is None or \
            not skips[0]["loss_scale"] < INIT_SCALE:
        return _fail(f"skip event carries no backed-off scale: {skips[0]}")
    rollbacks = [r for r in rem if r.get("kind") == "rollback"]
    if len(rollbacks) != 1:
        return _fail(f"expected 1 remediation rollback event, got "
                     f"{len(rollbacks)}")
    if rollbacks[0].get("restored_step") != HEALTHY_CKPT:
        return _fail(f"rollback restored step "
                     f"{rollbacks[0].get('restored_step')} != "
                     f"{HEALTHY_CKPT} (newest healthy checkpoint)")
    preempts = [r for r in rem if r.get("kind") == "preempt_save"]
    if not preempts or not preempts[-1].get("complete") \
            or preempts[-1]["step"] != SIGTERM_AT:
        return _fail(f"preempt_save event wrong: {preempts}")
    if not any(r.get("kind") == "data_skip" for r in rem):
        return _fail("poison window was not fast-forwarded on replay")

    # ---- phase B: no faults; resume from the emergency checkpoint
    journal_b = os.path.join(workdir, "journal_b.jsonl")
    env = dict(os.environ)
    env.update(base_env)
    env["MXTPU_TELEMETRY"] = journal_b
    env.pop("MXTPU_FAULT_SPEC", None)
    proc = subprocess.run(
        [sys.executable, here, "--child", "B", ckpt_dir],
        env=env, capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.dirname(here)))
    if proc.returncode != 0:
        return _fail(f"phase B child exited {proc.returncode}",
                     proc.stdout + proc.stderr)
    result_b = json.loads(proc.stdout.strip().splitlines()[-1])
    if result_b["status"] != "completed" or result_b["step"] != STEPS:
        return _fail(f"phase B did not complete: {result_b}")
    if result_b["trace_count"] != 1:
        return _fail(f"phase B retraced: {result_b['trace_count']}")
    rem_b = [r for r in _read_journal(journal_b)
             if r["event"] == "remediation"]
    resumes = [r for r in rem_b if r.get("kind") == "preempt_resume"]
    if not resumes or resumes[0]["step"] != SIGTERM_AT:
        return _fail(f"phase B did not resume from the emergency "
                     f"checkpoint at step {SIGTERM_AT}: {resumes}")

    print(f"chaos smoke OK: skip@{skips[0]['step']} (scale "
          f"{skips[0]['loss_scale']:g}), 1 rollback -> step "
          f"{rollbacks[0]['restored_step']}, preempt@{SIGTERM_AT} "
          f"(complete), resumed@{resumes[0]['step']} -> {STEPS} "
          f"[trace_count=1 in both phases]")
    return 0


if __name__ == "__main__":
    sys.exit(main())
