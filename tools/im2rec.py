#!/usr/bin/env python
"""Pack an image folder or .lst file into RecordIO (parity:
`tools/im2rec.py` of the reference; same .lst format
`index\tlabel[\tlabel...]\trelative_path`).

Uses the native C++ recordio writer when built. Requires PIL for image
re-encoding; with `--pass-through` the raw file bytes are packed without
decoding (no PIL needed).

Usage:
    python tools/im2rec.py --list prefix image_root   # generate prefix.lst
    python tools/im2rec.py prefix image_root          # pack prefix.rec/.idx
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def make_list(prefix, root, recursive=False, train_ratio=1.0, shuffle=True,
              exts=(".jpg", ".jpeg", ".png", ".bmp")):
    entries = []
    classes = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        if not recursive and os.path.abspath(dirpath) != os.path.abspath(root):
            label_dir = os.path.relpath(dirpath, root).split(os.sep)[0]
        for fn in sorted(filenames):
            if os.path.splitext(fn)[1].lower() not in exts:
                continue
            rel = os.path.relpath(os.path.join(dirpath, fn), root)
            top = rel.split(os.sep)[0] if os.sep in rel else ""
            label = classes.setdefault(top, len(classes))
            entries.append((label, rel))
    if shuffle:
        random.shuffle(entries)
    n_train = int(len(entries) * train_ratio)
    splits = [("", entries)] if train_ratio >= 1.0 else [
        ("_train", entries[:n_train]), ("_val", entries[n_train:])]
    for suffix, items in splits:
        with open(f"{prefix}{suffix}.lst", "w") as f:
            for i, (label, rel) in enumerate(items):
                f.write(f"{i}\t{label}\t{rel}\n")
    return classes


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            yield int(parts[0]), [float(x) for x in parts[1:-1]], parts[-1]


def pack(prefix, root, resize=0, quality=95, color=1, pass_through=False):
    from mxnet_tpu import recordio
    rec_path = prefix + ".rec"
    idx_path = prefix + ".idx"
    writer = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    count = 0
    for idx, labels, rel in read_list(prefix + ".lst"):
        path = os.path.join(root, rel)
        label = labels[0] if len(labels) == 1 else labels
        header = recordio.IRHeader(0, label, idx, 0)
        if pass_through or resize == 0:
            with open(path, "rb") as f:
                data = f.read()
        else:
            import io as _io

            from PIL import Image
            img = Image.open(path)
            if color == 0:
                img = img.convert("L")
            if resize:
                w, h = img.size
                scale = resize / min(w, h)
                img = img.resize((int(w * scale), int(h * scale)))
            buf = _io.BytesIO()
            img.save(buf, format="JPEG", quality=quality)
            data = buf.getvalue()
        writer.write_idx(idx, recordio.pack(header, data))
        count += 1
        if count % 1000 == 0:
            print(f"packed {count} images", file=sys.stderr)
    writer.close()
    print(f"wrote {count} records to {rec_path}")


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("prefix")
    ap.add_argument("root")
    ap.add_argument("--list", action="store_true",
                    help="generate .lst instead of packing")
    ap.add_argument("--recursive", action="store_true")
    ap.add_argument("--train-ratio", type=float, default=1.0)
    ap.add_argument("--no-shuffle", action="store_true")
    ap.add_argument("--resize", type=int, default=0)
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--color", type=int, default=1)
    ap.add_argument("--pass-through", action="store_true",
                    help="pack raw file bytes without re-encoding")
    args = ap.parse_args()
    if args.list:
        classes = make_list(args.prefix, args.root, args.recursive,
                            args.train_ratio, not args.no_shuffle)
        print(f"classes: {classes}")
    else:
        if not os.path.exists(args.prefix + ".lst"):
            make_list(args.prefix, args.root, args.recursive, 1.0,
                      not args.no_shuffle)
        pack(args.prefix, args.root, args.resize, args.quality, args.color,
             args.pass_through)


if __name__ == "__main__":
    main()
