#!/usr/bin/env python
"""Disaggregated-serving smoke (`make disagg-smoke`, wired into
`make test`).

CPU-only, <60 s end-to-end check of prefill/decode disaggregation
(docs/serving.md "Disaggregated serving") over 8 virtual devices:

- **1 prefill + 2 decode process replicas** spawned from ONE spec dir
  (per-worker ``--role`` / ``--tp`` overrides), the decode tier
  tensor-parallel over 2 virtual devices each;
- a **shared-prefix prompt mix**: half the prompts share a long prefix,
  so the prefill tier exercises chunked prefill while EVERY request
  crosses a real cross-process KV handoff — page contents shipped as
  length-prefixed binary wire frames (kv_export → kv_import →
  submit_prefilled → kv_free), never JSON floats;
- **one decode worker is SIGKILLed mid-stream** — its adopted streams
  fail over from the parent's stream ledger, re-queue at the PREFILL
  tier (re-prefill of prompt + generated, the ONE recovery rule), hand
  off AGAIN, and finish **bit-identical** to the unbatched
  ``generate()`` oracle, never re-emitting a token;
- the killed worker **respawns** under ``MXTPU_REPLICA_RESPAWNS``;
- zero dropped requests and handoff count > 0, asserted from both the
  fleet's counters and the telemetry journal.
"""
from __future__ import annotations

import json
import os
import signal
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    # 8 virtual devices — inherited by every spawned worker, so the
    # decode tier can shard tp=2 while tiers coexist on one host
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    t_start = time.time()
    journal_path = os.path.join(
        tempfile.mkdtemp(prefix="mxtpu_disagg_smoke_"), "journal.jsonl")

    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry as tele
    from mxnet_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from mxnet_tpu.serve import ServeConfig, ServeFleet

    tele.enable(journal_path=journal_path)

    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_heads=4, intermediate_size=64, max_position=64,
                    dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.initialize()
    model(mx.np.array([[1, 2]], dtype="int32"))

    rng = onp.random.RandomState(31)
    max_new = 12
    n_req = 8
    shared = rng.randint(0, 96, 8).tolist()   # the shared prefix
    prompts = []
    for i in range(n_req):
        if i % 2 == 0:
            prompts.append(shared + rng.randint(0, 96,
                                                2 + i % 3).tolist())
        else:
            prompts.append(rng.randint(0, 96,
                                       rng.randint(2, 11)).tolist())

    # unbatched references (the oracle): one generate() per request
    refs = []
    for p in prompts:
        ids = mx.np.array([p], dtype="int32")
        refs.append(onp.asarray(
            model.generate(ids, max_new_tokens=max_new)
            .asnumpy())[0].tolist())

    sc = ServeConfig(max_slots=2, page_size=4, num_pages=0,
                     prefill_chunk=4, max_len=32, tp=2)
    fleet = ServeFleet(model, config=sc, transport="process",
                       disagg=(1, 2), respawn_budget=2,
                       stall_timeout=15.0)
    roles = {r.name: (r.engine.role, r.engine.tp) for r in fleet.replicas}
    assert roles == {"p0": ("prefill", 1), "d1": ("decode", 2),
                     "d2": ("decode", 2)}, roles
    fleet.warmup()
    assert all(r.pid is not None and r.pid != os.getpid()
               for r in fleet.replicas), "workers must be real processes"

    streams = {i: [] for i in range(n_req)}

    def tok_cb(i):
        return lambda t, r: streams[i].append(t)

    try:
        fleet.start()
        handles = {}
        for i in range(n_req):
            handles[i] = fleet.submit(prompts[i], max_new_tokens=max_new,
                                      on_token=tok_cb(i))

        # wait until a DECODE worker holds an adopted stream with real
        # progress (> the prefill-emitted token), then SIGKILL it — the
        # hardest failover shape: ledger salvage must re-queue at the
        # prefill tier and the stream must resume without re-emitting
        decoders = [r for r in fleet.replicas
                    if r.engine.role == "decode"]
        victim = None
        deadline = time.time() + 40
        while victim is None and time.time() < deadline:
            for rep in decoders:
                sched = rep.engine.scheduler
                with sched._lock:
                    if any(len(e.req.tokens) >= 3
                           for e in sched._ledger.values()):
                        victim = rep
                        break
            time.sleep(0.002)
        assert victim is not None, \
            "no decode worker ever held a progressed adopted stream"
        victim_pid = victim.pid
        os.kill(victim_pid, signal.SIGKILL)

        deadline = time.time() + 30
        while fleet.respawns == 0 and time.time() < deadline:
            time.sleep(0.005)
        assert fleet.deaths >= 1, "SIGKILL never detected"
        assert fleet.respawns >= 1, "killed decode worker never respawned"

        # ---- zero dropped requests, bit-identical streams ------------
        for i in range(n_req):
            got = handles[i].result(timeout=90)
            assert got == refs[i], (
                f"request {i}: disagg output diverged from single-request"
                f" generate\n  got {got}\n  ref {refs[i]}")
            assert streams[i] == refs[i][len(prompts[i]):], (
                f"request {i}: streamed tokens diverged (re-emission or "
                f"loss): {streams[i]} vs {refs[i][len(prompts[i]):]}")
        assert fleet.quiesce(30), "fleet never went idle"
        assert fleet.handoffs >= n_req, (
            f"every request must cross the prefill->decode handoff "
            f"(handoffs={fleet.handoffs}, requests={n_req})")
    finally:
        st = fleet.stats()
        fleet.close()

    # ---- telemetry / journal contract --------------------------------
    snap = tele.snapshot()
    hand = snap.get("serve_handoffs_total", {}).get("series", [])
    assert sum(s["value"] for s in hand) == fleet.handoffs, hand
    assert "serve_handoff_ms" in snap, "handoff latency never observed"
    finished = [s for s in snap["serve_requests_total"]["series"]
                if s["labels"]["state"] == "finished"]
    assert finished and finished[0]["value"] == n_req, finished
    rows = tele.RunJournal.read(journal_path)
    assert any(r.get("event") == "handoff" for r in rows), \
        "journal missing handoff events"

    elapsed = time.time() - t_start
    print(json.dumps({
        "disagg_smoke": "ok", "requests": n_req,
        "handoffs": fleet.handoffs,
        "handoff_failures": fleet.handoff_failures,
        "deaths": fleet.deaths, "respawns": fleet.respawns,
        "roles": {n: list(v) for n, v in roles.items()},
        "router": st["router"]["routed"],
        "elapsed_s": round(elapsed, 1)}))
    assert elapsed < 60, f"smoke took {elapsed:.0f}s (budget 60s)"
    return 0


if __name__ == "__main__":
    sys.exit(main())
