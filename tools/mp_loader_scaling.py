"""MP DataLoader scaling microbench (VERDICT r3 next-step #5).

Measures epoch wall-clock of a CPU-bound pure-Python per-item transform
through `gluon.data.DataLoader` at 1..N worker processes — the workload a
thread pool cannot scale past ~1 core (GIL).  Parity target: the
reference's multiprocessing loader speedup
(`python/mxnet/gluon/data/dataloader.py` worker pool).

Run:  python tools/mp_loader_scaling.py [--workers 1 2 4] [--items 32]
      [--work 300000] [--batch 4]
Prints one JSON line per worker count:
  {"workers": W, "epoch_seconds": T, "speedup_vs_1": S}

`tests/unittest/test_gluon_data.py::test_mp_dataloader_scales_past_gil`
drives this same code path with an asserted >1.4x at 2 workers, so the
scaling property executes in CI (4-vCPU runners), not just here.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class CpuBoundDataset:
    """Pure-Python busy transform; scales only with real processes."""

    def __init__(self, n: int, work: int):
        self._n = n
        self._work = work

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        import numpy as onp
        acc = float(i)
        for k in range(self._work):
            acc = (acc * 1.0000001 + k % 7) % 1e9
        return onp.asarray([acc], onp.float32)


def epoch_seconds(workers: int, items: int, work: int, batch: int) -> float:
    from mxnet_tpu.gluon.data import DataLoader
    ds = CpuBoundDataset(items, work)
    dl = DataLoader(ds, batch_size=batch, num_workers=workers,
                    thread_pool=False, timeout=600)
    list(dl)                       # warm epoch: worker spawn + imports
    t0 = time.perf_counter()
    list(dl)
    return time.perf_counter() - t0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--workers", type=int, nargs="+", default=[1, 2, 4])
    ap.add_argument("--items", type=int, default=32)
    ap.add_argument("--work", type=int, default=300000)
    ap.add_argument("--batch", type=int, default=4)
    args = ap.parse_args(argv)
    # hard-set, not setdefault, and HERE rather than at import (pytest
    # imports epoch_seconds — an import side effect would overwrite the
    # suite's ambient platform): the measurement is host-side; an ambient
    # JAX_PLATFORMS pointing at a remote TPU tunnel would stall every
    # spawned worker on device init
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    base = None
    for w in args.workers:
        t = epoch_seconds(w, args.items, args.work, args.batch)
        if base is None:
            base = t
        print(json.dumps({"workers": w, "epoch_seconds": round(t, 3),
                          "speedup_vs_1": round(base / t, 2),
                          "nproc": os.cpu_count()}), flush=True)


if __name__ == "__main__":
    main()
