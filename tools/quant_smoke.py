#!/usr/bin/env python
"""Quantization end-to-end smoke (`make quant-smoke`, docs/quantization.md).

Under 60 s on CPU, proves the whole int8/int4 path:

- **capture child**: one GPT serves f32 reference streams, then two
  fresh engines export through ``QuantizePass(bits=8)`` and ``(bits=4)``
  — asserting the engine's total weight bytes shrink >= 1.9x (int8) /
  >= 3.5x (int4), the artifact manifest records the ``quant`` field,
  and the capacity freed by the smaller weights landed in the
  free-page gauges (bonus pages > 0).  The same child also runs the
  interpret-mode Pallas kernel against the jnp dequant-matmul oracle
  (int8 + packed int4, odd shapes) and the int8-gradient-compression
  convergence dryrun: 12 training steps with ``grad_compress="int8"``
  must track the f32 all-reduce loss curve within tolerance with
  ``trace_count == 1``.
- **load children** (one per bits, fresh process with
  ``MXTPU_QUANT_BITS`` set): load the artifact, serve 4 requests, and
  assert ZERO transformer-Python executions (the zero-retrace
  contract) with streams bit-identical to the capture child's
  quantized engine.
- **parent**: pins the top-1 token-agreement thresholds vs the f32
  streams (int8 >= INT8_AGREEMENT, int4 >= INT4_AGREEMENT) and checks
  a dense engine refuses the int8 artifact (scheme-mismatch fail-fast).

Usage: ``python tools/quant_smoke.py`` (parent), or with
``--role capture|load8|load4 <dir>`` as a child.
"""
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# pinned agreement thresholds (docs/quantization.md "accuracy
# expectations"): measured ~1.0/<=1.0 on the smoke model with margin —
# int4 compounds per-step divergence, so its floor is deliberately low
INT8_AGREEMENT = 0.70
INT4_AGREEMENT = 0.35
PROMPTS = [[1, 2, 3, 4], [9, 8, 7], [20, 21, 22, 23, 24], [5, 15, 25]]
MAX_NEW = 12


def _child_env(extra=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    for k in ("MXTPU_QUANT_BITS", "MXTPU_QUANT_ACT", "MXTPU_PALLAS",
              "MXTPU_PALLAS_INTERPRET", "MXTPU_GRAD_COMPRESS"):
        env.pop(k, None)
    env.update(extra or {})
    return env


def _build_model(seed=0):
    import mxnet_tpu as mx
    from mxnet_tpu import random as mxrng
    from mxnet_tpu.models.gpt import GPTConfig, GPTForCausalLM
    mxrng.seed(seed)
    # projection-dominated shape (the real-model regime the byte-
    # reduction floors assume): matmul weights ~10x the embedding
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, intermediate_size=256, max_position=64,
                    dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.initialize()
    model(mx.np.array([[1, 2]], dtype="int32"))
    return model


def _engine(model, bits=0):
    from mxnet_tpu.serve import InferenceEngine, ServeConfig
    return InferenceEngine(model, ServeConfig(max_len=64, max_slots=4,
                                              quant_bits=bits))


def _serve4(eng):
    handles = [eng.submit(p, max_new_tokens=MAX_NEW) for p in PROMPTS]
    eng.run_until_idle()
    return [h.result(timeout=0) for h in handles]


def _kernel_parity_check():
    """Interpret-mode Pallas kernel vs the jnp dequant-matmul oracle
    (env flips are trace-time reads — fresh jits see them)."""
    import numpy as onp
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas import quantized_matmul as qm
    os.environ["MXTPU_PALLAS"] = "kernel"
    os.environ["MXTPU_PALLAS_INTERPRET"] = "1"
    try:
        rng = onp.random.RandomState(3)
        x = jnp.asarray(rng.randn(5, 33), jnp.float32)   # odd K
        w = jnp.asarray(rng.randn(17, 33), jnp.float32)  # odd channels
        errs = {}
        for bits in (8, 4):
            qt = qm.quantize_weight(w, bits)
            kern = qm.quantized_matmul(x, qt, use_kernel=True)
            oracle = qm.quantized_matmul_reference(x, qt)
            errs[bits] = float(jnp.max(jnp.abs(kern - oracle)))
            assert errs[bits] <= 1e-4, \
                f"int{bits} kernel vs oracle err {errs[bits]}"
        return errs
    finally:
        os.environ.pop("MXTPU_PALLAS", None)
        os.environ.pop("MXTPU_PALLAS_INTERPRET", None)


def _grad_compress_dryrun():
    """12-step convergence dryrun: int8-compressed gradient reduction
    must track the f32 loss curve (docs/quantization.md)."""
    import jax
    import jax.numpy as jnp
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import optimizer as opt, random as mxrng
    from mxnet_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from mxnet_tpu.parallel import make_mesh, make_sharded_train_step
    from mxnet_tpu.ops.pallas.softmax_xent import softmax_cross_entropy

    def run(compress):
        mxrng.seed(7)
        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                        num_heads=4, intermediate_size=64,
                        max_position=64, dropout=0.0)
        model = GPTForCausalLM(cfg)
        model.initialize()
        rng = onp.random.RandomState(7)
        ids = mx.np.array(rng.randint(0, 128, (8, 16)), dtype="int32")
        lbl = mx.np.array(rng.randint(0, 128, (8, 16)), dtype="int32")
        model(ids)

        def loss_fn(out, input_ids, labels):
            o = out._data if hasattr(out, "_data") else out
            return jnp.mean(softmax_cross_entropy(
                o, labels.astype(jnp.int32)))

        mesh = make_mesh({"dp": 1}, jax.devices()[:1])
        step = make_sharded_train_step(
            model, opt.Adam(learning_rate=1e-2), loss_fn, mesh,
            num_model_args=1, grad_compress=compress)
        losses = [float(jax.device_get(step.dispatch(ids, lbl).loss))
                  for _ in range(12)]
        return losses, step.trace_count

    f32, tc_f = run(None)
    q, tc_q = run("int8")
    assert tc_f == 1 and tc_q == 1, (tc_f, tc_q)
    rel = abs(q[-1] - f32[-1]) / max(1e-9, abs(f32[-1]))
    assert rel < 0.15, \
        f"int8-compressed final loss {q[-1]} vs f32 {f32[-1]} (rel {rel})"
    assert q[-1] < q[0], f"compressed run failed to descend: {q}"
    return {"f32_final": f32[-1], "int8_final": q[-1],
            "rel": round(rel, 5)}


def role_capture(art_dir):
    from mxnet_tpu.export import QuantizePass

    model = _build_model()
    eng_f32 = _engine(model)
    f32_bytes = eng_f32.weight_bytes()
    f32_tokens = _serve4(eng_f32)

    out = {"f32_tokens": f32_tokens, "f32_bytes": f32_bytes}
    floors = {8: 1.9, 4: 3.5}
    for bits in (8, 4):
        eng = _engine(model)          # dense; the pass quantizes it
        eng.warmup()
        eng.export(os.path.join(art_dir, f"q{bits}"),
                   passes=[QuantizePass(bits=bits)])
        st = eng.stats()
        reduction = f32_bytes / max(1, st["weight_bytes"])
        assert reduction >= floors[bits], \
            (f"int{bits} weight bytes {st['weight_bytes']} vs f32 "
             f"{f32_bytes}: reduction {reduction:.2f} < {floors[bits]}")
        assert st["bonus_pages"] > 0, \
            f"int{bits}: freed weight bytes bought no pages: {st}"
        man = json.load(open(os.path.join(art_dir, f"q{bits}",
                                          "manifest.json")))
        assert man.get("quant", {}).get("bits") == bits, man.get("quant")
        assert "quantize" in [p["name"] for p in man["passes"]]
        out[f"q{bits}_tokens"] = _serve4(eng)
        out[f"q{bits}_reduction"] = round(reduction, 3)
        out[f"q{bits}_bonus_pages"] = st["bonus_pages"]

    # dense engine must refuse the quantized artifact (failure matrix)
    from mxnet_tpu.base import MXNetError
    try:
        _engine(model).load_export(os.path.join(art_dir, "q8"))
        raise AssertionError("dense engine loaded an int8 artifact")
    except MXNetError:
        pass

    out["kernel_parity_err"] = _kernel_parity_check()
    out["grad_compress"] = _grad_compress_dryrun()
    return out


def role_load(art_dir, bits):
    # count transformer-Python executions: the loaded artifact must
    # serve without ever running the model's Python (trace_count==0).
    # Patch BOTH bindings — decode owns the fn, engine imported it by
    # name at module load.
    import mxnet_tpu.serve.decode as dec
    import mxnet_tpu.serve.engine as eng_mod
    calls = {"n": 0}
    orig = dec.transformer_step

    def counting(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    dec.transformer_step = counting
    eng_mod.transformer_step = counting

    model = _build_model()
    eng = _engine(model, bits=bits)   # MXTPU_QUANT_BITS also set by env
    eng.warmup(artifact=os.path.join(art_dir, f"q{bits}"))
    tokens = _serve4(eng)
    assert calls["n"] == 0, \
        f"loaded int{bits} path ran transformer Python {calls['n']}x"
    return {"tokens": tokens, "transformer_calls": calls["n"]}


def _agreement(a, b):
    """Mean positional top-1 agreement over paired token streams."""
    num = den = 0
    for s1, s2 in zip(a, b):
        n = min(len(s1), len(s2))
        num += sum(x == y for x, y in zip(s1[:n], s2[:n]))
        den += n
    return num / max(1, den)


def main():
    if "--role" in sys.argv:
        i = sys.argv.index("--role")
        role, art_dir = sys.argv[i + 1], sys.argv[i + 2]
        if role == "capture":
            out = role_capture(art_dir)
        else:
            out = role_load(art_dir, int(role[len("load"):]))
        print("SMOKE_JSON:" + json.dumps(out))
        return

    with tempfile.TemporaryDirectory(prefix="mxtpu_quant_smoke_") as art:
        results = {}
        for role, extra in (
                ("capture", None),
                ("load8", {"MXTPU_QUANT_BITS": "8"}),
                ("load4", {"MXTPU_QUANT_BITS": "4"})):
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--role", role, art],
                capture_output=True, text=True, timeout=540,
                env=_child_env(extra), cwd=REPO)
            if proc.returncode != 0:
                sys.stderr.write(proc.stdout[-2000:])
                sys.stderr.write(proc.stderr[-4000:])
                raise SystemExit(f"quant smoke: {role} child failed "
                                 f"(rc={proc.returncode})")
            for line in proc.stdout.splitlines():
                if line.startswith("SMOKE_JSON:"):
                    results[role] = json.loads(line[len("SMOKE_JSON:"):])

    capt = results["capture"]
    for bits, floor in ((8, INT8_AGREEMENT), (4, INT4_AGREEMENT)):
        loaded = results[f"load{bits}"]
        assert loaded["tokens"] == capt[f"q{bits}_tokens"], \
            (f"int{bits} loaded stream drifted from the capture "
             f"engine: {loaded['tokens']} vs {capt[f'q{bits}_tokens']}")
        agree = _agreement(loaded["tokens"], capt["f32_tokens"])
        assert agree >= floor, \
            f"int{bits} top-1 agreement {agree:.3f} < pinned {floor}"
        print(f"  int{bits}: weight reduction "
              f"{capt[f'q{bits}_reduction']}x, bonus pages "
              f"{capt[f'q{bits}_bonus_pages']}, f32 agreement "
              f"{agree:.3f}, transformer_calls=0")
    print(f"  kernel parity err: {capt['kernel_parity_err']}")
    print(f"  grad-compress dryrun: {capt['grad_compress']}")
    print("quant smoke OK: int8/int4 artifacts load with zero "
          "transformer traces, capacity + agreement floors hold, "
          "int8 grad compression converges")


if __name__ == "__main__":
    main()
