"""Fused-kernel smoke check (`make kernels-smoke`, docs/perf.md).

CPU interpret-mode parity sweep for the Pallas kernel set — the exact
kernel code runs through the Pallas interpreter against each module's
jnp reference over odd/padded shapes (ragged rows, non-128 last dims,
capacity overflow) — followed by one autotune round asserting the
search-then-persist loop: a cold `tune()` times candidates and writes
the config JSON; a warm `tune()` (same key, fresh process-memory cache)
reloads it from disk with ZERO timed trials and increments the
`autotune_hits` counter.  Exits non-zero with a reason on any failure;
cheap enough for CI (<60s CPU).
"""
from __future__ import annotations

import os
import sys
import tempfile

# must happen before any jax backend initialisation: CPU backend, the
# Pallas interpreter, and the forced-kernel mode the sweep exercises
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["MXTPU_PALLAS_INTERPRET"] = "1"
os.environ["MXTPU_PALLAS"] = "kernel"
os.environ["MXTPU_TELEMETRY"] = "1"
_CACHE = tempfile.mkdtemp(prefix="mxtpu_autotune_smoke_")
os.environ["MXTPU_AUTOTUNE_CACHE"] = _CACHE

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def fail(msg: str) -> None:
    print(f"kernels-smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def check_close(name, got, want, atol):
    got = np.asarray(got, np.float32)
    want = np.asarray(want, np.float32)
    if got.shape != want.shape:
        fail(f"{name}: shape {got.shape} != {want.shape}")
    err = float(np.max(np.abs(got - want))) if got.size else 0.0
    if not np.isfinite(err) or err > atol:
        fail(f"{name}: max|err| {err:.3e} > atol {atol:.1e}")
    print(f"  {name}: max|err| {err:.3e} (atol {atol:.1e})")


def norm_sweep():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas import fused_norm as fn

    rng = np.random.RandomState(0)
    # odd/padded shapes: ragged rows, last dims off the 128-lane granule
    for rows, h in ((5, 37), (17, 128), (9, 200), (64, 1024)):
        for dt, atol in ((jnp.float32, 1e-5), (jnp.bfloat16, 3e-2)):
            x = jnp.asarray(rng.randn(rows, h), dt)
            res = jnp.asarray(rng.randn(rows, h), dt)
            g = jnp.asarray(rng.rand(h) + 0.5, dt)
            b = jnp.asarray(rng.randn(h), dt)
            # oracle in f32 (the kernel computes statistics in f32; a
            # low-precision reference would be the LESS accurate side)
            xf, rf = x.astype(jnp.float32), res.astype(jnp.float32)
            gf, bf = g.astype(jnp.float32), b.astype(jnp.float32)

            y = fn.fused_layer_norm(x, g, b, use_kernel=True)
            check_close(f"layer_norm {rows}x{h} {jnp.dtype(dt).name}",
                        y, fn.layer_norm_reference(xf, gf, bf), atol)
            y = fn.fused_rms_norm(x, g, use_kernel=True)
            check_close(f"rms_norm {rows}x{h} {jnp.dtype(dt).name}",
                        y, fn.rms_norm_reference(xf, gf), atol)
            y, s = fn.layer_norm_residual(x, res, g, b, use_kernel=True)
            yr, sr = fn.layer_norm_reference(xf, gf, bf, residual=rf)
            check_close(f"ln+res y {rows}x{h} {jnp.dtype(dt).name}",
                        y, yr, atol)
            check_close(f"ln+res s {rows}x{h} {jnp.dtype(dt).name}",
                        s, sr, atol)

    # gradients flow through the custom_vjp (Pallas fwd, jnp bwd)
    x = jnp.asarray(rng.randn(6, 40), jnp.float32)
    g = jnp.asarray(rng.rand(40) + 0.5, jnp.float32)
    b = jnp.zeros((40,), jnp.float32)

    def loss_k(xv):
        return jnp.sum(fn.fused_layer_norm(xv, g, b, use_kernel=True))

    def loss_r(xv):
        return jnp.sum(fn.layer_norm_reference(xv, g, b))

    check_close("layer_norm grad", jax.grad(loss_k)(x),
                jax.grad(loss_r)(x), 1e-4)


def moe_sweep():
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas import moe_dispatch as md

    rng = np.random.RandomState(1)
    # odd T, capacity overflow: E*C slots < kept tokens -> forced drops
    t, e, c, h = 53, 4, 6, 128
    x = jnp.asarray(rng.randn(t, h), jnp.float32)
    expert_np = rng.randint(0, e, t)
    # router invariant: pos is the token's arrival rank within its
    # expert (unique per (expert, slot)); rank >= capacity is dropped
    pos_np = np.zeros(t, np.int64)
    seen = np.zeros(e, np.int64)
    for i, ex in enumerate(expert_np):
        pos_np[i] = seen[ex]
        seen[ex] += 1
    expert = jnp.asarray(expert_np, jnp.int32)
    kept = jnp.asarray(pos_np < c)
    pos = jnp.asarray(np.where(pos_np < c, pos_np, 0), jnp.int32)
    gate = jnp.asarray(rng.rand(t), jnp.float32)

    buf_k = md.moe_dispatch(x, expert, pos, kept, e, c, use_kernel=True)
    buf_r = md.moe_dispatch_reference(x, expert, pos, kept, e, c)
    check_close(f"moe_dispatch T={t} E={e} C={c}", buf_k, buf_r, 1e-6)

    down = jnp.asarray(rng.randn(e, c, h), jnp.float32)
    out_k = md.moe_combine(down, expert, pos, kept, gate, use_kernel=True)
    out_r = md.moe_combine_reference(down, expert, pos, kept, gate)
    check_close("moe_combine (overflow drops)", out_k, out_r, 1e-6)
    # dropped tokens must be EXACT zero rows (the dense-einsum contract)
    dropped = ~np.asarray(kept)
    if np.any(np.asarray(out_k)[dropped] != 0.0):
        fail("moe_combine: dropped tokens produced non-zero rows")


def optimizer_sweep():
    import jax.numpy as jnp
    from mxnet_tpu.optimizer import SGD, Adam
    from mxnet_tpu.ops.pallas import fused_optimizer as fo

    rng = np.random.RandomState(2)
    hp = {"lr": jnp.float32(0.01), "wd": jnp.float32(0.01),
          "rescale_grad": jnp.float32(1.0),
          "clip_gradient": jnp.float32(1.0), "t": jnp.float32(3.0)}
    for opt, atol in ((Adam(learning_rate=0.01), 1e-6),
                      (SGD(learning_rate=0.01, momentum=0.9), 1e-6)):
        # odd leaf sizes force tile padding inside the packed chunk
        params = {n: jnp.asarray(rng.randn(sz), jnp.float32)
                  for n, sz in (("w", 1000), ("b", 37), ("s", 8))}
        grads = {n: jnp.asarray(rng.randn(v.size), jnp.float32)
                 for n, v in params.items()}
        states = {n: opt.create_state_jax(v) for n, v in params.items()}
        name = type(opt).__name__

        kp, ks = fo.apply_updates(opt, params, grads, states, hp,
                                  skip=None, use_kernel=True)
        rp, rs = fo.apply_updates(opt, params, grads, states, hp,
                                  skip=None, use_kernel=False)
        for n in params:
            check_close(f"{name} {n} (kernel vs reference)",
                        kp[n], rp[n], atol)
        # skip semantics: params AND state bit-identical to their
        # pre-step values when the non-finite probe fired
        sp, ss = fo.apply_updates(opt, params, grads, states, hp,
                                  skip=jnp.asarray(True),
                                  use_kernel=True)
        for n in params:
            if not np.array_equal(np.asarray(sp[n]),
                                  np.asarray(params[n])):
                fail(f"{name} {n}: skip=True changed params")
        import jax
        for a, b in zip(jax.tree_util.tree_leaves(ss),
                        jax.tree_util.tree_leaves(states)):
            if not np.array_equal(np.asarray(a), np.asarray(b)):
                fail(f"{name}: skip=True changed optimizer state")
        print(f"  {name}: skip guard bit-identical")


def autotune_round():
    from mxnet_tpu import telemetry as tele
    from mxnet_tpu.ops.pallas import autotune as at

    shapes, dtype = (64, 128), "float32"

    def hits():
        return tele.counter("autotune_hits").value()

    cold = at.tune("fused_norm", shapes, dtype, warmup=1, runs=2, top_k=2)
    if cold.cache_hit or cold.trials < 1:
        fail(f"cold tune was not a search: {cold}")
    path = os.path.join(_CACHE, "autotune_fused_norm.json")
    if not os.path.exists(path):
        fail(f"no persisted config at {path}")

    h0 = hits()
    warm = at.tune("fused_norm", shapes, dtype)
    if not warm.cache_hit or warm.trials != 0:
        fail(f"warm tune re-searched: {warm}")
    if hits() != h0 + 1:
        fail(f"autotune_hits did not increment ({h0} -> {hits()})")

    # fresh memory cache -> the DISK entry alone must serve the key
    at.clear_memory_cache()
    disk = at.tune("fused_norm", shapes, dtype)
    if not disk.cache_hit or disk.trials != 0:
        fail(f"disk warm start re-searched: {disk}")
    if at.cached_config("fused_norm", shapes, dtype) is None:
        fail("cached_config lookup missed after disk reload")
    print(f"  autotune: cold search {cold.trials} trials "
          f"({cold.search_ms:.0f}ms), warm + disk hits with 0 trials, "
          f"config at {path}")


def main():
    print("kernels-smoke: parity sweep (Pallas interpreter vs jnp "
          "references)")
    print("fused_norm:")
    norm_sweep()
    print("moe_dispatch:")
    moe_sweep()
    print("fused_optimizer:")
    optimizer_sweep()
    print("autotune:")
    autotune_round()
    print("kernels-smoke: OK")


if __name__ == "__main__":
    main()
