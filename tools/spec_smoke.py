#!/usr/bin/env python
"""Decode-fast-path smoke (`make spec-smoke`, wired into `make test`).

CPU-only, <60 s end-to-end check of speculative multi-token decoding +
cross-request prefix caching (docs/serving.md "Speculative decoding &
prefix caching"):

- a primer request warms the prefix cache, then 6 requests whose
  prompts share its prefix run under k=4 speculation through the
  continuous-batching scheduler;
- every stream must be BIT-IDENTICAL to an unbatched single-request
  `GPTForCausalLM.generate` — speculation and prefix reuse only change
  how many fused launches the output costs, never the output;
- measured fused-step launches per emitted token must be < 1.0 (the
  whole point of the fast path), with `prefix_hit_tokens > 0` (prefill
  chunks actually skipped) and at least one copy-on-write fork
  exercised (a write landed in a shared page and was isolated);
- the compiled-program count must be stable: exactly one compile per
  step width at warmup (prefill chunk, spec verify width, decode C=1)
  and ZERO additional compiles during the run.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    t_start = time.time()
    journal_path = os.path.join(
        tempfile.mkdtemp(prefix="mxtpu_spec_smoke_"), "journal.jsonl")

    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry as tele
    from mxnet_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from mxnet_tpu.serve import InferenceEngine, ServeConfig

    tele.enable(journal_path=journal_path)

    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_heads=4, intermediate_size=64, max_position=64,
                    dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.initialize()
    model(mx.np.array([[1, 2]], dtype="int32"))

    rng = onp.random.RandomState(11)
    max_new = 12
    # primer + 6 requests sharing its 14-token prefix (NOT page-aligned
    # at page_size 4, so the cached partial block forces a COW fork the
    # moment any attacher — or the primer itself — writes past it)
    base = rng.randint(0, 96, 14).tolist()
    prompts = [base] + [base + rng.randint(0, 96,
                                           rng.randint(1, 4)).tolist()
                        for _ in range(6)]

    refs = []
    for p in prompts:
        ids = mx.np.array([p], dtype="int32")
        refs.append(onp.asarray(
            model.generate(ids, max_new_tokens=max_new)
            .asnumpy())[0].tolist())

    sc = ServeConfig(max_slots=3, page_size=4, prefill_chunk=6,
                     max_len=40, spec_tokens=4, prefix_cache=True)
    eng = InferenceEngine(model, sc)
    eng.warmup()

    def compile_count():
        rows = tele.RunJournal.read(journal_path)
        return sum(1 for r in rows if r.get("event") == "compile_end"
                   and r.get("kind") == "serve_step")

    widths = eng._step_widths()
    compiles_warm = compile_count()
    assert compiles_warm == len(widths) == 3, (
        f"expected one warmup compile per width {widths}, journal shows "
        f"{compiles_warm}")

    # primer runs alone: its prompt prefill populates the prefix index
    h0 = eng.submit(prompts[0], max_new_tokens=max_new)
    eng.run_until_idle()
    assert h0.result(timeout=0) == refs[0], "primer stream diverged"

    streams = {i: [] for i in range(1, 7)}
    handles = []
    for i, p in enumerate(prompts[1:], start=1):
        handles.append(eng.submit(
            p, max_new_tokens=max_new,
            on_token=lambda t, r, i=i: streams[i].append(t)))
    steps0 = eng.scheduler._steps
    eng.run_until_idle()

    for i, (h, ref) in enumerate(zip(handles, refs[1:]), start=1):
        got = h.result(timeout=0)
        assert got == ref, (
            f"request {i}: speculative output diverged from generate\n"
            f"  got {got}\n  ref {ref}")
        assert streams[i] == ref[len(prompts[i]):], (
            f"request {i}: streamed tokens diverged")

    stats = eng.scheduler.spec_stats()
    steps_shared = eng.scheduler._steps - steps0
    toks_shared = 6 * max_new
    spt = steps_shared / toks_shared
    assert spt < 1.0, (
        f"steps-per-token {spt:.3f} >= 1.0 over the shared-prefix phase "
        f"({steps_shared} steps / {toks_shared} tokens) — speculation/"
        f"prefix reuse bought nothing: {stats}")
    assert stats["prefix_hit_tokens"] > 0, (
        f"no prefill tokens served from the prefix cache: {stats}")
    assert stats["cow_forks"] >= 1, (
        f"no copy-on-write fork exercised: {stats}")
    assert stats["proposed"] > 0 and stats["accepted"] > 0, stats

    assert compile_count() == compiles_warm, (
        f"serve step recompiled mid-run: {compile_count()} vs "
        f"{compiles_warm} at warmup")

    snap = tele.snapshot()
    for metric in ("serve_spec_accept_rate", "serve_tokens_per_step",
                   "serve_prefix_hit_tokens_total",
                   "serve_kv_cow_forks_total"):
        assert metric in snap, f"missing {metric} in telemetry snapshot"

    elapsed = time.time() - t_start
    print(json.dumps({
        "spec_smoke": "ok", "requests": len(prompts),
        "steps_per_token_shared_phase": round(spt, 4),
        "accept_rate": stats["accept_rate"],
        "prefix_hit_tokens": stats["prefix_hit_tokens"],
        "cow_forks": stats["cow_forks"],
        "compiled_widths": widths,
        "elapsed_s": round(elapsed, 1)}))
    assert elapsed < 60, f"smoke took {elapsed:.0f}s (budget 60s)"
    return 0


if __name__ == "__main__":
    sys.exit(main())
