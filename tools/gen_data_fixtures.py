"""Generate the committed real-bytes data fixtures (VERDICT r4 item 8).

Writes tests/fixtures/mnist/ (50-image IDX files, gzip, hand-encoded
with struct — NOT via any framework writer, so reader and fixture can't
share a bug) and tests/fixtures/imgrec/ (a RecordIO .rec/.idx pair of 8
PNG-encoded images, frames hand-packed per the reference's recordio
layout: <magic,u32 len> framing + IRHeader <IffQQ>).

Deterministic (seeded) so regeneration is reproducible byte-for-byte.
"""
from __future__ import annotations

import gzip
import io
import os
import struct

import numpy as onp

ROOT = os.path.join(os.path.dirname(__file__), "..", "tests", "fixtures")
_MAGIC = 0xCED7230A          # recordio frame magic (recordio.py:28)
_IR_FORMAT = "<IfQQ"         # IRHeader flag,label,id,id2


def write_mnist(root):
    os.makedirs(root, exist_ok=True)
    rng = onp.random.RandomState(1234)
    n = 50
    imgs = rng.randint(0, 256, size=(n, 28, 28)).astype(onp.uint8)
    labels = (onp.arange(n) % 10).astype(onp.uint8)
    # IDX3: >u32 magic 0x803, count, rows, cols + raw bytes
    with gzip.GzipFile(os.path.join(root, "train-images-idx3-ubyte.gz"),
                       "wb", mtime=0) as f:
        f.write(struct.pack(">IIII", 0x803, n, 28, 28))
        f.write(imgs.tobytes())
    # IDX1: >u32 magic 0x801, count + raw labels
    with gzip.GzipFile(os.path.join(root, "train-labels-idx1-ubyte.gz"),
                       "wb", mtime=0) as f:
        f.write(struct.pack(">II", 0x801, n))
        f.write(labels.tobytes())
    # t10k copies so train=False also resolves
    for src, dst in [("train-images-idx3-ubyte.gz",
                      "t10k-images-idx3-ubyte.gz"),
                     ("train-labels-idx1-ubyte.gz",
                      "t10k-labels-idx1-ubyte.gz")]:
        with open(os.path.join(root, src), "rb") as fs, \
                open(os.path.join(root, dst), "wb") as fd:
            fd.write(fs.read())
    # golden values for the test to assert real parsing happened
    onp.savez(os.path.join(root, "golden.npz"), imgs=imgs, labels=labels)


def write_imgrec(root):
    from PIL import Image
    os.makedirs(root, exist_ok=True)
    rng = onp.random.RandomState(99)
    n = 8
    rec_path = os.path.join(root, "fixture.rec")
    idx_path = os.path.join(root, "fixture.idx")
    goldens = []
    with open(rec_path, "wb") as rec, open(idx_path, "w") as idxf:
        for i in range(n):
            img = rng.randint(0, 256, size=(12, 16, 3)).astype(onp.uint8)
            goldens.append(img)
            buf = io.BytesIO()
            Image.fromarray(img).save(buf, format="PNG")  # lossless
            payload = struct.pack(_IR_FORMAT, 0, float(i % 4), i, 0) \
                + buf.getvalue()
            pos = rec.tell()
            rec.write(struct.pack("<II", _MAGIC, len(payload)))
            rec.write(payload)
            pad = (-len(payload)) % 4
            rec.write(b"\x00" * pad)
            idxf.write(f"{i}\t{pos}\n")
    onp.savez(os.path.join(root, "golden.npz"),
              imgs=onp.stack(goldens),
              labels=onp.arange(n) % 4)


if __name__ == "__main__":
    write_mnist(os.path.join(ROOT, "mnist"))
    write_imgrec(os.path.join(ROOT, "imgrec"))
    print("fixtures written under", ROOT)
