"""Tracing + performance-attribution smoke (`make trace-smoke`,
docs/observability.md "Tracing & performance attribution").

A short CPU run drives BOTH instrumented subsystems in one process —
3 serve requests through the continuous-batching scheduler and 5 train
steps through `ShardedTrainStep` — then asserts the whole observability
contract:

* the exported Chrome/Perfetto JSON loads and contains a COMPLETE span
  tree per request (queue -> prefill -> decode -> stream under one
  request root, one trace id per request),
* TTFT decomposes (queue/prefill/first-decode child spans + a ttft_ms
  tag on the root),
* train spans carry step ids that match the run journal's
  step_dispatched/step_retired rows (cross-correlation),
* the serve and train tracers share nothing (distinct trace-id spaces),
* the always-on `mfu_estimate` gauge is NONZERO on CPU (projected peak;
  flops from XLA cost_analysis captured at warmup),
* `tools/diagnose.py --trace` renders the timeline without error.

Exits non-zero with a reason on any failure — cheap enough for CI.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

SERVE_REQUESTS = 3
TRAIN_STEPS = 5


def fail(msg: str) -> int:
    print(f"FAIL: {msg}", file=sys.stderr)
    return 1


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import optimizer as opt
    from mxnet_tpu import telemetry, tracing
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from mxnet_tpu.parallel import make_mesh, make_sharded_train_step
    from mxnet_tpu.serve import InferenceEngine, ServeConfig

    workdir = tempfile.mkdtemp(prefix="mxtpu-trace-")
    journal_path = os.path.join(workdir, "journal.jsonl")
    telemetry.enable(journal_path=journal_path)
    tracing.enable(dir=workdir)

    # ---- serve: 3 requests through the scheduler ---------------------
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=1,
                    num_heads=2, intermediate_size=64, max_position=64,
                    dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.initialize()
    model(mx.np.array([[1, 2]], dtype="int32"))
    eng = InferenceEngine(model, ServeConfig(
        max_len=48, max_slots=2, num_pages=13, page_size=8,
        prefill_chunk=4))
    eng.warmup()
    streamed = {}
    handles = [
        eng.submit([1, 2, 3, 4, 5, 6], max_new_tokens=4,
                   on_token=lambda t, r: streamed.setdefault(r.id, [])
                   .append(t))
        for _ in range(SERVE_REQUESTS)]
    eng.run_until_idle()
    for h in handles:
        h.result(timeout=10)

    # ---- train: 5 steps with AOT warmup ------------------------------
    net = nn.Dense(4, in_units=8)
    net.initialize()
    mesh = make_mesh({"dp": 1}, jax.devices()[:1])
    step = make_sharded_train_step(
        net, opt.SGD(learning_rate=1e-2),
        lambda out, x, y: jnp.mean((out - y) ** 2), mesh,
        num_model_args=1)
    rng = onp.random.RandomState(0)
    xs = rng.uniform(-1, 1, (8, 8)).astype("float32")
    ys = rng.uniform(-1, 1, (8, 4)).astype("float32")
    step.warmup(xs, ys)
    for _ in range(TRAIN_STEPS):
        step.dispatch(*step.place_batch(xs, ys))
    step.drain()
    if step.trace_count != 1:
        return fail(f"trace_count {step.trace_count} != 1 — tracing "
                    "must never retrace the step")

    # ---- export + structural asserts ---------------------------------
    trace_path = tracing.export_chrome()
    with open(trace_path) as f:
        doc = json.load(f)              # must be loadable, plain JSON
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    if not spans:
        return fail("exported trace has no complete spans")

    by_req: dict = {}
    for s in spans:
        rid = (s.get("args") or {}).get("request_id")
        if rid is not None:
            by_req.setdefault(rid, []).append(s)
    if len([r for r in by_req.values()
            if any(s["name"] == "serve.request" for s in r)]) \
            != SERVE_REQUESTS:
        return fail(f"expected {SERVE_REQUESTS} serve.request roots, "
                    f"request ids seen: {sorted(by_req)}")
    for rid, ss in by_req.items():
        names = {s["name"] for s in ss}
        need = {"serve.request", "serve.queue", "serve.stream"}
        if not need <= names:
            return fail(f"request {rid} span tree incomplete: {names}")
        if not ({"serve.prefill_chunk", "serve.first_decode"} & names):
            return fail(f"request {rid} has no prefill span: {names}")
        if "serve.decode" not in names:
            return fail(f"request {rid} has no decode span: {names}")
        root = next(s for s in ss if s["name"] == "serve.request")
        args = root["args"]
        if args.get("state") != "finished":
            return fail(f"request {rid} root state {args.get('state')}")
        if not isinstance(args.get("ttft_ms"), (int, float)):
            return fail(f"request {rid} ttft_ms missing on root: {args}")
        # one trace id per request; every child hangs off the root tree
        tids = {s["args"]["trace_id"] for s in ss}
        if len(tids) != 1:
            return fail(f"request {rid} spans span {len(tids)} trace "
                        f"ids: {tids}")
        root_id = root["args"]["span_id"]
        children = [s for s in ss if s is not root]
        if not all(s["args"].get("parent_id") == root_id
                   for s in children):
            return fail(f"request {rid}: child spans not parented to "
                        "the request root")

    # no cross-contamination between the two tracers' id spaces
    serve_tids = {s["args"]["trace_id"] for s in spans
                  if s["cat"] == "serve"}
    train_tids = {s["args"]["trace_id"] for s in spans
                  if s["cat"] == "train"}
    if not serve_tids or not train_tids:
        return fail(f"missing a tracer: serve={len(serve_tids)} "
                    f"train={len(train_tids)} trace ids")
    if serve_tids & train_tids:
        return fail(f"serve/train trace ids overlap: "
                    f"{serve_tids & train_tids}")

    # train spans <-> journal step-id correlation
    dev_steps = sorted(s["args"]["step"] for s in spans
                       if s["name"] == "train.device")
    if dev_steps != list(range(1, TRAIN_STEPS + 1)):
        return fail(f"train.device steps {dev_steps} != "
                    f"{list(range(1, TRAIN_STEPS + 1))}")
    rows = telemetry.RunJournal.read(journal_path)
    retired = sorted(r["step"] for r in rows
                     if r["event"] == "step_retired")
    if retired != dev_steps:
        return fail(f"journal step_retired ids {retired} != train span "
                    f"steps {dev_steps}")
    costed = [r for r in rows if r["event"] == "step_retired"
              and isinstance(r.get("cost"), dict)]
    if not costed:
        return fail("no step_retired row carries the cost-feature "
                    "vector")
    if not costed[0]["cost"].get("flops"):
        return fail(f"cost vector has no flops: {costed[0]['cost']}")

    # always-on MFU gauge: nonzero on CPU (projected peak)
    g = telemetry.registry().get("mfu_estimate")
    if g is None:
        return fail("mfu_estimate gauge was never set")
    mfu = g.value(program="train_step")
    if not mfu > 0:
        return fail(f"mfu_estimate{{program=train_step}} = {mfu}, want "
                    "> 0 (CPU projected-peak proxy)")

    # diagnose renders the timeline
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "diagnose.py"),
         "--trace", trace_path], capture_output=True, text=True,
        timeout=60)
    if proc.returncode != 0 or "critical path" not in proc.stdout:
        return fail(f"diagnose --trace failed rc={proc.returncode}: "
                    f"{proc.stderr[-500:]}\n{proc.stdout[-500:]}")

    telemetry.disable()
    tracing.disable()
    print(f"trace smoke OK: {len(spans)} spans "
          f"({len(serve_tids)} serve / {len(train_tids)} train traces), "
          f"mfu_estimate {mfu:.3g} (projected), {trace_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
