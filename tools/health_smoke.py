"""Training-health smoke check (`make health-smoke`, docs/observability.md).

End-to-end proof of the health subsystem over the ENV wiring a production
run would use: the parent process arms ``MXTPU_HEALTH=1``, a crash dir, a
journal path, and a NaN injection via the existing ``MXTPU_FAULT_SPEC``
registry, then runs a 12-step CPU training loop in a child process that
ends in a forced crash.  It asserts:

1. the numerics probes counted the injected non-finite gradients
   (``health_nonfinite_total`` > 0 in the bundle's metrics snapshot),
2. an ``anomaly`` journal event carries the exact step the NaN entered,
3. the forced crash left a flight-recorder bundle in ``MXTPU_CRASH_DIR``
   holding >= 32 journal events plus the telemetry snapshot,
4. the probe branch kept the step at one trace (``trace_count == 1``).

Pure stdlib on the parent side; exits non-zero with a reason on failure.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STEPS = 12
NAN_AT = 5          # fault hit N fires on loop iteration N-1 = step id N


def _child() -> int:
    """The instrumented training run. Everything is armed through the
    environment (set by the parent) before mxnet_tpu imports."""
    import jax
    import jax.numpy as jnp
    import numpy as onp

    import mxnet_tpu as mx  # noqa: F401 — auto-enables telemetry + health
    from mxnet_tpu import health, optimizer as opt, telemetry
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import make_mesh, make_sharded_train_step
    from mxnet_tpu.resilience import FaultInjected, fault_point

    assert telemetry.enabled(), "MXTPU_TELEMETRY env wiring broken"
    assert health.enabled(), "MXTPU_HEALTH env wiring broken"

    net = nn.Dense(4, in_units=8)
    net.initialize()
    mesh = make_mesh({"dp": 1}, jax.devices()[:1])
    step = make_sharded_train_step(
        net, opt.SGD(learning_rate=1e-2),
        lambda out, x, y: jnp.mean((out - y) ** 2), mesh, num_model_args=1)
    rng = onp.random.RandomState(0)
    xs = rng.uniform(-1, 1, (8, 8)).astype("float32")
    ys = rng.uniform(-1, 1, (8, 4)).astype("float32")

    handle = None
    for _ in range(STEPS):
        x = xs
        try:
            # the injection *timing* comes from the armed MXTPU_FAULT_SPEC
            # registry (nan_batch@N); the payload is a poisoned batch —
            # exactly how a bad record or a corrupt H2D shows up for real
            fault_point("nan_batch")
        except FaultInjected:
            x = xs * float("nan")
        handle = step.dispatch(x, ys)
    jax.device_get(handle.loss)
    step.steps_in_flight()   # retire stragglers → health monitor observes

    assert step.trace_count == 1, \
        f"probes caused retrace: trace_count={step.trace_count}"
    mon = health.monitor()
    assert mon is not None and mon.anomalies, "no anomalies recorded"
    raise RuntimeError("health-smoke forced crash (expected)")


def main() -> int:
    if "--child" in sys.argv:
        return _child()

    workdir = tempfile.mkdtemp(prefix="mxtpu-health-smoke-")
    crash_dir = os.path.join(workdir, "crash")
    journal = os.path.join(workdir, "journal.jsonl")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "MXTPU_TELEMETRY": journal,
        "MXTPU_HEALTH": "1",
        "MXTPU_CRASH_DIR": crash_dir,
        "MXTPU_FAULT_SPEC": f"nan_batch@{NAN_AT}",
    })
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child"],
        env=env, capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    if proc.returncode == 0:
        print("FAIL: child was expected to crash but exited 0",
              file=sys.stderr)
        print(proc.stdout + proc.stderr, file=sys.stderr)
        return 1
    if "health-smoke forced crash" not in proc.stderr:
        print(f"FAIL: child died for the wrong reason (rc="
              f"{proc.returncode}):\n{proc.stderr[-3000:]}", file=sys.stderr)
        return 1

    # (b) anomaly journal event with the exact offending step id
    rows = []
    with open(journal) as f:
        for line in f:
            if line.strip():
                rows.append(json.loads(line))
    anomalies = [r for r in rows if r["event"] == "anomaly"]
    if not anomalies:
        print("FAIL: no anomaly journal event", file=sys.stderr)
        return 1
    if anomalies[0]["step"] != NAN_AT or \
            anomalies[0]["rule"] != "nonfinite_grads":
        print(f"FAIL: first anomaly should be nonfinite_grads at step "
              f"{NAN_AT}, got {anomalies[0]}", file=sys.stderr)
        return 1

    # (c) crash bundle with >= 32 events + metrics snapshot
    bundles = [os.path.join(crash_dir, f) for f in os.listdir(crash_dir)
               if f.startswith("crash_")] if os.path.isdir(crash_dir) else []
    if not bundles:
        print(f"FAIL: no crash bundle in {crash_dir}", file=sys.stderr)
        return 1
    with open(sorted(bundles)[0]) as f:
        bundle = json.load(f)
    if bundle.get("reason") != "exception":
        print(f"FAIL: bundle reason {bundle.get('reason')!r} != 'exception'",
              file=sys.stderr)
        return 1
    if len(bundle.get("events", [])) < 32:
        print(f"FAIL: bundle holds {len(bundle.get('events', []))} events, "
              f"want >= 32", file=sys.stderr)
        return 1
    if "metrics" not in bundle:
        print("FAIL: bundle carries no telemetry snapshot", file=sys.stderr)
        return 1

    # (a) the nonfinite counter actually incremented
    nonf = bundle["metrics"].get("health_nonfinite_total", {})
    total = sum(s.get("value", 0) for s in nonf.get("series", []))
    if total < 1:
        print(f"FAIL: health_nonfinite_total == {total}, want >= 1",
              file=sys.stderr)
        return 1

    print(f"health smoke OK: {len(anomalies)} anomalies (first at step "
          f"{anomalies[0]['step']}), bundle {sorted(bundles)[0]} with "
          f"{len(bundle['events'])} events, nonfinite={int(total)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
