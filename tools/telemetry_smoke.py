"""Telemetry smoke check (`make telemetry-smoke`, docs/observability.md).

Runs a 5-step CPU training loop with the metrics registry + run journal
enabled, then validates the Prometheus text exposition with a pure-stdlib
parser and cross-checks the journal. Exits non-zero (with a reason) on any
failure — cheap enough for CI.
"""
from __future__ import annotations

import json
import os
import re
import sys
import tempfile

# must happen before any jax backend initialisation
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STEPS = 5

# Prometheus text exposition 0.0.4, the subset the registry emits:
#   # HELP name text            # TYPE name kind
#   name{label="v",...} value   (labels optional; value int/float)
_COMMENT = re.compile(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* ?.*$")
_SAMPLE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\""
    r"(?:,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\")*)\})?"
    r" (?P<value>[0-9.eE+-]+|NaN|\+Inf|-Inf)$")


def parse_prometheus(text: str) -> dict:
    """Parse an exposition into {metric_name: [(labels_dict, float)]}.
    Raises ValueError on the first malformed line."""
    out: dict = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            if not _COMMENT.match(line):
                raise ValueError(f"line {lineno}: bad comment {line!r}")
            continue
        m = _SAMPLE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: bad sample {line!r}")
        labels = {}
        if m.group("labels"):
            for part in re.findall(
                    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"',
                    m.group("labels")):
                labels[part[0]] = part[1]
        out.setdefault(m.group("name"), []).append(
            (labels, float(m.group("value"))))
    return out


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as onp

    import mxnet_tpu as mx  # noqa: F401 — registers the CPU pin
    from mxnet_tpu import optimizer as opt
    from mxnet_tpu import telemetry
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import make_mesh, make_sharded_train_step

    journal_path = os.path.join(tempfile.mkdtemp(prefix="mxtpu-tele-"),
                                "smoke_journal.jsonl")
    telemetry.enable(journal_path=journal_path)

    net = nn.Dense(4, in_units=8)
    net.initialize()
    mesh = make_mesh({"dp": 1}, jax.devices()[:1])
    step = make_sharded_train_step(
        net, opt.SGD(learning_rate=1e-2),
        lambda out, x, y: jnp.mean((out - y) ** 2), mesh, num_model_args=1)
    rng = onp.random.RandomState(0)
    xs = rng.uniform(-1, 1, (8, 8)).astype("float32")
    ys = rng.uniform(-1, 1, (8, 4)).astype("float32")
    step.warmup(xs, ys)
    for _ in range(STEPS):
        step.dispatch(*step.place_batch(xs, ys))
    telemetry.memory_monitor() or telemetry.MemoryMonitor().sample_once()

    text = telemetry.to_prometheus()
    parsed = parse_prometheus(text)          # raises on malformed output
    json.loads(telemetry.to_json())          # JSON export parses too

    count = next((v for lb, v in parsed.get("step_dispatch_ms_count", [])
                  if not lb), 0)
    if count != STEPS:
        print(f"FAIL: step_dispatch_ms_count == {count}, want {STEPS}",
              file=sys.stderr)
        return 1
    if "steps_in_flight" not in parsed:
        print("FAIL: steps_in_flight gauge missing", file=sys.stderr)
        return 1

    rows = telemetry.RunJournal.read(journal_path)
    steps = [r["step"] for r in rows if r["event"] == "step_dispatched"]
    if steps != sorted(set(steps)) or len(steps) != STEPS:
        print(f"FAIL: journal step ids not strictly monotonic: {steps}",
              file=sys.stderr)
        return 1
    if not any(r["event"].startswith("compile") for r in rows):
        print("FAIL: journal has no compile event", file=sys.stderr)
        return 1

    telemetry.disable()
    print(f"telemetry smoke OK: {len(text.splitlines())} exposition lines, "
          f"{len(parsed)} series families, {len(rows)} journal rows "
          f"({journal_path})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
