#!/usr/bin/env python
"""Per-tenant QoS smoke (`make qos-smoke`, wired into `make test`): the
noisy-neighbor containment contract on CPU in under a minute.

1. solo baseline: a 2-replica fleet serves the PROTECTED tenant's
   requests alone; every greedy stream digest is recorded,
2. noisy-neighbor run: a fresh fleet with a QoS plane (protected tenant
   ``interactive``/weight 8, noisy tenant ``best_effort`` behind a tight
   request-rate quota + 1-slot bulkhead) serves the SAME protected
   requests while the noisy tenant floods the router,
3. asserts: every protected stream is bit-identical to its solo digest,
   the protected tenant's shed rate is exactly 0, the noisy tenant
   absorbs 100% of the sheds, shed journal rows carry tenant + reason,
   and the per-tenant QoS stats/gauges exist.

Everything asserted here is the docs/serving.md "Per-tenant QoS"
contract; a failure means a noisy neighbor can corrupt or starve a
protected tenant's streams.
"""
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

t_start = time.time()
workdir = tempfile.mkdtemp(prefix="mxtpu_qos_smoke_")

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["MXTPU_TRAFFIC_JOURNAL"] = os.path.join(workdir,
                                                   "traffic.jsonl")
QOS_SPEC = {
    "default": {"priority": "batch"},
    "tenants": {
        "prot": {"priority": "interactive", "weight": 8.0},
        "noisy": {"priority": "best_effort", "weight": 1.0,
                  "rps": 4.0, "burst_s": 1.0, "max_slots": 1}},
    "breaker": {"offenses": 0}}

import mxnet_tpu as mx                                    # noqa: E402
from mxnet_tpu import telemetry as tele                   # noqa: E402
from mxnet_tpu.models.gpt import GPTConfig, GPTForCausalLM  # noqa: E402
from mxnet_tpu.serve import ServeConfig, ServeFleet       # noqa: E402
from mxnet_tpu.serve.qos import QoSConfig                 # noqa: E402
from mxnet_tpu.serve.router import ShedError              # noqa: E402
from mxnet_tpu.serve.traffic import (TrafficJournal,      # noqa: E402
                                     stream_digest)

tele.enable(journal_path=os.path.join(workdir, "telemetry.jsonl"))

model = GPTForCausalLM(GPTConfig(
    vocab_size=96, hidden_size=32, num_layers=1, num_heads=4,
    intermediate_size=64, max_position=64, dropout=0.0))
model.initialize()
model(mx.np.array([[1, 2]], dtype="int32"))

SERVE = dict(config=ServeConfig(max_slots=2, page_size=4, num_pages=0,
                                prefill_chunk=4, max_len=32),
             stall_timeout=10.0, supervise_interval=0.05)
PROT = [([3 + i, 7, 11 + i], 6) for i in range(8)]   # (prompt, max_new)
NOISY = [([2 + (i % 5), 9], 8) for i in range(40)]

# -- 1. solo baseline -------------------------------------------------------
solo = {}
with ServeFleet(model, replicas=2, **SERVE) as fleet:
    handles = [fleet.submit(p, max_new_tokens=n, tenant="prot")
               for p, n in PROT]
    for i, h in enumerate(handles):
        solo[i] = stream_digest(h.result(timeout=60))
print(f"[1/3] solo baseline: {len(solo)} protected streams recorded")

# -- 2. noisy-neighbor run under QoS ---------------------------------------
sheds = {"prot": 0, "noisy": 0}
with ServeFleet(model, replicas=2,
                qos_config=QoSConfig.from_spec(QOS_SPEC),
                **SERVE) as fleet:
    prot_handles = []
    noisy_handles = []
    it_noisy = iter(NOISY)
    for i, (p, n) in enumerate(PROT):
        # 5 noisy floods between every protected arrival — the abusive
        # interleave the quota + WFQ must absorb
        for _ in range(5):
            np_, nn = next(it_noisy)
            try:
                noisy_handles.append(
                    fleet.submit(np_, max_new_tokens=nn,
                                 tenant="noisy"))
            except ShedError:
                sheds["noisy"] += 1
        try:
            prot_handles.append(
                (i, fleet.submit(p, max_new_tokens=n, tenant="prot")))
        except ShedError:
            sheds["prot"] += 1
    mismatched = []
    for i, h in prot_handles:
        got = stream_digest(h.result(timeout=60))
        if got != solo[i]:
            mismatched.append(i)
    # noisy survivors may still finish/expire; don't block on them
    qstats = fleet.stats()["qos"]
snap = tele.registry().snapshot()

# -- 3. the containment contract -------------------------------------------
assert sheds["prot"] == 0, \
    f"protected tenant was shed {sheds['prot']} time(s)"
assert len(prot_handles) == len(PROT), "protected submissions lost"
assert not mismatched, \
    f"protected streams diverged from solo digests: {mismatched}"
assert sheds["noisy"] >= 10, \
    f"quota never bit: only {sheds['noisy']} noisy sheds"
pt = qstats["tenants"]
assert pt["prot"]["sheds"] == {}, pt["prot"]
assert sum(pt["noisy"]["sheds"].values()) == sheds["noisy"], pt["noisy"]
assert pt["noisy"]["sheds"].get("quota", 0) > 0, pt["noisy"]
assert "serve_tenant_sheds_total" in snap, sorted(snap)
assert "serve_tenant_quota_fill" in snap, sorted(snap)
wfq = snap.get("serve_tenant_wfq_share", {}).get("series", [])
assert any(s["labels"].get("tenant") == "prot" for s in wfq), wfq

# journal shed rows carry tenant + reason (the satellite-1 contract)
rows = TrafficJournal.read(os.environ["MXTPU_TRAFFIC_JOURNAL"])
shed_rows = [r for r in rows if r.get("state") == "shed"]
assert shed_rows and all(r.get("tenant") == "noisy" and
                         r.get("shed_reason") for r in shed_rows), \
    shed_rows[:3]
print(f"[2/3] noisy neighbor contained: {sheds['noisy']} noisy sheds "
      f"({pt['noisy']['sheds']}), 0 protected sheds")
print(f"[3/3] {len(PROT)} protected streams bit-identical to solo; "
      f"shed rows tenant-tagged")

elapsed = time.time() - t_start
print(json.dumps({
    "protected": len(PROT), "noisy_submitted": len(NOISY),
    "noisy_sheds": sheds["noisy"], "protected_sheds": sheds["prot"],
    "noisy_shed_reasons": pt["noisy"]["sheds"],
    "elapsed_s": round(elapsed, 1)}))
assert elapsed < 90, f"qos smoke exceeded budget: {elapsed:.1f}s"
print("QOS SMOKE PASS")
