#!/usr/bin/env python
"""Environment diagnosis (parity: `tools/diagnose.py`): platform, python,
framework features, device inventory, key environment variables.

Also pretty-prints crash flight-recorder bundles (docs/observability.md,
"Training health & post-mortems") and recovery timelines
(docs/resilience.md, "Recovery policies & preemption"):

    python tools/diagnose.py --bundle <crash_*.json>
    python tools/diagnose.py --crash-dir <dir>     # newest bundle in dir
    python tools/diagnose.py --journal <run.jsonl> # remediation timeline
                                                   # + rollback lineage
    python tools/diagnose.py --trace <trace.json>  # span timeline +
                                                   # critical-path summary
                                                   # + per-replica fleet
                                                   # rollup (served /
                                                   # failovers / shed /
                                                   # p99 TTFT)
    python tools/diagnose.py --capsule <dir>       # incident capsule:
                                                   # burn state, topology,
                                                   # traffic window
    python tools/diagnose.py --capsule <dir> --replay \
        [--speed X] [--kill-at T] [--transport thread|process] \
        [--replicas N]              # re-drive the capsule window and
                                    # print the divergence report
                                    # (rc 0 iff bit-identical)
    python tools/diagnose.py --tenants <path>      # per-tenant QoS
                                                   # table (admits, sheds
                                                   # by reason, quota
                                                   # fill, WFQ share,
                                                   # breaker + SLO burn
                                                   # state) from a
                                                   # metrics-snapshot
                                                   # JSON, a fleet
                                                   # stats() dump, or an
                                                   # incident capsule dir
    python tools/diagnose.py --trace <dir-or-files...> \
        [--merged-out merged.json]  # merge per-process trace_<pid>.json
                                    # exports into ONE Perfetto doc:
                                    # tids are remapped per source file,
                                    # every pid gets a process_name row
                                    # (replica name when the parent
                                    # registered one, else the source
                                    # file), and the request table is
                                    # computed over the union
"""
from __future__ import annotations

import glob
import json
import os
import platform
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _fmt_ts(ts):
    import time
    try:
        return time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime(ts))
    except (TypeError, ValueError, OverflowError):
        return str(ts)


def print_bundle(path: str) -> int:
    """Human-readable view of one flight-recorder bundle."""
    try:
        with open(path) as f:
            b = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"cannot read bundle {path}: {e}", file=sys.stderr)
        return 1
    print(f"========== crash bundle: {path} ==========")
    print(f"reason    : {b.get('reason')}")
    print(f"time      : {_fmt_ts(b.get('time'))}")
    print(f"pid       : {b.get('pid')}   last step: {b.get('last_step')}")
    if b.get("argv"):
        print(f"argv      : {' '.join(b['argv'])}")
    exc = b.get("exception")
    if exc:
        print(f"exception : {exc.get('type')}: {exc.get('message')}")
    hb = b.get("heartbeats") or {}
    if hb:
        print("---------- heartbeat ages (s) ----------")
        for name, age in sorted(hb.items()):
            print(f"  {name:<24} {age}")
    for src in b.get("steps_in_flight") or []:
        print(f"in flight : {src.get('count')} step(s) from "
              f"{src.get('source')}: {src.get('ids')}")
    anomalies = b.get("anomalies") or []
    if anomalies:
        print(f"---------- anomalies ({len(anomalies)}) ----------")
        for a in anomalies[-20:]:
            extra = {k: v for k, v in a.items()
                     if k not in ("rule", "step", "time")}
            print(f"  step {a.get('step')}: {a.get('rule')} {extra}")
    remediations = [ev for ev in b.get("events") or []
                    if ev.get("event") == "remediation"]
    if remediations:
        print(f"---------- remediation ladder ({len(remediations)}) "
              f"----------")
        for ev in remediations[-20:]:
            print("  " + _fmt_remediation(ev))
    events = b.get("events") or []
    print(f"---------- last events ({len(events)} in ring) ----------")
    for ev in events[-30:]:
        extra = {k: v for k, v in ev.items()
                 if k not in ("event", "step", "ts", "seq")}
        print(f"  step {str(ev.get('step')):>6}  {ev.get('event'):<20} "
              f"{extra if extra else ''}")
    metrics = b.get("metrics") or {}
    health_metrics = {k: v for k, v in metrics.items()
                      if k.startswith(("health_", "steps_in_flight",
                                       "trace_count"))}
    if health_metrics:
        print("---------- health metrics ----------")
        for name, m in sorted(health_metrics.items()):
            for s in m.get("series", []):
                val = s.get("value", s.get("count"))
                lbl = s.get("labels") or ""
                print(f"  {name}{lbl} = {val}")
    if exc and exc.get("traceback"):
        print("---------- traceback ----------")
        print(exc["traceback"].rstrip())
    if b.get("stacks"):
        print("---------- all-thread stacks (tail) ----------")
        print(b["stacks"][-4000:].rstrip())
    return 0


def _fmt_remediation(ev: dict) -> str:
    """One remediation journal event as a human-readable ladder line."""
    kind = ev.get("kind")
    step = str(ev.get("step"))  # None-safe: a partial preempt_save from a
    #                             checkpoint-less run carries step=null
    if kind == "skip":
        scale = ev.get("loss_scale")
        return (f"step {step:>6}  tier-1 SKIP     update dropped "
                f"({ev.get('rule')})"
                + (f", loss scale -> {scale:g}" if scale else ""))
    if kind == "rollback":
        return (f"step {step:>6}  tier-2 ROLLBACK {ev.get('from_step')} -> "
                f"{ev.get('restored_step')} ({ev.get('reason')}); poison "
                f"steps {ev.get('poison')}, discarded ckpts "
                f"{ev.get('discarded')}")
    if kind == "data_skip":
        return f"step {step:>6}  tier-2 replay   poison batch skipped"
    if kind == "exit":
        return (f"step {step:>6}  tier-3 EXIT     {ev.get('reason')}; "
                f"bundle {ev.get('bundle')}")
    if kind == "preempt_save":
        state = "complete" if ev.get("complete") else \
            "PARTIAL (marker only)"
        return (f"step {step:>6}  preemption      emergency save {state} "
                f"-> {ev.get('checkpoint')} in {ev.get('elapsed_s')}s")
    if kind == "preempt_resume":
        return (f"step {step:>6}  preemption      resumed from emergency "
                f"checkpoint {ev.get('checkpoint')}")
    extra = {k: v for k, v in ev.items()
             if k not in ("event", "kind", "step", "ts", "seq")}
    return f"step {step:>6}  {kind:<15} {extra}"


def print_journal(path: str) -> int:
    """Remediation timeline + rollback lineage from a run journal."""
    rows = []
    try:
        with open(path) as f:
            for line in f:
                if line.strip():
                    rows.append(json.loads(line))
    except (OSError, json.JSONDecodeError) as e:
        print(f"cannot read journal {path}: {e}", file=sys.stderr)
        return 1
    rem = [r for r in rows if r.get("event") == "remediation"]
    anomalies = [r for r in rows if r.get("event") == "anomaly"]
    print(f"========== run journal: {path} ==========")
    print(f"events    : {len(rows)} total, {len(anomalies)} anomalies, "
          f"{len(rem)} remediation")
    if rem:
        print("---------- remediation timeline ----------")
        for ev in rem:
            print("  " + _fmt_remediation(ev))
    rollbacks = [r for r in rem if r.get("kind") == "rollback"]
    if rollbacks:
        print("---------- rollback lineage ----------")
        # each rollback forks the run: show the abandoned span and what
        # the replay continued from
        for i, rb in enumerate(rollbacks):
            print(f"  [{i}] timeline abandoned at step "
                  f"{rb.get('from_step')} ({rb.get('reason')}): resumed "
                  f"from healthy checkpoint step {rb.get('restored_step')}"
                  + (f"; discarded diverged checkpoint(s) at steps "
                     f"{rb.get('discarded')}" if rb.get("discarded")
                     else ""))
    discards = [r for r in rows if r.get("event") == "checkpoint_discard"]
    for d in discards:
        print(f"  checkpoint step {d.get('step')} sidelined "
              f"(*.rolledback) after rollback to {d.get('rolled_back_to')}")
    if not rem and not anomalies:
        print("no anomalies or remediation recorded — a healthy run")
    return 0


def _pctl(sorted_vals, p):
    if not sorted_vals:
        return None
    import math
    # ceiling nearest-rank: p99 of a small population is its max, not
    # the second-to-last sample
    return sorted_vals[min(len(sorted_vals) - 1,
                           math.ceil(p * (len(sorted_vals) - 1)))]


def _load_trace(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):        # bare event array (older exports)
        return {"traceEvents": doc, "otherData": {}}
    return doc


def merge_traces(paths, out=None) -> dict:
    """Merge per-process `export_chrome` files into one Perfetto doc.

    Each source keeps its events under its original pids, but tids are
    remapped per (file, pid, tid) — two processes both counting tids
    from 1 would otherwise fold distinct request tracks onto one
    thread row.  Every pid ends up with exactly one `process_name`
    metadata row: the name a source already carries (the parent's
    export names replicas via `tracing.note_remote_process`) wins;
    unnamed pids fall back to the source file's basename."""
    import itertools
    events = []
    named: dict = {}                 # pid -> process name (first wins)
    file_info = []                   # (path, pids_seen, otherData)
    tid_map: dict = {}
    next_tid = itertools.count(1)
    for path in paths:
        doc = _load_trace(path)
        pids = set()
        for e in doc.get("traceEvents", []):
            if e.get("ph") == "M" and e.get("name") == "process_name":
                name = (e.get("args") or {}).get("name")
                if e.get("pid") is not None and name:
                    named.setdefault(e["pid"], name)
                continue             # re-emitted unified below
            e = dict(e)
            pid = e.get("pid")
            if pid is not None:
                pids.add(pid)
            if e.get("tid") is not None:
                key = (path, pid, e["tid"])
                if key not in tid_map:
                    tid_map[key] = next(next_tid)
                e["tid"] = tid_map[key]
            events.append(e)
        file_info.append((path, pids, doc.get("otherData") or {}))
    for path, pids, other in file_info:
        label = os.path.splitext(os.path.basename(path))[0]
        for pid in sorted(pids):
            if pid not in named:
                named[pid] = label if pid == other.get("pid") \
                    else f"{label} pid {pid}"
    events += [{"name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": name}}
               for pid, name in sorted(named.items())]
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "otherData": {"exporter": "tools/diagnose.py merge",
                         "sources": [p for p, _, _ in file_info]}}
    if out:
        d = os.path.dirname(os.path.abspath(out))
        if d:
            os.makedirs(d, exist_ok=True)
        with open(out, "w") as f:
            json.dump(doc, f)
    return doc


def print_trace(paths, merged_out=None) -> int:
    """Per-request / per-step timeline + critical-path summary from a
    Chrome trace exported by `mx.tracing.export_chrome` (docs/
    observability.md, "Tracing & performance attribution").  `paths`
    may be one file, several, or a directory of `trace_*.json` — more
    than one source is merged (see `merge_traces`); `merged_out`
    additionally writes the merged doc as a Perfetto-loadable file."""
    if isinstance(paths, str):
        paths = [paths]
    expanded = []
    for p in paths:
        if os.path.isdir(p):
            found = sorted(glob.glob(os.path.join(p, "trace_*.json")))
            if not found:
                print(f"no trace_*.json in {p}", file=sys.stderr)
                return 1
            expanded.extend(found)
        else:
            expanded.append(p)
    try:
        if len(expanded) == 1 and merged_out is None:
            doc = _load_trace(expanded[0])
        else:
            doc = merge_traces(expanded, out=merged_out)
    except (OSError, json.JSONDecodeError) as e:
        print(f"cannot read trace: {e}", file=sys.stderr)
        return 1
    label = expanded[0] if len(expanded) == 1 \
        else f"{len(expanded)} files merged"
    spans = [e for e in doc.get("traceEvents", [])
             if e.get("ph") == "X"]
    print(f"========== trace: {label} ==========")
    if len(expanded) > 1:
        for p in expanded:
            print(f"  source  : {p}")
        if merged_out:
            print(f"  merged  : {merged_out}")
        procs = {e["pid"]: (e.get("args") or {}).get("name")
                 for e in doc.get("traceEvents", [])
                 if e.get("ph") == "M"
                 and e.get("name") == "process_name"}
        for pid, name in sorted(procs.items()):
            print(f"  process : {pid:>7}  {name}")
    print(f"spans     : {len(spans)}")
    if not spans:
        return 0

    # ---- serve: one line per request, TTFT decomposed ---------------
    by_req: dict = {}
    for s in spans:
        rid = (s.get("args") or {}).get("request_id")
        if rid is not None:
            by_req.setdefault(rid, []).append(s)
    reqs = {rid: ss for rid, ss in by_req.items()
            if any(s["name"] == "serve.request" for s in ss)}
    if reqs:
        print(f"---------- serve requests ({len(reqs)}) ----------")
        print(f"  {'req':>5} {'state':<10} {'queue':>9} {'prefill':>9} "
              f"{'1st dec':>9} {'decode':>9} {'wire':>9} {'handoff':>9} "
              f"{'ttft':>9} {'total':>9}  (ms)")
        rows = []
        for rid in sorted(reqs):
            ss = reqs[rid]

            def total(name):
                return sum(s["dur"] for s in ss
                           if s["name"] == name) / 1e3

            root = next(s for s in ss if s["name"] == "serve.request")
            args = root.get("args") or {}
            q, pf, fd = (total("serve.queue"),
                         total("serve.prefill_chunk"),
                         total("serve.first_decode"))
            # process transport: submit/cancel RPC wall (serve.rpc
            # spans tagged with the rid) — TTFT spent on the wire, not
            # in the worker; disaggregation adds the KV-page handoff
            # (export + import + submit_prefilled, one span per move)
            wire = total("serve.rpc")
            handoff = total("serve.handoff")
            ttft = args.get("ttft_ms")
            if ttft is None:
                ttft = q + pf + fd
            rows.append({"rid": rid, "queue": q, "prefill": pf,
                         "first_decode": fd, "wire": wire,
                         "handoff": handoff, "ttft": float(ttft)})
            print(f"  {rid:>5} {str(args.get('state')):<10} {q:>9.2f} "
                  f"{pf:>9.2f} {fd:>9.2f} "
                  f"{total('serve.decode'):>9.2f} {wire:>9.2f} "
                  f"{handoff:>9.2f} {float(ttft):>9.2f} "
                  f"{root['dur'] / 1e3:>9.2f}")
        # critical path at the tail: which phase owns the p99 TTFT
        ordered = sorted(rows, key=lambda r: r["ttft"])
        ttfts = [r["ttft"] for r in ordered]
        p50, p99 = _pctl(ttfts, 0.50), _pctl(ttfts, 0.99)
        import math as _math
        worst = ordered[min(len(ordered) - 1,
                            _math.ceil(0.99 * (len(ordered) - 1)))]
        denom = max(worst["ttft"], 1e-9)
        print(f"  TTFT p50 = {p50:.2f} ms, p99 = {p99:.2f} ms")
        wire_pct = (f", {100 * worst['wire'] / denom:.0f}% wire"
                    if worst.get("wire") else "")
        handoff_pct = (f", {100 * worst['handoff'] / denom:.0f}% "
                       f"handoff" if worst.get("handoff") else "")
        print(f"  critical path @p99 (req {worst['rid']}): "
              f"{100 * worst['queue'] / denom:.0f}% queue wait, "
              f"{100 * worst['prefill'] / denom:.0f}% prefill, "
              f"{100 * worst['first_decode'] / denom:.0f}% first decode"
              f"{wire_pct}{handoff_pct}")
        # decode fast path (docs/serving.md "Speculative decoding &
        # prefix caching"): serve.step spans carry per-step draft/
        # accept/prefix-hit tags
        step_spans = [s for s in spans if s["name"] == "serve.step"]
        drafted = sum((s.get("args") or {}).get("drafted") or 0
                      for s in step_spans)
        emitted = sum((s.get("args") or {}).get("emitted") or 0
                      for s in step_spans)
        pfx = sum((s.get("args") or {}).get("prefix_hit") or 0
                  for s in step_spans)
        if drafted or pfx:
            accepted = sum((s.get("args") or {}).get("accepted") or 0
                           for s in step_spans)
            rate = f"{accepted / drafted:.2f}" if drafted else "-"
            tps = (f"{emitted / len(step_spans):.2f}"
                   if step_spans else "-")
            print(f"  speculation: accept rate {rate} "
                  f"({accepted}/{drafted} drafts), "
                  f"{tps} tokens/step; prefix cache: {pfx} prompt "
                  f"tokens served without prefill")

    # ---- fleet: per-replica rollup (docs/serving.md) ----------------
    # spans carry a `replica` tag when the scheduler belongs to a
    # ServeFleet; serve.route / serve.failover / serve.shed come from
    # the router
    replica_spans = [s for s in spans
                     if (s.get("args") or {}).get("replica") is not None]
    fleet_sheds = [s for s in spans if s["name"] == "serve.shed"]
    if replica_spans or fleet_sheds:
        rollup: dict = {}

        def rep_row(name):
            return rollup.setdefault(name, {
                "served": set(), "fo_in": 0, "fo_out": 0, "ttfts": [],
                "drafted": 0, "accepted": 0, "prefix_hit": 0,
                "transport": None, "pid": None, "gen": 0,
                "rpc": 0, "rpc_retries": 0, "rpc_bytes": 0})

        for s in spans:
            args = s.get("args") or {}
            rep = args.get("replica")
            if s["name"] == "serve.route" and rep is not None:
                if args.get("failover"):
                    rep_row(rep)["fo_in"] += 1
            elif s["name"] == "serve.failover" and rep is not None:
                rep_row(rep)["fo_out"] += args.get("requests", 0)
            elif s["name"] == "serve.step" and rep is not None:
                row = rep_row(rep)
                row["drafted"] += args.get("drafted") or 0
                row["accepted"] += args.get("accepted") or 0
                row["prefix_hit"] += args.get("prefix_hit") or 0
            elif s["name"] == "serve.replica" and rep is not None:
                # lifecycle span per spawn/respawn: the highest
                # generation seen IS the respawn count for that name
                row = rep_row(rep)
                row["transport"] = args.get("transport",
                                            row["transport"])
                row["pid"] = args.get("pid", row["pid"])
                row["gen"] = max(row["gen"],
                                 args.get("generation") or 0)
            elif s["name"] == "serve.rpc" and rep is not None:
                row = rep_row(rep)
                row["rpc"] += 1
                row["rpc_retries"] += args.get("retries") or 0
                row["rpc_bytes"] += args.get("bytes") or 0
        # a request is SERVED BY the replica that ran its last
        # prefill/decode span; its TTFT belongs to the replica that
        # produced the first token
        for rid, ss in by_req.items():
            root = next((s for s in ss
                         if s["name"] == "serve.request"), None)
            if root is None or \
                    (root.get("args") or {}).get("state") != "finished":
                continue
            phases = [s for s in ss
                      if s["name"] in ("serve.prefill_chunk",
                                       "serve.decode",
                                       "serve.first_decode")
                      and (s.get("args") or {}).get("replica")]
            if phases:
                last = max(phases, key=lambda s: s["ts"] + s["dur"])
                rep_row(last["args"]["replica"])["served"].add(rid)
                first = next((s for s in phases
                              if (s.get("args") or {}).get(
                                  "first_token")), None)
                ttft = (root.get("args") or {}).get("ttft_ms")
                if first is not None and ttft is not None:
                    rep_row(first["args"]["replica"])["ttfts"].append(
                        float(ttft))
        print(f"---------- fleet replicas ({len(rollup)}) ----------")
        if rollup:
            print(f"  {'replica':<10} {'trans':<8} {'pid':>7} "
                  f"{'resp':>5} {'served':>7} {'fo in':>6} "
                  f"{'fo out':>7} {'p99 ttft':>10} {'accept':>7} "
                  f"{'pfx tok':>8} {'rpc(retry)':>11}  (ms)")
            for name in sorted(rollup):
                row = rollup[name]
                ttfts = sorted(row["ttfts"])
                p99 = _pctl(ttfts, 0.99)
                p99_s = "-" if p99 is None else f"{p99:.2f}"
                acc = ("-" if not row["drafted"]
                       else f"{row['accepted'] / row['drafted']:.2f}")
                rpc = ("-" if not row["rpc"]
                       else f"{row['rpc']}({row['rpc_retries']})")
                print(f"  {name:<10} {row['transport'] or 'thread':<8} "
                      f"{str(row['pid'] or '-'):>7} "
                      f"{row['gen']:>5} {len(row['served']):>7} "
                      f"{row['fo_in']:>6} {row['fo_out']:>7} "
                      f"{p99_s:>10} {acc:>7} {row['prefix_hit']:>8} "
                      f"{rpc:>11}")
        by_reason: dict = {}
        for s in fleet_sheds:
            reason = (s.get("args") or {}).get("reason", "?")
            by_reason[reason] = by_reason.get(reason, 0) + 1
        if by_reason:
            detail = ", ".join(f"{r}={n}"
                               for r, n in sorted(by_reason.items()))
            print(f"  shed: {len(fleet_sheds)} requests ({detail})")
        failovers = [s for s in spans if s["name"] == "serve.failover"]
        for s in failovers:
            args = s.get("args") or {}
            print(f"  failover: replica {args.get('replica')} -> "
                  f"{args.get('requests', '?')} request(s) "
                  f"re-dispatched in {s['dur'] / 1e3:.1f} ms "
                  f"({args.get('error', '')})")

    # ---- train: step cadence + per-phase wall -----------------------
    t_disp = [s for s in spans if s["name"] == "train.dispatch"]
    t_dev = [s for s in spans if s["name"] == "train.device"]
    if t_disp or t_dev:
        print(f"---------- train steps ({max(len(t_disp), len(t_dev))}) "
              f"----------")
        for name, group in (("dispatch (host)", t_disp),
                            ("device (dispatch->retire)", t_dev)):
            if not group:
                continue
            durs = sorted(s["dur"] / 1e3 for s in group)
            steps_seen = [s.get("args", {}).get("step") for s in group]
            mean = sum(durs) / len(durs)
            print(f"  {name:<26} n={len(durs):<5} mean={mean:>8.2f} ms  "
                  f"p99={_pctl(durs, 0.99):>8.2f} ms  steps "
                  f"{min(x for x in steps_seen if x is not None)}-"
                  f"{max(x for x in steps_seen if x is not None)}")
        compiles = [s for s in spans
                    if s["name"] in ("train.compile", "serve.compile")]
        for s in compiles:
            print(f"  compile: {s['name']} {s['dur'] / 1e3:.0f} ms "
                  f"{s.get('args', {})}")

    # ---- everything else: count + total wall per span name ----------
    other = {}
    for s in spans:
        if s["name"].startswith(("serve.", "train.")):
            continue
        agg = other.setdefault(s["name"], [0, 0.0])
        agg[0] += 1
        agg[1] += s["dur"] / 1e3
    if other:
        print("---------- other spans ----------")
        for name in sorted(other):
            n, tot = other[name]
            print(f"  {name:<28} n={n:<6} total={tot:>10.2f} ms")
    return 0


def _newest_bundle(crash_dir: str):
    paths = glob.glob(os.path.join(crash_dir, "crash_*.json"))
    return max(paths, key=os.path.getmtime) if paths else None


def print_capsule(path: str) -> int:
    """Human-readable view of one incident capsule (docs/serving.md,
    "Flight recorder & replay")."""
    from mxnet_tpu.serve import traffic as _traffic
    try:
        cap = _traffic.read_capsule(path)
    except Exception as e:
        print(f"cannot read capsule {path}: {e}", file=sys.stderr)
        return 1
    print(f"========== incident capsule: {path} ==========")
    print(f"slo       : {cap.get('slo')}")
    print(f"fired     : {_fmt_ts(cap.get('fired_wall'))}")
    w = cap.get("window") or {}
    print(f"window    : -{w.get('pre_s')}s .. +{w.get('post_s')}s "
          f"(finalized: {cap.get('finalized')})")
    entry = cap.get("entry") or {}
    win = entry.get("windows") or {}
    fast, slow = win.get("fast") or {}, win.get("slow") or {}
    print(f"burn      : fast {fast.get('burn')}x / slow {slow.get('burn')}x "
          f"(threshold {entry.get('burn_threshold')}x, "
          f"signal {entry.get('signal')}, target {entry.get('target')})")
    topo = cap.get("topology") or {}
    print(f"topology  : {topo.get('replicas')} x {topo.get('transport')}"
          f" replica(s), tp={topo.get('tp')}, disagg={topo.get('disagg')}")
    fl = cap.get("fleet") or {}
    reps = fl.get("replicas") or {}
    if reps:
        states = ", ".join(f"{n}={r.get('state')}"
                           for n, r in sorted(reps.items()))
        print(f"fleet     : {states}  deaths={fl.get('deaths')} "
              f"respawns={fl.get('respawns')} "
              f"handoffs={fl.get('handoffs')}")
    files = cap.get("files") or {}
    print(f"files     : {', '.join(sorted(files.values())) or '(none)'}")
    arrivals, outcomes = cap["arrivals"], cap["outcomes"]
    print(f"---------- traffic window ({len(arrivals)} arrivals, "
          f"{len(outcomes)} outcomes) ----------")
    if arrivals:
        by_state = {}
        for o in outcomes.values():
            by_state[o.get("state")] = by_state.get(o.get("state"), 0) + 1
        print(f"  outcomes : " + ", ".join(
            f"{s}={n}" for s, n in sorted(by_state.items())))
        digests = sum(1 for o in outcomes.values() if o.get("digest"))
        print(f"  digests  : {digests} recorded token-stream digest(s)")
        for metric in ("ttft_ms", "latency_ms"):
            vals = sorted(o[metric] for o in outcomes.values()
                          if o.get(metric) is not None)
            if vals:
                print(f"  {metric:<9}: p50 {_pctl(vals, 50):.1f}  "
                      f"p99 {_pctl(vals, 99):.1f}  max {vals[-1]:.1f}")
        tenants = {}
        for a in arrivals:
            tenants[a.get("tenant")] = tenants.get(a.get("tenant"), 0) + 1
        print(f"  tenants  : " + ", ".join(
            f"{t}={n}" for t, n in sorted(tenants.items(),
                                          key=lambda kv: -kv[1])))
    if not cap.get("finalized"):
        print("  (not finalized — traffic window incomplete)")
    print(f"replay    : python tools/diagnose.py --capsule {path} --replay")
    return 0


def print_tenants(path: str) -> int:
    """Per-tenant QoS rollup (docs/serving.md, "Per-tenant QoS") from
    any of the three places the plane leaves evidence:

    * an incident capsule dir — joins the manifest's ``fleet.qos``
      stats with the captured ``metrics.json`` snapshot,
    * a metrics-snapshot JSON (``telemetry.snapshot()`` /
      ``metrics.json``) — the ``serve_tenant_*`` and ``slo_tenant_*``
      series,
    * a ``fleet.stats()`` dump (or its bare ``qos`` sub-dict).
    """
    snap, qstats = None, None
    if os.path.isdir(path):
        from mxnet_tpu.serve import traffic as _traffic
        try:
            cap = _traffic.read_capsule(path)
        except Exception as e:
            print(f"cannot read capsule {path}: {e}", file=sys.stderr)
            return 1
        qstats = (cap.get("fleet") or {}).get("qos")
        mpath = os.path.join(path, "metrics.json")
        if os.path.exists(mpath):
            try:
                with open(mpath) as f:
                    snap = json.load(f)
            except (OSError, json.JSONDecodeError):
                pass
    else:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"cannot read {path}: {e}", file=sys.stderr)
            return 1
        if "qos" in doc:                       # fleet.stats() dump
            qstats = doc["qos"]
        elif "tenants" in doc and "policy" in doc:   # bare qos stats
            qstats = doc
        else:                                  # metrics snapshot
            snap = doc

    def _series(name):
        return ((snap or {}).get(name) or {}).get("series", [])

    # fold every source into {tenant -> row}
    tenants: dict = {}

    def row(t):
        return tenants.setdefault(t, {
            "priority": None, "weight": None, "admitted": 0,
            "sheds": {}, "offenses": {}, "breaker": None,
            "breaker_trips": None, "quota": {}, "wfq": None,
            "slo": None})

    for t, st in ((qstats or {}).get("tenants") or {}).items():
        r = row(t)
        off = st.get("offenses") or {}
        if not isinstance(off, dict):        # stats() carries a count
            off = {"total": off} if off else {}
        r.update(priority=st.get("priority"), weight=st.get("weight"),
                 admitted=st.get("admitted", 0),
                 sheds=dict(st.get("sheds") or {}),
                 offenses=off,
                 breaker=st.get("breaker"),
                 breaker_trips=st.get("breaker_trips"),
                 quota=dict(st.get("quota_fill") or {}))
    if snap is not None:
        for s in _series("serve_tenant_admitted_total"):
            t = (s.get("labels") or {}).get("tenant")
            r = row(t)
            r["admitted"] = max(r["admitted"],
                                int(s.get("value", s.get("count", 0))))
        for s in _series("serve_tenant_sheds_total"):
            lbl = s.get("labels") or {}
            r = row(lbl.get("tenant"))
            n = int(s.get("value", s.get("count", 0)))
            reason = lbl.get("reason", "?")
            r["sheds"][reason] = max(r["sheds"].get(reason, 0), n)
        for s in _series("serve_tenant_quota_fill"):
            lbl = s.get("labels") or {}
            row(lbl.get("tenant")).setdefault(
                "quota", {})[lbl.get("bucket", "?")] = s.get("value")
        for s in _series("serve_tenant_wfq_share"):
            row((s.get("labels") or {}).get("tenant"))["wfq"] = \
                s.get("value")
        breaker_names = {0: "closed", 1: "half_open", 2: "open"}
        for s in _series("serve_tenant_breaker_state"):
            r = row((s.get("labels") or {}).get("tenant"))
            if r["breaker"] is None:
                r["breaker"] = breaker_names.get(
                    int(s.get("value", 0)), "?")
        for s in _series("slo_tenant_burn"):
            lbl = s.get("labels") or {}
            r = row(lbl.get("tenant"))
            r["slo"] = {"slo": lbl.get("slo"),
                        "burn": s.get("value"),
                        "alert": (r["slo"] or {}).get("alert", 0.0)}
        for s in _series("slo_tenant_alert"):
            lbl = s.get("labels") or {}
            r = row(lbl.get("tenant"))
            if r["slo"] is None:
                r["slo"] = {"slo": lbl.get("slo"), "burn": None}
            r["slo"]["alert"] = s.get("value")

    if not tenants:
        print(f"no per-tenant QoS data in {path} (QoS plane not "
              f"configured, or snapshot predates it)", file=sys.stderr)
        return 1

    print(f"========== tenants: {path} ==========")
    if qstats is not None:
        print(f"policy    : {qstats.get('policy')}")
    print(f"  {'tenant':<12} {'class':<12} {'wt':>5} {'admit':>7} "
          f"{'shed':>6} {'quota r/t':>11} {'wfq':>6} {'breaker':<9} "
          f"{'slo burn':<14}")
    for t in sorted(tenants):
        r = tenants[t]
        shed_n = sum(r["sheds"].values())
        q = r["quota"] or {}

        def fq(k):
            v = q.get(k)
            return "-" if v is None else f"{v:.2f}"

        wfq = "-" if r["wfq"] is None else f"{r['wfq']:.2f}"
        slo = r["slo"]
        if slo is None:
            slo_s = "-"
        else:
            burn = slo.get("burn")
            slo_s = ("ALERT" if slo.get("alert") else
                     ("-" if burn is None else f"{burn:.2f}x"))
            if slo.get("slo"):
                slo_s += f" ({slo['slo']})"
        brk = r["breaker"] or "-"
        if r.get("breaker_trips"):
            brk += f"({r['breaker_trips']})"
        print(f"  {str(t):<12} {str(r['priority'] or '-'):<12} "
              f"{r['weight'] if r['weight'] is not None else '-':>5} "
              f"{r['admitted']:>7} {shed_n:>6} "
              f"{fq('requests') + '/' + fq('tokens'):>11} {wfq:>6} "
              f"{brk:<9} {slo_s:<14}")
        details = []
        if r["sheds"]:
            details.append("sheds: " + ", ".join(
                f"{k}={v}" for k, v in sorted(r["sheds"].items())))
        if r["offenses"]:
            details.append("offenses: " + ", ".join(
                f"{k}={v}" for k, v in sorted(r["offenses"].items())))
        for d in details:
            print(f"  {'':<12} {d}")
    return 0


def replay_capsule_cli(path: str) -> int:
    """Re-drive a capsule's traffic window (`serve.replay`) and print
    the divergence report.  rc 0 iff every verifiable greedy stream
    reproduced its recorded digest bit-for-bit."""
    import mxnet_tpu  # noqa: F401  (jax init before fleet construction)
    from mxnet_tpu.serve import replay as _replay

    def _opt(flag, cast, default):
        if flag in sys.argv:
            return cast(_flag_operand(flag))
        return default

    report = _replay.replay_capsule(
        path,
        speed=_opt("--speed", float, 0.0),
        kill_at=_opt("--kill-at", float, None),
        transport=_opt("--transport", str, None),
        replicas=_opt("--replicas", int, None),
        timeout=_opt("--timeout", float, 180.0))
    print(f"========== capsule replay: {path} ==========")
    print(f"mode      : {report['mode']}   wall: "
          f"{report['replay_wall_s']}s")
    print(f"requests  : {report['requests']} recorded, "
          f"{report['submitted']} replayed, "
          f"{len(report['shed_replay'])} shed in replay")
    print(f"digests   : {len(report['matched'])} matched, "
          f"{len(report['divergent'])} divergent, "
          f"{len(report['unverified'])} unverifiable")
    for d in report["divergent"][:10]:
        print(f"  DIVERGED rid {d['rid']}: recorded "
              f"{str(d['recorded'])[:16]}... got "
              f"{str(d['replayed'])[:16]}... ({d['replay_state']})")
    for f in report["replay_failed"][:10]:
        print(f"  FAILED   rid {f['rid']}: {f['error']}")
    for metric in ("ttft_ms", "latency_ms"):
        rec, rep = report[metric]["recorded"], report[metric]["replayed"]
        if rec and rep:
            print(f"{metric:<10}: recorded p50 {rec['p50']} / p99 "
                  f"{rec['p99']}  ->  replayed p50 {rep['p50']} / p99 "
                  f"{rep['p99']}")
    print(f"slo       : recorded alert on {report.get('slo_recorded')!r}; "
          f"re-fired in replay: {report['slo_alert_refired']}")
    print("verdict   : " + ("REPRODUCED — streams bit-identical"
                            if report["ok"] else "DIVERGED"))
    return 0 if report["ok"] else 1


def _flag_operand(flag: str) -> str:
    idx = sys.argv.index(flag)
    if idx + 1 >= len(sys.argv):
        print(f"usage: diagnose.py {flag} <path>", file=sys.stderr)
        sys.exit(2)
    return sys.argv[idx + 1]


def main():
    if "--capsule" in sys.argv:
        path = _flag_operand("--capsule")
        if "--replay" in sys.argv:
            return sys.exit(replay_capsule_cli(path))
        return sys.exit(print_capsule(path))
    if "--tenants" in sys.argv:
        return sys.exit(print_tenants(_flag_operand("--tenants")))
    if "--bundle" in sys.argv:
        return sys.exit(print_bundle(_flag_operand("--bundle")))
    if "--journal" in sys.argv:
        return sys.exit(print_journal(_flag_operand("--journal")))
    if "--trace" in sys.argv:
        rest = sys.argv[sys.argv.index("--trace") + 1:]
        paths, merged_out, i = [], None, 0
        while i < len(rest):
            if rest[i] == "--merged-out":
                if i + 1 >= len(rest):
                    print("usage: diagnose.py --trace <paths...> "
                          "[--merged-out <file>]", file=sys.stderr)
                    sys.exit(2)
                merged_out = rest[i + 1]
                i += 2
            else:
                paths.append(rest[i])
                i += 1
        if not paths:
            print("usage: diagnose.py --trace <paths...> "
                  "[--merged-out <file>]", file=sys.stderr)
            sys.exit(2)
        return sys.exit(print_trace(paths, merged_out=merged_out))
    if "--crash-dir" in sys.argv:
        d = _flag_operand("--crash-dir")
        newest = _newest_bundle(d)
        if newest is None:
            print(f"no crash_*.json bundles in {d}", file=sys.stderr)
            return sys.exit(1)
        return sys.exit(print_bundle(newest))
    print("----------Platform Info----------")
    print(f"system  : {platform.system()} {platform.release()}")
    print(f"machine : {platform.machine()}")
    print(f"python  : {sys.version.split()[0]}")
    try:
        import numpy
        print(f"numpy   : {numpy.__version__}")
    except ImportError:
        pass
    try:
        import jax
        if os.environ.get("JAX_PLATFORMS"):
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        print(f"jax     : {jax.__version__}")
    except ImportError:
        print("jax     : NOT FOUND")
        return
    print("----------Framework Info----------")
    import mxnet_tpu as mx
    print(f"mxnet_tpu: {mx.__version__} ({os.path.dirname(mx.__file__)})")
    feats = mx.runtime.feature_list() if hasattr(mx.runtime, "feature_list") \
        else []
    if feats:
        enabled = [f.name for f in feats if getattr(f, "enabled", False)]
        print(f"features : {', '.join(enabled)}")
    from mxnet_tpu import _native
    print(f"native io: {'built' if _native.available() else 'python fallback'}")
    print("----------Device Info----------")
    try:
        for d in __import__("jax").devices():
            print(f"  {d.id}: {d.platform} {d.device_kind}")
    except Exception as e:
        print(f"  device init failed: {e}")
    print("----------Environment----------")
    for k, v in sorted(os.environ.items()):
        if k.startswith(("MXTPU_", "MXNET_", "XLA_", "JAX_", "DMLC_")):
            print(f"  {k}={v}")


if __name__ == "__main__":
    main()
