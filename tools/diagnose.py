#!/usr/bin/env python
"""Environment diagnosis (parity: `tools/diagnose.py`): platform, python,
framework features, device inventory, key environment variables."""
from __future__ import annotations

import os
import platform
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    print("----------Platform Info----------")
    print(f"system  : {platform.system()} {platform.release()}")
    print(f"machine : {platform.machine()}")
    print(f"python  : {sys.version.split()[0]}")
    try:
        import numpy
        print(f"numpy   : {numpy.__version__}")
    except ImportError:
        pass
    try:
        import jax
        if os.environ.get("JAX_PLATFORMS"):
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        print(f"jax     : {jax.__version__}")
    except ImportError:
        print("jax     : NOT FOUND")
        return
    print("----------Framework Info----------")
    import mxnet_tpu as mx
    print(f"mxnet_tpu: {mx.__version__} ({os.path.dirname(mx.__file__)})")
    feats = mx.runtime.feature_list() if hasattr(mx.runtime, "feature_list") \
        else []
    if feats:
        enabled = [f.name for f in feats if getattr(f, "enabled", False)]
        print(f"features : {', '.join(enabled)}")
    from mxnet_tpu import _native
    print(f"native io: {'built' if _native.available() else 'python fallback'}")
    print("----------Device Info----------")
    try:
        for d in __import__("jax").devices():
            print(f"  {d.id}: {d.platform} {d.device_kind}")
    except Exception as e:
        print(f"  device init failed: {e}")
    print("----------Environment----------")
    for k, v in sorted(os.environ.items()):
        if k.startswith(("MXTPU_", "MXNET_", "XLA_", "JAX_", "DMLC_")):
            print(f"  {k}={v}")


if __name__ == "__main__":
    main()
