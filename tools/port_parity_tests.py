"""Port reference unit-test bodies into tests/parity/ (VERDICT r4 item 2).

Extracts the SOURCE of a curated list of test functions from a reference
test file (decorators included) and assembles them into a parity-tier
test module with a provenance header.  The bodies are kept faithful — the
point is to run the reference's OWN assertions against this framework —
with documented deviations xfailed inline afterwards by hand.

Usage:
    python tools/port_parity_tests.py <ref_file> <out_file> name1 name2 ...
    python tools/port_parity_tests.py --list <ref_file>
"""
from __future__ import annotations

import ast
import sys


def extract(ref_path: str, names: list[str]) -> tuple[str, list[str]]:
    src = open(ref_path).read()
    lines = src.splitlines(keepends=True)
    tree = ast.parse(src)
    wanted = {n: None for n in names}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in wanted:
            start = min([node.lineno] +
                        [d.lineno for d in node.decorator_list]) - 1
            end = node.end_lineno
            wanted[node.name] = "".join(lines[start:end])
    missing = [n for n, v in wanted.items() if v is None]
    chunks = [v for v in wanted.values() if v is not None]
    return "\n\n".join(chunks), missing


HEADER = '''\
"""Reference unit-test bodies, run against mxnet_tpu (VERDICT r4 item 2).

PROVENANCE: the test functions below are ported from the reference's
`{ref}`
(Apache-2.0) — intentionally faithful, because these bodies ARE the
behavior-parity oracle: they encode the reference's op semantics
(dtype promotion, degenerate shapes, error paths) independently of this
repo's own builder-authored sweeps.  The `mxnet` import resolves to
`mxnet_tpu` via the alias finder in `tests/parity/conftest.py`.
Deviations that are documented design decisions are xfailed inline with
one-line reasons (an xfail is an assertion about the design, not a TODO).
"""
import itertools
import random

import numpy as onp
import pytest
import scipy.stats as ss
import scipy.special as scipy_special
from numpy.testing import assert_allclose

import mxnet as mx
from mxnet import np, npx
from mxnet.base import MXNetError
from mxnet.gluon import HybridBlock
from mxnet.gluon.parameter import Parameter
from mxnet.test_utils import (
    assert_almost_equal, check_numeric_gradient, collapse_sum_like,
    effective_dtype, environment, gen_buckets_probs_with_ppf, is_op_runnable,
    has_tvm_ops, new_matrix_with_real_eigvals_nd,
    new_sym_matrix_with_real_eigvals_nd, rand_ndarray, rand_shape_2d,
    rand_shape_nd, retry, same, use_np, verify_generator,
)
import mxnet.ndarray.numpy._internal as _npi
from mxnet.numpy_op_signature import _get_builtin_op
from common import (
    assertRaises, assert_raises_cuda_not_satisfied,
    xfail_when_nonstandard_decimal_separator, with_environment,
)

pytestmark = pytest.mark.parity

'''


def main():
    args = sys.argv[1:]
    if args and args[0] == "--list":
        tree = ast.parse(open(args[1]).read())
        for node in tree.body:
            if isinstance(node, ast.FunctionDef) \
                    and node.name.startswith("test"):
                print(node.name)
        return
    ref, out, names = args[0], args[1], args[2:]
    body, missing = extract(ref, names)
    if missing:
        print("MISSING:", missing, file=sys.stderr)
    with open(out, "w") as f:
        f.write(HEADER.format(ref=ref.replace("/root/reference/", "")))
        f.write(body)
    print(f"wrote {out}: {len(names) - len(missing)} tests")


if __name__ == "__main__":
    main()
