"""Op-microbenchmark regression gate (VERDICT r4 item 5; SURVEY §4 —
"op microbenchmarks double as perf regression tests").

Re-runs a pinned subset of the ops in `bench_results/opperf_cpu.md` and
FAILS (exit 1) when any op's forward or backward latency exceeds
`--factor`× the committed baseline (default 2.0 plus a floor, to ride
out the contended shared-core CI boxes).  Refresh procedure after an
intentional perf change:

    python -m mxnet_tpu.benchmark.opperf --output bench_results/opperf_cpu.md
    git add bench_results/opperf_cpu.md   # review the delta!

Usage: python tools/opperf_check.py [--factor 2.0] [--ops a,b,c]
"""
from __future__ import annotations

import argparse
import os
import re
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

BASELINE = os.path.join(os.path.dirname(__file__), "..", "bench_results",
                        "opperf_cpu.md")

# pinned subset: cheap-but-representative ops across families (elemwise,
# reduction, matmul, NN layers, attention); ~20 entries keeps the gate
# under a couple of minutes on one core
PINNED = [
    "abs", "add", "clip", "cumsum", "divide", "dot", "exp",
    "fully_connected", "gelu", "layer_norm", "log", "log_softmax",
    "max", "mean", "multiply", "relu", "sigmoid", "softmax", "sum",
    "tanh",
]

# latencies under this many ms are timer noise on a contended box; the
# gate only engages above it
ABS_FLOOR_MS = 0.25


def load_baseline():
    rows = {}
    for line in open(BASELINE):
        m = re.match(r"\| (\w+) \| `[^`]*` \| ([0-9.e+-]+|None) \| "
                     r"([0-9.e+-]+|None) \|", line)
        if m:
            fwd = None if m.group(2) == "None" else float(m.group(2))
            bwd = None if m.group(3) == "None" else float(m.group(3))
            rows[m.group(1)] = (fwd, bwd)
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--factor", type=float, default=2.0)
    ap.add_argument("--ops", type=str, default=None)
    args = ap.parse_args()
    ops = args.ops.split(",") if args.ops else PINNED

    baseline = load_baseline()
    missing = [o for o in ops if o not in baseline]
    if missing:
        print(f"FAIL: pinned ops missing from baseline: {missing}")
        return 1

    from mxnet_tpu.benchmark.opperf import DEFAULT_OPS, run_performance_test
    suite = {name: specs for name, specs in DEFAULT_OPS}

    measured, errors = [], []
    for op in ops:
        if op not in suite:
            print(f"FAIL: op {op!r} not in DEFAULT_OPS")
            return 1
        res = run_performance_test(op, inputs=suite[op], warmup=3, runs=10)
        for r in res:
            if "error" in r:
                errors.append(f"{op}: errored: {r['error']}")
                continue
            base_fwd, base_bwd = baseline[op]
            for leg, got, base in (
                    ("fwd", r.get("avg_forward_time_ms"), base_fwd),
                    ("bwd", r.get("avg_backward_time_ms"), base_bwd)):
                if got is None or base is None or base <= 0:
                    continue
                if got < ABS_FLOOR_MS and base < ABS_FLOOR_MS:
                    continue    # both in timer-noise territory
                measured.append((op, leg, got, base, got / base))

    # the machine running this gate is rarely the one that produced the
    # baseline (and CI cores are contended), so a UNIFORM slowdown is
    # expected — the gate flags ops whose ratio-to-baseline exceeds
    # `factor`x the MEDIAN ratio of the whole pinned set: a genuine
    # single-kernel regression sticks out; global contention cancels
    ratios = sorted(r for *_, r in measured)
    med = ratios[len(ratios) // 2] if ratios else 1.0
    norm = max(med, 1.0)
    failures = list(errors)
    for op, leg, got, base, ratio in measured:
        limit = norm * args.factor
        flag = " <-- REGRESSION" if ratio > limit else ""
        print(f"{op:18s} {leg}: {got:8.3f} ms (baseline {base:8.3f}, "
              f"ratio {ratio:5.2f}, limit {limit:5.2f}x){flag}")
        if ratio > limit:
            failures.append(
                f"{op} {leg}: {ratio:.2f}x baseline vs median machine "
                f"ratio {med:.2f} (limit {limit:.2f}x)")
    print(f"\nchecked {len(measured)} latencies across {len(ops)} ops "
          f"(median machine ratio {med:.2f})")
    if failures:
        print("\nREGRESSIONS:")
        for f in failures:
            print(" ", f)
        print("\nIf intentional, refresh the baseline (see module "
              "docstring).")
        return 1
    print("opperf-check: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
