#!/usr/bin/env python
"""Collective bandwidth microbenchmark (parity: `tools/bandwidth/measure.py`
— the reference measures kvstore push/pull; here the wire is XLA collectives
over the device mesh, so we time psum/all_gather at increasing sizes).

Run with a virtual mesh for smoke tests:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python tools/measure_bandwidth.py --sizes 1,4,16
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sizes", default="1,4,16,64",
                    help="comma-separated tensor sizes in MiB")
    ap.add_argument("--runs", type=int, default=5)
    args = ap.parse_args()

    import jax
    if os.environ.get("JAX_PLATFORMS"):
        # some PJRT plugins register themselves as default regardless of the
        # env var; re-assert the user's choice before backend init
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = jax.devices()
    n = len(devices)
    print(f"devices: {n} x {devices[0].platform}")
    mesh = Mesh(jax.numpy.array(devices).reshape(n), ("dp",))

    for mib in [float(s) for s in args.sizes.split(",")]:
        elems = int(mib * (1 << 20) / 4)
        x = jnp.ones((n, max(elems // 1, 1)), jnp.float32)
        x = jax.device_put(x, NamedSharding(mesh, P("dp", None)))

        @jax.jit
        def allreduce(v):
            return jax.lax.with_sharding_constraint(
                v.sum(axis=0, keepdims=True), NamedSharding(mesh, P()))

        allreduce(x).block_until_ready()
        t0 = time.perf_counter()
        for _ in range(args.runs):
            out = allreduce(x)
        out.block_until_ready()
        dt = (time.perf_counter() - t0) / args.runs
        gbps = (mib / 1024) * 2 * (n - 1) / n / dt if dt else float("inf")
        print(f"size {mib:8.1f} MiB  allreduce {dt*1e3:8.2f} ms  "
              f"algbw {gbps:6.2f} GiB/s")


if __name__ == "__main__":
    main()
