#!/usr/bin/env python
"""Fleet observability-plane smoke (`make obsplane-smoke`, wired into
`make test`).

CPU-only, <90 s end-to-end check of the cross-process observability
plane (docs/observability.md, "Fleet observability") over a
1-prefill + 2-decode process fleet:

- **one trace id per request across three processes**: the router's
  ``serve.request`` root (parent pid), the prefill worker's
  ``serve.worker`` subtree, the parent-side ``serve.handoff`` span,
  and the decode worker's adopted subtree all share the root's trace
  id, every ``serve.worker`` span parents directly on the root, and
  clock-rebased worker timestamps land inside the root's window;
- ``tools/diagnose.py --trace <dir> --merged-out`` produces a loadable
  merged Perfetto doc whose ``process_name`` metadata names the parent
  and each worker pid;
- **metrics federation**: worker registry snapshots ride heartbeats
  and re-export on the parent's ``/metrics`` with a ``replica`` label
  (asserted on worker-only ``serve_replica_free_pages`` series), and a
  drained replica's series retire with it while survivors stay;
- **SLO burn-rate engine**: a generous ``MXTPU_SLO_SPEC`` stays silent
  through clean traffic, then an adaptive latency objective fires a
  ``slo_burn`` journal event + ``slo_burn_alerts_total`` counter when
  one decode worker is SIGSTOPped mid-stream (induced failover
  latency) and then SIGKILLed — the victim request still finishes
  bit-identical to the unbatched ``generate()`` oracle after respawn;
- **cost-vector shipping**: ``cost_analysis`` rows from worker-process
  compiles land in the parent's journal tagged ``origin=worker``.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
# declarative objectives from the environment: generous thresholds
# that a clean run must never trip (the burn assert below is two-sided)
os.environ["MXTPU_SLO_SPEC"] = json.dumps({"objectives": [
    {"name": "availability", "signal": "availability", "target": 0.99,
     "fast_s": 30, "slow_s": 120},
    {"name": "ttft_generous", "signal": "ttft_ms", "threshold": 120000,
     "target": 0.99, "fast_s": 30, "slow_s": 120},
]})
os.environ["MXTPU_CLOCK_SYNC_INTERVAL"] = "2.0"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _metrics(port: int) -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
        return r.read().decode()


def main() -> int:
    t_start = time.time()
    tmp = tempfile.mkdtemp(prefix="mxtpu_obsplane_smoke_")
    journal_path = os.path.join(tmp, "journal.jsonl")
    trace_dir = os.path.join(tmp, "traces")

    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry as tele
    from mxnet_tpu import tracing as trace
    from mxnet_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from mxnet_tpu.serve import ServeConfig, ServeFleet
    from mxnet_tpu.slo import Objective

    tele.enable(journal_path=journal_path)
    trace.enable(trace_dir)
    srv = tele.serve_metrics(port=0)

    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_heads=4, intermediate_size=64, max_position=64,
                    dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.initialize()
    model(mx.np.array([[1, 2]], dtype="int32"))

    rng = onp.random.RandomState(47)
    max_new = 10
    n_req = 6
    prompts = [rng.randint(0, 96, rng.randint(2, 10)).tolist()
               for _ in range(n_req + 1)]        # [-1] is the victim
    refs = []
    for p in prompts:
        ids = mx.np.array([p], dtype="int32")
        refs.append(onp.asarray(
            model.generate(ids, max_new_tokens=max_new)
            .asnumpy())[0].tolist())

    sc = ServeConfig(max_slots=2, page_size=4, num_pages=0,
                     prefill_chunk=4, max_len=32)
    fleet = ServeFleet(model, config=sc, transport="process",
                       disagg=(1, 2), respawn_budget=2,
                       stall_timeout=15.0)
    assert fleet.slo is not None, "MXTPU_SLO_SPEC was not picked up"
    assert {o.name for o in fleet.slo.objectives()} == \
        {"availability", "ttft_generous"}
    fleet.warmup()

    streams = {i: [] for i in range(n_req + 1)}

    def tok_cb(i):
        return lambda t, r: streams[i].append(t)

    try:
        fleet.start()

        # ---- phase A: clean traffic ----------------------------------
        t0 = time.time()
        handles = {i: fleet.submit(prompts[i], max_new_tokens=max_new,
                                   on_token=tok_cb(i))
                   for i in range(n_req)}
        for i in range(n_req):
            got = handles[i].result(timeout=90)
            assert got == refs[i], \
                f"request {i} diverged from the generate() oracle"
        clean_max_ms = (time.time() - t0) * 1e3

        for rep in fleet.replicas:
            assert rep.clock.samples >= 1, \
                f"{rep.name}: no round-trip clock sample ({rep.clock})"
            assert abs(rep.clock.offset) < 60.0, rep.clock

        # federation: worker-only series appear per replica on /metrics
        want = [f'serve_replica_free_pages{{replica="{r.name}"}}'
                for r in fleet.replicas]
        deadline = time.time() + 20
        text = ""
        while time.time() < deadline:
            text = _metrics(srv.port)
            if all(w in text for w in want):
                break
            time.sleep(0.25)
        missing = [w for w in want if w not in text]
        assert not missing, f"federated series never appeared: {missing}"

        # generous objectives stay silent through clean traffic
        assert all(not e["alerting"]
                   for e in fleet.slo.evaluate().values()), \
            fleet.slo.evaluate()

        # ---- trace: one id, three processes --------------------------
        os.makedirs(trace_dir, exist_ok=True)
        parent_export = os.path.join(trace_dir,
                                     f"trace_{os.getpid()}.json")
        deadline = time.time() + 15
        trees = {}
        while time.time() < deadline:
            trace.export_chrome(parent_export)
            with open(parent_export) as f:
                evs = [e for e in json.load(f)["traceEvents"]
                       if e.get("ph") == "X"]
            roots = [e for e in evs if e["name"] == "serve.request"
                     and (e.get("args") or {}).get("state") == "finished"]
            trees = {}
            for root in roots:
                tid_ = root["args"]["trace_id"]
                trees[tid_] = {"root": root,
                               "events": [e for e in evs
                                          if (e.get("args") or {})
                                          .get("trace_id") == tid_]}
            if len(trees) >= n_req and all(
                    len({e["pid"] for e in t["events"]}) >= 3
                    and any(e["name"] == "serve.handoff"
                            for e in t["events"])
                    for t in trees.values()):
                break
            time.sleep(0.5)
        assert len(trees) >= n_req, \
            f"only {len(trees)} finished request trees in the export"
        for tid_, t in trees.items():
            pids = {e["pid"] for e in t["events"]}
            assert len(pids) >= 3, (
                f"trace {tid_}: request tree spans pids {pids}, "
                f"expected parent + prefill + decode")
            root = t["root"]
            workers = [e for e in t["events"]
                       if e["name"] == "serve.worker"]
            assert workers, f"trace {tid_}: no serve.worker spans"
            for w in workers:
                assert w["args"]["parent_id"] == \
                    root["args"]["span_id"], (tid_, w)
                assert w["pid"] != root["pid"], (tid_, w)
            lo = root["ts"] - 250e3
            hi = root["ts"] + root["dur"] + 250e3
            for e in t["events"]:
                assert lo <= e["ts"] <= hi, (
                    f"trace {tid_}: span {e['name']} at {e['ts']} "
                    f"outside rebased root window [{lo}, {hi}]")

        merged = os.path.join(tmp, "merged.json")
        proc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "diagnose.py"),
             "--trace", trace_dir, "--merged-out", merged],
            capture_output=True, text=True)
        assert proc.returncode == 0, \
            f"diagnose --trace failed: {proc.stderr[-2000:]}"
        with open(merged) as f:
            mdoc = json.load(f)
        pnames = {e["pid"]: e["args"]["name"]
                  for e in mdoc["traceEvents"]
                  if e.get("ph") == "M" and e["name"] == "process_name"}
        assert len(pnames) >= 3, pnames
        assert any("worker" in n for n in pnames.values()), pnames

        # ---- phase B: induced failover latency -> burn ---------------
        hold_s = min(clean_max_ms * 1.5 + 2000, 10000) / 1e3
        fleet.slo.add_objective(Objective(
            name="victim_latency", signal="latency_ms",
            threshold=hold_s * 1e3 * 0.6, target=0.99,
            fast_s=15.0, slow_s=60.0, burn=2.0, min_events=1))

        vi = n_req
        vh = fleet.submit(prompts[vi], max_new_tokens=max_new,
                          on_token=tok_cb(vi))
        decoders = [r for r in fleet.replicas
                    if r.engine.role == "decode"]
        victim = None
        deadline = time.time() + 40
        while victim is None and time.time() < deadline:
            for rep in decoders:
                sched = rep.engine.scheduler
                with sched._lock:
                    if any(len(e.req.tokens) >= 2
                           for e in sched._ledger.values()):
                        victim = rep
                        break
            time.sleep(0.002)
        assert victim is not None, \
            "no decode worker ever held the victim's stream"
        victim_pid = victim.pid
        os.kill(victim_pid, signal.SIGSTOP)   # stall: latency builds...
        time.sleep(hold_s)
        os.kill(victim_pid, signal.SIGKILL)   # ...then die mid-stream

        deadline = time.time() + 30
        while fleet.respawns == 0 and time.time() < deadline:
            time.sleep(0.005)
        assert fleet.deaths >= 1 and fleet.respawns >= 1

        got = vh.result(timeout=90)
        assert got == refs[vi], "victim diverged after failover"
        assert streams[vi] == refs[vi][len(prompts[vi]):], \
            "victim stream re-emitted or lost tokens across failover"

        deadline = time.time() + 15
        ev = {}
        while time.time() < deadline:
            ev = fleet.slo.evaluate().get("victim_latency", {})
            if ev.get("alerts", 0) >= 1:
                break
            time.sleep(0.1)
        assert ev.get("alerts", 0) >= 1, \
            f"victim_latency burn alert never fired: {ev}"

        # ---- retirement: drain a survivor, its series vanish ---------
        survivor = next(r for r in decoders if r.name != victim.name)
        keeper = next(r for r in fleet.replicas
                      if r.name not in (survivor.name,))
        assert fleet.drain(survivor.name, timeout=60), \
            f"{survivor.name} never drained"
        gone = f'serve_replica_free_pages{{replica="{survivor.name}"}}'
        kept = f'serve_replica_free_pages{{replica="{keeper.name}"}}'
        text = _metrics(srv.port)
        assert gone not in text, \
            f"drained {survivor.name} series still on /metrics"
        assert kept in text, \
            f"surviving {keeper.name} series retired with the drain"

        time.sleep(1.0)   # let final heartbeat obs batches land
    finally:
        fleet.close()
        srv.stop()

    # ---- journal contract --------------------------------------------
    rows = tele.RunJournal.read(journal_path)
    burns = [r for r in rows if r.get("event") == "slo_burn"]
    assert burns and all(r.get("slo") == "victim_latency"
                         for r in burns), (
        f"expected victim_latency burn rows only, got "
        f"{[r.get('slo') for r in burns]}")
    snap = tele.snapshot()
    alerts = snap.get("slo_burn_alerts_total", {}).get("series", [])
    assert any(s["labels"].get("slo") == "victim_latency"
               and s["value"] >= 1 for s in alerts), alerts
    costs = [r for r in rows if r.get("event") == "cost_analysis"
             and r.get("origin") == "worker"]
    assert costs, "no worker-process cost_analysis rows in the journal"
    assert all(r.get("replica") for r in costs[:8]), costs[0]

    elapsed = time.time() - t_start
    print(json.dumps({
        "obsplane_smoke": "ok", "requests": n_req + 1,
        "trace_trees": len(trees),
        "processes_in_merge": len(pnames),
        "burn_alerts": int(sum(s["value"] for s in alerts)),
        "worker_cost_rows": len(costs),
        "deaths": fleet.deaths, "respawns": fleet.respawns,
        "elapsed_s": round(elapsed, 1)}))
    assert elapsed < 90, f"smoke took {elapsed:.0f}s (budget 90s)"
    return 0


if __name__ == "__main__":
    sys.exit(main())
