#!/usr/bin/env python
"""Export/AOT smoke (docs/export.md; wired into `make test`).

Under 60 s on CPU: capture a small GPT train step + serving step
through the offline pass pipeline (remat-policy search under a
synthetic tight HBM budget + a sharding no-op retarget), then reload
BOTH in a fresh process and assert:

- the loaded train step's losses are bit-identical to the capturing
  process's live-traced losses (3 steps), with ``trace_count == 0``
  on the loaded path (zero Python-level retraces),
- the loaded serving engine streams bit-identical tokens,
- the remat search picked a NON-default policy (the tight budget
  excludes the no-remat program) and recorded its candidate table,
- stale-version and wrong-topology artifacts fail fast with clear
  errors.

Usage: ``python tools/export_smoke.py`` (parent), or with ``--role
capture|load <dir>`` as one of the two child processes.
"""
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _child_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = flags + \
            " --xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("MXTPU_REMAT_POLICY", None)   # the search must own the knob
    return env


def _build(seed=0):
    import mxnet_tpu as mx
    from mxnet_tpu import optimizer as opt
    from mxnet_tpu import random as mxrng
    from mxnet_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from mxnet_tpu.parallel import make_mesh, make_sharded_train_step
    import jax
    import jax.numpy as jnp
    import numpy as onp

    mxrng.seed(seed)
    cfg = GPTConfig(vocab_size=256, hidden_size=32, num_layers=2,
                    num_heads=4, intermediate_size=64, max_position=64,
                    dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.initialize()
    rng = onp.random.RandomState(0)
    ids = mx.np.array(rng.randint(0, 256, (8, 16)), dtype="int32")
    labels = mx.np.array(rng.randint(0, 256, (8, 16)), dtype="int32")
    model(ids)

    def loss_fn(out, input_ids, labels):
        from mxnet_tpu.ops.pallas.softmax_xent import softmax_cross_entropy
        o = out._data if hasattr(out, "_data") else out
        return jnp.mean(softmax_cross_entropy(o, labels.astype(jnp.int32)))

    mesh = make_mesh({"dp": 4, "tp": 2}, jax.devices())
    step = make_sharded_train_step(model, opt.Adam(learning_rate=1e-3),
                                   loss_fn, mesh, num_model_args=1)
    return model, step, ids, labels


def _serve_engine(model):
    from mxnet_tpu.serve import InferenceEngine, ServeConfig
    return InferenceEngine(model, ServeConfig(max_len=64, max_slots=4))


def role_capture(art_dir):
    import jax
    from mxnet_tpu.export import (PassManager, RematSearchPass,
                                  ShardingRetargetPass, capture_train_step)

    model, step, ids, labels = _build()
    # synthetic tight budget: params/state/args fit, the no-remat
    # activation set does not — the search MUST land off-default
    cap = capture_train_step(step, ids, labels)
    rec = cap.artifact.module_record(step.topology())
    stats = cap.compile_stats()
    arg_bytes = stats["argument_bytes"] or 0
    from mxnet_tpu.export.passes import _analytic_saved_bytes
    cfg = model.cfg
    tight = arg_bytes + int(
        (_analytic_saved_bytes(cfg, rec["batch_avals"], "none") +
         _analytic_saved_bytes(cfg, rec["batch_avals"], "dots_saveable"))
        / 2)
    cap = PassManager([
        RematSearchPass(hbm_budget=float(tight)),
        ShardingRetargetPass({"dp": 2, "tp": 2}),
    ]).run(cap)
    cap.save(os.path.join(art_dir, "train"))

    eng = _serve_engine(model)
    eng.warmup()
    eng.export(os.path.join(art_dir, "serve"))

    # live reference numbers AFTER capture (cfg.remat now = winner)
    losses = [float(jax.device_get(step.dispatch(ids, labels).loss))
              for _ in range(3)]
    tokens = eng.generate(list(range(1, 9)), max_new_tokens=6)
    man = json.load(open(os.path.join(art_dir, "train",
                                      "manifest.json")))
    return {"losses": losses, "tokens": tokens,
            "remat_policy": man["remat_policy"],
            "live_trace_count": step.trace_count,
            "passes": [p["name"] for p in man["passes"]]}


def role_load(art_dir):
    import jax
    from mxnet_tpu.base import MXNetError

    model, step, ids, labels = _build()
    # serve first: the engine extracts the block's (initial) weights —
    # after train dispatches they are mesh-sharded trained values, which
    # would neither match the capture child's reference tokens nor the
    # single-device serve executable's avals
    eng = _serve_engine(model)
    eng.warmup(artifact=os.path.join(art_dir, "serve"))
    tokens = eng.generate(list(range(1, 9)), max_new_tokens=6)

    step.load_export(os.path.join(art_dir, "train"), ids, labels)
    losses = [float(jax.device_get(step.dispatch(ids, labels).loss))
              for _ in range(3)]
    assert step.trace_count == 0, \
        f"loaded path traced {step.trace_count}x (contract: 0)"

    # failure matrix: stale version + wrong topology fail FAST
    man_path = os.path.join(art_dir, "train", "manifest.json")
    man = json.load(open(man_path))
    man["format_version"] = 999
    stale_dir = os.path.join(art_dir, "stale")
    import shutil
    shutil.copytree(os.path.join(art_dir, "train"), stale_dir)
    with open(os.path.join(stale_dir, "manifest.json"), "w") as f:
        json.dump(man, f)
    try:
        step2 = _build()[1]
        step2.load_export(stale_dir, ids, labels)
        raise AssertionError("stale-version artifact loaded silently")
    except MXNetError as e:
        assert "format_version" in str(e), e
    from mxnet_tpu.parallel import make_mesh, make_sharded_train_step
    try:
        from mxnet_tpu.export import load
        la = load(os.path.join(art_dir, "train"))
        la.artifact.module_bytes({"devices": 3, "axes": {"dp": 3}})
        raise AssertionError("wrong-topology lookup did not raise")
    except MXNetError as e:
        assert "topology" in str(e), e

    return {"losses": losses, "tokens": tokens,
            "trace_count": step.trace_count}


def main():
    if "--role" in sys.argv:
        i = sys.argv.index("--role")
        role, art_dir = sys.argv[i + 1], sys.argv[i + 2]
        out = role_capture(art_dir) if role == "capture" \
            else role_load(art_dir)
        print("SMOKE_JSON:" + json.dumps(out))
        return

    with tempfile.TemporaryDirectory(prefix="mxtpu_export_smoke_") as art:
        results = {}
        for role in ("capture", "load"):
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--role", role, art],
                capture_output=True, text=True, timeout=540,
                env=_child_env(), cwd=REPO)
            if proc.returncode != 0:
                sys.stderr.write(proc.stdout[-2000:])
                sys.stderr.write(proc.stderr[-4000:])
                raise SystemExit(f"export smoke: {role} child failed "
                                 f"(rc={proc.returncode})")
            for line in proc.stdout.splitlines():
                if line.startswith("SMOKE_JSON:"):
                    results[role] = json.loads(line[len("SMOKE_JSON:"):])
    capt, load = results["capture"], results["load"]
    assert load["trace_count"] == 0, load
    assert load["losses"] == capt["losses"], \
        f"loss drift: live {capt['losses']} vs loaded {load['losses']}"
    assert load["tokens"] == capt["tokens"], \
        f"token drift: live {capt['tokens']} vs loaded {load['tokens']}"
    assert capt["remat_policy"] not in (None, "none"), \
        f"remat search stayed on the default: {capt['remat_policy']!r}"
    assert "remat_search" in capt["passes"] and \
        "sharding_retarget" in capt["passes"], capt["passes"]
    print("export smoke OK: 3-step loss parity "
          f"{load['losses'][0]:.6f}.., tokens {load['tokens'][:6]}.., "
          f"loaded trace_count=0, remat winner {capt['remat_policy']!r}")


if __name__ == "__main__":
    main()
