#!/usr/bin/env python
"""Multi-host job launcher (parity: `tools/launch.py:72-109` of the
reference — dmlc_tracker's `local|ssh|mpi` launchers).

TPU-native mapping: there are no parameter servers; each launched worker is
a JAX process in a multi-controller job. The launcher exports the
environment `jax.distributed.initialize` reads:

    MXTPU_COORDINATOR   (≈ DMLC_PS_ROOT_URI:PORT)
    MXTPU_NUM_PROCESSES (≈ DMLC_NUM_WORKER)
    MXTPU_PROCESS_ID    (rank)

- `--launcher local` spawns N copies of the command on this machine (the
  reference's single-machine multi-process test trick,
  `tests/nightly/test_distributed_training-gpu.sh:25-38`).
- `--launcher ssh -H hostfile` prints/execs ssh commands per host.
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys


def launch_local(n, command, port=29500):
    procs = []
    for rank in range(n):
        env = dict(os.environ)
        env["MXTPU_COORDINATOR"] = f"127.0.0.1:{port}"
        env["MXTPU_NUM_PROCESSES"] = str(n)
        env["MXTPU_PROCESS_ID"] = str(rank)
        # legacy names so reference scripts keep working
        env["DMLC_ROLE"] = "worker"
        env["DMLC_NUM_WORKER"] = str(n)
        env["DMLC_WORKER_ID"] = str(rank)
        procs.append(subprocess.Popen(command, shell=True, env=env))
    # a failed worker must not leave siblings wedged in a collective: kill
    # the remaining workers as soon as any worker exits nonzero
    import time
    rc = 0
    pending = set(procs)
    while pending:
        for p in list(pending):
            code = p.poll()
            if code is None:
                continue
            pending.discard(p)
            rc |= code
            if code != 0:
                for q in pending:
                    q.terminate()
        time.sleep(0.1)
    return rc


def launch_ssh(hosts, n, command, port=29500, dry_run=False):
    coordinator = f"{hosts[0]}:{port}"
    procs = []
    for rank in range(n):
        host = hosts[rank % len(hosts)]
        env = (f"MXTPU_COORDINATOR={coordinator} "
               f"MXTPU_NUM_PROCESSES={n} MXTPU_PROCESS_ID={rank}")
        cmd = f"ssh -o StrictHostKeyChecking=no {host} '{env} {command}'"
        if dry_run:
            print(cmd)
        else:
            procs.append(subprocess.Popen(cmd, shell=True))
    rc = 0
    for p in procs:
        rc |= p.wait()
    return rc


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("--launcher", choices=["local", "ssh"], default="local")
    ap.add_argument("-H", "--hostfile", default=None)
    ap.add_argument("-p", "--port", type=int, default=29500)
    ap.add_argument("--dry-run", action="store_true")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    command = " ".join(args.command)
    if not command:
        ap.error("no command given")
    if args.launcher == "local":
        sys.exit(launch_local(args.num_workers, command, args.port))
    hosts = [l.strip() for l in open(args.hostfile) if l.strip()]
    sys.exit(launch_ssh(hosts, args.num_workers, command, args.port,
                        args.dry_run))


if __name__ == "__main__":
    main()
