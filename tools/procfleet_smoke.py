#!/usr/bin/env python
"""Process-fleet smoke (`make procfleet-smoke`, wired into `make test`).

CPU-only, <60 s end-to-end check of the PROCESS transport
(docs/serving.md "Process fleet"): real `serve.worker` OS processes
behind the wire RPC protocol, under chaos:

- **2 process replicas** spawned from a spec dir, serving staggered
  mixed-length streaming load over length-prefixed JSON frames;
- **dropped control frames**: ``rpc_send`` / ``rpc_recv`` fault points
  armed mid-run (``MXTPU_FAULT_SPEC``) — the wire client's
  retry-with-backoff plus worker-side rid dedupe must absorb them with
  zero dropped requests and zero double-submissions;
- **one worker is SIGKILLed mid-stream** — no scheduler survives to
  salvage, so failover MUST come from the router's stream ledger: the
  emitted tokens fold into the re-prefill prefix and every greedy
  stream resumes **bit-identical** on the survivor, never re-emitting
  a token (streams are compared exactly, not as sets);
- the killed replica **respawns** under ``MXTPU_REPLICA_RESPAWNS`` (a
  ``replica_respawn`` journal event, same name, generation + 1);
- the OTHER replica is then **drained over the wire** (queued work
  handed back, actives finished, clean worker exit) — leaving only the
  respawned worker, which must serve a fresh batch alone: proof the
  reborn replica takes traffic again.
"""
from __future__ import annotations

import json
import os
import signal
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    t_start = time.time()
    journal_path = os.path.join(
        tempfile.mkdtemp(prefix="mxtpu_procfleet_smoke_"), "journal.jsonl")

    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry as tele
    from mxnet_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from mxnet_tpu.serve import ServeConfig, ServeFleet

    tele.enable(journal_path=journal_path)

    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_heads=4, intermediate_size=64, max_position=64,
                    dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.initialize()
    model(mx.np.array([[1, 2]], dtype="int32"))

    rng = onp.random.RandomState(23)
    max_new = 12
    n_req = 8       # phase A (chaos) load
    n_post = 4      # phase C (respawned-replica-alone) load
    prompts = [rng.randint(0, 96, rng.randint(2, 13)).tolist()
               for _ in range(n_req + n_post)]

    # unbatched references (the oracle): one generate() per request
    refs = []
    for p in prompts:
        ids = mx.np.array([p], dtype="int32")
        refs.append(onp.asarray(
            model.generate(ids, max_new_tokens=max_new)
            .asnumpy())[0].tolist())

    sc = ServeConfig(max_slots=2, page_size=4, num_pages=0,
                     prefill_chunk=4, max_len=32)
    fleet = ServeFleet(model, replicas=2, config=sc, transport="process",
                       respawn_budget=2, stall_timeout=15.0)
    assert all(r.transport == "process" for r in fleet.replicas)
    fleet.warmup()
    assert all(r.pid is not None and r.pid != os.getpid()
               for r in fleet.replicas), "workers must be real processes"

    streams = {i: [] for i in range(len(prompts))}

    def tok_cb(i):
        return lambda t, r: streams[i].append(t)

    # ---- phase A: chaos load — dropped frames + SIGKILL mid-stream ----
    # arm AFTER warmup so spawn RPCs keep deterministic hit counts: the
    # 3rd control send and 5th control receive are dropped mid-load; the
    # wire client must retry and the worker must dedupe the re-sent rid
    os.environ["MXTPU_FAULT_SPEC"] = "rpc_send@3,rpc_recv@5"
    try:
        fleet.start()
        handles = {}
        for i in range(n_req):
            handles[i] = fleet.submit(prompts[i], max_new_tokens=max_new,
                                      on_token=tok_cb(i))

        # wait until the target worker holds a request WITH streamed
        # progress — the hardest failover shape: the ledger must fold
        # those tokens into the re-prefill prefix, not replay them
        victim = fleet.replicas[0]
        victim_pid = victim.pid
        deadline = time.time() + 30
        while time.time() < deadline:
            sched = victim.engine.scheduler
            with sched._lock:
                progressed = any(e.req.tokens for e in
                                 sched._ledger.values())
            if progressed:
                break
            time.sleep(0.002)
        assert progressed, "victim never held a progressed stream"
        os.kill(victim_pid, signal.SIGKILL)

        # the supervisor/reader must declare it dead, fail the streams
        # over from the ledger, and respawn within the budget
        deadline = time.time() + 30
        while fleet.respawns == 0 and time.time() < deadline:
            time.sleep(0.005)
        assert fleet.deaths >= 1, "SIGKILL never detected"
        assert fleet.respawns >= 1, "killed worker never respawned"
        deadline = time.time() + 30
        while time.time() < deadline:
            reborn = fleet._rep(victim.name)
            if reborn is not victim and reborn.state == "running":
                break
            time.sleep(0.005)
        assert reborn.generation == victim.generation + 1
        assert reborn.pid not in (victim_pid, None, os.getpid())

        # ---- zero dropped requests, bit-identical streams ------------
        for i in range(n_req):
            got = handles[i].result(timeout=60)
            assert got == refs[i], (
                f"request {i}: fleet output diverged from single-request "
                f"generate\n  got {got}\n  ref {refs[i]}")
            assert streams[i] == refs[i][len(prompts[i]):], (
                f"request {i}: streamed tokens diverged (re-emission or "
                f"loss): {streams[i]} vs {refs[i][len(prompts[i]):]}")
        failovers = sum(handles[i].failovers for i in range(n_req))
        assert failovers >= 1, (
            "the SIGKILLed worker was expected to fail over >= 1 "
            "in-flight request")

        # ---- phase B: drain the surviving ORIGINAL over the wire -----
        other = next(r for r in fleet.replicas if r is not reborn)
        assert fleet.drain(other.name, timeout=45), "wire drain timed out"
        assert other.state == "drained", other.state
        assert other.proc.wait(timeout=15) == 0, (
            "drained worker should exit cleanly")

        # ---- phase C: the respawned worker serves ALONE --------------
        for i in range(n_req, n_req + n_post):
            handles[i] = fleet.submit(prompts[i], max_new_tokens=max_new,
                                      on_token=tok_cb(i))
        for i in range(n_req, n_req + n_post):
            got = handles[i].result(timeout=60)
            assert got == refs[i], (
                f"post-respawn request {i} diverged:\n  got {got}\n  "
                f"ref {refs[i]}")
            assert streams[i] == refs[i][len(prompts[i]):], i
        assert reborn.engine.scheduler.inflight == 0

        # the armed drops must actually have fired (otherwise this smoke
        # proved nothing about frame loss) and the wire client must have
        # healed them by retrying
        from mxnet_tpu.resilience import fault_registry
        assert fault_registry().hits("rpc_send") >= 3, (
            "rpc_send fault point never reached its armed hit")
        assert fault_registry().hits("rpc_recv") >= 5, (
            "rpc_recv fault point never reached its armed hit")
        wire_retries = sum(
            r._control.retried for r in (reborn, other)
            if r._control is not None) + (
            victim._control.retried if victim._control else 0)
        assert wire_retries >= 1, "dropped frames were never retried"
    finally:
        os.environ.pop("MXTPU_FAULT_SPEC", None)
        fleet.close()

    # ---- telemetry / journal contract --------------------------------
    snap = tele.snapshot()
    deaths = snap["serve_replica_deaths_total"]["series"]
    assert sum(s["value"] for s in deaths) == fleet.deaths
    respawn_metric = snap["serve_replica_respawns_total"]["series"]
    assert sum(s["value"] for s in respawn_metric) == fleet.respawns
    finished = [s for s in snap["serve_requests_total"]["series"]
                if s["labels"]["state"] == "finished"]
    assert finished and finished[0]["value"] == n_req + n_post, finished
    rows = tele.RunJournal.read(journal_path)
    rphases = {r.get("phase") for r in rows if r.get("event") == "replica"}
    for needed in ("started", "dead", "respawned", "draining", "drained"):
        assert needed in rphases, f"journal missing replica phase {needed}"
    respawn_rows = [r for r in rows if r.get("event") == "replica_respawn"]
    assert respawn_rows, "journal missing replica_respawn event"
    assert respawn_rows[0].get("transport") == "process", respawn_rows
    assert respawn_rows[0].get("generation") == 1, respawn_rows

    elapsed = time.time() - t_start
    print(json.dumps({
        "procfleet_smoke": "ok", "requests": n_req + n_post,
        "deaths": fleet.deaths, "respawns": fleet.respawns,
        "failovers": failovers, "drained": other.name,
        "elapsed_s": round(elapsed, 1)}))
    assert elapsed < 60, f"smoke took {elapsed:.0f}s (budget 60s)"
    return 0


if __name__ == "__main__":
    sys.exit(main())
