#!/usr/bin/env python
"""Parse training logs into a table (parity: `tools/parse_log.py`).

Understands the LoggingHandler/estimator format
(`[Epoch N] ... metric: value`) and speedometer-style
`Epoch[N] Batch [M] Speed: S samples/sec` lines.
"""
from __future__ import annotations

import argparse
import re
import sys

EPOCH_RE = re.compile(r"\[?Epoch[\s\[](\d+)\]?")
METRIC_RE = re.compile(r"([\w\- ]+):\s*([-+0-9.eE]+)")
SPEED_RE = re.compile(r"Speed[:=]\s*([0-9.]+)")


def parse(lines):
    rows = {}
    for line in lines:
        m = EPOCH_RE.search(line)
        if not m:
            continue
        epoch = int(m.group(1))
        row = rows.setdefault(epoch, {})
        sp = SPEED_RE.search(line)
        if sp:
            row.setdefault("speeds", []).append(float(sp.group(1)))
        for name, value in METRIC_RE.findall(line):
            name = name.strip().lower()
            if name in ("epoch", "batch", "samples"):
                continue
            try:
                row[name] = float(value)
            except ValueError:
                pass
    return rows


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("logfile", nargs="?", default="-")
    ap.add_argument("--format", choices=["table", "csv"], default="table")
    args = ap.parse_args()
    lines = sys.stdin if args.logfile == "-" else open(args.logfile)
    rows = parse(lines)
    if not rows:
        print("no epochs found", file=sys.stderr)
        return
    cols = sorted({k for r in rows.values() for k in r if k != "speeds"})
    header = ["epoch"] + cols + ["avg_speed"]
    sep = "," if args.format == "csv" else "\t"
    print(sep.join(header))
    for epoch in sorted(rows):
        r = rows[epoch]
        speeds = r.get("speeds", [])
        avg = sum(speeds) / len(speeds) if speeds else ""
        print(sep.join([str(epoch)] + [str(r.get(c, "")) for c in cols]
                       + [str(avg)]))


if __name__ == "__main__":
    main()
