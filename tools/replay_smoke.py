#!/usr/bin/env python
"""Flight-recorder smoke (`make replay-smoke`, wired into `make test`):
the end-to-end incident loop on CPU in under a minute.

1. generate a seeded bursty shared-prefix workload trace,
2. serve it on a 2-replica fleet with a traffic journal, a tight TTFT
   SLO, and a mid-burst replica kill — the burn alert fires during the
   live drive and auto-writes an incident capsule,
3. replay the capsule window on a fresh fleet: every greedy stream
   must reproduce its recorded token digest bit-for-bit AND the same
   SLO objective must re-enter burn during replay,
4. `tools/diagnose.py --capsule` renders the capsule with rc 0.

Everything asserted here is the docs/serving.md "Flight recorder &
replay" contract; a failure means an incident captured in production
could not be reproduced from its own capsule.
"""
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

t_start = time.time()
workdir = tempfile.mkdtemp(prefix="mxtpu_replay_smoke_")

# env BEFORE mxnet_tpu import: CPU backend, traffic journal, capsule
# sink with a short post-alert window, and a TTFT objective tight
# enough that the bursty drive (queue pileup on max_slots=2) plus the
# replica kill always push it into burn on CPU
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["MXTPU_TRAFFIC_JOURNAL"] = os.path.join(workdir,
                                                   "traffic.jsonl")
os.environ["MXTPU_CAPSULE_DIR"] = os.path.join(workdir, "capsules")
os.environ["MXTPU_CAPSULE_WINDOW_S"] = "120"
os.environ["MXTPU_CAPSULE_POST_S"] = "1"
SLO_SPEC = {"objectives": [
    {"name": "ttft_burst", "signal": "ttft_ms", "threshold": 25.0,
     "target": 0.9, "fast_s": 10, "slow_s": 20, "burn": 1.0,
     "min_events": 3}]}
os.environ["MXTPU_SLO_SPEC"] = json.dumps(SLO_SPEC)

import mxnet_tpu as mx                                    # noqa: E402
from mxnet_tpu import telemetry as tele                   # noqa: E402
from mxnet_tpu import tracing                             # noqa: E402
from mxnet_tpu.models.gpt import GPTConfig, GPTForCausalLM  # noqa: E402
from mxnet_tpu.serve import (                             # noqa: E402
    ServeConfig, ServeFleet, WorkloadSpec, generate_workload,
    read_capsule, replay_trace, write_trace)
from mxnet_tpu.serve import traffic as traffic_mod        # noqa: E402
from mxnet_tpu.serve.replay import replay_capsule         # noqa: E402

tele.enable(journal_path=os.path.join(workdir, "telemetry.jsonl"))
tracing.enable()

# -- 1. deterministic bursty shared-prefix workload -------------------------
spec = WorkloadSpec(seed=20260807, requests=20, rate_rps=80.0,
                    burst_factor=4.0, burst_period_s=2.0, burst_duty=0.5,
                    vocab=96, prompt_min=3, prompt_max=10,
                    output_mu=1.6, output_sigma=0.4, output_min=3,
                    output_max=8, prefix_families=2, prefix_len=4,
                    prefix_frac=0.6)
rows = generate_workload(spec)
rows2 = generate_workload(spec)
assert json.dumps(rows) == json.dumps(rows2), \
    "generator is not a pure function of its seed"
trace_path = write_trace(rows, os.path.join(workdir, "workload.jsonl"),
                         spec)
print(f"[1/4] generated {len(rows)} arrivals over "
      f"{rows[-1]['ts_mono']:.2f}s of trace time -> {trace_path}")

# -- 2. live incident: bursty drive + mid-burst kill ------------------------
model = GPTForCausalLM(GPTConfig(
    vocab_size=96, hidden_size=32, num_layers=1, num_heads=4,
    intermediate_size=64, max_position=64, dropout=0.0))
model.initialize()
model(mx.np.array([[1, 2]], dtype="int32"))

fleet = ServeFleet(model, replicas=2,
                   config=ServeConfig(max_slots=2, page_size=4,
                                      num_pages=0, prefill_chunk=4,
                                      max_len=32),
                   stall_timeout=5.0, supervise_interval=0.05)
with fleet:
    live = replay_trace(fleet, trace_path, speed=0.0, kill_at=0.02,
                        timeout=120.0, wait_slo_s=15.0)
    assert live["replay_failed"] == [], live["replay_failed"]
    assert live["kill"] is not None, "chaos kill never fired"
    assert fleet.deaths == 1, f"expected 1 replica death, {fleet.deaths}"
    assert live["slo_alert_refired"], \
        "SLO burn alert did not fire during the live incident"
    t0 = time.perf_counter()
    while not fleet.capsules and time.perf_counter() - t0 < 10.0:
        time.sleep(0.05)
    assert fleet.capsules, "burn alert did not auto-write a capsule"
# fleet.close() force-finalized pending capsules
capsule = fleet.capsules[0]
cap = read_capsule(capsule)
assert cap["finalized"], "capsule traffic window was not finalized"
assert cap["slo"] == "ttft_burst"
assert cap["arrivals"], "capsule captured no traffic"
n_digests = sum(1 for o in cap["outcomes"].values() if o.get("digest"))
assert n_digests > 0, "capsule has no recorded stream digests"
for fname in ("metrics.json", "trace.json", "journal_tail.jsonl",
              os.path.join("spec", "config.json")):
    assert os.path.exists(os.path.join(capsule, fname)), \
        f"capsule missing {fname}"
print(f"[2/4] live incident captured: kill at {live['kill']['at_s']}s "
      f"on {live['kill']['replica']}, alert fired, capsule {capsule} "
      f"({len(cap['arrivals'])} arrivals, {n_digests} digests)")

# -- 3. replay the capsule: digests bit-identical, alert re-fires -----------
tele.disable()          # the replay enables its own telemetry plane
tracing.disable()
traffic_mod.disable()   # stop journaling live traffic into the capture
report = replay_capsule(capsule, speed=0.0, timeout=120.0,
                        wait_slo_s=15.0)
assert report["ok"], {
    "divergent": report["divergent"], "failed": report["replay_failed"]}
assert report["matched"], "no digest was verifiable in replay"
assert report["divergent"] == [], report["divergent"]
assert report["slo_alert_refired"], \
    "SLO objective did not re-enter burn during capsule replay"
print(f"[3/4] capsule replayed: {len(report['matched'])} greedy "
      f"streams bit-identical to the recording, 0 divergent, "
      f"'{report['slo_recorded']}' re-fired in replay")

# -- 4. diagnose renders the capsule --------------------------------------
env = dict(os.environ)
env.pop("MXTPU_SLO_SPEC", None)
proc = subprocess.run(
    [sys.executable,
     os.path.join(os.path.dirname(__file__), "diagnose.py"),
     "--capsule", capsule],
    capture_output=True, text=True, timeout=120, env=env)
assert proc.returncode == 0, proc.stderr
assert "incident capsule" in proc.stdout
assert "ttft_burst" in proc.stdout
print("[4/4] diagnose --capsule rendered (rc 0)")

elapsed = time.time() - t_start
print(json.dumps({
    "requests": len(rows),
    "capsule": capsule,
    "capsule_arrivals": len(cap["arrivals"]),
    "digests_recorded": n_digests,
    "replay_matched": len(report["matched"]),
    "replay_divergent": len(report["divergent"]),
    "alert_refired": report["slo_alert_refired"],
    "elapsed_s": round(elapsed, 1),
}))
assert elapsed < 60, f"replay smoke exceeded budget: {elapsed:.1f}s"
print("REPLAY SMOKE PASS")
