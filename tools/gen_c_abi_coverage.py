"""Generate docs/c_abi_coverage.md: map every reference `MX*` C-API
function (include/mxnet/c_api.h) to its status in this framework
(VERDICT r4 item 7).

Statuses:
  covered   — an `MXTPU*` equivalent exists in cpp-package/src/c_api.cc
  subsumed  — capability delivered by the runtime design (XLA/PjRt/jit);
              the mapped mechanism is named
  variant   — per-dtype/64-bit/extended spelling of a covered family
  non-goal  — CUDA/TVM/profiler-daemon surfaces that have no meaning on
              this runtime, or deprecated entry points

Run: python tools/gen_c_abi_coverage.py  (rewrites the doc in place).
"""
from __future__ import annotations

import os
import re

REF = "/root/reference/include/mxnet/c_api.h"
OUT = os.path.join(os.path.dirname(__file__), "..", "docs",
                   "c_abi_coverage.md")
OURS = os.path.join(os.path.dirname(__file__), "..", "cpp-package", "src",
                    "c_api.cc")

# Explicit mapping rules, checked in order (first match wins).
# (regex on the reference name, status, mapping/reason)
RULES = [
    # --- deprecated / legacy-doc'd entry points --------------------------
    (r".*(Ex64|64\b|64$)", "variant",
     "64-bit index spelling; the MXTPU ABI is 64-bit-native (int64_t "
     "lens throughout)"),
    (r"MXSymbolCreateAtomicSymbol|MXSymbolGetAtomicSymbolInfo|"
     r"MXSymbolListAtomicSymbolCreators|MXSymbolGetAtomicSymbolName",
     "covered", "MXTPUSymbolCreateFromOp/MXTPUListOps (registry-backed "
     "op construction)"),
    (r"MXNDArrayCreateNone|MXNDArrayCreate\b|MXNDArrayCreateEx",
     "covered", "MXTPUNDArrayCreate"),
    (r"MXNDArrayCreateSparseEx", "non-goal",
     "sparse storage is the scoped Python-side subset (SURVEY §7); no C "
     "sparse surface"),
    (r"MXNDArrayLoadFromRawBytes|MXNDArraySaveRawBytes", "covered",
     "MXTPUNDArraySave/Load (binary .params wire format)"),
    (r"MXNDArraySyncCopyFromNDArray", "covered",
     "MXTPUInvoke(\"copyto\") — op-level device copy"),
    (r"MXNDArraySyncCopy(From|To)CPU", "covered",
     "MXTPUNDArrayCreateEx (copy-in) / MXTPUNDArrayCopyTo (copy-out)"),
    (r"MXNDArraySyncCheckFormat", "non-goal", "sparse-format validation"),
    (r"MXNDArrayWaitToRead|MXNDArrayWaitToWrite", "subsumed",
     "PjRt orders by dataflow; MXTPUWaitAll is the barrier"),
    (r"MXNDArrayWaitAll", "covered", "MXTPUWaitAll"),
    (r"MXNDArrayFree", "covered", "MXTPUNDArrayFree"),
    (r"MXNDArraySlice|MXNDArrayAt", "covered",
     "MXTPUInvoke(\"slice\"/\"slice_axis\") — op-level view"),
    (r"MXNDArrayReshape", "covered", "MXTPUInvoke(\"reshape\")"),
    (r"MXNDArrayGetShape", "covered", "MXTPUNDArrayShape"),
    (r"MXNDArrayGetData", "covered",
     "MXTPUNDArrayCopyTo (XLA buffers are not raw-pointer aliasable; "
     "reads copy out)"),
    (r"MXNDArrayGetDType", "covered", "MXTPUNDArrayDType"),
    (r"MXNDArrayGetContext", "subsumed",
     "one logical device per process; device identity is Python-side"),
    (r"MXNDArrayGetStorageType", "subsumed",
     "always dense on this runtime (kDefaultStorage)"),
    (r"MXNDArrayGetAuxType|MXNDArrayGetAuxNDArray|MXNDArrayGetDataNDArray",
     "non-goal", "sparse aux accessors"),
    (r"MXNDArrayGetGrad", "covered", "MXTPUNDArrayGetGrad"),
    (r"MXNDArrayDetach", "variant",
     "of the MXTPUNDArrayAttachGrad/GetGrad autograd family (detach = "
     "handle copy outside recording)"),
    (r"MXNDArraySetGradState|MXNDArrayGetGradState", "covered",
     "MXTPUAutogradRecordBegin/RecordEnd (state rides the tape)"),
    (r"MXNDArray.*DLPack|MXNDArray.*Dltensor", "subsumed",
     "DLPack interop is Python-side mx.dlpack (jax.dlpack under the "
     "hood); no C-level capsule surface"),
    (r"MXNDArray.*", "covered",
     "MXTPUNDArray* family (create/free/copy/shape/dtype/eval)"),
    # --- autograd / imperative -------------------------------------------
    (r"MXAutograd.*|MXImperative.*|MXCachedOp.*|MXInvokeCachedOp.*|"
     r"MXCreateCachedOp.*|MXFreeCachedOp.*",
     "covered", "MXTPUAutogradRecordBegin/RecordEnd/Backward + MXTPUInvoke "
     "+ MXTPUModelForward (tape + jit cache)"),
    # --- symbol -----------------------------------------------------------
    (r"MXSymbolCutSubgraph|MXGenAtomicSymbolFromSymbol|MXGenBackendSubgraph|"
     r"MXOptimizeForBackend|MXBuildSubgraphByOpNames|MXSetSubgraphPropertyOpNames.*|"
     r"MXRemoveSubgraphPropertyOpNames.*",
     "subsumed", "graph partitioning is the jaxpr SubgraphBackend "
     "(mxnet_tpu/subgraph); XLA does pass-level rewriting"),
    (r"MXSymbolInferShape.*|MXSymbolInferType.*", "subsumed",
     "MXTPUSymbolEval concretizes shapes; Symbol.infer_shape serves "
     "queries Python-side"),
    (r"MXSymbol.*|MXQuantizeSymbol|MXReducePrecisionSymbol.*|MXSetCalibTableToQuantizedSymbol",
     "covered", "MXTPUSymbol* family (create/compose/attr/json/eval); "
     "quantization via Python mx.contrib.quantization"),
    # --- executor ----------------------------------------------------------
    (r"MXExecutor.*", "subsumed",
     "legacy executor collapses into jit cache; C surface is "
     "MXTPUSymbolEval + MXTPUModelForward"),
    # --- IO / datasets ------------------------------------------------------
    (r"MXListDataIters|MXDataIterCreateIter|MXDataIterGetIterInfo|"
     r"MXDataIterFree|MXDataIterNext|MXDataIterBeforeFirst|"
     r"MXDataIterGetData|MXDataIterGetLabel|MXDataIterGetIndex|"
     r"MXDataIterGetPadNum",
     "covered", "MXTPUDataIter* family (MNIST/ImageRecord/CSV/LibSVM/"
     "NDArray iterators over the C++ io library)"),
    (r"MXDataIter.*|MXListDatasets|MXDatasetCreateDataset|MXDatasetFree|"
     r"MXDatasetGetLen|MXDatasetGetItems|MXListDatasetLoaders|"
     r"MXDatasetLoaderCreate.*",
     "variant", "2.x C dataset handles; the MXTPUDataIter* family plus "
     "Python gluon.data cover the capability"),
    # --- KVStore ------------------------------------------------------------
    (r"MXInitPSEnv|MXKVStoreRunServer|MXKVStoreSendCommmandToServers|"
     r"MXKVStoreGetGroupSize|MXKVStoreGetRank|MXKVStoreSetBarrierBeforeExit|"
     r"MXKVStoreBarrier|MXKVStoreIsWorkerNode|MXKVStoreIsServerNode|"
     r"MXKVStoreIsSchedulerNode",
     "subsumed", "no parameter-server role split: GSPMD collectives over "
     "jax.distributed (mxnet_tpu/kvstore dist store)"),
    (r"MXKVStore.*", "covered",
     "MXTPUKVStore* family (create/init/push/pull/rank/numworkers)"),
    # --- profiler / process -------------------------------------------------
    (r"MXSetProcessProfilerConfig|MXSetProfilerConfig|MXSetProcessProfilerState|"
     r"MXSetProfilerState|MXDumpProcessProfile|MXDumpProfile|"
     r"MXAggregateProfileStatsPrint.*|MXProcessProfilePause|MXProfilePause|"
     r"MXProfileCreateDomain|MXProfileCreateTask|MXProfileCreateFrame|"
     r"MXProfileCreateEvent|MXProfileCreateCounter|MXProfileDestroyHandle|"
     r"MXProfileDurationStart|MXProfileDurationStop|MXProfileSetCounter|"
     r"MXProfileAdjustCounter|MXProfileSetMarker|MXSetProfilerScope",
     "covered", "MXTPUProfilerStart/Stop/Dump (aggregate tables + chrome "
     "trace); fine-grained domain/task handles are Python mx.profiler"),
    # --- engine / threading -------------------------------------------------
    (r"MXEngine.*|MXSetNumOMPThreads|MXEngineSetBulkSize|"
     r"MXEnginePushAsync.*|MXEnginePushSync.*",
     "subsumed", "no user-visible dependency engine: XLA dataflow + PjRt "
     "streams (SURVEY §2.1 design rows)"),
    (r"MXShallowCopyNDArray|MXShallowCopySymbol", "subsumed",
     "handle copies are reference-counted Python objects"),
    # --- GPU / CUDA ---------------------------------------------------------
    (r".*(GPU|Gpu|Cuda|CUDA|NVTX|MKLDNN|OneDNN).*", "non-goal",
     "CUDA/oneDNN runtime surface; XLA:TPU owns kernels (SURVEY §2.1)"),
    (r"MXGetGPUCount|MXGetGPUMemoryInformation.*", "non-goal",
     "CUDA device query"),
    # --- libinfo / runtime ---------------------------------------------------
    (r"MXLibInfoFeatures|MXLibInfoCompiledWithCXX11ABI", "covered",
     "MXTPUFeatureIsEnabled"),
    (r"MXGetVersion", "covered", "MXTPUGetVersion"),
    (r"MXLoadLib", "subsumed",
     "extensions load Python-side (mx.library.load; native pieces dlopen "
     "through _native)"),
    (r"MXGetLastError", "covered", "MXTPUGetLastError"),
    (r"MXRandomSeed.*", "covered", "MXTPURandomSeed"),
    (r"MXNotifyShutdown", "covered", "MXTPUShutdown"),
    (r"MXSetFlag|MXGetFlag|MXSetIsNumpyShape|MXIsNumpyShape|"
     r"MXSetIsNumpyDefaultDtype|MXIsNumpyDefaultDtype",
     "covered", "MXTPUModelSetFlags/GetFlags + np-shape scope"),
    (r"MXGetEnv|MXSetEnv", "subsumed",
     "typed flags module (mx.utils.config) + process env Python-side"),
    (r"MXStorageEmptyCache", "subsumed",
     "XLA arena allocator; donation handles reuse (parallel/train.py)"),
    (r"MXGetOpHandle|MXListAllOpNames|MXGetAllOpNames", "covered",
     "MXTPUListOps"),
    (r"MXCustomOpRegister|MXCustomFunction.*|MXRtc.*|MXRtcCuda.*",
     "non-goal", "CUDA RTC / C custom-op shims; custom ops are Python "
     "pure_callback CustomOp"),
    (r"MXRecordIO.*", "covered",
     "recordio via the C++ io library (_native/io.cc) and MXTPUDataIter"),
    (r"MXOperator.*|MXOpAttr.*", "covered", "MXTPUListOps + Python "
     "operator registry introspection"),
    (r"MXQuantize.*|MXCalib.*", "covered",
     "int8 path: Python mx.contrib.quantization (quantize_net)"),
    (r"MXSparse.*", "non-goal", "C sparse surface (scoped Python subset)"),
    (r"MXTensor.*|MXPred.*", "non-goal",
     "C predict API superseded by MXTPUSymbolEval + CachedOp"),
    # --- remaining tail -----------------------------------------------------
    (r"MXSetFlushDenorms", "subsumed",
     "denormal handling is XLA's (TPUs flush denormals in hardware)"),
    (r"MXGetBranch|MXGetCommitHash", "covered",
     "MXTPUGetVersion carries the build identity string"),
    (r"MXLoadTVMOp|MXLoadTVMConfig", "non-goal",
     "TVM op bridge (documented non-goal, VERDICT §2.1)"),
    (r"MXListFunctions|MXGetFunction|MXFuncGetInfo|MXFuncDescribe|"
     r"MXFuncInvoke", "variant",
     "pre-NNVM legacy function table (deprecated in the reference "
     "itself); op calls go through MXTPUImperativeInvoke"),
    (r"MXDatasetGetDatasetInfo|MXListBatchifyFunctions|"
     r"MXBatchifyFunction.*", "variant",
     "2.x C batchify handles; batchify lives in Python gluon.data "
     "(batchify fns) over the C++ io library"),
    (r"MXCheckDynamicShapeOp", "subsumed",
     "dynamic-shape detection is trace-time in jax (ConcretizationError "
     "surfaces it); eager dynamic ops documented per-op"),
    (r"MXPushStreamDep|MXGetCurrentStream", "subsumed",
     "PjRt owns streams; no user-visible stream dependencies"),
    (r"MXSetOptimizeLayout|MXGetOptimizeLayout", "subsumed",
     "XLA layout assignment replaces oneDNN layout optimization"),
]


def classify(name):
    for pat, status, note in RULES:
        if re.fullmatch(pat, name):
            return status, note
    return None, None


def main():
    src = open(REF).read()
    names = re.findall(r"MXNET_DLL\s+int\s+(MX\w+)\s*\(", src)
    seen = set()
    ordered = [n for n in names if not (n in seen or seen.add(n))]
    ours = sorted(set(re.findall(r"(MXTPU\w+)\s*\(", open(OURS).read())))

    rows, counts = [], {}
    for n in ordered:
        status, note = classify(n)
        if status is None:
            status, note = "UNMAPPED", "!! needs a rule"
        counts[status] = counts.get(status, 0) + 1
        rows.append((n, status, note))

    with open(OUT, "w") as f:
        f.write(
"""# C ABI coverage ledger

Every `MX*` function exported by the reference's `include/mxnet/c_api.h`
(%d functions), mapped to its status in this framework's C ABI
(`cpp-package/src/c_api.cc`, %d `MXTPU*` functions + the RAII C++
header).  Generated by `tools/gen_c_abi_coverage.py` — regenerate after
ABI changes.

Status key: **covered** = MXTPU equivalent exists; **subsumed** =
capability delivered by the runtime design (the mechanism is named);
**variant** = per-dtype/64-bit/extended spelling of a covered family;
**non-goal** = CUDA/TVM/sparse-C surfaces with no meaning on this
runtime (documented decisions, SURVEY §2.1).

Tally: %s

| Reference function | Status | Mapping / reason |
|---|---|---|
""" % (len(ordered), len(ours),
       ", ".join(f"{k} {v}" for k, v in sorted(counts.items()))))
        for n, status, note in rows:
            f.write(f"| `{n}` | {status} | {note} |\n")
        f.write("\n## MXTPU* inventory\n\n")
        for n in ours:
            f.write(f"- `{n}`\n")
    unmapped = [r for r in rows if r[1] == "UNMAPPED"]
    print(f"{len(ordered)} functions, counts={counts}")
    if unmapped:
        print("UNMAPPED:")
        for n, _, _ in unmapped:
            print(" ", n)


if __name__ == "__main__":
    main()
