"""Generate the committed golden .onnx fixtures (VERDICT r4 item 4).

Four tiny models exported with fixed seeds; the exporter is
deterministic, so tests/unittest/test_onnx_goldens.py asserts fresh
exports reproduce these bytes (offline regression), and CI's
onnx-validate job runs the same fixtures through onnx.checker +
onnxruntime against the in-repo interpreter (the external oracle).
"""
from __future__ import annotations

import os

# hard-set BOTH (ambient shells carry JAX_PLATFORMS=axon; setdefault
# and config-only updates are silently overridden — docs/performance.md)
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as onp  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import onnx as monnx  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402

ROOT = os.path.join(os.path.dirname(__file__), "..", "tests", "fixtures",
                    "onnx")


def build_cases():
    """name -> (net, example input). Seeded for reproducible params."""
    onp.random.seed(7)
    mx.random.seed(7)

    mlp = nn.HybridSequential()
    mlp.add(nn.Dense(8, in_units=6, activation="relu"),
            nn.Dense(3, in_units=8))
    mlp.initialize()

    conv = nn.HybridSequential()
    conv.add(nn.Conv2D(4, kernel_size=3, padding=1, in_channels=2),
             nn.Activation("relu"),
             nn.MaxPool2D(pool_size=2),
             nn.Flatten(),
             nn.Dense(5, in_units=4 * 4 * 4))
    conv.initialize()

    norm = nn.HybridSequential()
    norm.add(nn.Dense(6, in_units=4), nn.BatchNorm(in_channels=6),
             nn.Activation("sigmoid"))
    norm.initialize()

    emb = nn.HybridSequential()
    emb.add(nn.Embedding(11, 5), nn.Dense(2, in_units=5, flatten=False))
    emb.initialize()

    return {
        "mlp": (mlp, mx.np.array(onp.random.rand(2, 6), dtype="float32")),
        "conv": (conv, mx.np.array(onp.random.rand(1, 2, 8, 8),
                                   dtype="float32")),
        "batchnorm": (norm, mx.np.array(onp.random.rand(3, 4),
                                        dtype="float32")),
        "embedding": (emb, mx.np.array(onp.array([[1, 4, 9]]),
                                       dtype="int32")),
    }


def main():
    os.makedirs(ROOT, exist_ok=True)
    for name, (net, x) in build_cases().items():
        path = os.path.join(ROOT, f"{name}.onnx")
        monnx.export_model(net, path, example_inputs=x)
        ref = net(x).asnumpy()
        onp.savez(os.path.join(ROOT, f"{name}.io.npz"),
                  x=x.asnumpy(), y=ref)
        print(f"{name}: {os.path.getsize(path)} bytes, out {ref.shape}")


if __name__ == "__main__":
    main()
