#!/usr/bin/env python
"""Elastic mesh-reformation smoke (`make elastic-smoke`,
docs/resilience.md "Elastic scale-out").

End-to-end proof that a multi-host-shaped job survives host loss AND
host join **without a process restart**, on CPU in well under a minute.
The multichip-dryrun trick (8 virtual CPU devices) simulates two hosts
of 4 devices each; the chaos sequence is:

1. a 30-step `ElasticLoop` + `ShardedTrainStep` (dp=4 × tp=2,
   ``zero=True`` so the 1-D bucket reshard path is exercised) trains
   with both hosts heartbeating;
2. at step 12 host ``h1`` is **killed** (its heartbeat stops): the
   `ElasticMeshController` detects the stale heartbeat, drains, re-forms
   the mesh at 4 devices (dp=2 × tp=2), and restores the **agreed step**
   (10 — the newest checkpoint) through the topology-agnostic restore
   path; training resumes and replays 11..13 (the unanimous-stale
   detection defers one window, so step 13 trains once pre-shrink);
3. at step 20 ``h1`` **rejoins**: a live gather→re-place grows the mesh
   back to 8 devices and training continues to 30 — with
   ``trace_count == 1`` on the final topology.

A separate **reference child** restores the same step-10 checkpoint on a
fresh dp=2 × tp=2 mesh and runs 11..20 uninterrupted: the elastic run's
post-shrink loss trajectory must match it **bit-for-bit** (same mesh →
same XLA program → identical floats).  Step continuity is asserted from
the per-attempt loss log: every step id 1..30 trained, none lost.

Pure stdlib on the parent side; exits non-zero with a reason on failure.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import zlib

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STEPS = 30
SAVE_EVERY = 5
KILL_AT = 12          # h1's heartbeat stops after this step completes
# a unanimous-stale round (the kill-sleep stales BOTH beats) defers one
# window, so the loss is named after the NEXT step trains
DETECT_AT = KILL_AT + 1
REJOIN_AT = 20
RESTORE_STEP = 10     # newest checkpoint when the loss lands
HEARTBEAT_S = 0.75   # generous: a loaded CI box must not fake a loss

IN_UNITS, UNITS, BATCH = 8, 16, 8


def _make_batch(i):
    """Deterministic batch for 1-based step id `i` — shared by both
    children so trajectories are comparable."""
    import numpy as onp
    rng = onp.random.RandomState(7)
    xs = rng.uniform(-1, 1, (BATCH, IN_UNITS)).astype("float32")
    ys = rng.uniform(-1, 1, (BATCH, UNITS)).astype("float32")
    return xs * (1 + 0.01 * i), ys


def _build_step(mesh):
    import numpy as onp
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import optimizer as opt
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import make_sharded_train_step

    net = nn.Dense(UNITS, in_units=IN_UNITS)
    net.initialize()
    for n, p in net.collect_params().items():
        v = onp.random.RandomState(
            zlib.crc32(n.encode()) % 2 ** 31).standard_normal(
                p.shape).astype("float32")
        p.set_data(mx.np.array(v))
        # tp-sharded bias: the exact 1-D leaf the ZeRO bucket covers
        p.sharding = ("tp",) if n.endswith("bias") else ("tp", None)
    return make_sharded_train_step(
        net, opt.Adam(learning_rate=1e-2),
        lambda out, x, y: jnp.mean((out - y) ** 2), mesh,
        num_model_args=1, zero=True)


def _child_elastic(ckpt_dir: str) -> int:
    import jax

    from mxnet_tpu.elastic import ElasticLoop
    from mxnet_tpu.parallel import ElasticMeshController, make_mesh
    from mxnet_tpu.parallel.train import _spec_axes

    devs = jax.devices()
    mesh = make_mesh({"dp": 4, "tp": 2}, devs[:8])
    step = _build_step(mesh)

    # the ZeRO acceptance check: every >=dp-element state leaf carries dp
    for n in step.diff_names:
        for leaf in jax.tree_util.tree_leaves(step.opt_state[n]):
            if leaf.ndim and leaf.size >= 4 and \
                    "dp" not in _spec_axes(leaf.sharding.spec):
                raise AssertionError(
                    f"ZeRO leaf not dp-sharded: {n} {leaf.shape}")

    ctl = ElasticMeshController(
        step, hosts={"h0": devs[:4], "h1": devs[4:8]},
        heartbeat_timeout_s=HEARTBEAT_S)
    loop = ElasticLoop(step, ckpt_dir, save_every=SAVE_EVERY, keep=16,
                       mesh_controller=ctl)

    losses: dict = {}
    meshes: dict = {}
    state = {"killed": False, "rejoined": False}

    def step_fn(i):
        x, y = _make_batch(i + 1)
        h = step.dispatch(x, y, rng_key=jax.random.PRNGKey(i + 1))
        losses.setdefault(i + 1, []).append(h.result())
        meshes.setdefault(i + 1, []).append(step.mesh.size)
        return h

    def on_step(i, _loss):
        ctl.heartbeat("h0")
        if not state["killed"] or state["rejoined"]:
            ctl.heartbeat("h1")
        if i == KILL_AT and not state["killed"]:
            state["killed"] = True          # h1 dies: no more heartbeats
            time.sleep(HEARTBEAT_S + 0.3)
        if i == REJOIN_AT and state["killed"] and not state["rejoined"]:
            state["rejoined"] = True
            ctl.request_join("h1")

    out = loop.run(step_fn, total_steps=STEPS, on_step=on_step)
    step.drain()
    print(json.dumps({
        "status": out["status"], "step": out["step"],
        "reforms": out["reforms"], "trace_count": step.trace_count,
        "final_axes": step.topology()["axes"],
        "hosts": ctl.hosts(),
        "losses": {str(k): v for k, v in losses.items()},
        "meshes": {str(k): v for k, v in meshes.items()},
    }))
    return 0


def _child_ref(ckpt_dir: str) -> int:
    """Uninterrupted reference: restore the step-10 checkpoint on a
    fresh shrunk mesh and run 11..20 — the trajectory the elastic run's
    post-shrink segment must reproduce bit-for-bit."""
    import jax
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.utils.checkpoint import CheckpointManager

    devs = jax.devices()
    mesh = make_mesh({"dp": 2, "tp": 2}, devs[:4])
    step = _build_step(mesh)
    mgr = CheckpointManager(ckpt_dir, keep=16)
    got = mgr.restore(step, step=RESTORE_STEP)
    assert got == RESTORE_STEP
    losses = {}
    for i in range(RESTORE_STEP + 1, REJOIN_AT + 1):
        x, y = _make_batch(i)
        h = step.dispatch(x, y, rng_key=jax.random.PRNGKey(i))
        losses[str(i)] = h.result()
    print(json.dumps({"losses": losses, "trace_count": step.trace_count}))
    return 0


def _read_journal(path):
    rows = []
    with open(path) as f:
        for line in f:
            if line.strip():
                rows.append(json.loads(line))
    return rows


def _fail(msg, extra=""):
    print(f"FAIL: {msg}", file=sys.stderr)
    if extra:
        print(extra[-4000:], file=sys.stderr)
    return 1


def _run_child(mode, ckpt_dir, env):
    here = os.path.abspath(__file__)
    proc = subprocess.run(
        [sys.executable, here, "--child", mode, ckpt_dir],
        env=env, capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.dirname(here)))
    if proc.returncode != 0:
        return None, proc
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1]), proc
    except (ValueError, IndexError):
        return None, proc


def main() -> int:
    if "--child" in sys.argv:
        mode = sys.argv[sys.argv.index("--child") + 1]
        ckpt = sys.argv[sys.argv.index("--child") + 2]
        return (_child_elastic if mode == "elastic" else _child_ref)(ckpt)

    workdir = tempfile.mkdtemp(prefix="mxtpu-elastic-smoke-")
    ckpt_dir = os.path.join(workdir, "ckpt")
    journal = os.path.join(workdir, "journal.jsonl")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (env.get("XLA_FLAGS", "")
                      + " --xla_force_host_platform_device_count=8"),
        "MXTPU_TELEMETRY": journal,
    })
    env.pop("MXTPU_FAULT_SPEC", None)

    result, proc = _run_child("elastic", ckpt_dir, env)
    if result is None:
        return _fail(f"elastic child failed (rc={proc.returncode})",
                     proc.stdout + proc.stderr)

    if result["status"] != "completed" or result["step"] != STEPS:
        return _fail(f"run did not complete: {result['status']} at "
                     f"{result['step']}", proc.stderr)
    if result["reforms"] != 2:
        return _fail(f"expected 2 reforms (shrink+grow), got "
                     f"{result['reforms']}", proc.stderr)
    if result["trace_count"] != 1:
        return _fail(f"final topology retraced: trace_count="
                     f"{result['trace_count']}")
    if result["final_axes"] != {"dp": 4, "tp": 2}:
        return _fail(f"mesh did not grow back: {result['final_axes']}")
    if result["hosts"] != {"h0": True, "h1": True}:
        return _fail(f"h1 not back in membership: {result['hosts']}")

    losses = {int(k): v for k, v in result["losses"].items()}
    meshes = {int(k): v for k, v in result["meshes"].items()}
    # step continuity: every id 1..30 trained at least once — a reform
    # may REPLAY steps (restore semantics) but must never skip a batch
    missing = [i for i in range(1, STEPS + 1) if i not in losses]
    if missing:
        return _fail(f"lost batches: steps {missing} never trained")
    # the shrink landed at the agreed step: 11..13 replayed on the small
    # mesh, 14..20 ran once, 21..30 ran once on the re-grown mesh
    for i in range(RESTORE_STEP + 1, DETECT_AT + 1):
        if len(losses[i]) != 2:
            return _fail(f"step {i} should have exactly 2 attempts "
                         f"(original + replay), got {len(losses[i])}")
    for i in range(DETECT_AT + 1, STEPS + 1):
        if len(losses[i]) != 1:
            return _fail(f"step {i} should have run once, got "
                         f"{len(losses[i])}")
    if not all(m == 4 for i in range(RESTORE_STEP + 1, REJOIN_AT + 1)
               for m in meshes[i][-1:]):
        return _fail("post-shrink steps did not run on the 4-device mesh")
    if not all(meshes[i][-1] == 8 for i in range(REJOIN_AT + 1, STEPS + 1)):
        return _fail("post-grow steps did not run on the 8-device mesh")

    # journal: one shrink (checkpoint restore) + one grow (live) reform
    rows = _read_journal(journal)
    reforms = [r for r in rows if r.get("event") == "mesh_reform"]
    if len(reforms) != 2:
        return _fail(f"expected 2 mesh_reform journal events, got "
                     f"{len(reforms)}")
    shrink, grow = reforms
    if shrink["kind"] != "shrink" or shrink["live"] or \
            shrink["step"] != RESTORE_STEP or \
            shrink["new_axes"] != {"dp": 2, "tp": 2}:
        return _fail(f"shrink reform event wrong: {shrink}")
    if grow["kind"] != "grow" or not grow["live"] or \
            grow["new_axes"] != {"dp": 4, "tp": 2}:
        return _fail(f"grow reform event wrong: {grow}")
    if not any(r.get("event") == "membership" for r in rows):
        return _fail("no membership journal events")
    if not any(r.get("event") == "checkpoint_cross_topology"
               for r in rows):
        return _fail("shrink restore did not cross topologies")

    # loss-trajectory equivalence: the post-shrink segment must be
    # BIT-identical to an uninterrupted run restored from the same
    # checkpoint on the same (shrunk) mesh
    env_ref = dict(env)
    env_ref["MXTPU_TELEMETRY"] = os.path.join(workdir, "ref.jsonl")
    ref, proc_ref = _run_child("ref", ckpt_dir, env_ref)
    if ref is None:
        return _fail(f"reference child failed (rc={proc_ref.returncode})",
                     proc_ref.stdout + proc_ref.stderr)
    for i in range(RESTORE_STEP + 1, REJOIN_AT + 1):
        got = losses[i][-1]             # the attempt on the shrunk mesh
        want = ref["losses"][str(i)]
        if got != want:
            return _fail(
                f"loss trajectory diverged from the clean run at step "
                f"{i}: elastic={got!r} ref={want!r}")

    print(f"elastic smoke OK: host loss @ {KILL_AT} -> shrink to "
          f"dp2xtp2 + resume @ {RESTORE_STEP}, rejoin @ {REJOIN_AT} -> "
          f"grow to dp4xtp2, completed @ {STEPS}; post-shrink losses "
          f"bit-identical to the clean run, trace_count=1 on the final "
          f"topology")
    return 0


if __name__ == "__main__":
    sys.exit(main())
