#!/usr/bin/env python
"""Serving-fleet smoke (`make fleet-smoke`, wired into `make test`).

CPU-only, <60 s end-to-end check of the fleet robustness tier
(docs/serving.md "Fleet, failover & overload"):

- **3 replicas** behind the `RequestRouter`, staggered mixed-length
  load streaming through all of them;
- **overload shedding is deterministic**: before the drivers start, a
  submit burst fills every replica's headroom and the bounded global
  queue — the shed counter must be ZERO until the bound is hit and the
  overflow submissions must raise `ShedError` (reason `queue_full`,
  with a retry-after hint);
- **one replica is killed mid-stream** via the `replica_step` fault
  point (``MXTPU_FAULT_SPEC``) — its in-flight requests fail over and
  must finish on survivors;
- **one replica is drained gracefully** while streams are active — it
  must exit with an EMPTY active set and hand queued work back;
- **zero dropped requests**: every request completes, and every
  streamed token sequence is **bit-identical** to an unbatched
  single-request `GPTForCausalLM.generate` run — eviction, failover,
  draining and shedding backpressure are all invisible to the output,
  and no token is ever re-emitted (streams are compared exactly, not
  as sets).
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    t_start = time.time()
    journal_path = os.path.join(
        tempfile.mkdtemp(prefix="mxtpu_fleet_smoke_"), "journal.jsonl")

    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry as tele
    from mxnet_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from mxnet_tpu.serve import ServeConfig, ServeFleet, ShedError

    tele.enable(journal_path=journal_path)

    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_heads=4, intermediate_size=64, max_position=64,
                    dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.initialize()
    model(mx.np.array([[1, 2]], dtype="int32"))

    rng = onp.random.RandomState(11)
    max_new = 12
    n_req = 14
    prompts = [rng.randint(0, 96, rng.randint(2, 13)).tolist()
               for _ in range(n_req)]

    # unbatched references (the oracle): one generate() per request
    refs = []
    for p in prompts:
        ids = mx.np.array([p], dtype="int32")
        refs.append(onp.asarray(
            model.generate(ids, max_new_tokens=max_new)
            .asnumpy())[0].tolist())

    sc = ServeConfig(max_slots=2, page_size=4, num_pages=0,
                     prefill_chunk=4, max_len=32)
    # tiny global queue bound so the overload phase can hit it with a
    # handful of requests
    queue_bound = 3
    fleet = ServeFleet(model, replicas=3, config=sc,
                       router_queue=queue_bound, stall_timeout=8.0)
    fleet.warmup()

    streams = {i: [] for i in range(n_req)}

    def tok_cb(i):
        return lambda t, r: streams[i].append(t)

    # ---- phase A: deterministic overload shedding --------------------
    # drivers are NOT running yet, so dispatch/parking is synchronous:
    # capacity before shedding = 3 replicas x max_slots(2) headroom in
    # local queues + the global bound.  Everything beyond that MUST shed
    # with reason queue_full — and nothing before it may.
    capacity = 3 * sc.max_slots + queue_bound          # 9
    handles, shed_errors = [], []
    for i, p in enumerate(prompts):
        try:
            handles.append(
                fleet.submit(p, max_new_tokens=max_new,
                             on_token=tok_cb(i)))
        except ShedError as e:
            handles.append(None)
            shed_errors.append((i, e))
            assert e.reason == "queue_full", e.reason
            assert e.retry_after_ms > 0, e.retry_after_ms
            assert len([h for h in handles if h is not None]) >= capacity, (
                f"shed fired at admission {i} BEFORE the fleet was at "
                f"capacity {capacity}")
    assert len(shed_errors) == n_req - capacity, (
        f"expected exactly {n_req - capacity} sheds past the bound, got "
        f"{len(shed_errors)}")
    snap = tele.snapshot()
    shed_metric = snap["serve_shed_total"]["series"]
    assert sum(s["value"] for s in shed_metric) == len(shed_errors)
    assert all(s["labels"]["reason"] == "queue_full"
               for s in shed_metric), shed_metric

    # ---- phase B: chaos — kill one replica mid-stream, drain another -
    # arm the fault AFTER phase A so hit counts are deterministic: the
    # 6th executed fused step across the fleet dies mid-stream (every
    # replica starts loaded, so whichever driver hits it holds active
    # streams — the hardest failover shape: ctx advanced past tokens
    # that never landed)
    os.environ["MXTPU_FAULT_SPEC"] = "replica_step@6"
    try:
        fleet.start()
        # resubmit the shed overflow as capacity frees up (the caller
        # retry loop the ShedError contract implies)
        pending = [(i, prompts[i]) for i, h in enumerate(handles)
                   if h is None]
        deadline = time.time() + 60
        while pending and time.time() < deadline:
            i, p = pending[0]
            try:
                handles[i] = fleet.submit(p, max_new_tokens=max_new,
                                          on_token=tok_cb(i))
                pending.pop(0)
            except ShedError as e:
                time.sleep(min(e.retry_after_ms, 50.0) / 1e3)
        assert not pending, f"overflow requests never admitted: {pending}"

        # wait for the injected death to be handled
        deadline = time.time() + 30
        while fleet.deaths == 0 and time.time() < deadline:
            time.sleep(0.005)
        assert fleet.deaths >= 1, "replica_step fault never killed a replica"
        dead = [r for r in fleet.replicas if r.state == "dead"]
        assert dead, [r.state for r in fleet.replicas]

        # drain one SURVIVING replica gracefully while work is live
        survivor = next(r for r in fleet.replicas if r.state == "running")
        drained_ok = fleet.drain(survivor.name, timeout=45)
        assert drained_ok, f"drain of {survivor.name} timed out"
        assert survivor.state == "drained", survivor.state
        assert survivor.engine.scheduler.active_count == 0, (
            "drained replica exited with a non-empty active set")

        # ---- zero dropped requests, bit-identical streams ------------
        for i, (h, ref) in enumerate(zip(handles, refs)):
            got = h.result(timeout=60)
            assert got == ref, (
                f"request {i}: fleet output diverged from single-request "
                f"generate\n  got {got}\n  ref {ref}")
            assert streams[i] == ref[len(prompts[i]):], (
                f"request {i}: streamed tokens diverged (re-emission or "
                f"loss): {streams[i]} vs {ref[len(prompts[i]):]}")
    finally:
        os.environ.pop("MXTPU_FAULT_SPEC", None)
        fleet.close()

    failovers = sum(h.failovers for h in handles)
    assert failovers >= 1, (
        "the killed replica was expected to fail over >= 1 in-flight "
        "request")

    # ---- telemetry / journal contract --------------------------------
    snap = tele.snapshot()
    deaths = snap["serve_replica_deaths_total"]["series"]
    assert sum(s["value"] for s in deaths) == fleet.deaths
    finished = [s for s in snap["serve_requests_total"]["series"]
                if s["labels"]["state"] == "finished"]
    assert finished and finished[0]["value"] == n_req, finished
    rows = tele.RunJournal.read(journal_path)
    rphases = {r.get("phase") for r in rows if r.get("event") == "replica"}
    for needed in ("started", "dead", "draining", "drained"):
        assert needed in rphases, f"journal missing replica phase {needed}"
    qphases = {r.get("phase") for r in rows if r.get("event") == "request"}
    for needed in ("submitted", "routed", "finished"):
        assert needed in qphases, f"journal missing request phase {needed}"
    assert any(r.get("event") == "shed" for r in rows)

    elapsed = time.time() - t_start
    print(json.dumps({
        "fleet_smoke": "ok", "requests": n_req,
        "sheds": len(shed_errors), "deaths": fleet.deaths,
        "failovers": failovers,
        "drained": survivor.name,
        "elapsed_s": round(elapsed, 1)}))
    assert elapsed < 60, f"smoke took {elapsed:.0f}s (budget 60s)"
    return 0


if __name__ == "__main__":
    sys.exit(main())
