#!/usr/bin/env python
"""Serving-stack smoke (`make serve-smoke`, wired into `make test`).

CPU-only, <60 s end-to-end check of the whole `mxnet_tpu/serve/` path:

- 8 concurrent requests with STAGGERED arrival and mixed prompt lengths
  run through the continuous-batching scheduler over a paged KV pool
  deliberately sized too small for all slots at full length — at least
  one sequence must be EVICTED mid-stream (pages recycled, request
  re-queued) and re-admitted (recompute prefill) before finishing;
- every request's streamed tokens must be IDENTICAL to an unbatched
  single-request `GPTForCausalLM.generate` run — continuous batching,
  chunked prefill, paged attention, eviction and re-admission are all
  invisible to the output;
- the telemetry snapshot must show populated per-request TTFT/latency
  histograms and page-occupancy/queue-depth gauges, and the run journal
  must carry the request lifecycle events (docs/serving.md).
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    t_start = time.time()
    journal_path = os.path.join(
        tempfile.mkdtemp(prefix="mxtpu_serve_smoke_"), "journal.jsonl")

    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry as tele
    from mxnet_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from mxnet_tpu.serve import InferenceEngine, ServeConfig

    tele.enable(journal_path=journal_path)

    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_heads=4, intermediate_size=64, max_position=64,
                    dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.initialize()
    model(mx.np.array([[1, 2]], dtype="int32"))

    rng = onp.random.RandomState(7)
    n_req, max_new = 8, 10
    prompts = [rng.randint(0, 96, rng.randint(2, 13)).tolist()
               for _ in range(n_req)]

    # unbatched references (the oracle): one generate() per request
    refs = []
    for p in prompts:
        ids = mx.np.array([p], dtype="int32")
        refs.append(onp.asarray(
            model.generate(ids, max_new_tokens=max_new)
            .asnumpy())[0].tolist())

    # pool sized for pressure: a full-length sequence (22 tokens at
    # page_size 3) needs 8 pages — exactly the allocatable pool — so any
    # two sequences whose decode phases overlap MUST collide and evict,
    # while every sequence still fits alone (re-admission always succeeds)
    sc = ServeConfig(max_slots=2, page_size=3, num_pages=9,
                     prefill_chunk=4, max_len=24)
    eng = InferenceEngine(model, sc)
    eng.warmup()

    streams = {i: [] for i in range(n_req)}
    handles = []
    for i, p in enumerate(prompts[:4]):     # initial burst
        handles.append(eng.submit(
            p, max_new_tokens=max_new,
            on_token=lambda t, r, i=i: streams[i].append(t)))
    arrivals = iter(enumerate(prompts[4:], start=4))
    steps = 0
    while True:
        progressed = eng.step()
        steps += 1
        if steps % 3 == 0:                   # staggered arrival
            nxt = next(arrivals, None)
            if nxt is not None:
                i, p = nxt
                handles.append(eng.submit(
                    p, max_new_tokens=max_new,
                    on_token=lambda t, r, i=i: streams[i].append(t)))
        if not progressed and len(handles) == n_req \
                and eng.scheduler.queue_depth == 0:
            break
        assert steps < 5000, "serve smoke did not converge"

    evictions = sum(h.evictions for h in handles)
    assert evictions >= 1, (
        f"expected >= 1 mid-stream eviction under page pressure, got "
        f"{evictions} (pool too large for the smoke's pressure scenario?)")

    for i, (h, ref) in enumerate(zip(handles, refs)):
        got = h.result(timeout=0)
        assert got == ref, (
            f"request {i}: batched output diverged from single-request "
            f"generate\n  got {got}\n  ref {ref}")
        assert streams[i] == ref[len(prompts[i]):], (
            f"request {i}: streamed tokens diverged: {streams[i]} vs "
            f"{ref[len(prompts[i]):]}")
        assert h.ttft_s is not None and h.latency_s is not None

    snap = tele.snapshot()
    ttft = snap.get("serve_ttft_ms")
    assert ttft and ttft["series"][0]["count"] == n_req, \
        f"TTFT histogram not populated for all requests: {ttft}"
    lat = snap.get("serve_request_latency_ms")
    assert lat and lat["series"][0]["count"] == n_req
    assert "serve_page_occupancy_ratio" in snap
    assert "serve_queue_depth" in snap
    assert snap["serve_evictions_total"]["series"][0]["value"] >= 1
    toks = snap["serve_tokens_generated_total"]["series"][0]["value"]
    assert toks == n_req * max_new, toks

    rows = tele.RunJournal.read(journal_path)
    phases = {r.get("phase") for r in rows if r.get("event") == "request"}
    for needed in ("submitted", "admitted", "first_token", "evicted",
                   "readmitted", "finished"):
        assert needed in phases, f"journal missing request phase {needed}"

    elapsed = time.time() - t_start
    print(json.dumps({
        "serve_smoke": "ok", "requests": n_req, "steps": steps,
        "evictions": evictions,
        "ttft_ms_count": ttft["series"][0]["count"],
        "elapsed_s": round(elapsed, 1)}))
    assert elapsed < 60, f"smoke took {elapsed:.0f}s (budget 60s)"
    return 0


if __name__ == "__main__":
    sys.exit(main())
