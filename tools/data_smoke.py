#!/usr/bin/env python
"""Deterministic data-pipeline smoke (`make data-smoke`, docs/data.md).

End-to-end proof of the three contracts the data subsystem makes, on CPU
in well under a minute:

1. **Fresh-process resume parity** — a training child consumes 12
   mixture+packed batches, checkpointing through a pipeline-attached
   `CheckpointManager` every 5 steps, then dies mid-epoch.  A SEPARATE
   process restores: the manager re-seeks the pipeline from the manifest
   (O(1), no replay) and the replayed stream (batch 11 onward) must be
   **bit-identical** to an uninterrupted reference child's.
2. **Elastic exactly-once** — the same global stream is consumed through
   a 1-host → 2-host → 1-host shrink/grow sequence (each phase re-slices
   the global batches via `set_hosts` from the carried `PipelineState`);
   the union of delivered samples must equal the uninterrupted reference
   stream exactly — zero lost, zero duplicated.
3. **Zero retraces** — packed batches have static shapes, so a jitted
   step fed through `DevicePrefetcher` over the pipeline traces exactly
   once across 8 steps.

Pure stdlib + the framework; exits non-zero with a reason on failure.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import zlib

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

SEED = 13
BATCH = 8          # global batch (rows after packing)
SEQ_LEN = 32
TOTAL = 20         # reference stream length (batches)
KILL_AT = 12       # training child dies after this many batches
SAVE_EVERY = 5     # -> newest checkpoint at batch 10

import numpy as onp  # noqa: E402


def _build_corpus(root: str):
    """Two corpora of indexed RecordIO shards; token payloads encode
    (corpus, doc) so samples are identifiable downstream."""
    from mxnet_tpu import recordio
    specs = {"a": [60, 60], "b": [50]}
    paths = {}
    for name, shard_sizes in specs.items():
        shards = []
        base = 0
        for s, count in enumerate(shard_sizes):
            rec = os.path.join(root, f"{name}-{s}.rec")
            idx = os.path.join(root, f"{name}-{s}.idx")
            w = recordio.MXIndexedRecordIO(idx, rec, "w")
            for i in range(count):
                doc_id = base + i
                toks = onp.full(1 + doc_id % 7,
                                (10000 if name == "b" else 0) + doc_id,
                                dtype=onp.int32)
                w.write_idx(i, toks.tobytes())
            w.close()
            shards.append((idx, rec))
            base += count
        paths[name] = shards
    return paths


def _packed_pipeline(root: str, num_hosts: int = 1, host_id: int = 0):
    from mxnet_tpu.data import (DataPipeline, MixtureDataset,
                                ShardedRecordDataset)
    mix = MixtureDataset(
        [ShardedRecordDataset(os.path.join(root, "a-*.rec")),
         ShardedRecordDataset(os.path.join(root, "b-*.rec"))],
        weights=[0.7, 0.3], seed=SEED)
    return DataPipeline(mix, batch_size=BATCH, seed=SEED, seq_len=SEQ_LEN,
                        num_hosts=num_hosts, host_id=host_id)


def _plain_pipeline(root: str, num_hosts: int = 1, host_id: int = 0):
    from mxnet_tpu.data import DataPipeline, ShardedRecordDataset
    ds = ShardedRecordDataset(os.path.join(root, "a-*.rec"))
    return DataPipeline(ds, batch_size=BATCH, seed=SEED,
                        num_hosts=num_hosts, host_id=host_id,
                        batchify=lambda rows: [int(r[0]) for r in rows])


def _bhash(batch: dict) -> int:
    h = 0
    for k in sorted(batch):
        h = zlib.crc32(onp.ascontiguousarray(batch[k]).tobytes(), h)
    return h


class _Target:
    """Stand-in train state (the smoke grades the DATA stream)."""

    def __init__(self):
        self.step = 0

    def save(self, path):
        with open(path, "wb") as f:
            onp.savez(f, step=self.step)

    def load(self, path):
        self.step = int(onp.load(path)["step"])


# -- children ---------------------------------------------------------------

def _role_ref(root: str):
    pipe = _packed_pipeline(root)
    print(json.dumps({"hashes": [_bhash(next(pipe)) for _ in range(TOTAL)]}))


def _role_train(root: str, ckpt: str):
    from mxnet_tpu.utils.checkpoint import CheckpointManager
    pipe = _packed_pipeline(root)
    mgr = CheckpointManager(ckpt, keep=3)
    mgr.attach_pipeline(pipe)
    tgt = _Target()
    hashes = []
    for i in range(1, KILL_AT + 1):
        hashes.append(_bhash(next(pipe)))
        tgt.step = i
        if i % SAVE_EVERY == 0:
            mgr.save(tgt, i)
    print(json.dumps({"hashes": hashes}))
    # no cleanup: this child "dies" mid-epoch (the point of the test)


def _role_resume(root: str, ckpt: str):
    from mxnet_tpu.utils.checkpoint import CheckpointManager
    pipe = _packed_pipeline(root)
    mgr = CheckpointManager(ckpt, keep=3)
    mgr.attach_pipeline(pipe)
    tgt = _Target()
    start = mgr.restore(tgt)          # O(1) seek via the manifest state
    hashes = [_bhash(next(pipe)) for _ in range(start, TOTAL)]
    print(json.dumps({"start": start, "target_step": tgt.step,
                      "hashes": hashes}))


def _child(args) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, os.path.abspath(__file__)] + args,
                         capture_output=True, text=True, timeout=240,
                         env=env)
    if out.returncode != 0:
        _fail(f"child {args[0]} exited {out.returncode}:\n"
              f"{out.stdout[-2000:]}\n{out.stderr[-2000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def _fail(msg: str):
    print(f"DATA-SMOKE FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


# -- parent phases ----------------------------------------------------------

def _phase_resume_parity(root: str, tmp: str):
    ref = _child(["ref", root])["hashes"]
    ckpt = os.path.join(tmp, "ckpt")
    trained = _child(["train", root, ckpt])["hashes"]
    if trained != ref[:KILL_AT]:
        _fail("training child's stream diverged from the reference "
              "BEFORE the kill — the order function is not pure")
    resumed = _child(["resume", root, ckpt])
    start = resumed["start"]
    if start != (KILL_AT // SAVE_EVERY) * SAVE_EVERY:
        _fail(f"resume restored step {start}, expected "
              f"{(KILL_AT // SAVE_EVERY) * SAVE_EVERY}")
    if resumed["target_step"] != start:
        _fail("model state and restored step disagree")
    if resumed["hashes"] != ref[start:]:
        _fail(f"resumed stream is NOT bit-identical to the reference "
              f"(from batch {start + 1}): fresh-process restore parity "
              "is broken")
    print(f"  resume parity OK: killed at batch {KILL_AT}, fresh process "
          f"re-seeked to {start}, batches {start + 1}..{TOTAL} "
          "bit-identical (mixture + packing)")


def _phase_elastic_exactly_once(root: str):
    ref_pipe = _plain_pipeline(root)
    expect = []
    for _ in range(10):
        expect.extend(next(ref_pipe))
    state = _plain_pipeline(root).state()
    delivered = []

    def run_hosts(num_hosts, state, nbatches):
        pipes = []
        for h in range(num_hosts):
            p = _plain_pipeline(root, num_hosts=num_hosts, host_id=h)
            p.load_state(state)
            pipes.append(p)
        for _ in range(nbatches):
            for p in pipes:
                delivered.extend(next(p))
        return pipes[0].state()

    state = run_hosts(1, state, 4)     # steady state
    state = run_hosts(2, state, 4)     # grow: host joins
    state = run_hosts(1, state, 2)     # shrink: host lost
    if len(delivered) != len(expect):
        _fail(f"elastic reform delivered {len(delivered)} samples, "
              f"expected {len(expect)} (lost or duplicated)")
    if sorted(delivered) != sorted(expect):
        _fail("elastic reform changed WHICH samples were delivered")
    print(f"  elastic exactly-once OK: {len(delivered)} samples through "
          "1->2->1 host reforms, zero lost, zero duplicated")


def _phase_zero_retrace(root: str):
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel.prefetch import DevicePrefetcher

    traces = {"n": 0}

    def _step(tokens, mask):
        traces["n"] += 1              # trace-time only
        return (tokens.astype(jnp.float32) * mask).sum()

    step = jax.jit(_step)
    pipe = _packed_pipeline(root)
    pf = DevicePrefetcher(
        pipe, place=lambda b: {k: jax.device_put(v) for k, v in b.items()},
        depth=2)
    losses = []
    for i, batch in enumerate(pf):
        losses.append(float(step(batch["tokens"], batch["loss_mask"])))
        if i == 7:
            break
    pf.close()
    if traces["n"] != 1:
        _fail(f"the data path caused retraces: trace_count={traces['n']} "
              "over 8 packed batches (shapes must be static)")
    print(f"  zero-retrace OK: trace_count=1 over 8 prefetched packed "
          f"batches ({len(losses)} losses)")


def main():
    if len(sys.argv) > 1:
        role = sys.argv[1]
        if role == "ref":
            return _role_ref(sys.argv[2])
        if role == "train":
            return _role_train(sys.argv[2], sys.argv[3])
        if role == "resume":
            return _role_resume(sys.argv[2], sys.argv[3])
        _fail(f"unknown role {role}")
    import tempfile
    import time
    t0 = time.time()
    with tempfile.TemporaryDirectory(prefix="mxtpu_data_smoke") as tmp:
        root = os.path.join(tmp, "corpus")
        os.makedirs(root)
        _build_corpus(root)
        print("data-smoke: corpus built (2 corpora, 3 shards)")
        _phase_resume_parity(root, tmp)
        _phase_elastic_exactly_once(root)
        _phase_zero_retrace(root)
    print(f"DATA-SMOKE PASS ({time.time() - t0:.1f}s)")


if __name__ == "__main__":
    main()
