"""Benchmark: BERT-base pretraining train-step on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
North-star (BASELINE.json): BERT-base pretraining at >=40% MFU on v5p-32;
vs_baseline = measured_MFU / 0.40. Also reports samples/sec/chip in extras.

Backend robustness (round-2 fix for BENCH_r01 rc=1): the default platform in
this environment is a remote-TPU tunnel whose initialisation can fail or
block indefinitely. The orchestrator (no args) therefore runs the measurement
in a child process with a hard timeout, retries once, and falls back to a CPU
measurement — ALWAYS emitting one valid JSON line with the failure diagnostic
in extras.
"""
from __future__ import annotations

import glob
import json
import math
import os
import signal
import subprocess
import sys
import time

import numpy as _onp

ATTEMPT_TIMEOUTS = (480, 300)   # seconds per TPU attempt
CPU_TIMEOUT = 600


def _peak_flops(device) -> float:
    # single source of truth for the peak table: mxnet_tpu.tracing
    # (the MFU gauge and this bench must agree on the denominator)
    try:
        from mxnet_tpu import tracing as _tracing
        return _tracing.peak_flops(getattr(device, "device_kind", ""))
    except Exception:
        return 197e12  # conservative default (import failure only)


def _measure(platform: str) -> dict:
    import jax
    if platform == "cpu":
        # env var too: mxnet_tpu's import honors JAX_PLATFORMS and would
        # re-override a config-only choice with the ambient env value
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu import optimizer as opt
    from mxnet_tpu.models.bert import BertConfig, BertForPretraining
    from mxnet_tpu.parallel import make_mesh, make_sharded_train_step

    # --telemetry (env MXTPU_TELEMETRY): instrumentation was auto-enabled
    # at import; attach a run journal BEFORE the measured loop so step/
    # compile events land somewhere inspectable (docs/observability.md)
    from mxnet_tpu import telemetry as _tele
    telemetry_on = _tele.enabled()
    if telemetry_on and _tele.journal() is None:
        _tele.enable(journal_path=os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "bench_results",
            f"telemetry_journal_{os.getpid()}.jsonl"))

    dev = jax.devices()[0]
    on_accel = dev.platform.lower() != "cpu"

    # BERT-base; bf16 weights/compute for the MXU, seq 128 (phase-1
    # pretrain), MLM loss on masked positions only (the GluonNLP
    # create_pretraining_data shape: max_predictions_per_seq=20 at seq 128)
    if on_accel:
        batch = int(os.environ.get("MXTPU_BENCH_BATCH", 64))
        seq, n_mask = 128, 20
        cfg = BertConfig(dtype="bfloat16")
    else:  # CI/CPU smoke config
        batch, seq, n_mask = 4, 64, 10
        cfg = BertConfig(hidden_size=128, num_layers=2, num_heads=4,
                         intermediate_size=512, vocab_size=1024)

    from mxnet_tpu.gluon.block import HybridBlock

    class BenchBert(HybridBlock):
        """Positional adapter: the sharded step passes batch args
        positionally; pretraining uses (ids, valid_length,
        masked_positions) — valid_length builds the padding attention
        mask, so the bench measures the masked (production-shaped) path."""

        def __init__(self, c):
            super().__init__()
            self.model = BertForPretraining(c)

        def forward(self, input_ids, valid_length, masked_positions):
            return self.model(input_ids, valid_length=valid_length,
                              masked_positions=masked_positions)

    model = BenchBert(cfg)
    model.initialize()
    rng = _onp.random.RandomState(0)
    ids = mx.np.array(rng.randint(0, cfg.vocab_size, (batch, seq)),
                      dtype="int32")
    # padded batches like real pretraining data (mean ~94% of seq)
    vlen = mx.np.array(rng.randint(int(0.85 * seq), seq + 1, (batch,)),
                       dtype="int32")
    mpos = mx.np.array(
        _onp.sort(rng.rand(batch, seq).argsort(axis=1)[:, :n_mask], axis=1),
        dtype="int32")
    labels = mx.np.array(rng.randint(0, cfg.vocab_size, (batch, n_mask)),
                         dtype="int32")
    model(ids, vlen, mpos)  # deferred init

    def loss_fn(out, input_ids, valid_length, masked_positions, lbl):
        mlm, nsp = out
        # fused streaming CE (Pallas on TPU): no fp32 (tokens, vocab)
        # log-prob materialisation (ops/pallas/softmax_xent.py)
        from mxnet_tpu.ops.pallas.softmax_xent import softmax_cross_entropy
        return jnp.mean(softmax_cross_entropy(mlm, lbl.astype(jnp.int32)))

    mesh = make_mesh({"dp": 1}, jax.devices()[:1])
    step = make_sharded_train_step(model, opt.Adam(learning_rate=1e-4),
                                   loss_fn, mesh, num_model_args=3)

    from mxnet_tpu.parallel import AsyncMetricBuffer, DevicePrefetcher

    # warmup: AOT-compile (with MXTPU_COMPILE_CACHE set the binary comes
    # back from the persistent cache on a warm start), then two real steps;
    # sync via device_get — on tunneled backends block_until_ready can
    # return before remote execution finishes
    compile_s = step.warmup(ids, vlen, mpos, labels)
    for _ in range(2):
        loss = step(ids, vlen, mpos, labels)
    jax.device_get(loss)

    pipe = {"steps_in_flight_max": 0, "deferred_fetch_max": 0,
            "prefetch": None}

    def timed(n):
        # pipelined path: device prefetch on a background thread +
        # non-blocking dispatch + deferred metric fetches every 8 steps
        src = ((ids, vlen, mpos, labels) for _ in range(n))
        buf = AsyncMetricBuffer(drain_every=8)
        handle = None
        t0 = time.perf_counter()
        with DevicePrefetcher(src, place=step.place_batch) as pf:
            for b in pf:
                handle = step.dispatch(*b)
                buf.append(handle)
                # device truth: dispatched steps not yet complete. The
                # deferred-fetch window (buf.in_flight) is reported
                # separately — it reaches drain_every-1 even when every
                # dispatch blocks, so it must not masquerade as overlap.
                n_fly = step.steps_in_flight()
                if n_fly > pipe["steps_in_flight_max"]:
                    pipe["steps_in_flight_max"] = n_fly
                if buf.in_flight > pipe["deferred_fetch_max"]:
                    pipe["deferred_fetch_max"] = buf.in_flight
        buf.drain()
        loss = handle.loss
        jax.device_get(loss)
        pipe["prefetch"] = pf.stats()
        return time.perf_counter() - t0, loss

    # two run lengths; slope removes the fixed dispatch/fetch overhead.
    # CPU (the CI proxy): the r05 "regression" bisected to pure timing
    # noise — a 6-step slope on a shared 2-core box swings ±30% run to
    # run — so the CPU path runs longer slopes and keeps the BEST of
    # three (min is the standard noise-robust estimator for a
    # lower-bound-style perf number; timeit does the same).
    if on_accel:
        n1, n2, reps = 10, 50, 1
    else:
        n1, n2, reps = 4, 16, 3
    slopes = []
    for _ in range(reps):
        t1, _ = timed(n1)
        t2, loss = timed(n2)
        if t2 - t1 > 0:
            slopes.append((t2 - t1) / (n2 - n1))
    step_time = min(slopes) if slopes else t2 / n2
    samples_per_sec = batch / step_time

    # train FLOPs: 3x forward; forward = matmul MACs * 2. The MLM head
    # (hidden->hidden + hidden->vocab) runs only on the n_mask gathered
    # positions — counting it per token would inflate MFU.
    h, l, i, V = (cfg.hidden_size, cfg.num_layers, cfg.intermediate_size,
                  cfg.vocab_size)
    fwd_per_token = 2 * l * (4 * h * h + 2 * h * i) + 4 * l * seq * h
    fwd_per_masked = 2 * (h * h + h * V)
    flops_per_step = 3 * batch * (fwd_per_token * seq
                                  + fwd_per_masked * n_mask)
    achieved = flops_per_step / step_time

    dstats = step.dispatch_stats()
    extras = {
        "samples_per_sec_per_chip": round(samples_per_sec, 2),
        "step_time_ms": round(step_time * 1e3, 2),
        "achieved_tflops": round(achieved / 1e12, 2),
        "batch": batch, "seq": seq, "n_mask": n_mask,
        "device": getattr(dev, "device_kind", str(dev)),
        "platform": dev.platform,
        "loss": float(loss),
        # async-pipeline health: host dispatch latency should sit far
        # below step_time_ms when overlap works; trace_count must be 1
        "steps_in_flight": pipe["steps_in_flight_max"],
        "deferred_fetch_max": pipe["deferred_fetch_max"],
        "dispatch_ms_mean": dstats["mean_ms"],
        "trace_count": step.trace_count,
        "compile_seconds": round(compile_s, 2),
        "prefetch": pipe["prefetch"],
    }
    # per-executable cost attribution (mx.tracing, captured at warmup):
    # XLA-counted flops/bytes + the always-on MFU estimate.  On CPU the
    # flop count is exact and the peak is the PROJECTED peak of the
    # configured device kind (MXTPU_MFU_DEVICE_KIND) — a defensible
    # trajectory proxy until the TPU tunnel reopens, marked projected.
    from mxnet_tpu import tracing as _tracing
    cost_feats = step.cost_features()
    if cost_feats:
        mfu_est = step.mfu_estimate(step_time)
        extras["cost"] = {
            "flops": cost_feats.get("flops"),
            "bytes_accessed": cost_feats.get("bytes_accessed"),
            "hbm_bytes_est": cost_feats.get("hbm_bytes_est"),
            "flops_analytic": flops_per_step,
            "mfu_estimate": (mfu_est["mfu_estimate"]
                             if mfu_est else None),
            "mfu_projected": (mfu_est["projected"]
                              if mfu_est else None),
            "peak_device_kind": (mfu_est["device_kind"]
                                 if mfu_est else None),
        }
    if telemetry_on:
        extras["telemetry"] = {"journal": getattr(_tele.journal(), "path",
                                                  None),
                               "snapshot": _tele.snapshot()}
    if dev.platform.lower() != "tpu":
        # no MFU on the fallback: a CPU-throughput / TPU-peak ratio is a
        # meaningless number (VERDICT r3 weak #6) — report throughput only
        return {
            "metric": "bert_base_pretrain_samples_per_sec",
            "value": round(samples_per_sec, 2),
            "unit": "samples_per_sec_per_chip",
            "vs_baseline": 0.0,   # north-star baseline is MFU-on-TPU
            "extras": extras,
        }
    mfu = achieved / _peak_flops(dev)
    return {
        "metric": "bert_base_pretrain_mfu",
        "value": round(mfu, 4),
        "unit": "MFU_fraction",
        "vs_baseline": round(mfu / 0.40, 4),
        "extras": extras,
    }


def _decode_rate_pcts(handles) -> dict:
    """Per-request DECODE tokens/sec (first token -> last token; the
    number speculation moves, reported per stream so a tail win is
    visible even when aggregate tokens/s is flat)."""
    rates = sorted(
        (len(h.tokens) - 1) / (h.finished_ts - h.first_token_ts)
        for h in handles
        if h.first_token_ts is not None and h.finished_ts is not None
        and len(h.tokens) > 1 and h.finished_ts > h.first_token_ts)

    def pct(p):
        if not rates:
            return None
        return round(rates[min(len(rates) - 1,
                               int(p * (len(rates) - 1)))], 2)

    return {"decode_tok_s_p50": pct(0.50), "decode_tok_s_p99": pct(0.99)}


def _spec_prompts(rng, cfg, n_req: int):
    """Shared-prefix workload mix: 3 prompt families sharing a long
    common prefix (the prefix-cache target) + unique tails, plus a few
    fully random prompts — the realistic many-users-one-template
    shape."""
    fams = [rng.randint(0, cfg.vocab_size, 24).tolist() for _ in range(3)]
    prompts = []
    for i in range(n_req):
        if i % 4 == 3:
            prompts.append(rng.randint(0, cfg.vocab_size,
                                       rng.randint(4, 32)).tolist())
        else:
            prompts.append(fams[i % 3]
                           + rng.randint(0, cfg.vocab_size,
                                         rng.randint(2, 8)).tolist())
    return prompts


def _measure_serve(spec: int = 0) -> dict:
    """`bench.py --serve [--spec k]`: throughput + tail-TTFT of the
    serving stack under simulated concurrent-request load (CPU-sized
    model unless a TPU is attached).  Reports tokens/sec across the
    whole run and p50/p99 time-to-first-token over the request
    population — the two numbers the "millions of users" north star is
    graded on — plus per-request decode tokens/s percentiles.  With
    ``--spec k`` the engine runs k-token speculative decoding AND the
    cross-request prefix cache over a shared-prefix workload mix,
    reporting accept-rate / steps-per-token / prefix-hit extras
    (docs/serving.md "Speculative decoding & prefix caching")."""
    import jax
    # pin the backend BEFORE jax initializes (touching jax.devices()
    # first would lock in whatever default exists — e.g. a GPU — and a
    # later env set is a silent no-op); only an ambient JAX_PLATFORMS
    # explicitly naming a TPU-ish backend keeps the accelerator path
    ambient = os.environ.get("JAX_PLATFORMS", "").lower()
    if not any(t in ambient for t in ("tpu", "axon")):
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from mxnet_tpu.serve import InferenceEngine, ServeConfig

    dev = jax.devices()[0]
    on_accel = dev.platform.lower() == "tpu"
    if on_accel:
        cfg = GPTConfig(vocab_size=32000, hidden_size=1024, num_layers=8,
                        num_heads=16, intermediate_size=4096,
                        max_position=1024, dropout=0.0, dtype="bfloat16")
        n_req, max_new, max_len = 64, 64, 512
    else:
        cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                        num_heads=4, intermediate_size=128,
                        max_position=256, dropout=0.0)
        n_req, max_new, max_len = 24, 16, 128
    model = GPTForCausalLM(cfg)
    model.initialize()
    model(mx.np.array([[1, 2]], dtype="int32"))

    eng = InferenceEngine(model, ServeConfig(
        max_len=max_len, spec_tokens=spec, prefix_cache=spec > 0))
    compile_s = eng.warmup()

    rng = _onp.random.RandomState(0)
    if spec > 0:
        prompts = _spec_prompts(rng, cfg, n_req)
    else:
        prompts = [rng.randint(0, cfg.vocab_size,
                               rng.randint(4, 48)).tolist()
                   for _ in range(n_req)]
    # staggered arrival: a burst up front, then one request every other
    # step — the queue stays non-empty while slots churn (the
    # continuous-batching regime, not a static batch)
    handles = []
    t0 = time.perf_counter()
    for p in prompts[:8]:
        handles.append(eng.submit(p, max_new_tokens=max_new))
    arrivals = iter(prompts[8:])
    steps = 0
    while True:
        progressed = eng.step()
        steps += 1
        if steps % 2 == 0:
            nxt = next(arrivals, None)
            if nxt is not None:
                handles.append(eng.submit(nxt, max_new_tokens=max_new))
        if not progressed and len(handles) == n_req and \
                eng.scheduler.queue_depth == 0:
            break
        if steps > 100000:
            break
    wall = time.perf_counter() - t0
    toks = sum(len(h.tokens) for h in handles)
    ttfts = sorted(h.ttft_s * 1e3 for h in handles
                   if h.ttft_s is not None)

    def pct(p):
        if not ttfts:
            return None
        return round(ttfts[min(len(ttfts) - 1,
                               int(p * (len(ttfts) - 1)))], 2)

    from mxnet_tpu import telemetry as _tele
    from mxnet_tpu import tracing as _tracing
    extras = {
        "requests": n_req,
        "generated_tokens": toks,
        "ttft_p50_ms": pct(0.50),
        "ttft_p99_ms": pct(0.99),
        "steps": steps,
        "wall_s": round(wall, 3),
        "compile_seconds": round(compile_s, 2),
        "evictions": sum(h.evictions for h in handles),
        "page_size": eng.serve_config.page_size,
        "slots": eng.serve_config.max_slots,
        "device": getattr(dev, "device_kind", str(dev)),
        "platform": dev.platform,
        # actual fused launches per emitted token (the loop's `steps`
        # count includes idle polls during staggered arrivals)
        "steps_per_token": round(eng.scheduler._steps / max(1, toks), 4),
        **_decode_rate_pcts(handles),
    }
    if spec > 0:
        extras["spec"] = eng.scheduler.spec_stats()
        extras["spec"]["spec_tokens"] = spec
        if eng.prefix_index is not None:
            extras["prefix_cache"] = eng.prefix_index.stats()
    # quantized capacity table (ROADMAP item 2): weight bytes + MEASURED
    # max-concurrent-pages at each weight precision — engines are cheap
    # to construct (no warmup), and the auto pool sizing converts the
    # freed weight bytes into extra pages, so the capacity win is a
    # number, not a claim (docs/quantization.md)
    cap_table = {}
    for label, bits in (("f32", 0), ("int8", 8), ("int4", 4)):
        # quant_bits explicit per row: an ambient MXTPU_QUANT_BITS (the
        # ServeConfig default) must not quantize the f32 baseline row
        e = eng if eng.quant_bits == bits else InferenceEngine(
            model, ServeConfig(max_len=max_len, quant_bits=bits))
        st = e.stats()
        cap_table[label] = {
            "weight_bytes": st["weight_bytes"],
            "total_pages": e.allocator.total_pages,
            "bonus_pages": st["bonus_pages"],
        }
        if bits:
            cap_table[label]["weight_reduction"] = round(
                cap_table["f32"]["weight_bytes"]
                / max(1, st["weight_bytes"]), 3)
    extras["quant_capacity"] = cap_table
    # per-width serving-step cost (mx.tracing): XLA flops/bytes of both
    # compiled widths + an MFU estimate at the run's mean step cadence
    cost_by_width = eng.cost_features()
    if cost_by_width:
        mean_step_s = wall / max(1, steps)
        cost = {}
        for C, feats in sorted(cost_by_width.items()):
            entry = {"flops": feats.get("flops"),
                     "bytes_accessed": feats.get("bytes_accessed"),
                     "hbm_bytes_est": feats.get("hbm_bytes_est")}
            mfu = _tracing.estimate_mfu(feats.get("flops"), mean_step_s)
            if mfu is not None:
                entry["mfu_estimate"] = mfu["mfu_estimate"]
                entry["mfu_projected"] = mfu["projected"]
            cost[f"c{C}"] = entry
        extras["cost"] = cost
    if _tele.enabled():
        extras["telemetry"] = {"snapshot": _tele.snapshot()}
    return {
        "metric": "serve_tokens_per_sec",
        "value": round(toks / wall, 2),
        "unit": "tokens_per_sec",
        "vs_baseline": 0.0,   # north-star baseline is MFU-on-TPU
        "extras": extras,
    }


def _measure_serve_fleet(replicas: int, kill_at: float,
                         spec: int = 0,
                         kill_mode: str = "thread") -> dict:
    """`bench.py --serve --replicas N [--kill-at S] [--spec k]
    [--kill-mode thread|process]`: aggregate fleet throughput +
    tail-TTFT UNDER REPLICA LOSS (the ROADMAP item 1 metric).  One
    replica is killed `kill_at` seconds into the load window; its
    in-flight streams fail over to survivors, and the run must still
    report nonzero aggregate tokens/s and a finite p99 TTFT measured
    across the whole population — loss window included.  ``--spec k``
    turns on per-replica speculative decoding + prefix caching (with
    router prefix affinity) over the shared-prefix mix and reports the
    fleet-aggregate accept rate.  ``--kill-mode process`` runs the
    fleet on the process transport and SIGKILLs a worker instead —
    ledger failover + respawn; extras gain the failover loss window
    (ms between the kill and the next token streamed anywhere) and the
    respawn count."""
    import jax
    ambient = os.environ.get("JAX_PLATFORMS", "").lower()
    if not any(t in ambient for t in ("tpu", "axon")):
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from mxnet_tpu.serve import ServeConfig, ServeFleet, ShedError

    dev = jax.devices()[0]
    on_accel = dev.platform.lower() == "tpu"
    if on_accel:
        cfg = GPTConfig(vocab_size=32000, hidden_size=1024, num_layers=8,
                        num_heads=16, intermediate_size=4096,
                        max_position=1024, dropout=0.0, dtype="bfloat16")
        n_req, max_new, max_len = 64, 64, 512
    else:
        cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                        num_heads=4, intermediate_size=128,
                        max_position=256, dropout=0.0)
        n_req, max_new, max_len = 24, 16, 128
    model = GPTForCausalLM(cfg)
    model.initialize()
    model(mx.np.array([[1, 2]], dtype="int32"))

    fleet = ServeFleet(model, replicas=replicas,
                       config=ServeConfig(max_len=max_len,
                                          spec_tokens=spec,
                                          prefix_cache=spec > 0),
                       transport=kill_mode)
    compile_s = fleet.warmup()

    rng = _onp.random.RandomState(0)
    if spec > 0:
        prompts = _spec_prompts(rng, cfg, n_req)
    else:
        prompts = [rng.randint(0, cfg.vocab_size,
                               rng.randint(4, 48)).tolist()
                   for _ in range(n_req)]
    handles = []
    killed = None
    kill_ts = None
    # per-token wall timestamps: the failover loss window is the gap
    # between the kill and the next token streamed ANYWHERE in the fleet
    tok_times = []

    def _on_token(tok, req):
        tok_times.append(time.perf_counter())

    # pace arrivals so the load window straddles the kill: with
    # --kill-at S the last request arrives around 2S, guaranteeing the
    # loss lands mid-load however fast the backend decodes
    pace = (2.0 * kill_at / n_req) if kill_at else 0.0
    next_arrival = 0.0
    t0 = time.perf_counter()
    with fleet:
        arrivals = list(prompts)
        # burst, then staggered arrivals — the queue stays non-empty
        # while slots churn across all replicas
        while arrivals or not all(h.done() for h in handles):
            if killed is None and kill_at is not None and \
                    time.perf_counter() - t0 >= kill_at:
                # kill a loaded replica mid-window (prefer one holding
                # active streams so the failover path is exercised)
                busy = (lambda r: getattr(r.engine.scheduler, "inflight",
                                          None)
                        or r.engine.scheduler.active_count)
                victim = max(
                    (r for r in fleet.replicas if r.state == "running"),
                    key=busy, default=None)
                if victim is not None:
                    killed = victim.name
                    kill_ts = time.perf_counter()
                    if kill_mode == "process":
                        # the real thing: SIGKILL the worker — no
                        # scheduler survives, failover comes from the
                        # router's stream ledger and the worker respawns
                        os.kill(victim.pid, signal.SIGKILL)
                    else:
                        fleet.kill(victim.name,
                                   error="bench --kill-at replica loss")
            now = time.perf_counter() - t0
            if arrivals and now >= next_arrival:
                try:
                    handles.append(fleet.submit(
                        arrivals[0], max_new_tokens=max_new,
                        on_token=_on_token))
                    arrivals.pop(0)
                    next_arrival = now + pace
                except ShedError as e:
                    time.sleep(min(e.retry_after_ms, 100.0) / 1e3)
            else:
                time.sleep(0.002)
            if time.perf_counter() - t0 > 600:
                break
        for h in handles:
            h.result(timeout=120)
    wall = time.perf_counter() - t0
    toks = sum(len(h.tokens) for h in handles)
    ttfts = sorted(h.ttft_s * 1e3 for h in handles
                   if h.ttft_s is not None)

    def pct(p):
        if not ttfts:
            return None
        return round(ttfts[min(len(ttfts) - 1,
                               int(p * (len(ttfts) - 1)))], 2)

    stats = fleet.stats()
    extras = {
        "requests": n_req,
        "generated_tokens": toks,
        "ttft_p50_ms": pct(0.50),
        "ttft_p99_ms": pct(0.99),
        "wall_s": round(wall, 3),
        "compile_seconds": round(compile_s, 2),
        "replicas": replicas,
        "kill_at_s": kill_at,
        "kill_mode": kill_mode,
        "killed_replica": killed,
        "deaths": fleet.deaths,
        "respawns": fleet.respawns,
        "failovers": sum(h.failovers for h in handles),
        "evictions": sum(h.evictions for h in handles),
        "sheds": stats["router"]["sheds"],
        "routed": stats["router"]["routed"],
        "replica_states": {n: r["state"]
                           for n, r in stats["replicas"].items()},
        # ms from the kill to the next token streamed anywhere in the
        # fleet — the user-visible failover stall (None: no kill, or no
        # token landed after it)
        "failover_loss_window_ms": (round(
            (min(ts for ts in tok_times if ts > kill_ts) - kill_ts)
            * 1e3, 1)
            if kill_ts is not None
            and any(ts > kill_ts for ts in tok_times) else None),
        "device": getattr(dev, "device_kind", str(dev)),
        "platform": dev.platform,
        **_decode_rate_pcts(handles),
    }
    if stats.get("slo"):
        # burn-rate posture at end of run (MXTPU_SLO_SPEC objectives):
        # per-objective fast/slow burn + whether any alert fired
        extras["slo"] = {
            name: {"burn_fast": round(e["windows"]["fast"]["burn"], 3),
                   "burn_slow": round(e["windows"]["slow"]["burn"], 3),
                   "alerts": e["alerts"]}
            for name, e in stats["slo"].items()}
    if spec > 0:
        # fleet-aggregate speculation outcome (dead replicas included —
        # their accepted tokens were streamed before the loss)
        agg = {"proposed": 0, "accepted": 0, "steps": 0, "tokens": 0,
               "prefix_hit_tokens": 0, "cow_forks": 0}
        for rep in fleet.replicas:
            # process replicas run speculation inside the worker; their
            # proxy scheduler has no spec counters to aggregate
            if not hasattr(rep.engine.scheduler, "spec_stats"):
                continue
            ss = rep.engine.scheduler.spec_stats()
            for k in agg:
                agg[k] += ss[k] or 0
        agg["accept_rate"] = (round(agg["accepted"] / agg["proposed"], 4)
                              if agg["proposed"] else None)
        agg["steps_per_token"] = (round(agg["steps"]
                                        / agg["tokens"], 4)
                                  if agg["tokens"] else None)
        agg["spec_tokens"] = spec
        extras["spec"] = agg
    return {
        "metric": "serve_fleet_tokens_per_sec",
        "value": round(toks / wall, 2),
        "unit": "tokens_per_sec",
        "vs_baseline": 0.0,   # north-star baseline is MFU-on-TPU
        "extras": extras,
    }


def _measure_serve_replay(trace_path: str, replicas: int,
                          speed: float = 0.0,
                          kill_at: float = None,
                          kill_mode: str = "thread") -> dict:
    """`bench.py --serve --trace FILE [--speed X] [--kill-at S]
    [--replicas N] [--kill-mode thread|process]`: re-drive a recorded
    traffic journal or generated workload trace (docs/serving.md,
    "Flight recorder & replay") through a fresh fleet and report the
    divergence summary — matched vs divergent token-stream digests plus
    recorded-vs-replayed TTFT/latency percentiles.  The trace is served
    with the bench model, so digest verification only applies when the
    trace was recorded against it (a re-recorded bench trace, or one
    produced by ``--gen-trace`` + a previous ``--trace`` run)."""
    import jax
    ambient = os.environ.get("JAX_PLATFORMS", "").lower()
    if not any(t in ambient for t in ("tpu", "axon")):
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from mxnet_tpu.serve import ServeConfig, ServeFleet
    from mxnet_tpu.serve import traffic as _traffic
    from mxnet_tpu.serve.replay import replay_trace

    meta, arrivals, outcomes = _traffic.read_trace(trace_path)
    if not arrivals:
        raise SystemExit(f"--trace {trace_path}: no arrival rows")
    dev = jax.devices()[0]
    on_accel = dev.platform.lower() == "tpu"
    if on_accel:
        cfg = GPTConfig(vocab_size=32000, hidden_size=1024, num_layers=8,
                        num_heads=16, intermediate_size=4096,
                        max_position=1024, dropout=0.0, dtype="bfloat16")
        max_len = 512
    else:
        cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                        num_heads=4, intermediate_size=128,
                        max_position=256, dropout=0.0)
        max_len = 128
    top = max(max(a["prompt"], default=0) for a in arrivals)
    if top >= cfg.vocab_size:
        raise SystemExit(
            f"--trace {trace_path}: prompt token {top} >= bench vocab "
            f"{cfg.vocab_size} — this trace was not recorded against "
            f"the bench model")
    model = GPTForCausalLM(cfg)
    model.initialize()
    model(mx.np.array([[1, 2]], dtype="int32"))

    fleet = ServeFleet(model, replicas=replicas,
                       config=ServeConfig(max_len=max_len),
                       transport=kill_mode)
    compile_s = fleet.warmup()
    with fleet:
        report = replay_trace(fleet, (meta, arrivals, outcomes),
                              speed=speed, kill_at=kill_at,
                              timeout=600.0)
    extras = {
        "trace": os.path.abspath(trace_path),
        "mode": report["mode"],
        "requests": report["requests"],
        "submitted": report["submitted"],
        "digest_matched": len(report["matched"]),
        "digest_divergent": len(report["divergent"]),
        "unverified": len(report["unverified"]),
        "replay_failed": len(report["replay_failed"]),
        "shed_replay": len(report["shed_replay"]),
        "kill": report["kill"],
        "ttft_ms": report["ttft_ms"],
        "latency_ms": report["latency_ms"],
        "compile_seconds": round(compile_s, 2),
        "replicas": replicas,
        "kill_mode": kill_mode,
        "ok": report["ok"],
        "device": getattr(dev, "device_kind", str(dev)),
        "platform": dev.platform,
    }
    return {
        "metric": "serve_replay_wall_s",
        "value": report["replay_wall_s"],
        "unit": "seconds",
        "vs_baseline": 0.0,
        "extras": extras,
    }


def _pct_of(vals, p):
    vals = sorted(vals)
    if not vals:
        return None
    return round(vals[min(len(vals) - 1, int(p * (len(vals) - 1)))], 2)


def _run_disagg_phase(fleet, prompts, max_new: int) -> dict:
    """Submit one workload phase and drain it; returns the phase's
    aggregate tokens/s plus the handoff count it generated (counters on
    the fleet are cumulative, so the caller snapshots around us)."""
    from mxnet_tpu.serve import ShedError
    h0 = fleet.handoffs
    handles = []
    t0 = time.perf_counter()
    for p in prompts:
        while True:
            try:
                handles.append(fleet.submit(p, max_new_tokens=max_new))
                break
            except ShedError as e:
                time.sleep(min(e.retry_after_ms, 50.0) / 1e3)
    for h in handles:
        h.result(timeout=300)
    wall = time.perf_counter() - t0
    toks = sum(len(h.tokens) for h in handles)
    ttfts = [h.ttft_s * 1e3 for h in handles if h.ttft_s is not None]
    return {
        "requests": len(prompts),
        "generated_tokens": toks,
        "tokens_per_sec": round(toks / wall, 2),
        "wall_s": round(wall, 3),
        "ttft_p50_ms": _pct_of(ttfts, 0.50),
        "ttft_p99_ms": _pct_of(ttfts, 0.99),
        "handoffs": fleet.handoffs - h0,
    }


def _role_steps(fleet) -> dict:
    out = {}
    for rep in fleet.replicas:
        role = getattr(rep.engine, "role", "both")
        out[role] = out.get(role, 0) + getattr(
            rep.engine.scheduler, "_steps", 0)
    return out


def _measure_serve_disagg(disagg: str, tp: int) -> dict:
    """`bench.py --serve --disagg PxD [--tp N]`: prefill/decode
    disaggregation throughput (docs/serving.md "Disaggregated
    serving").  Runs a P-prefill/D-decode fleet (thread transport —
    the handoff semantics are identical to the process wire, without
    process-spawn noise in the numbers) through two workload phases:

    - **prefill-bound**: long prompts, tiny completions — the phase
      that saturates the prefill tier;
    - **decode-bound**: short prompts, long completions — the phase
      the tensor-parallel fused decode step is for.

    Reports per-phase aggregate tokens/s, handoff latency p50/p99,
    per-role step-share utilization, and the INDEPENDENT-SCALING
    check: the prefill-bound phase re-run with one extra prefill
    replica (decode tier untouched) — aggregate tokens/s should
    improve, the whole point of splitting the tiers."""
    # tp decode shards need devices to shard over: give the CPU
    # backend 8 virtual devices BEFORE jax initializes
    if "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")
    import jax
    ambient = os.environ.get("JAX_PLATFORMS", "").lower()
    if not any(t in ambient for t in ("tpu", "axon")):
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from mxnet_tpu.serve import ServeConfig, ServeFleet

    try:
        p_reps, d_reps = (int(x) for x in disagg.lower().split("x"))
    except ValueError:
        raise SystemExit(f"--disagg must be PxD (e.g. 1x2), "
                         f"got {disagg!r}")

    dev = jax.devices()[0]
    cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                    num_heads=4, intermediate_size=128,
                    max_position=256, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.initialize()
    model(mx.np.array([[1, 2]], dtype="int32"))

    rng = _onp.random.RandomState(0)
    n_req = 12
    # prefill-bound: 48..64-token prompts, 4 new tokens each
    pre_prompts = [rng.randint(0, cfg.vocab_size,
                               rng.randint(48, 65)).tolist()
                   for _ in range(n_req)]
    # decode-bound: 4..8-token prompts, 32 new tokens each
    dec_prompts = [rng.randint(0, cfg.vocab_size,
                               rng.randint(4, 9)).tolist()
                   for _ in range(n_req)]

    sc = ServeConfig(max_slots=4, page_size=8, max_len=128,
                     prefill_chunk=16, tp=tp)

    def run_fleet(p, d):
        fleet = ServeFleet(model, config=sc, transport="thread",
                           disagg=(p, d))
        compile_s = fleet.warmup()
        with fleet:
            s0 = _role_steps(fleet)
            pre = _run_disagg_phase(fleet, pre_prompts, max_new=4)
            s1 = _role_steps(fleet)
            dec = _run_disagg_phase(fleet, dec_prompts, max_new=32)
            s2 = _role_steps(fleet)
            fleet.quiesce(30)
            stats = fleet.stats()
            hand_ms = list(fleet.handoff_ms)
        roles = sorted(s0)

        def share(a, b):
            tot = max(1, sum(b[r] - a.get(r, 0) for r in roles))
            return {r: round((b[r] - a.get(r, 0)) / tot, 3)
                    for r in roles}
        pre["role_step_share"] = share(s0, s1)
        dec["role_step_share"] = share(s1, s2)
        return {
            "phases": {"prefill_bound": pre, "decode_bound": dec},
            "compile_seconds": round(compile_s, 2),
            "handoffs": stats["handoffs"],
            "handoff_failures": stats["handoff_failures"],
            "handoff_ms_p50": _pct_of(hand_ms, 0.50),
            "handoff_ms_p99": _pct_of(hand_ms, 0.99),
            "tp_resolved": {n: r["tp"]
                            for n, r in stats["replicas"].items()},
        }

    base = run_fleet(p_reps, d_reps)
    # independent scaling: +1 PREFILL replica, decode tier untouched —
    # the prefill-bound phase is the one that should speed up
    scaled = run_fleet(p_reps + 1, d_reps)
    base_pre = base["phases"]["prefill_bound"]["tokens_per_sec"]
    scaled_pre = scaled["phases"]["prefill_bound"]["tokens_per_sec"]

    total_toks = sum(ph["generated_tokens"]
                     for ph in base["phases"].values())
    total_wall = sum(ph["wall_s"] for ph in base["phases"].values())
    extras = {
        "disagg": [p_reps, d_reps],
        "tp": tp,
        **base,
        "prefill_scaling": {
            "disagg": [p_reps + 1, d_reps],
            "prefill_bound_tokens_per_sec": scaled_pre,
            "base_tokens_per_sec": base_pre,
            "improvement": (round(scaled_pre / base_pre, 3)
                            if base_pre else None),
        },
        "device": getattr(dev, "device_kind", str(dev)),
        "platform": dev.platform,
    }
    return {
        "metric": "serve_disagg_tokens_per_sec",
        "value": round(total_toks / total_wall, 2) if total_wall else 0.0,
        "unit": "tokens_per_sec",
        "vs_baseline": 0.0,   # north-star baseline is MFU-on-TPU
        "extras": extras,
    }


def _measure_serve_tenants(replicas: int = 2, requests: int = 128,
                           seed: int = 0, speed: float = 1.0) -> dict:
    """`bench.py --serve --tenants [--requests N] [--replicas R]
    [--seed S] [--speed X]`: the noisy-neighbor containment headline
    (docs/serving.md "Per-tenant QoS").

    One seeded `WorkloadSpec` tenant mix — a protected ``gold`` tenant,
    an abusive ``abuser`` tenant (3x the arrival weight, every request
    inflated to the max output length: a deliberate priority-inversion
    attempt from the lowest class), and three short-lived ``churn-*``
    tenants — is driven through three fleets on the SAME trace:

    1. **solo**: only gold's arrivals, no contention — the reference
       tail,
    2. **qos on**: the full mix behind the QoS plane (gold
       interactive/weight 8; abuser best_effort behind a request-rate
       quota + 1-slot bulkhead),
    3. **qos off**: the full mix with ``MXTPU_QOS=0`` — what the same
       trace does to gold without the plane.

    Headline: gold's p99 TTFT degradation vs solo with QoS on (the
    contract is < 20% while the abuser absorbs >= 90% of the sheds);
    the QoS-off arm is reported alongside so the containment is
    attributable to the plane, not the trace."""
    import jax
    ambient = os.environ.get("JAX_PLATFORMS", "").lower()
    if not any(t in ambient for t in ("tpu", "axon")):
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from mxnet_tpu.serve import ServeConfig, ServeFleet, ShedError
    from mxnet_tpu.serve import traffic as _traffic
    from mxnet_tpu.serve.qos import QoSConfig

    # phases must not journal into an ambient capture or pick up an
    # ambient QoS spec (the off arm sets its own)
    scoped = {}
    for var in ("MXTPU_TRAFFIC_JOURNAL", "MXTPU_QOS", "MXTPU_QOS_SPEC"):
        if var in os.environ:
            scoped[var] = os.environ.pop(var)

    spec = _traffic.WorkloadSpec(
        seed=seed, requests=requests, rate_rps=12.0, burst_factor=3.0,
        burst_period_s=4.0, prompt_max=24, output_max=16,
        deadline_ms=0.0,
        tenants={"abuser": 6.0, "churn-a": 0.5,
                 "churn-b": 0.5, "churn-c": 0.5})
    rows = _traffic.generate_workload(spec)
    abuse_new = 48                 # prompt_max 24 + 48 + 1 < max_len
    gold_prompt = 112              # gold TTFT is prefill-dominated (one
    #                                full chunk, several decode-steps
    #                                deep), so the p99 ratio measures
    #                                scheduling interference, not clock
    #                                jitter or fixed dispatch overhead
    for a in rows:
        if a["tenant"] == "abuser":
            # the abusive shape: every request demands 4x the mix's
            # max output — slot time the other tenants never asked for
            a["max_new"] = abuse_new
    # gold is a deterministic PROBE TRAIN overlaid on the mix, evenly
    # spaced so it never self-collides: its solo tail is then a stable
    # reference and any p99 movement in the mixed arms is interference
    # from the neighbors, not gold-on-gold burst luck
    span = rows[-1]["ts_mono"] if rows else 8.0
    n_gold = 16
    gap = span / n_gold
    for k in range(n_gold):
        rows.append({
            "kind": "arrival", "rid": requests + k + 1,
            "ts_wall": None, "ts_mono": round((k + 0.5) * gap, 6),
            "tenant": "gold",
            "prompt": [(7 * (k + i) + 13) % spec.vocab
                       for i in range(gold_prompt)],
            "max_new": 8, "temperature": 1.0, "greedy": True,
            "eos_token_id": None, "seed": k, "deadline_ms": 0.0})
    rows.sort(key=lambda a: a["ts_mono"])

    qos_cfg = QoSConfig.from_spec({
        "default": {"priority": "batch"},
        "tenants": {
            "gold": {"priority": "interactive", "weight": 8.0},
            "abuser": {"priority": "best_effort", "weight": 1.0,
                       "rps": 1.0, "burst_s": 1.0, "max_slots": 1}},
        "breaker": {"offenses": 0}})

    dev = jax.devices()[0]
    # heavier than the other CPU serve benches on purpose: a 64-token
    # prefill must cost several decode steps, or the p99 ratio would
    # measure fixed dispatch overhead instead of interference
    cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=3,
                    num_heads=4, intermediate_size=256,
                    max_position=256, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.initialize()
    model(mx.np.array([[1, 2]], dtype="int32"))
    # prefill_chunk covers the longest prompt in ONE chunk: an
    # interactive prefill then pays at most one step of queueing behind
    # a seated neighbor instead of one per chunk
    # 3 slots/replica with the abuser bulkheaded to 1: a protected
    # arrival always finds a free slot, so its tail is interference,
    # not slot starvation
    sc = ServeConfig(max_slots=3, page_size=8, num_pages=0,
                     prefill_chunk=112, max_len=176)

    def drive(fleet, only=None):
        """Timing-faithful drive with NO shed retries — a shed is the
        datapoint here, not an obstacle."""
        t0 = time.perf_counter()
        handles, sheds = [], {}
        for a in rows:
            t = a["tenant"]
            if only is not None and t not in only:
                continue
            due = t0 + a["ts_mono"] / max(speed, 1e-6)
            while True:
                now = time.perf_counter()
                if now >= due:
                    break
                time.sleep(min(0.02, due - now))
            try:
                handles.append((t, fleet.submit(
                    a["prompt"], max_new_tokens=a["max_new"],
                    greedy=True, tenant=t)))
            except ShedError as e:
                by = sheds.setdefault(t, {})
                by[e.reason] = by.get(e.reason, 0) + 1
        drain_to = time.perf_counter() + 300.0
        for t, h in handles:
            try:
                h.result(timeout=max(0.1,
                                     drain_to - time.perf_counter()))
            except Exception:
                pass
        per = {}
        for t, h in handles:
            row = per.setdefault(t, {"submitted": 0, "finished": 0,
                                     "tokens": 0, "ttfts": []})
            row["submitted"] += 1
            if h.state == "finished":
                row["finished"] += 1
            row["tokens"] += len(h.tokens)
            if h.ttft_s is not None:
                row["ttfts"].append(h.ttft_s * 1e3)
        out = {}
        for t in sorted(set(per) | set(sheds)):
            row = per.get(t, {"submitted": 0, "finished": 0,
                              "tokens": 0, "ttfts": []})
            out[t] = {
                "submitted": row["submitted"],
                "finished": row["finished"],
                "shed": sum(sheds.get(t, {}).values()),
                "shed_reasons": dict(sorted(
                    sheds.get(t, {}).items())),
                "generated_tokens": row["tokens"],
                "ttft_p50_ms": _pct_of(row["ttfts"], 0.50),
                "ttft_p99_ms": _pct_of(row["ttfts"], 0.99),
            }
        return out

    def phase(label, qos, only=None, qos_off=False):
        if qos_off:
            os.environ["MXTPU_QOS"] = "0"
        try:
            fleet = ServeFleet(model, replicas=replicas, config=sc,
                               qos_config=qos, stall_timeout=30.0)
            fleet.warmup()
            with fleet:
                # prime the decode widths OUTSIDE the timed window so
                # no phase's tail is first-compile cost in disguise
                for p, n in ((list(range(2, 10)), 4),
                             (list(range(2, 26)), abuse_new),
                             (list(range(2, 2 + gold_prompt)), 8)):
                    fleet.submit(p, max_new_tokens=n).result(timeout=60)
                table = drive(fleet, only=only)
                qstats = (fleet.stats() or {}).get("qos")
        finally:
            if qos_off:
                os.environ.pop("MXTPU_QOS", None)
        return {"tenants": table, "qos": qstats}

    solo = phase("solo", qos_cfg, only={"gold"})
    on = phase("qos_on", qos_cfg)
    off = phase("qos_off", None, qos_off=True)

    def p99(ph):
        return (ph["tenants"].get("gold") or {}).get("ttft_p99_ms")

    def degrade(ph):
        base, got = p99(solo), p99(ph)
        if not base or got is None:
            return None
        return round(100.0 * (got - base) / base, 1)

    total_sheds = sum(t["shed"] for t in on["tenants"].values())
    abuser_sheds = (on["tenants"].get("abuser") or {}).get("shed", 0)
    abuser_share = (round(abuser_sheds / total_sheds, 3)
                    if total_sheds else None)
    deg_on, deg_off = degrade(on), degrade(off)
    contained = (deg_on is not None and deg_on < 20.0
                 and abuser_share is not None and abuser_share >= 0.9)
    os.environ.update(scoped)
    extras = {
        "replicas": replicas,
        "requests": requests,
        "seed": seed,
        "speed": speed,
        "workload_tenants": spec.tenants,
        "solo": solo["tenants"],
        "qos_on": on["tenants"],
        "qos_off": off["tenants"],
        "qos_stats": on["qos"],
        "gold_ttft_p99_ms": {"solo": p99(solo), "qos_on": p99(on),
                             "qos_off": p99(off)},
        "gold_degradation_pct": {"qos_on": deg_on, "qos_off": deg_off},
        "abuser_shed_share_qos_on": abuser_share,
        "contained": contained,
        "device": getattr(dev, "device_kind", str(dev)),
        "platform": dev.platform,
    }
    return {
        "metric": "serve_tenant_gold_p99_degradation_pct",
        "value": deg_on if deg_on is not None else -1.0,
        "unit": "percent",
        "vs_baseline": 0.0,
        "extras": extras,
    }


def _measure_data() -> dict:
    """`bench.py --data`: throughput of the deterministic input pipeline
    (docs/data.md) — indexed RecordIO shards through the mixture
    interleave and sequence packer, consumed via `DevicePrefetcher`.
    Reports host samples/sec plus the two latency numbers that say where
    the bottleneck is: the pipeline's batch-build time (`data_wait_ms`)
    and the consumer's wait at the prefetcher hand-out."""
    import tempfile

    import jax

    ambient = os.environ.get("JAX_PLATFORMS", "").lower()
    if not any(t in ambient for t in ("tpu", "axon")):
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")

    from mxnet_tpu import recordio
    from mxnet_tpu.data import (DataPipeline, MixtureDataset,
                                ShardedRecordDataset)
    from mxnet_tpu.parallel.prefetch import DevicePrefetcher

    n_shards, docs_per_shard, batches = 4, 2000, 200
    batch, seq_len = 16, 256
    rng = _onp.random.RandomState(0)
    with tempfile.TemporaryDirectory(prefix="mxtpu_bench_data") as root:
        t0 = time.perf_counter()
        for corpus, count in (("a", n_shards), ("b", 2)):
            for s in range(count):
                rec = os.path.join(root, f"{corpus}-{s}.rec")
                w = recordio.MXIndexedRecordIO(
                    rec.replace(".rec", ".idx"), rec, "w")
                for i in range(docs_per_shard):
                    toks = rng.randint(
                        0, 32000, 16 + int(rng.randint(0, 240))
                    ).astype(_onp.int32)
                    w.write_idx(i, toks.tobytes())
                w.close()
        build_s = time.perf_counter() - t0

        mix = MixtureDataset(
            [ShardedRecordDataset(os.path.join(root, "a-*.rec")),
             ShardedRecordDataset(os.path.join(root, "b-*.rec"))],
            weights=[0.8, 0.2], seed=0)
        pipe = DataPipeline(mix, batch_size=batch, seed=0,
                            seq_len=seq_len)
        pf = DevicePrefetcher(
            pipe,
            place=lambda b: {k: jax.device_put(v) for k, v in b.items()},
            depth=2)
        # warmup (readers open, first window fills), then timed run
        for _ in range(10):
            next(pf)
        t1 = time.perf_counter()
        tokens = 0
        for _ in range(batches):
            got = next(pf)
            tokens += int(got["tokens"].size)
        wall = time.perf_counter() - t1
        pstats, fstats = pipe.stats(), pf.stats()
        pf.close()

    samples = batches * batch
    return {
        "metric": "data_samples_per_sec",
        "value": round(samples / wall, 2),
        "unit": "samples_per_sec",
        "vs_baseline": 0.0,   # north-star baseline is MFU-on-TPU
        "extras": {
            "batches": batches,
            "batch_size": batch,
            "seq_len": seq_len,
            "tokens_per_sec": round(tokens / wall, 1),
            "pipeline_wait_ms_mean": pstats["mean_wait_ms"],
            "prefetch_wait_ms_mean": fstats["mean_wait_ms"],
            "prefetch_occupancy_mean": fstats["mean_occupancy"],
            "corpus_build_s": round(build_s, 2),
            "wall_s": round(wall, 3),
            "platform": jax.devices()[0].platform,
        },
    }


def _measure_ops() -> dict:
    """`bench.py --ops`: per-kernel microbenchmarks for the fused Pallas
    set (docs/perf.md "Fused kernels & autotuning").

    Times each kernel's ACTIVE path (Pallas on TPU, jnp reference on
    CPU — `ops.pallas.kernel_active`) against its forced-reference
    path, plus the pre-fusion legacy formulation where one exists (the
    dense MoE einsum pair, the unfused norm+residual chain), all
    through `opperf.time_callable` (median-of-k, synchronized).  The
    emitted JSON rides next to the standard bench fields so BENCH
    rounds can track kernel-level wins, not just end-to-end slope.
    """
    import jax
    import jax.numpy as jnp

    import mxnet_tpu  # noqa: F401  (backend + telemetry init)
    from mxnet_tpu.benchmark.opperf import time_callable
    from mxnet_tpu.ops import pallas as _pallas
    from mxnet_tpu.ops.pallas import fused_norm as _fnorm
    from mxnet_tpu.ops.pallas import moe_dispatch as _moed

    dev = jax.devices()[0]
    on_kernel_path = _pallas.kernel_active()
    rng = _onp.random.RandomState(0)
    f32 = jnp.float32
    ops: dict = {}

    from mxnet_tpu import tracing as _tracing

    def timed(fn, *args):
        jfn = jax.jit(fn)
        res = time_callable(lambda: jfn(*args), warmup=2, runs=5)
        # per-kernel cost attribution: the AOT lower/compile is served
        # from jit's cache (time_callable already compiled it), so this
        # costs one cost_analysis walk, not a second XLA compile
        try:
            feats = _tracing.cost_features_of(jfn.lower(*args).compile())
        except Exception:
            feats = None
        if feats:
            res["cost"] = {"flops": feats.get("flops"),
                           "bytes_accessed": feats.get("bytes_accessed")}
            mfu = _tracing.estimate_mfu(feats.get("flops"),
                                        res["median_ms"] / 1e3)
            if mfu is not None:
                res["cost"]["mfu_estimate"] = mfu["mfu_estimate"]
                res["cost"]["mfu_projected"] = mfu["projected"]
        return res

    # --- fused LayerNorm + residual ------------------------------------
    rows, h = 2048, 1024
    x = jnp.asarray(rng.randn(rows, h), f32)
    res = jnp.asarray(rng.randn(rows, h), f32)
    gam = jnp.ones((h,), f32)
    bet = jnp.zeros((h,), f32)

    def _ln_legacy(xv, rv, g, b):
        # pre-fusion chain: separate add, then the plain-op norm
        s = rv + xv
        mean = jnp.mean(s, axis=-1, keepdims=True)
        var = jnp.var(s, axis=-1, keepdims=True)
        return (s - mean) * jax.lax.rsqrt(var + 1e-5) * g + b, s

    ops["fused_norm"] = {
        "shape": [rows, h],
        "fused": timed(lambda a, r, g, b: _fnorm.layer_norm_residual(
            a, r, g, b, use_kernel=on_kernel_path), x, res, gam, bet),
        "reference": timed(lambda a, r, g, b: _fnorm.layer_norm_residual(
            a, r, g, b, use_kernel=False), x, res, gam, bet),
        "legacy": timed(_ln_legacy, x, res, gam, bet),
    }

    # --- blockwise MoE dispatch/combine --------------------------------
    t, e, cap, hm = 1024, 8, 192, 512
    xt = jnp.asarray(rng.randn(t, hm), f32)
    expert = jnp.asarray(rng.randint(0, e, t), jnp.int32)
    pos = jnp.asarray(rng.randint(0, cap, t), jnp.int32)
    kept = jnp.asarray(rng.rand(t) < 0.9)
    gate = jnp.asarray(rng.rand(t), f32)
    down = jnp.asarray(rng.randn(e, cap, hm), f32)

    # routing tensors ride as jit ARGUMENTS, never closure constants:
    # XLA would constant-fold the dispatch-tensor build (the very cost
    # the blockwise path removes) right out of the timed program
    def _moe_pair(use_kernel):
        def fn(xv, dn, ex, ps, kp, gt):
            buf = _moed.moe_dispatch(xv, ex, ps, kp, e, cap,
                                     use_kernel=use_kernel)
            out = _moed.moe_combine(dn, ex, ps, kp, gt,
                                    use_kernel=use_kernel)
            return buf, out
        return fn

    def _moe_dense(xv, dn, ex, ps, kp, gt):
        onehot = jax.nn.one_hot(ex, e, dtype=xv.dtype)
        disp = (onehot * kp[:, None].astype(xv.dtype))[:, :, None] * \
            jax.nn.one_hot(ps, cap, dtype=xv.dtype)[:, None, :]
        buf = jnp.einsum("tec,th->ech", disp, xv)
        out = jnp.einsum("tec,ech->th",
                         disp * gt[:, None, None].astype(xv.dtype), dn)
        return buf, out

    moe_args = (xt, down, expert, pos, kept, gate)
    ops["moe_dispatch"] = {
        "shape": [t, e, cap, hm],
        "fused": timed(_moe_pair(on_kernel_path), *moe_args),
        "reference": timed(_moe_pair(False), *moe_args),
        "legacy": timed(_moe_dense, *moe_args),
    }

    # --- fused multi-tensor optimizer ----------------------------------
    from mxnet_tpu.ops.pallas import fused_optimizer as _fopt
    from mxnet_tpu.optimizer import Adam
    opt = Adam(learning_rate=1e-3)
    # a transformer-ish leaf zoo: a few big matrices + a bias/scale tail
    sizes = [1 << 18] * 3 + [1 << 10] * 24
    params = {f"p{i}": jnp.asarray(rng.randn(n), f32)
              for i, n in enumerate(sizes)}
    grads = {k: jnp.asarray(rng.randn(v.size), f32)
             for k, v in params.items()}
    states = {k: (jnp.zeros_like(v), jnp.zeros_like(v))
              for k, v in params.items()}
    hp = {"lr": jnp.float32(1e-3), "wd": jnp.float32(0.0),
          "rescale_grad": jnp.float32(1.0), "clip_gradient": None,
          "t": jnp.float32(1.0)}
    skip = jnp.asarray(False)

    # hp and the skip flag are traced args like in the real step — a
    # closed-over concrete False would let XLA fold the skip selects away
    def _opt_fn(use_kernel):
        def fn(p, g, s, hpv, sk):
            return _fopt.apply_updates(opt, p, g, s, hpv, sk,
                                       use_kernel=use_kernel)
        return fn

    ops["fused_optimizer"] = {
        "shape": [int(sum(sizes)), len(sizes)],
        "fused": timed(_opt_fn(on_kernel_path and
                               _fopt.kernel_supported(opt)),
                       params, grads, states, hp, skip),
        "reference": timed(_opt_fn(False), params, grads, states, hp,
                           skip),
    }

    # --- flash attention (Pallas kernel only on the TPU backend) -------
    from mxnet_tpu.ops.attention import reference_attention
    b, nh, l, d = 4, 8, 512, 64
    q = jnp.asarray(rng.randn(b, nh, l, d), f32)
    k = jnp.asarray(rng.randn(b, nh, l, d), f32)
    v = jnp.asarray(rng.randn(b, nh, l, d), f32)
    ops["flash_attention"] = {
        "shape": [b, nh, l, d],
        "reference": timed(lambda a1, a2, a3: reference_attention(
            a1, a2, a3, causal=True), q, k, v),
    }
    if on_kernel_path:
        from mxnet_tpu.ops.pallas.flash_attention import flash_attention
        ops["flash_attention"]["fused"] = timed(
            lambda a1, a2, a3: flash_attention(a1, a2, a3, causal=True),
            q, k, v)

    # --- fused dequant-matmul (int8/int4 weight-only) ------------------
    from mxnet_tpu.ops.pallas import autotune as _at
    from mxnet_tpu.ops.pallas import quantized_matmul as _qmm
    qm, qn, qk = 256, 512, 512
    xq = jnp.asarray(rng.randn(qm, qk), f32)
    wq = jnp.asarray(rng.randn(qn, qk), f32)
    for bits in (8, 4):
        qt = _qmm.quantize_weight(wq, bits)
        # tuned block sizes: a warm second run must be a cache hit —
        # set MXTPU_AUTOTUNE_CACHE to persist across bench runs
        try:
            tr = _at.tune("quantized_matmul", (qm, qn, qk),
                          f"int{bits}", runs=2, top_k=2)
            tune_info = {"source": tr.source, "cache_hit": tr.cache_hit,
                         "trials": tr.trials}
        except Exception as e:   # tuning must never fail the bench
            tune_info = {"error": str(e).splitlines()[0]}

        # quantized planes ride as jit ARGUMENTS (the MoE rule): a
        # closed-over weight would let XLA constant-fold the dequant —
        # the very traffic the fused kernel deletes — out of the timing
        def _fused(a, qp, sp, b=bits, kk=qk):
            t = _qmm.QuantizedTensor(qp, sp, b, kk)
            return _qmm.quantized_matmul(a, t,
                                         use_kernel=on_kernel_path)

        def _deq_then_mm(a, qp, sp, b=bits, kk=qk):
            t = _qmm.QuantizedTensor(qp, sp, b, kk)
            return a @ _qmm.dequantize_weight(t).T

        rf = _qmm._roofline(
            _at.BlockConfig(block_m=128, block_n=128, block_k=512),
            (qm, qn, qk), f"int{bits}")
        ops[f"quantized_matmul_int{bits}"] = {
            "shape": [qm, qn, qk],
            "fused": timed(_fused, xq, qt.q, qt.scale),
            "reference": timed(_deq_then_mm, xq, qt.q, qt.scale),
            "f32": timed(lambda a, w: a @ w.T, xq, wq),
            "weight_bytes": qt.nbytes(),
            "weight_bytes_f32": int(wq.size) * 4,
            "weight_reduction": round(int(wq.size) * 4 / qt.nbytes(), 3),
            "bytes_moved_fused": int(rf["bytes"]),
            "bytes_moved_f32": int(qm * qk * 4 + qn * qk * 4
                                   + qm * qn * 4),
            "autotune": tune_info,
        }

    for entry in ops.values():
        f = entry.get("fused", {}).get("median_ms")
        r = entry.get("reference", {}).get("median_ms")
        if f and r:
            entry["speedup_vs_reference"] = round(r / f, 3)
        lg = entry.get("legacy", {}).get("median_ms")
        if f and lg:
            entry["speedup_vs_legacy"] = round(lg / f, 3)
        d = entry.get("f32", {}).get("median_ms")
        if f and d:
            entry["speedup_vs_f32"] = round(d / f, 3)

    return {
        "metric": "kernel_microbench",
        "value": round(ops["fused_norm"]["fused"]["median_ms"], 4),
        "unit": "ms_fused_norm_median",
        "vs_baseline": 0.0,   # north-star baseline is MFU-on-TPU
        "extras": {
            "ops": ops,
            "kernel_path": "pallas" if on_kernel_path else "reference",
            "pallas_mode": _pallas.pallas_mode(),
            "device": getattr(dev, "device_kind", str(dev)),
            "platform": dev.platform,
        },
    }


def _coldstart_child(role: str, art_dir: str) -> dict:
    """One cold-start measurement in THIS (fresh) process.

    ``live``: build a small GPT train step + serving engine, measure
    time-to-first-step/-token through trace+compile, then capture the
    export artifacts for the ``load`` child.  ``load``: same models,
    but warm-start from the artifacts — measure the same
    time-to-first-step with ZERO Python-level retraces (asserted)."""
    import jax

    ambient = os.environ.get("JAX_PLATFORMS", "").lower()
    if not any(t in ambient for t in ("tpu", "axon")):
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import optimizer as opt
    from mxnet_tpu import telemetry as _tele
    from mxnet_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from mxnet_tpu.parallel import make_mesh, make_sharded_train_step
    from mxnet_tpu.serve import InferenceEngine, ServeConfig
    import jax.numpy as jnp

    telemetry_on = _tele.enabled()
    cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                    num_heads=4, intermediate_size=128, max_position=128,
                    dropout=0.0)
    model = GPTForCausalLM(cfg)
    # deterministic init: live and load children must hold the same
    # weights for the loss/logit parity cross-check in extras
    from mxnet_tpu import random as _mxrng
    _mxrng.seed(0)
    model.initialize()
    rng = _onp.random.RandomState(0)
    ids = mx.np.array(rng.randint(0, 512, (8, 32)), dtype="int32")
    labels = mx.np.array(rng.randint(0, 512, (8, 32)), dtype="int32")
    model(ids)   # deferred init (outside the timed window for both roles)

    def loss_fn(out, input_ids, labels):
        from mxnet_tpu.ops.pallas.softmax_xent import softmax_cross_entropy
        o = out._data if hasattr(out, "_data") else out
        return jnp.mean(softmax_cross_entropy(o, labels.astype(jnp.int32)))

    mesh = make_mesh({"dp": 1}, jax.devices()[:1])
    step = make_sharded_train_step(model, opt.Adam(learning_rate=1e-3),
                                   loss_fn, mesh, num_model_args=1)
    train_art = os.path.join(art_dir, "train")
    serve_art = os.path.join(art_dir, "serve")

    # --- train: time to first retired step ----------------------------
    t0 = time.perf_counter()
    if role == "load":
        step.load_export(train_art, ids, labels)
    else:
        step.warmup(ids, labels)
    loss = float(jax.device_get(step.dispatch(ids, labels).loss))
    train_ttfs = time.perf_counter() - t0

    # --- serve: time to first token -----------------------------------
    eng = InferenceEngine(model, ServeConfig(max_len=64, max_slots=4))
    t0 = time.perf_counter()
    if role == "load":
        eng.warmup(artifact=serve_art)
    else:
        eng.warmup()
    first = {}
    h = eng.submit(list(range(1, 9)), max_new_tokens=4,
                   on_token=lambda t, r: first.setdefault(
                       "t", time.perf_counter()))
    eng.run_until_idle()
    serve_ttft = first.get("t", time.perf_counter()) - t0
    tokens = h.result(timeout=0)

    if role == "live":
        step.export(train_art, ids, labels)
        eng.export(serve_art)

    out = {
        "role": role,
        "train_ttfs_s": round(train_ttfs, 3),
        "serve_ttft_s": round(serve_ttft, 3),
        "loss": loss,
        "tokens": tokens,
        "trace_count": step.trace_count,
        "compile_seconds": round(step.compile_seconds or 0.0, 3),
    }
    if telemetry_on:
        out["telemetry"] = {"snapshot": _tele.snapshot()}
    return out


def _measure_coldstart() -> dict:
    """`bench.py --coldstart`: time-to-first-step (train) and
    time-to-first-token (serve) for the live-trace path vs the
    export-artifact load path, each measured in a FRESH child process
    (docs/export.md).  The headline value is the train cold-start
    speedup; extras carry both raw timings plus the loaded path's
    ``trace_count`` (must be 0 — the zero-retrace contract)."""
    import tempfile
    with tempfile.TemporaryDirectory(prefix="mxtpu_coldstart_") as art:
        results = {}
        for role in ("live", "load"):
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--coldstart-child", role, art],
                capture_output=True, text=True, timeout=900,
                cwd=os.path.dirname(os.path.abspath(__file__)))
            if proc.returncode != 0:
                tail = (proc.stderr or proc.stdout or "").strip()
                raise RuntimeError(
                    f"coldstart {role} child failed: {tail[-800:]}")
            for line in reversed(proc.stdout.strip().splitlines()):
                if line.startswith("{"):
                    results[role] = json.loads(line)
                    break
    live, load = results["live"], results["load"]
    parity = (live["loss"] == load["loss"]
              and live["tokens"] == load["tokens"])
    speedup = (live["train_ttfs_s"] / load["train_ttfs_s"]
               if load["train_ttfs_s"] > 0 else 0.0)
    return {
        "metric": "coldstart_train_speedup",
        "value": round(speedup, 3),
        "unit": "live_ttfs_over_artifact_ttfs",
        "vs_baseline": 0.0,   # north-star baseline is MFU-on-TPU
        "extras": {
            "train_ttfs_live_s": live["train_ttfs_s"],
            "train_ttfs_load_s": load["train_ttfs_s"],
            "serve_ttft_live_s": live["serve_ttft_s"],
            "serve_ttft_load_s": load["serve_ttft_s"],
            "serve_ttft_speedup": round(
                live["serve_ttft_s"] / load["serve_ttft_s"], 3)
            if load["serve_ttft_s"] > 0 else 0.0,
            "loaded_trace_count": load["trace_count"],
            "parity": parity,
            "loss": load["loss"],
        },
    }


def _run_child(platform: str, timeout: float):
    """Run `bench.py --measure <platform>` in a child; return (dict|None, err).

    On timeout the child gets SIGINT + a grace period before SIGKILL:
    a hard-killed process holding (or waiting on) the remote TPU claim
    wedges the tunnel for every later attempt, so exiting cleanly matters
    more than exiting fast."""
    import signal as _signal
    popen = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--measure", platform],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    try:
        out, err_s = popen.communicate(timeout=timeout)
        proc = subprocess.CompletedProcess(popen.args, popen.returncode,
                                           out, err_s)
    except subprocess.TimeoutExpired:
        popen.send_signal(_signal.SIGINT)
        try:
            popen.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            popen.kill()
            try:
                popen.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        return None, f"timeout after {timeout}s"
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-8:]
        return None, (f"rc={proc.returncode}: " + " | ".join(tail))[-500:]
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except json.JSONDecodeError:
                continue
    return None, "no JSON line in child output"


_LATEST_TPU = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "bench_results", "latest_tpu.json")


def _remember_tpu_result(result: dict) -> None:
    """Persist the newest successful TPU measurement so a later run that
    hits a wedged/unavailable tunnel can still report the last real
    number alongside its fallback (clearly labeled, never substituted)."""
    try:
        if result.get("extras", {}).get("platform") == "tpu":
            os.makedirs(os.path.dirname(_LATEST_TPU), exist_ok=True)
            stamped = dict(result)
            stamped["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                   time.gmtime())
            with open(_LATEST_TPU, "w") as f:
                json.dump(stamped, f)
    except OSError:
        pass


def _last_known_tpu():
    """Load the carried TPU record, stamped ``stale: true`` +
    ``rounds_stale`` so a reader of the driver's BENCH_r{N}.json can never
    mistake a carried number for a current measurement.  ``rounds_stale``
    counts the committed BENCH_r*.json files that carried this same
    ``measured_at`` (i.e. rounds whose driver bench run could not reach
    the TPU) plus the current run."""
    try:
        with open(_LATEST_TPU) as f:
            rec = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    measured = rec.get("measured_at")
    rounds = 1
    root = os.path.dirname(os.path.abspath(__file__))
    for fn in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        try:
            with open(fn) as f:
                prev = json.load(f)
            carried = ((prev.get("parsed") or {}).get("extras", {})
                       .get("last_known_tpu") or {})
            if measured and carried.get("measured_at") == measured:
                rounds += 1
        except (OSError, json.JSONDecodeError):
            pass
    rec["stale"] = True
    rec["rounds_stale"] = rounds
    return rec


def _emit_stale_telemetry(last: dict) -> None:
    """Surface served-stale-TPU-results LOUDLY: a human-readable warning
    line on stderr (a reader skimming driver logs must not need to parse
    the JSON blob or a gauge to notice the TPU number is carried), plus a
    ``bench_stale_rounds`` gauge and a ``stale_bench`` journal event.
    Telemetry is lazy + guarded: the orchestrator only reaches this on
    the already-slow TPU-unreachable path, and a broken telemetry import
    must not cost the driver its bench line."""
    print(
        f"WARNING: bench rounds_stale={int(last.get('rounds_stale', 1))} — "
        f"TPU unreachable; the reported last_known_tpu value was measured "
        f"{last.get('measured_at', '<unknown>')} and is NOT current "
        f"(re-measure on the first round the tunnel is back)",
        file=sys.stderr)
    try:
        from mxnet_tpu import telemetry as _tele
        rounds = int(last.get("rounds_stale", 1))
        _tele.gauge(
            "bench_stale_rounds",
            "Consecutive bench rounds serving the carried last-known-TPU "
            "result instead of a fresh measurement").set(rounds)
        _tele.event("stale_bench", rounds_stale=rounds,
                    measured_at=last.get("measured_at"))
    except Exception:
        pass


_CLAIM_LOCK = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "bench_results", ".tpu_claim.lock")


def _wait_for_claim_lock(max_wait=5700.0):
    """If another measurement (the tunnel watcher's bench/ablation run)
    holds the TPU claim, wait for it instead of contending — two clients
    fighting over the exclusive claim is how attempts turn into hangs.
    The cap exceeds the 5400 s staleness window, so the only way past a
    LIVE holder is the holder finishing; stale locks are ignored."""
    if os.environ.get("MXTPU_CLAIM_HOLDER"):
        return   # we ARE the lock holder (the watcher invoking bench.py)
    t0 = time.time()
    while time.time() - t0 < max_wait:
        try:
            age = time.time() - os.path.getmtime(_CLAIM_LOCK)
        except OSError:
            return
        if age > 5400:
            return
        time.sleep(30)


class _ClaimLock:
    """Advertise THIS process's TPU use via the shared lockfile (refreshed
    by a daemon thread) so watcher and driver benches never contend —
    whichever starts first holds the chip, the other waits."""

    def __enter__(self):
        if os.environ.get("MXTPU_CLAIM_HOLDER"):
            self._mine = False   # the watcher already owns + refreshes it
            return self
        self._mine = True
        self._stop = False
        os.makedirs(os.path.dirname(_CLAIM_LOCK), exist_ok=True)
        try:   # synchronously, so the lock exists when __enter__ returns
            with open(_CLAIM_LOCK, "w") as f:
                f.write(str(os.getpid()))
        except OSError:
            pass

        def keepalive():
            while not self._stop:
                for _ in range(60):
                    if self._stop:
                        return
                    time.sleep(1)
                try:
                    os.utime(_CLAIM_LOCK)
                except OSError:
                    try:
                        with open(_CLAIM_LOCK, "w") as f:
                            f.write(str(os.getpid()))
                    except OSError:
                        pass

        import threading
        self._thread = threading.Thread(target=keepalive, daemon=True)
        self._thread.start()
        return self

    def __exit__(self, *exc):
        if self._mine:
            self._stop = True
            self._thread.join(timeout=5)
            try:
                os.remove(_CLAIM_LOCK)
            except OSError:
                pass
        return False


def _flag_operand(flag: str, default: str) -> str:
    """Value following `flag` in argv (or `default` when absent/bare)."""
    if flag not in sys.argv:
        return default
    idx = sys.argv.index(flag)
    if idx + 1 >= len(sys.argv) or sys.argv[idx + 1].startswith("--"):
        return default
    return sys.argv[idx + 1]


def main():
    if "--telemetry" in sys.argv:
        # flag travels to the measurement child through the environment
        # (which also auto-enables instrumentation at mxnet_tpu import)
        sys.argv.remove("--telemetry")
        os.environ["MXTPU_TELEMETRY"] = "1"
    if len(sys.argv) >= 3 and sys.argv[1] == "--measure":
        print(json.dumps(_measure(sys.argv[2])))
        return
    if len(sys.argv) >= 4 and sys.argv[1] == "--coldstart-child":
        print(json.dumps(_coldstart_child(sys.argv[2], sys.argv[3])))
        return
    if "--coldstart" in sys.argv:
        # live-trace vs artifact-load time-to-first-step, fresh child
        # process per role (docs/export.md); may claim the TPU
        _wait_for_claim_lock()
        with _ClaimLock():
            print(json.dumps(_measure_coldstart()))
        return
    if "--ops" in sys.argv:
        # per-kernel microbenchmarks (fused vs reference vs legacy) —
        # claim-locked like --serve: the measurement may run on the TPU
        _wait_for_claim_lock()
        with _ClaimLock():
            print(json.dumps(_measure_ops()))
        return
    if "--data" in sys.argv:
        # input-pipeline throughput (docs/data.md) — CPU-side work, but
        # device placement runs through the prefetcher, so serialize
        # behind the claim lock like every other entry point that may
        # touch the chip
        _wait_for_claim_lock()
        with _ClaimLock():
            print(json.dumps(_measure_data()))
        return
    if "--gen-trace" in sys.argv:
        # deterministic workload generation (docs/serving.md "Flight
        # recorder & replay"): emit a journal-format trace as a pure
        # function of --seed — no device work, no claim lock
        from mxnet_tpu.serve import traffic as _traffic
        overrides = {}
        for flag, field, cast in (("--seed", "seed", int),
                                  ("--requests", "requests", int),
                                  ("--rps", "rate_rps", float),
                                  ("--burst", "burst_factor", float),
                                  ("--prefix-frac", "prefix_frac", float)):
            if flag in sys.argv:
                overrides[field] = cast(_flag_operand(flag, "0"))
        wspec = _traffic.WorkloadSpec.from_env(**overrides)
        path = _flag_operand("--gen-trace", "trace.jsonl")
        rows = _traffic.generate_workload(wspec)
        _traffic.write_trace(rows, path, wspec)
        print(json.dumps({"trace": os.path.abspath(path),
                          "requests": len(rows),
                          "seed": wspec.seed,
                          "span_s": round(rows[-1]["ts_mono"], 3)
                          if rows else 0.0}))
        return
    if "--serve" in sys.argv:
        # a direct user entry point that may claim the TPU — go through
        # the same exclusive claim lock as the orchestrated bench (two
        # clients contending for the chip is how attempts become hangs);
        # harmless extra serialization when the backend resolves to CPU
        _wait_for_claim_lock()
        with _ClaimLock():
            # --spec k: k-token speculative decoding + cross-request
            # prefix caching over a shared-prefix workload mix
            # (docs/serving.md "Speculative decoding & prefix caching")
            spec = int(_flag_operand("--spec", "0")) \
                if "--spec" in sys.argv else 0
            if "--trace" in sys.argv:
                # replay mode: re-drive a recorded/generated trace and
                # report digest divergence (docs/serving.md "Flight
                # recorder & replay")
                kill_mode = _flag_operand("--kill-mode", "thread")
                if kill_mode not in ("thread", "process"):
                    raise SystemExit(
                        f"--kill-mode must be thread|process, "
                        f"got {kill_mode!r}")
                print(json.dumps(_measure_serve_replay(
                    _flag_operand("--trace", "trace.jsonl"),
                    int(_flag_operand("--replicas", "2")),
                    speed=float(_flag_operand("--speed", "0")),
                    kill_at=(float(_flag_operand("--kill-at", "0"))
                             if "--kill-at" in sys.argv else None),
                    kill_mode=kill_mode)))
            elif "--disagg" in sys.argv:
                # prefill/decode disaggregation: P prefill + D decode
                # replicas, tp-sharded decode (docs/serving.md
                # "Disaggregated serving"); --tp defaults to 2 so the
                # tensor-parallel fused step is on the measured path
                print(json.dumps(_measure_serve_disagg(
                    _flag_operand("--disagg", "1x2"),
                    int(_flag_operand("--tp", "2")))))
            elif "--tenants" in sys.argv:
                # multi-tenant QoS mode: solo / qos-on / qos-off arms
                # over one seeded tenant mix with an abusive tenant
                # (docs/serving.md "Per-tenant QoS"); headline is the
                # protected tenant's p99 TTFT degradation vs solo
                print(json.dumps(_measure_serve_tenants(
                    replicas=int(_flag_operand("--replicas", "2")),
                    requests=int(_flag_operand("--requests", "128")),
                    seed=int(_flag_operand("--seed", "0")),
                    speed=float(_flag_operand("--speed", "1.0")))))
            elif "--replicas" in sys.argv:
                # fleet mode: aggregate tokens/s + tail TTFT under
                # replica loss (docs/serving.md "Fleet, failover &
                # overload"); --kill-at S kills a loaded replica S
                # seconds into the load window
                # --kill-mode process: process-transport fleet, the
                # kill is a real SIGKILL on a worker (ledger failover
                # + respawn instead of in-process salvage)
                kill_mode = _flag_operand("--kill-mode", "thread") \
                    if "--kill-mode" in sys.argv else "thread"
                if kill_mode not in ("thread", "process"):
                    raise SystemExit(
                        f"--kill-mode must be thread|process, "
                        f"got {kill_mode!r}")
                print(json.dumps(_measure_serve_fleet(
                    int(_flag_operand("--replicas", "2")),
                    (float(_flag_operand("--kill-at", "0"))
                     if "--kill-at" in sys.argv else None),
                    spec=spec, kill_mode=kill_mode)))
            else:
                print(json.dumps(_measure_serve(spec=spec)))
        return

    _wait_for_claim_lock()
    with _ClaimLock():
        _main_attempts()


def _main_attempts():
    errors = []
    oom_retry_left = True
    attempts = list(ATTEMPT_TIMEOUTS)
    # fast-fail wedges (UNAVAILABLE in seconds) sometimes heal within
    # minutes: spend up to this much extra wall clock on patient, clean
    # retries (child exits on its own each time — never a hard kill)
    patience = 900.0
    while attempts:
        timeout = attempts.pop(0)
        t0 = time.time()
        result, err = _run_child("default", timeout)
        if result is not None:
            _remember_tpu_result(result)
            print(json.dumps(result))
            return
        errors.append(err)
        elapsed = time.time() - t0
        if oom_retry_left and (
                "MEMORY" in (err or "").upper() or "OOM" in (err or "").upper()):
            # larger default batch blew HBM: drop to the proven round-1
            # config and guarantee one more TPU attempt at that size
            os.environ["MXTPU_BENCH_BATCH"] = "32"
            oom_retry_left = False
            if not attempts:
                attempts.append(ATTEMPT_TIMEOUTS[-1])
        elif elapsed < 90 and patience > 0 and "UNAVAILABLE" in (err or ""):
            # tunnel fast-fail mode: wait out a slice of the patience
            # budget and queue another attempt
            wait = min(120.0, patience)
            time.sleep(wait)
            patience -= wait + elapsed
            attempts.append(ATTEMPT_TIMEOUTS[-1])

    # TPU unreachable — CPU fallback so the driver still gets a numeric line
    result, err = _run_child("cpu", CPU_TIMEOUT)
    if result is None:
        out = {"metric": "bert_base_pretrain_mfu", "value": 0.0,
               "unit": "MFU_fraction", "vs_baseline": 0.0,
               "extras": {"error": f"tpu: {errors}; cpu: {err}"}}
        last = _last_known_tpu()
        if last is not None:
            out["extras"]["last_known_tpu"] = last
            _emit_stale_telemetry(last)
        print(json.dumps(out))
        return
    result["extras"]["tpu_unavailable"] = "; ".join(e or "" for e in errors)
    last = _last_known_tpu()
    if last is not None:
        # the value above is the honest CPU fallback; this is the most
        # recent REAL TPU measurement for context (timestamped)
        result["extras"]["last_known_tpu"] = last
        _emit_stale_telemetry(last)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
