/*
 * Tour of the widened C ABI surface (parity with the reference C API groups
 * in `include/mxnet/c_api.h`): runtime introspection (version, op listing,
 * feature discovery), dtype-aware NDArray create, .npz save/load, waitall,
 * autograd record/backward/grad, KVStore init/push/pull, and the profiler.
 *
 * Prints "CAPI TOUR OK" at the end for the test harness to grep; any
 * failed Check throws and exits nonzero.
 */
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <string>
#include <vector>

#include <mxnet-tpu-cpp/MxNetTpuCpp.hpp>

namespace {

void Expect(bool ok, const char* what) {
  if (!ok) {
    std::fprintf(stderr, "FAILED: %s\n", what);
    std::exit(1);
  }
}

}  // namespace

int main(int argc, char** argv) {
  const char* platform = argc > 1 ? argv[1] : "cpu";
  const std::string tmpdir = argc > 2 ? argv[2] : ".";
  mxtpu::Runtime rt(platform);

  /* --- introspection --------------------------------------------------- */
  int version = mxtpu::Runtime::Version();
  std::printf("version: %d\n", version);
  Expect(version >= 100, "version >= 0.1.0");

  auto ops = mxtpu::Runtime::ListOps();
  std::printf("ops: %zu\n", ops.size());
  Expect(ops.size() > 300, "op registry lists the full surface");
  bool has_add = false, has_conv = false;
  for (const auto& n : ops) {
    if (n == "add") has_add = true;
    if (n == "convolution") has_conv = true;
  }
  Expect(has_add, "'add' listed");
  Expect(has_conv, "'convolution' listed");

  Expect(mxtpu::Runtime::FeatureEnabled("XLA"), "XLA feature on");
  Expect(mxtpu::Runtime::FeatureEnabled("BF16"), "BF16 feature on");
  Expect(!mxtpu::Runtime::FeatureEnabled("CUDA"), "CUDA feature off");

  /* --- dtype-aware create + waitall ------------------------------------ */
  std::vector<float> xs = {1.f, 2.f, 3.f, 4.f, 5.f, 6.f};
  auto xbf = mxtpu::NDArray::FromVector({2, 3}, xs, "bfloat16");
  Expect(xbf.DType() == "bfloat16", "bfloat16 create");
  auto xi = mxtpu::NDArray::FromVector({2, 3}, xs, "int32");
  Expect(xi.DType() == "int32", "int32 create");
  mxtpu::Runtime::WaitAll();

  /* --- save / load ----------------------------------------------------- */
  auto a = mxtpu::NDArray::FromVector({2, 2}, {1.f, 2.f, 3.f, 4.f});
  auto b = mxtpu::NDArray::FromVector({3}, {5.f, 6.f, 7.f});
  const std::string npz = tmpdir + "/capi_tour_params.npz";
  mxtpu::NDArray::Save(npz, {{"weight", &a}, {"bias", &b}});
  auto loaded = mxtpu::NDArray::Load(npz);
  Expect(loaded.size() == 2, "load count");
  for (auto& kv : loaded) {
    auto v = kv.second.ToVector();
    if (kv.first == "bias") {
      Expect(v.size() == 3 && v[2] == 7.f, "bias round-trip");
    } else {
      Expect(kv.first == "weight" && v.size() == 4 && v[3] == 4.f,
             "weight round-trip");
    }
  }

  /* --- autograd: d/dx sum(x*x) = 2x ------------------------------------ */
  auto x = mxtpu::NDArray::FromVector({3}, {1.f, 2.f, 3.f});
  x.AttachGrad();
  {
    mxtpu::AutogradRecord rec;
    auto y = mxtpu::Op("multiply")(x, x);
    auto s = mxtpu::Op("sum")(y);
    s.Backward();
  }
  auto g = x.Grad().ToVector();
  Expect(g.size() == 3, "grad size");
  for (int i = 0; i < 3; ++i) {
    Expect(std::fabs(g[i] - 2.f * (i + 1)) < 1e-5, "grad = 2x");
  }

  /* --- kvstore --------------------------------------------------------- */
  mxtpu::KVStore kv("local");
  Expect(kv.Rank() == 0 && kv.NumWorkers() == 1, "local kv topology");
  auto w0 = mxtpu::NDArray::FromVector({2}, {1.f, 1.f});
  kv.Init(7, w0);
  /* push of a per-device value list reduces before storing (no updater
   * set -> the reduced value replaces the store, reference local-store
   * semantics) */
  auto grad = mxtpu::NDArray::FromVector({2}, {0.5f, -0.5f});
  kv.Push(7, grad);
  auto pulled = kv.Pull(7).ToVector();
  Expect(std::fabs(pulled[0] - 0.5f) < 1e-5 &&
             std::fabs(pulled[1] + 0.5f) < 1e-5,
         "kv push/pull reduce-and-store");

  /* --- profiler -------------------------------------------------------- */
  mxtpu::Profiler::Start();
  auto r = mxtpu::Op("add")(a, a);
  (void)r.ToVector();
  mxtpu::Profiler::Stop();
  std::string table = mxtpu::Profiler::Dumps();
  std::printf("profiler table bytes: %zu\n", table.size());
  Expect(!table.empty(), "profiler dumps non-empty");
  Expect(mxtpu::Profiler::Dumps() == table, "Dumps() is non-destructive");
  (void)mxtpu::Profiler::Dumps(/*reset=*/true);

  std::printf("CAPI TOUR OK\n");
  return 0;
}
