/*
 * Tour of the widened C ABI surface (parity with the reference C API groups
 * in `include/mxnet/c_api.h`): runtime introspection (version, op listing,
 * feature discovery), dtype-aware NDArray create, .npz save/load, waitall,
 * autograd record/backward/grad, KVStore init/push/pull, and the profiler.
 *
 * Prints "CAPI TOUR OK" at the end for the test harness to grep; any
 * failed Check throws and exits nonzero.
 */
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <string>
#include <vector>

#include <mxnet-tpu-cpp/MxNetTpuCpp.hpp>

namespace {

void Expect(bool ok, const char* what) {
  if (!ok) {
    std::fprintf(stderr, "FAILED: %s\n", what);
    std::exit(1);
  }
}

}  // namespace

int main(int argc, char** argv) {
  const char* platform = argc > 1 ? argv[1] : "cpu";
  const std::string tmpdir = argc > 2 ? argv[2] : ".";
  mxtpu::Runtime rt(platform);

  /* --- introspection --------------------------------------------------- */
  int version = mxtpu::Runtime::Version();
  std::printf("version: %d\n", version);
  Expect(version >= 100, "version >= 0.1.0");

  auto ops = mxtpu::Runtime::ListOps();
  std::printf("ops: %zu\n", ops.size());
  Expect(ops.size() > 300, "op registry lists the full surface");
  bool has_add = false, has_conv = false;
  for (const auto& n : ops) {
    if (n == "add") has_add = true;
    if (n == "convolution") has_conv = true;
  }
  Expect(has_add, "'add' listed");
  Expect(has_conv, "'convolution' listed");

  Expect(mxtpu::Runtime::FeatureEnabled("XLA"), "XLA feature on");
  Expect(mxtpu::Runtime::FeatureEnabled("BF16"), "BF16 feature on");
  Expect(!mxtpu::Runtime::FeatureEnabled("CUDA"), "CUDA feature off");

  /* --- dtype-aware create + waitall ------------------------------------ */
  std::vector<float> xs = {1.f, 2.f, 3.f, 4.f, 5.f, 6.f};
  auto xbf = mxtpu::NDArray::FromVector({2, 3}, xs, "bfloat16");
  Expect(xbf.DType() == "bfloat16", "bfloat16 create");
  auto xi = mxtpu::NDArray::FromVector({2, 3}, xs, "int32");
  Expect(xi.DType() == "int32", "int32 create");
  mxtpu::Runtime::WaitAll();

  /* --- save / load ----------------------------------------------------- */
  auto a = mxtpu::NDArray::FromVector({2, 2}, {1.f, 2.f, 3.f, 4.f});
  auto b = mxtpu::NDArray::FromVector({3}, {5.f, 6.f, 7.f});
  const std::string npz = tmpdir + "/capi_tour_params.npz";
  mxtpu::NDArray::Save(npz, {{"weight", &a}, {"bias", &b}});
  auto loaded = mxtpu::NDArray::Load(npz);
  Expect(loaded.size() == 2, "load count");
  for (auto& kv : loaded) {
    auto v = kv.second.ToVector();
    if (kv.first == "bias") {
      Expect(v.size() == 3 && v[2] == 7.f, "bias round-trip");
    } else {
      Expect(kv.first == "weight" && v.size() == 4 && v[3] == 4.f,
             "weight round-trip");
    }
  }

  /* --- autograd: d/dx sum(x*x) = 2x ------------------------------------ */
  auto x = mxtpu::NDArray::FromVector({3}, {1.f, 2.f, 3.f});
  x.AttachGrad();
  {
    mxtpu::AutogradRecord rec;
    auto y = mxtpu::Op("multiply")(x, x);
    auto s = mxtpu::Op("sum")(y);
    s.Backward();
  }
  auto g = x.Grad().ToVector();
  Expect(g.size() == 3, "grad size");
  for (int i = 0; i < 3; ++i) {
    Expect(std::fabs(g[i] - 2.f * (i + 1)) < 1e-5, "grad = 2x");
  }

  /* --- kvstore --------------------------------------------------------- */
  mxtpu::KVStore kv("local");
  Expect(kv.Rank() == 0 && kv.NumWorkers() == 1, "local kv topology");
  auto w0 = mxtpu::NDArray::FromVector({2}, {1.f, 1.f});
  kv.Init(7, w0);
  /* push of a per-device value list reduces before storing (no updater
   * set -> the reduced value replaces the store, reference local-store
   * semantics) */
  auto grad = mxtpu::NDArray::FromVector({2}, {0.5f, -0.5f});
  kv.Push(7, grad);
  auto pulled = kv.Pull(7).ToVector();
  Expect(std::fabs(pulled[0] - 0.5f) < 1e-5 &&
             std::fabs(pulled[1] + 0.5f) < 1e-5,
         "kv push/pull reduce-and-store");

  /* --- profiler -------------------------------------------------------- */
  mxtpu::Profiler::Start();
  auto r = mxtpu::Op("add")(a, a);
  (void)r.ToVector();
  mxtpu::Profiler::Stop();
  std::string table = mxtpu::Profiler::Dumps();
  std::printf("profiler table bytes: %zu\n", table.size());
  Expect(!table.empty(), "profiler dumps non-empty");
  Expect(mxtpu::Profiler::Dumps() == table, "Dumps() is non-destructive");
  (void)mxtpu::Profiler::Dumps(/*reset=*/true);

  /* --- symbol construction + JSON round trip (MXSymbol* parity) -------- */
  MXTPUSymbolHandle sx = nullptr, sw = nullptr, sdot = nullptr,
                    sout = nullptr, sback = nullptr;
  Expect(MXTPUSymbolCreateVariable("x", &sx) == 0, "sym var x");
  Expect(MXTPUSymbolCreateVariable("w", &sw) == 0, "sym var w");
  MXTPUSymbolHandle dot_in[2] = {sx, sw};
  Expect(MXTPUSymbolCreateFromOp("dot", "xw", dot_in, 2, nullptr, &sdot) == 0,
         "sym dot(x, w)");
  MXTPUSymbolHandle one_in[1] = {sdot};
  Expect(MXTPUSymbolCreateFromOp("_plus_scalar", "biased", one_in, 1,
                                 "{\"scalar\": 1.0}", &sout) == 0,
         "sym + scalar");
  const char* names[8];
  int n_names = 8;
  Expect(MXTPUSymbolListArguments(sout, names, &n_names) == 0 &&
             n_names == 2,
         "sym arguments = {x, w}");
  const char* sjson = nullptr;
  Expect(MXTPUSymbolSaveJSON(sout, &sjson) == 0 && sjson[0] == '{',
         "sym to json");
  std::string json_copy(sjson);
  Expect(MXTPUSymbolLoadJSON(json_copy.c_str(), &sback) == 0,
         "sym json round trip");

  /* --- iterator-fed eval loop (MXDataIter* parity): stream batches from
   * an NDArrayIter through the symbol executor ------------------------- */
  const char* iter_names = nullptr;
  int n_iters = 0;
  Expect(MXTPUListDataIters(&iter_names, &n_iters) == 0 && n_iters >= 5,
         "iterator registry lists 5 types");
  std::vector<float> feat(8 * 3);
  std::vector<float> lab(8);
  for (int i = 0; i < 8; ++i) {
    for (int j = 0; j < 3; ++j) feat[i * 3 + j] = 0.1f * (i + j);
    lab[i] = static_cast<float>(i);
  }
  auto fx = mxtpu::NDArray::FromVector({8, 3}, feat);
  auto fy = mxtpu::NDArray::FromVector({8}, lab);
  auto wv = mxtpu::NDArray::FromVector({3}, {1.f, 2.f, 3.f});
  MXTPUDataIterHandle it = nullptr;
  Expect(MXTPUDataIterCreateFromArrays(fx.handle(), fy.handle(), 4, 0,
                                       &it) == 0,
         "NDArrayIter from arrays");
  int batches = 0;
  float first_out = -1.f;
  for (int epoch = 0; epoch < 2; ++epoch) {
    int more = 0;
    Expect(MXTPUDataIterReset(it) == 0, "iter reset");
    while (MXTPUDataIterNext(it, &more) == 0 && more) {
      MXTPUNDArrayHandle bd = nullptr, bl = nullptr;
      Expect(MXTPUDataIterGetData(it, &bd) == 0, "batch data");
      Expect(MXTPUDataIterGetLabel(it, &bl) == 0, "batch label");
      const char* arg_names[2] = {"x", "w"};
      MXTPUNDArrayHandle arg_vals[2] = {bd, wv.handle()};
      MXTPUNDArrayHandle outs[2];
      int n_out = 2;
      Expect(MXTPUSymbolEval(sback, arg_names, arg_vals, 2, outs,
                             &n_out) == 0 &&
                 n_out == 1,
             "iterator-fed symbol eval");
      if (batches == 0) {
        float buf[4];
        Expect(MXTPUNDArrayCopyTo(outs[0], buf, 4) == 0, "eval out copy");
        first_out = buf[0];   // row0 = dot([0, .1, .2], [1,2,3]) + 1
      }
      MXTPUNDArrayFree(outs[0]);
      MXTPUNDArrayFree(bd);
      MXTPUNDArrayFree(bl);
      ++batches;
    }
  }
  Expect(batches == 4, "2 epochs x 2 batches of 4");
  Expect(std::fabs(first_out - 1.8f) < 1e-5, "eval numerics");
  MXTPUDataIterFree(it);

  /* file-driven iterator: CSVIter over a file written here */
  const std::string csv = tmpdir + "/capi_tour.csv";
  {
    std::FILE* f = std::fopen(csv.c_str(), "w");
    Expect(f != nullptr, "csv open");
    for (int i = 0; i < 6; ++i) {
      std::fprintf(f, "%d,%d,%d\n", i, i + 1, i + 2);
    }
    std::fclose(f);
  }
  std::string csv_params = "{\"data_csv\": \"" + csv +
                           "\", \"data_shape\": [3], \"batch_size\": 3}";
  MXTPUDataIterHandle cit = nullptr;
  Expect(MXTPUDataIterCreate("CSVIter", csv_params.c_str(), &cit) == 0,
         "CSVIter create");
  int more = 0, csv_batches = 0;
  while (MXTPUDataIterNext(cit, &more) == 0 && more) ++csv_batches;
  Expect(csv_batches == 2, "CSVIter batches");
  MXTPUDataIterFree(cit);

  /* --- model (CachedOp) flags ------------------------------------------ */
  MXTPUModelHandle mflags = nullptr;
  Expect(MXTPUModelCreate(
             "{\"type\":\"mlp\",\"in_units\":3,\"layers\":[4,2]}",
             &mflags) == 0,
         "model for flags");
  const char* fjson = nullptr;
  Expect(MXTPUModelGetFlags(mflags, &fjson) == 0, "get flags");
  Expect(std::string(fjson).find("\"static_alloc\": true") !=
             std::string::npos,
         "static_alloc always true");
  Expect(MXTPUModelSetFlags(mflags, "{\"training\": true}") == 0,
         "set training flag");
  Expect(MXTPUModelSetFlags(mflags, "{\"static_alloc\": false}") != 0,
         "disabling static_alloc errors");
  Expect(MXTPUModelSetFlags(mflags, "{\"bogus\": 1}") != 0,
         "unknown flag errors");
  MXTPUModelFree(mflags);

  MXTPUSymbolFree(sback);
  MXTPUSymbolFree(sout);
  MXTPUSymbolFree(sdot);
  MXTPUSymbolFree(sw);
  MXTPUSymbolFree(sx);

  std::printf("CAPI TOUR OK\n");
  return 0;
}
