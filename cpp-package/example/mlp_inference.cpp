/*
 * C++ inference walkthrough (parity: the reference's
 * cpp-package/example/mlp.cpp, redesigned for exported-model inference):
 * load a `HybridBlock.export` artifact pair, run it, and exercise the
 * by-name operator surface. Expects argv[1] = symbol file, argv[2] =
 * params file; prints the argmax of the first output row.
 */
#include <cstdio>
#include <vector>

#include "mxnet-tpu-cpp/MxNetTpuCpp.hpp"

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: %s <symbol.stablehlo> <params>\n", argv[0]);
    return 2;
  }
  const char* platform = argc > 3 ? argv[3] : "";
  mxtpu::Runtime rt(platform);
  mxtpu::Runtime::Seed(7);

  // by-name operator invocation
  auto x = mxtpu::NDArray::FromVector({2, 4}, {1, -2, 3, -4,
                                               -1, 2, -3, 4});
  auto r = mxtpu::Op("relu")(x);
  auto v = r.ToVector();
  std::printf("relu: %.1f %.1f %.1f %.1f\n", v[0], v[1], v[2], v[3]);

  // exported-model inference
  mxtpu::Model model(argv[1], argv[2]);
  auto in = mxtpu::NDArray::FromVector({1, 4}, {0.5f, -0.5f, 0.25f, 1.0f});
  auto out = model.Forward({&in});
  auto probs = out[0].ToVector();
  int best = 0;
  for (size_t i = 1; i < probs.size(); ++i) {
    if (probs[i] > probs[best]) best = static_cast<int>(i);
  }
  std::printf("model outputs %zu values; argmax=%d\n", probs.size(), best);
  std::printf("MXTPU_CPP_OK\n");
  return 0;
}
