/*
 * Train an MLP classifier entirely from C++ (parity: the reference's
 * `cpp-package/example/mlp.cpp`, which builds Symbols, binds an Executor,
 * and steps an Optimizer). Here: Model::Create(spec) + Trainer::Step.
 *
 * Task: 2-class separation of synthetic 4-d points, label = sign of a
 * fixed linear functional. Prints per-epoch loss, asserts it falls,
 * round-trips parameters through SaveParams/LoadParams, and prints
 * "MLP TRAIN OK" for the test harness to grep.
 */
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <string>
#include <vector>

#include <mxnet-tpu-cpp/MxNetTpuCpp.hpp>

namespace {

/* deterministic LCG so the run is reproducible without <random> */
struct Lcg {
  uint64_t s = 12345;
  float next() {  // uniform [-1, 1)
    s = s * 6364136223846793005ULL + 1442695040888963407ULL;
    return static_cast<float>(static_cast<int32_t>(s >> 33)) /
           static_cast<float>(1u << 31);
  }
};

}  // namespace

int main(int argc, char** argv) {
  const char* platform = argc > 1 ? argv[1] : "cpu";
  mxtpu::Runtime rt(platform);
  mxtpu::Runtime::Seed(7);

  const int kBatch = 32, kDim = 4, kSteps = 60;
  auto model = mxtpu::Model::Create(
      "{\"type\":\"mlp\",\"in_units\":4,\"layers\":[16,2],"
      "\"activation\":\"relu\"}");
  mxtpu::Trainer trainer(model, "adam", "{\"learning_rate\": 0.01}");

  Lcg rng;
  const float w[kDim] = {1.0f, -2.0f, 0.5f, 1.5f};
  float first_avg = 0.0f, last_avg = 0.0f;
  for (int step = 0; step < kSteps; ++step) {
    std::vector<float> x(kBatch * kDim), y(kBatch);
    for (int i = 0; i < kBatch; ++i) {
      float dot = 0.0f;
      for (int d = 0; d < kDim; ++d) {
        x[i * kDim + d] = rng.next();
        dot += w[d] * x[i * kDim + d];
      }
      y[i] = dot > 0 ? 1.0f : 0.0f;
    }
    auto xb = mxtpu::NDArray::FromVector({kBatch, kDim}, x);
    auto yb = mxtpu::NDArray::FromVector({kBatch}, y);
    float loss = trainer.Step(model, {&xb}, yb, "softmax_ce");
    if (step < 10) first_avg += loss / 10.0f;
    if (step >= kSteps - 10) last_avg += loss / 10.0f;
    if (step % 20 == 0) std::printf("step %d loss %.4f\n", step, loss);
  }
  std::printf("first10 %.4f last10 %.4f\n", first_avg, last_avg);
  if (!(last_avg < 0.6f * first_avg)) {
    std::fprintf(stderr, "loss did not fall\n");
    return 1;
  }

  /* checkpoint round-trip: fresh model + loaded params must agree */
  const char* params = "/tmp/mxtpu_mlp_train.params";
  model.SaveParams(params);
  auto fresh = mxtpu::Model::Create(
      "{\"type\":\"mlp\",\"in_units\":4,\"layers\":[16,2],"
      "\"activation\":\"relu\"}");
  fresh.LoadParams(params);
  std::vector<float> probe(kDim, 0.25f);
  auto pb = mxtpu::NDArray::FromVector({1, kDim}, probe);
  auto a = model.Forward({&pb})[0].ToVector();
  auto b = fresh.Forward({&pb})[0].ToVector();
  for (size_t i = 0; i < a.size(); ++i) {
    if (std::abs(a[i] - b[i]) > 1e-5f) {
      std::fprintf(stderr, "param round-trip mismatch at %zu\n", i);
      return 1;
    }
  }
  std::printf("MLP TRAIN OK\n");
  return 0;
}
