/*
 * mxnet_tpu C++ user API — RAII wrappers over the C ABI (c_api.h).
 *
 * Parity target: `cpp-package/include/mxnet-cpp/MxNetCpp.h` and friends
 * (NDArray ndarray.h, Operator operator.h, model load/run executor.h).
 * The surface is redesigned for the TPU runtime's shape: there is no
 * Symbol/Executor split (a Model IS a compiled XLA executable restored
 * from `HybridBlock.export`), and operator invocation is by name against
 * the `mx.np`/`mx.npx` namespaces — the registry the Python front end
 * uses, so the two APIs can never drift.
 *
 * Usage:
 *   mxtpu::Runtime rt("cpu");                  // or "tpu" / "" = default
 *   auto x = mxtpu::NDArray::FromVector({2, 3}, data);
 *   auto y = mxtpu::Op("relu")(x);
 *   mxtpu::Model m("net-symbol.stablehlo", "net-0000.params");
 *   auto out = m.Forward({x});
 */
#ifndef MXNET_TPU_CPP_MXNETTPUCPP_HPP_
#define MXNET_TPU_CPP_MXNETTPUCPP_HPP_

#include <cstdint>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

#include "c_api.h"

namespace mxtpu {

inline void Check(int rc, const char* what) {
  if (rc != 0) {
    throw std::runtime_error(std::string(what) + ": " + MXTPUGetLastError());
  }
}

/* Owns runtime init/teardown. Construct exactly one, first. */
class Runtime {
 public:
  explicit Runtime(const std::string& platform = "") {
    Check(MXTPUInit(platform.empty() ? nullptr : platform.c_str()),
          "MXTPUInit");
  }
  ~Runtime() { MXTPUShutdown(); }
  Runtime(const Runtime&) = delete;
  Runtime& operator=(const Runtime&) = delete;

  static void Seed(int seed) { Check(MXTPURandomSeed(seed), "Seed"); }

  /* MAJOR*10000 + MINOR*100 + PATCH (reference MXGetVersion). */
  static int Version() {
    int v = 0;
    Check(MXTPUGetVersion(&v), "GetVersion");
    return v;
  }

  /* All registered operator names (reference MXListAllOpNames). */
  static std::vector<std::string> ListOps() {
    const char* s = nullptr;
    int n = 0;
    Check(MXTPUListOps(&s, &n), "ListOps");
    std::vector<std::string> out;
    out.reserve(n);
    std::string cur;
    for (const char* p = s;; ++p) {
      if (*p == ',' || *p == '\0') {
        if (!cur.empty()) out.push_back(cur);
        cur.clear();
        if (*p == '\0') break;
      } else {
        cur.push_back(*p);
      }
    }
    return out;
  }

  /* Runtime feature discovery (reference mx.runtime / libinfo). */
  static bool FeatureEnabled(const std::string& name) {
    int v = 0;
    Check(MXTPUFeatureIsEnabled(name.c_str(), &v), "FeatureIsEnabled");
    return v != 0;
  }

  /* Engine::WaitForAll parity — block until device work completes. */
  static void WaitAll() { Check(MXTPUWaitAll(), "WaitAll"); }
};

class NDArray {
 public:
  NDArray() = default;
  explicit NDArray(MXTPUNDArrayHandle h) : handle_(h) {}

  static NDArray FromVector(const std::vector<int64_t>& shape,
                            const std::vector<float>& data) {
    MXTPUNDArrayHandle h = nullptr;
    Check(MXTPUNDArrayCreate(data.data(), shape.data(),
                             static_cast<int>(shape.size()), &h),
          "NDArrayCreate");
    return NDArray(h);
  }

  /* Explicit-dtype create ("bfloat16", "int32", ...); host data is
   * float32, cast on device (reference MXNDArrayCreateEx convention). */
  static NDArray FromVector(const std::vector<int64_t>& shape,
                            const std::vector<float>& data,
                            const std::string& dtype) {
    MXTPUNDArrayHandle h = nullptr;
    Check(MXTPUNDArrayCreateEx(data.data(), shape.data(),
                               static_cast<int>(shape.size()),
                               dtype.c_str(), &h),
          "NDArrayCreateEx");
    return NDArray(h);
  }

  std::string DType() const {
    const char* s = nullptr;
    Check(MXTPUNDArrayDType(handle_, &s), "NDArrayDType");
    return s;
  }

  /* Autograd surface (reference autograd.py:196,245 via the C ABI). */
  void AttachGrad() { Check(MXTPUNDArrayAttachGrad(handle_), "AttachGrad"); }
  void Backward() { Check(MXTPUAutogradBackward(handle_), "Backward"); }
  NDArray Grad() const {
    MXTPUNDArrayHandle g = nullptr;
    Check(MXTPUNDArrayGetGrad(handle_, &g), "GetGrad");
    return NDArray(g);
  }

  /* Save/load named arrays (.npz; reference MXNDArraySave/Load). */
  static void Save(const std::string& path,
                   const std::vector<std::pair<std::string,
                                               const NDArray*>>& items) {
    std::vector<MXTPUNDArrayHandle> hs;
    std::vector<const char*> names;
    hs.reserve(items.size());
    names.reserve(items.size());
    for (const auto& kv : items) {
      names.push_back(kv.first.c_str());
      hs.push_back(kv.second->handle());
    }
    Check(MXTPUNDArraySave(path.c_str(), hs.data(), names.data(),
                           static_cast<int>(items.size())),
          "NDArraySave");
  }
  static std::vector<std::pair<std::string, NDArray>> Load(
      const std::string& path, int max_arrays = 64) {
    std::vector<MXTPUNDArrayHandle> hs(max_arrays, nullptr);
    std::vector<const char*> names(max_arrays, nullptr);
    int n = max_arrays;
    Check(MXTPUNDArrayLoad(path.c_str(), hs.data(), names.data(), &n),
          "NDArrayLoad");
    std::vector<std::pair<std::string, NDArray>> out;
    out.reserve(n);
    for (int i = 0; i < n; ++i) out.emplace_back(names[i], NDArray(hs[i]));
    return out;
  }

  ~NDArray() { reset(); }
  NDArray(NDArray&& o) noexcept : handle_(o.handle_) { o.handle_ = nullptr; }
  NDArray& operator=(NDArray&& o) noexcept {
    if (this != &o) {
      reset();
      handle_ = o.handle_;
      o.handle_ = nullptr;
    }
    return *this;
  }
  NDArray(const NDArray&) = delete;
  NDArray& operator=(const NDArray&) = delete;

  std::vector<int64_t> Shape() const {
    int64_t dims[8];
    int ndim = 0;
    Check(MXTPUNDArrayShape(handle_, dims, &ndim), "NDArrayShape");
    return std::vector<int64_t>(dims, dims + ndim);
  }

  int64_t Size() const {
    int64_t n = 0;
    Check(MXTPUNDArraySize(handle_, &n), "NDArraySize");
    return n;
  }

  /* Blocking device->host fetch (the reference's SyncCopyToCPU). */
  std::vector<float> ToVector() const {
    std::vector<float> out(static_cast<size_t>(Size()));
    Check(MXTPUNDArrayCopyTo(handle_, out.data(),
                             static_cast<int64_t>(out.size())),
          "NDArrayCopyTo");
    return out;
  }

  MXTPUNDArrayHandle handle() const { return handle_; }

 private:
  void reset() {
    if (handle_ != nullptr) {
      MXTPUNDArrayFree(handle_);
      handle_ = nullptr;
    }
  }
  MXTPUNDArrayHandle handle_ = nullptr;
};

/* Named-operator functor (the reference's Operator("relu")(x).Invoke()). */
class Op {
 public:
  explicit Op(std::string name, std::string kwargs_json = "")
      : name_(std::move(name)), kwargs_(std::move(kwargs_json)) {}

  NDArray operator()(const NDArray& a) const { return Invoke({&a}); }
  NDArray operator()(const NDArray& a, const NDArray& b) const {
    return Invoke({&a, &b});
  }
  NDArray Invoke(const std::vector<const NDArray*>& inputs) const {
    std::vector<MXTPUNDArrayHandle> hs;
    hs.reserve(inputs.size());
    for (const NDArray* p : inputs) hs.push_back(p->handle());
    MXTPUNDArrayHandle out = nullptr;
    Check(MXTPUInvoke(name_.c_str(), hs.data(),
                      static_cast<int>(hs.size()),
                      kwargs_.empty() ? nullptr : kwargs_.c_str(), &out),
          name_.c_str());
    return NDArray(out);
  }

 private:
  std::string name_;
  std::string kwargs_;
};

/* A model: either a compiled artifact restored from HybridBlock.export
 * (inference) or a trainable net built from a JSON spec (training —
 * parity: the reference's cpp-package builds + trains MLPs in C++). */
class Model {
 public:
  Model(const std::string& symbol_file, const std::string& params_file) {
    Check(MXTPUModelLoad(symbol_file.c_str(),
                         params_file.empty() ? nullptr : params_file.c_str(),
                         &handle_),
          "ModelLoad");
  }

  /* e.g. Model::Create("{\"type\":\"mlp\",\"in_units\":4,"
   *                    "\"layers\":[16,2]}") */
  static Model Create(const std::string& spec_json) {
    MXTPUModelHandle h = nullptr;
    Check(MXTPUModelCreate(spec_json.c_str(), &h), "ModelCreate");
    return Model(h, 0);
  }

  void SaveParams(const std::string& path) const {
    Check(MXTPUModelSaveParams(handle_, path.c_str()), "SaveParams");
  }
  void LoadParams(const std::string& path) {
    Check(MXTPUModelLoadParams(handle_, path.c_str()), "LoadParams");
  }
  ~Model() {
    if (handle_ != nullptr) MXTPUModelFree(handle_);
  }
  Model(const Model&) = delete;
  Model& operator=(const Model&) = delete;

  std::vector<NDArray> Forward(const std::vector<const NDArray*>& inputs,
                               int max_outputs = 8) const {
    std::vector<MXTPUNDArrayHandle> hs;
    hs.reserve(inputs.size());
    for (const NDArray* p : inputs) hs.push_back(p->handle());
    std::vector<MXTPUNDArrayHandle> outs(max_outputs, nullptr);
    int n_out = max_outputs;
    Check(MXTPUModelForward(handle_, hs.data(),
                            static_cast<int>(hs.size()), outs.data(),
                            &n_out),
          "ModelForward");
    std::vector<NDArray> result;
    result.reserve(n_out);
    for (int i = 0; i < n_out; ++i) result.emplace_back(outs[i]);
    return result;
  }

  MXTPUModelHandle handle() const { return handle_; }

 private:
  Model(MXTPUModelHandle h, int) : handle_(h) {}
  MXTPUModelHandle handle_ = nullptr;
};

/* Optimizer-driven training over a Model's parameters (parity: the
 * reference's Optimizer + Executor loop in cpp-package/example/mlp.cpp). */
class Trainer {
 public:
  Trainer(const Model& model, const std::string& optimizer,
          const std::string& optimizer_params_json = "") {
    Check(MXTPUTrainerCreate(model.handle(), optimizer.c_str(),
                             optimizer_params_json.empty()
                                 ? nullptr
                                 : optimizer_params_json.c_str(),
                             &handle_),
          "TrainerCreate");
  }
  ~Trainer() {
    if (handle_ != nullptr) MXTPUTrainerFree(handle_);
  }
  Trainer(const Trainer&) = delete;
  Trainer& operator=(const Trainer&) = delete;

  /* Forward + loss + backward + update; returns the mean batch loss. */
  float Step(const Model& model,
             const std::vector<const NDArray*>& inputs,
             const NDArray& label, const std::string& loss = "softmax_ce") {
    std::vector<MXTPUNDArrayHandle> hs;
    hs.reserve(inputs.size());
    for (const NDArray* p : inputs) hs.push_back(p->handle());
    float out = 0.0f;
    Check(MXTPUTrainerStep(handle_, model.handle(), hs.data(),
                           static_cast<int>(hs.size()), label.handle(),
                           loss.c_str(), &out),
          "TrainerStep");
    return out;
  }

 private:
  MXTPUTrainerHandle handle_ = nullptr;
};

/* Scoped autograd recording (autograd.record() as RAII). */
class AutogradRecord {
 public:
  AutogradRecord() { Check(MXTPUAutogradRecordBegin(), "RecordBegin"); }
  ~AutogradRecord() { MXTPUAutogradRecordEnd(); }
  AutogradRecord(const AutogradRecord&) = delete;
  AutogradRecord& operator=(const AutogradRecord&) = delete;
};

/* KVStore over the C ABI (reference kvstore.h:104-238 workflow). */
class KVStore {
 public:
  explicit KVStore(const std::string& type = "local") {
    Check(MXTPUKVStoreCreate(type.c_str(), &handle_), "KVStoreCreate");
  }
  ~KVStore() {
    if (handle_ != nullptr) MXTPUKVStoreFree(handle_);
  }
  KVStore(const KVStore&) = delete;
  KVStore& operator=(const KVStore&) = delete;

  void Init(int key, const NDArray& val) {
    Check(MXTPUKVStoreInit(handle_, key, val.handle()), "KVStoreInit");
  }
  void Push(int key, const NDArray& val) {
    Check(MXTPUKVStorePush(handle_, key, val.handle()), "KVStorePush");
  }
  NDArray Pull(int key) {
    MXTPUNDArrayHandle h = nullptr;
    Check(MXTPUKVStorePull(handle_, key, &h), "KVStorePull");
    return NDArray(h);
  }
  int Rank() const {
    int r = 0;
    Check(MXTPUKVStoreRank(handle_, &r), "KVStoreRank");
    return r;
  }
  int NumWorkers() const {
    int n = 0;
    Check(MXTPUKVStoreNumWorkers(handle_, &n), "KVStoreNumWorkers");
    return n;
  }

 private:
  MXTPUKVStoreHandle handle_ = nullptr;
};

/* Profiler control (reference profiler.py:34,125 via c_api_profile.cc). */
class Profiler {
 public:
  static void Start() { Check(MXTPUProfilerStart(), "ProfilerStart"); }
  static void Stop() { Check(MXTPUProfilerStop(), "ProfilerStop"); }
  /* Non-destructive by default; reset=true clears the stats after read. */
  static std::string Dumps(bool reset = false) {
    const char* s = nullptr;
    Check(MXTPUProfilerDumps(&s, reset ? 1 : 0), "ProfilerDumps");
    return s;
  }
};

}  // namespace mxtpu

#endif  // MXNET_TPU_CPP_MXNETTPUCPP_HPP_
