/*
 * mxnet_tpu C++ user API — RAII wrappers over the C ABI (c_api.h).
 *
 * Parity target: `cpp-package/include/mxnet-cpp/MxNetCpp.h` and friends
 * (NDArray ndarray.h, Operator operator.h, model load/run executor.h).
 * The surface is redesigned for the TPU runtime's shape: there is no
 * Symbol/Executor split (a Model IS a compiled XLA executable restored
 * from `HybridBlock.export`), and operator invocation is by name against
 * the `mx.np`/`mx.npx` namespaces — the registry the Python front end
 * uses, so the two APIs can never drift.
 *
 * Usage:
 *   mxtpu::Runtime rt("cpu");                  // or "tpu" / "" = default
 *   auto x = mxtpu::NDArray::FromVector({2, 3}, data);
 *   auto y = mxtpu::Op("relu")(x);
 *   mxtpu::Model m("net-symbol.stablehlo", "net-0000.params");
 *   auto out = m.Forward({x});
 */
#ifndef MXNET_TPU_CPP_MXNETTPUCPP_HPP_
#define MXNET_TPU_CPP_MXNETTPUCPP_HPP_

#include <cstdint>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

#include "c_api.h"

namespace mxtpu {

inline void Check(int rc, const char* what) {
  if (rc != 0) {
    throw std::runtime_error(std::string(what) + ": " + MXTPUGetLastError());
  }
}

/* Owns runtime init/teardown. Construct exactly one, first. */
class Runtime {
 public:
  explicit Runtime(const std::string& platform = "") {
    Check(MXTPUInit(platform.empty() ? nullptr : platform.c_str()),
          "MXTPUInit");
  }
  ~Runtime() { MXTPUShutdown(); }
  Runtime(const Runtime&) = delete;
  Runtime& operator=(const Runtime&) = delete;

  static void Seed(int seed) { Check(MXTPURandomSeed(seed), "Seed"); }
};

class NDArray {
 public:
  NDArray() = default;
  explicit NDArray(MXTPUNDArrayHandle h) : handle_(h) {}

  static NDArray FromVector(const std::vector<int64_t>& shape,
                            const std::vector<float>& data) {
    MXTPUNDArrayHandle h = nullptr;
    Check(MXTPUNDArrayCreate(data.data(), shape.data(),
                             static_cast<int>(shape.size()), &h),
          "NDArrayCreate");
    return NDArray(h);
  }

  ~NDArray() { reset(); }
  NDArray(NDArray&& o) noexcept : handle_(o.handle_) { o.handle_ = nullptr; }
  NDArray& operator=(NDArray&& o) noexcept {
    if (this != &o) {
      reset();
      handle_ = o.handle_;
      o.handle_ = nullptr;
    }
    return *this;
  }
  NDArray(const NDArray&) = delete;
  NDArray& operator=(const NDArray&) = delete;

  std::vector<int64_t> Shape() const {
    int64_t dims[8];
    int ndim = 0;
    Check(MXTPUNDArrayShape(handle_, dims, &ndim), "NDArrayShape");
    return std::vector<int64_t>(dims, dims + ndim);
  }

  int64_t Size() const {
    int64_t n = 0;
    Check(MXTPUNDArraySize(handle_, &n), "NDArraySize");
    return n;
  }

  /* Blocking device->host fetch (the reference's SyncCopyToCPU). */
  std::vector<float> ToVector() const {
    std::vector<float> out(static_cast<size_t>(Size()));
    Check(MXTPUNDArrayCopyTo(handle_, out.data(),
                             static_cast<int64_t>(out.size())),
          "NDArrayCopyTo");
    return out;
  }

  MXTPUNDArrayHandle handle() const { return handle_; }

 private:
  void reset() {
    if (handle_ != nullptr) {
      MXTPUNDArrayFree(handle_);
      handle_ = nullptr;
    }
  }
  MXTPUNDArrayHandle handle_ = nullptr;
};

/* Named-operator functor (the reference's Operator("relu")(x).Invoke()). */
class Op {
 public:
  explicit Op(std::string name, std::string kwargs_json = "")
      : name_(std::move(name)), kwargs_(std::move(kwargs_json)) {}

  NDArray operator()(const NDArray& a) const { return Invoke({&a}); }
  NDArray operator()(const NDArray& a, const NDArray& b) const {
    return Invoke({&a, &b});
  }
  NDArray Invoke(const std::vector<const NDArray*>& inputs) const {
    std::vector<MXTPUNDArrayHandle> hs;
    hs.reserve(inputs.size());
    for (const NDArray* p : inputs) hs.push_back(p->handle());
    MXTPUNDArrayHandle out = nullptr;
    Check(MXTPUInvoke(name_.c_str(), hs.data(),
                      static_cast<int>(hs.size()),
                      kwargs_.empty() ? nullptr : kwargs_.c_str(), &out),
          name_.c_str());
    return NDArray(out);
  }

 private:
  std::string name_;
  std::string kwargs_;
};

/* A model: either a compiled artifact restored from HybridBlock.export
 * (inference) or a trainable net built from a JSON spec (training —
 * parity: the reference's cpp-package builds + trains MLPs in C++). */
class Model {
 public:
  Model(const std::string& symbol_file, const std::string& params_file) {
    Check(MXTPUModelLoad(symbol_file.c_str(),
                         params_file.empty() ? nullptr : params_file.c_str(),
                         &handle_),
          "ModelLoad");
  }

  /* e.g. Model::Create("{\"type\":\"mlp\",\"in_units\":4,"
   *                    "\"layers\":[16,2]}") */
  static Model Create(const std::string& spec_json) {
    MXTPUModelHandle h = nullptr;
    Check(MXTPUModelCreate(spec_json.c_str(), &h), "ModelCreate");
    return Model(h, 0);
  }

  void SaveParams(const std::string& path) const {
    Check(MXTPUModelSaveParams(handle_, path.c_str()), "SaveParams");
  }
  void LoadParams(const std::string& path) {
    Check(MXTPUModelLoadParams(handle_, path.c_str()), "LoadParams");
  }
  ~Model() {
    if (handle_ != nullptr) MXTPUModelFree(handle_);
  }
  Model(const Model&) = delete;
  Model& operator=(const Model&) = delete;

  std::vector<NDArray> Forward(const std::vector<const NDArray*>& inputs,
                               int max_outputs = 8) const {
    std::vector<MXTPUNDArrayHandle> hs;
    hs.reserve(inputs.size());
    for (const NDArray* p : inputs) hs.push_back(p->handle());
    std::vector<MXTPUNDArrayHandle> outs(max_outputs, nullptr);
    int n_out = max_outputs;
    Check(MXTPUModelForward(handle_, hs.data(),
                            static_cast<int>(hs.size()), outs.data(),
                            &n_out),
          "ModelForward");
    std::vector<NDArray> result;
    result.reserve(n_out);
    for (int i = 0; i < n_out; ++i) result.emplace_back(outs[i]);
    return result;
  }

  MXTPUModelHandle handle() const { return handle_; }

 private:
  Model(MXTPUModelHandle h, int) : handle_(h) {}
  MXTPUModelHandle handle_ = nullptr;
};

/* Optimizer-driven training over a Model's parameters (parity: the
 * reference's Optimizer + Executor loop in cpp-package/example/mlp.cpp). */
class Trainer {
 public:
  Trainer(const Model& model, const std::string& optimizer,
          const std::string& optimizer_params_json = "") {
    Check(MXTPUTrainerCreate(model.handle(), optimizer.c_str(),
                             optimizer_params_json.empty()
                                 ? nullptr
                                 : optimizer_params_json.c_str(),
                             &handle_),
          "TrainerCreate");
  }
  ~Trainer() {
    if (handle_ != nullptr) MXTPUTrainerFree(handle_);
  }
  Trainer(const Trainer&) = delete;
  Trainer& operator=(const Trainer&) = delete;

  /* Forward + loss + backward + update; returns the mean batch loss. */
  float Step(const Model& model,
             const std::vector<const NDArray*>& inputs,
             const NDArray& label, const std::string& loss = "softmax_ce") {
    std::vector<MXTPUNDArrayHandle> hs;
    hs.reserve(inputs.size());
    for (const NDArray* p : inputs) hs.push_back(p->handle());
    float out = 0.0f;
    Check(MXTPUTrainerStep(handle_, model.handle(), hs.data(),
                           static_cast<int>(hs.size()), label.handle(),
                           loss.c_str(), &out),
          "TrainerStep");
    return out;
  }

 private:
  MXTPUTrainerHandle handle_ = nullptr;
};

}  // namespace mxtpu

#endif  // MXNET_TPU_CPP_MXNETTPUCPP_HPP_
