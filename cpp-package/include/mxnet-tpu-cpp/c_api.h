/*
 * mxnet_tpu C ABI — the native entry point for non-Python users.
 *
 * Parity target: the reference's `cpp-package/include/mxnet-cpp/` wraps a C
 * ABI (`include/mxnet/c_api.h`, 246 MX* functions) over its C++ engine. In
 * this framework the "engine" is the JAX/XLA runtime, which owns the PjRt
 * TPU client from Python — so the TPU-native C ABI hosts an embedded CPython
 * interpreter and drives the same runtime a Python user gets: one compile
 * path, one allocator, one device claim. (Design decision, not a stand-in:
 * a second, Python-free PjRt client in the same process would fight the
 * first for the exclusive TPU chip claim.)
 *
 * Thread-safety: every call acquires the GIL; concurrent calls serialize.
 * Error handling mirrors the reference (`MXGetLastError`): failing calls
 * return -1 and the message is retrievable via MXTPUGetLastError().
 */
#ifndef MXNET_TPU_CPP_C_API_H_
#define MXNET_TPU_CPP_C_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* MXTPUNDArrayHandle;
typedef void* MXTPUModelHandle;

/* Start the embedded runtime. `platform` selects the JAX backend ("tpu",
 * "cpu", or NULL/"" for the environment default). Idempotent. */
int MXTPUInit(const char* platform);

/* Finalize the embedded interpreter. After this no handle is valid. */
int MXTPUShutdown(void);

/* Message for the last failing call on this thread ("" if none). */
const char* MXTPUGetLastError(void);

/* --- NDArray ---------------------------------------------------------- */

/* Create a float32 NDArray on the active device from host data. */
int MXTPUNDArrayCreate(const float* data, const int64_t* shape, int ndim,
                       MXTPUNDArrayHandle* out);

/* Query rank and dims. `shape` must hold at least 8 entries. */
int MXTPUNDArrayShape(MXTPUNDArrayHandle handle, int64_t* shape, int* ndim);

/* Total element count. */
int MXTPUNDArraySize(MXTPUNDArrayHandle handle, int64_t* size);

/* Blocking device->host copy of all elements (float32). */
int MXTPUNDArrayCopyTo(MXTPUNDArrayHandle handle, float* buf, int64_t size);

int MXTPUNDArrayFree(MXTPUNDArrayHandle handle);

/* Invoke any `mx.np` / `mx.npx` operator by name on NDArray inputs —
 * the analogue of the reference's MXImperativeInvoke. `kwargs_json` is a
 * JSON object of keyword scalars/strings (NULL = none). Ops with one
 * output write it to `out`. */
int MXTPUInvoke(const char* op_name, MXTPUNDArrayHandle* inputs, int n_in,
                const char* kwargs_json, MXTPUNDArrayHandle* out);

/* --- Model (exported HybridBlock) -------------------------------------- */

/* Load a `HybridBlock.export` artifact pair: `*-symbol.stablehlo` +
 * `*-NNNN.params` (params_file may be NULL for param-free graphs). */
int MXTPUModelLoad(const char* symbol_file, const char* params_file,
                   MXTPUModelHandle* out);

/* Run the model. On entry *n_out is the capacity of `outputs`; on exit it
 * is the number of outputs written. */
int MXTPUModelForward(MXTPUModelHandle model, MXTPUNDArrayHandle* inputs,
                      int n_in, MXTPUNDArrayHandle* outputs, int* n_out);

int MXTPUModelFree(MXTPUModelHandle handle);

/* Seed the global RNG (`mx.random.seed`). */
int MXTPURandomSeed(int seed);

/* --- Training (parity: reference cpp-package Optimizer/KVStore/Executor,
 * --- which trains models from C++ — `cpp-package/example/mlp.cpp`) ----- */

typedef void* MXTPUTrainerHandle;

/* Build a trainable model from a JSON spec, e.g.
 * {"type":"mlp","in_units":4,"layers":[16,2],"activation":"relu"}.
 * The model owns initialized parameters and can Forward immediately. */
int MXTPUModelCreate(const char* spec_json, MXTPUModelHandle* out);

/* Create an optimizer-driven trainer over the model's parameters.
 * `optimizer` is any registered name ("sgd", "adam", ...);
 * `optimizer_params_json` e.g. {"learning_rate": 0.1} (NULL = defaults). */
int MXTPUTrainerCreate(MXTPUModelHandle model, const char* optimizer,
                       const char* optimizer_params_json,
                       MXTPUTrainerHandle* out);

/* One training step: forward under autograd, `loss` in {"softmax_ce",
 * "sigmoid_bce", "l2", "l1"}, backward, optimizer update (batch size is
 * label's leading dim). Writes the mean batch loss to `loss_out`. */
int MXTPUTrainerStep(MXTPUTrainerHandle trainer, MXTPUModelHandle model,
                     MXTPUNDArrayHandle* inputs, int n_in,
                     MXTPUNDArrayHandle label, const char* loss,
                     float* loss_out);

int MXTPUTrainerFree(MXTPUTrainerHandle handle);

/* Parameter checkpointing (`save_parameters`/`load_parameters`). */
int MXTPUModelSaveParams(MXTPUModelHandle model, const char* path);
int MXTPUModelLoadParams(MXTPUModelHandle model, const char* path);

#ifdef __cplusplus
}  /* extern "C" */
#endif

#endif  /* MXNET_TPU_CPP_C_API_H_ */
