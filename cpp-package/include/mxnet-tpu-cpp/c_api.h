/*
 * mxnet_tpu C ABI — the native entry point for non-Python users.
 *
 * Parity target: the reference's `cpp-package/include/mxnet-cpp/` wraps a C
 * ABI (`include/mxnet/c_api.h`, 246 MX* functions) over its C++ engine. In
 * this framework the "engine" is the JAX/XLA runtime, which owns the PjRt
 * TPU client from Python — so the TPU-native C ABI hosts an embedded CPython
 * interpreter and drives the same runtime a Python user gets: one compile
 * path, one allocator, one device claim. (Design decision, not a stand-in:
 * a second, Python-free PjRt client in the same process would fight the
 * first for the exclusive TPU chip claim.)
 *
 * Thread-safety: every call acquires the GIL; concurrent calls serialize.
 * Error handling mirrors the reference (`MXGetLastError`): failing calls
 * return -1 and the message is retrievable via MXTPUGetLastError().
 */
#ifndef MXNET_TPU_CPP_C_API_H_
#define MXNET_TPU_CPP_C_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* MXTPUNDArrayHandle;
typedef void* MXTPUModelHandle;

/* Start the embedded runtime. `platform` selects the JAX backend ("tpu",
 * "cpu", or NULL/"" for the environment default). Idempotent. */
int MXTPUInit(const char* platform);

/* Finalize the embedded interpreter. After this no handle is valid. */
int MXTPUShutdown(void);

/* Message for the last failing call on this thread ("" if none). */
const char* MXTPUGetLastError(void);

/* --- NDArray ---------------------------------------------------------- */

/* Create a float32 NDArray on the active device from host data. */
int MXTPUNDArrayCreate(const float* data, const int64_t* shape, int ndim,
                       MXTPUNDArrayHandle* out);

/* Query rank and dims. `shape` must hold at least 8 entries. */
int MXTPUNDArrayShape(MXTPUNDArrayHandle handle, int64_t* shape, int* ndim);

/* Total element count. */
int MXTPUNDArraySize(MXTPUNDArrayHandle handle, int64_t* size);

/* Blocking device->host copy of all elements (float32). */
int MXTPUNDArrayCopyTo(MXTPUNDArrayHandle handle, float* buf, int64_t size);

int MXTPUNDArrayFree(MXTPUNDArrayHandle handle);

/* Invoke any `mx.np` / `mx.npx` operator by name on NDArray inputs —
 * the analogue of the reference's MXImperativeInvoke. `kwargs_json` is a
 * JSON object of keyword scalars/strings (NULL = none). Ops with one
 * output write it to `out`. */
int MXTPUInvoke(const char* op_name, MXTPUNDArrayHandle* inputs, int n_in,
                const char* kwargs_json, MXTPUNDArrayHandle* out);

/* --- Model (exported HybridBlock) -------------------------------------- */

/* Load a `HybridBlock.export` artifact pair: `*-symbol.stablehlo` +
 * `*-NNNN.params` (params_file may be NULL for param-free graphs). */
int MXTPUModelLoad(const char* symbol_file, const char* params_file,
                   MXTPUModelHandle* out);

/* Run the model. On entry *n_out is the capacity of `outputs`; on exit it
 * is the number of outputs written. */
int MXTPUModelForward(MXTPUModelHandle model, MXTPUNDArrayHandle* inputs,
                      int n_in, MXTPUNDArrayHandle* outputs, int* n_out);

int MXTPUModelFree(MXTPUModelHandle handle);

/* Seed the global RNG (`mx.random.seed`). */
int MXTPURandomSeed(int seed);

/* --- Training (parity: reference cpp-package Optimizer/KVStore/Executor,
 * --- which trains models from C++ — `cpp-package/example/mlp.cpp`) ----- */

typedef void* MXTPUTrainerHandle;

/* Build a trainable model from a JSON spec, e.g.
 * {"type":"mlp","in_units":4,"layers":[16,2],"activation":"relu"}.
 * The model owns initialized parameters and can Forward immediately. */
int MXTPUModelCreate(const char* spec_json, MXTPUModelHandle* out);

/* Create an optimizer-driven trainer over the model's parameters.
 * `optimizer` is any registered name ("sgd", "adam", ...);
 * `optimizer_params_json` e.g. {"learning_rate": 0.1} (NULL = defaults). */
int MXTPUTrainerCreate(MXTPUModelHandle model, const char* optimizer,
                       const char* optimizer_params_json,
                       MXTPUTrainerHandle* out);

/* One training step: forward under autograd, `loss` in {"softmax_ce",
 * "sigmoid_bce", "l2", "l1"}, backward, optimizer update (batch size is
 * label's leading dim). Writes the mean batch loss to `loss_out`. */
int MXTPUTrainerStep(MXTPUTrainerHandle trainer, MXTPUModelHandle model,
                     MXTPUNDArrayHandle* inputs, int n_in,
                     MXTPUNDArrayHandle label, const char* loss,
                     float* loss_out);

int MXTPUTrainerFree(MXTPUTrainerHandle handle);

/* Parameter checkpointing (`save_parameters`/`load_parameters`). */
int MXTPUModelSaveParams(MXTPUModelHandle model, const char* path);
int MXTPUModelLoadParams(MXTPUModelHandle model, const char* path);

/* --- Runtime introspection (parity: reference MXGetVersion,
 * --- MXListAllOpNames `src/c_api/c_api.cc`, MXLibInfoFeatures
 * --- `include/mxnet/libinfo.h:132-213`) ------------------------------- */

/* Library version as MAJOR*10000 + MINOR*100 + PATCH. */
int MXTPUGetVersion(int* out);

/* Comma-separated list of all registered `mx.np`/`mx.npx`/`mx.nd` operator
 * names. The returned pointer stays valid until the next MXTPU* call on
 * this thread. `n_ops` (optional, may be NULL) receives the count. */
int MXTPUListOps(const char** out, int* n_ops);

/* 1 if the named runtime feature (mx.runtime.Features; e.g. "TPU",
 * "BF16", "INT64_TENSOR_SIZE") is enabled, else 0. */
int MXTPUFeatureIsEnabled(const char* name, int* out);

/* --- NDArray breadth (parity: MXNDArrayCreateEx dtype surface,
 * --- MXNDArraySave/MXNDArrayLoad `src/c_api/c_api.cc`,
 * --- MXNDArrayWaitAll = Engine::WaitForAll) --------------------------- */

/* Create an NDArray with an explicit dtype ("float32", "float16",
 * "bfloat16", "int32", "int64", "uint8", "bool"...). `data` is always
 * host float32 and is cast on device — the reference's MXNDArraySyncCopy
 * convention for mixed-precision feeds. */
int MXTPUNDArrayCreateEx(const float* data, const int64_t* shape, int ndim,
                         const char* dtype, MXTPUNDArrayHandle* out);

/* Dtype name of an array (pointer valid until the next call). */
int MXTPUNDArrayDType(MXTPUNDArrayHandle handle, const char** out);

/* Save named arrays to an `.npz` (the reference's MXNDArraySave dict
 * format). `names` is n nul-terminated keys. */
int MXTPUNDArraySave(const char* path, MXTPUNDArrayHandle* arrays,
                     const char** names, int n);

/* Load an `.npz` saved by MXTPUNDArraySave. On entry *n is the capacity
 * of `arrays`/`name_buf`; on exit the count. Each name_buf[i] points into
 * a thread-local buffer valid until the next call. */
int MXTPUNDArrayLoad(const char* path, MXTPUNDArrayHandle* arrays,
                     const char** name_buf, int* n);

/* Block until all pending device work completes (MXNDArrayWaitAll). */
int MXTPUWaitAll(void);

/* --- Autograd (parity: MXAutogradSetIsRecording, MXAutogradMarkVariables,
 * --- MXAutogradBackward, MXNDArrayGetGrad — `src/c_api/c_api_ndarray.cc`,
 * --- `python/mxnet/autograd.py:121,196,245`) -------------------------- */

/* Enter/exit a recording scope (autograd.record()). Not nestable (one
 * active scope at a time), and THREAD-LOCAL like the reference's
 * `Imperative` recording state (`include/mxnet/imperative.h:51`): ops
 * recorded between Begin/End must run on the thread that called Begin —
 * calls from other threads execute un-recorded. */
int MXTPUAutogradRecordBegin(void);
int MXTPUAutogradRecordEnd(void);

/* Mark an array as a differentiable input (x.attach_grad()). */
int MXTPUNDArrayAttachGrad(MXTPUNDArrayHandle handle);

/* Backward from a (scalar or summed) head computed inside the recording
 * scope; gradients land on attached arrays. */
int MXTPUAutogradBackward(MXTPUNDArrayHandle head);

/* Fetch the gradient of an attached array (new handle; caller frees). */
int MXTPUNDArrayGetGrad(MXTPUNDArrayHandle handle, MXTPUNDArrayHandle* out);

/* --- KVStore (parity: MXKVStoreCreate/Init/Push/Pull, rank/size —
 * --- `include/mxnet/c_api.h`, `src/kvstore/kvstore.cc:41-79`) --------- */

typedef void* MXTPUKVStoreHandle;

/* `type` as in the Python registry: "local", "device", "dist_sync", ... */
int MXTPUKVStoreCreate(const char* type, MXTPUKVStoreHandle* out);
int MXTPUKVStoreInit(MXTPUKVStoreHandle kv, int key, MXTPUNDArrayHandle val);
int MXTPUKVStorePush(MXTPUKVStoreHandle kv, int key, MXTPUNDArrayHandle val);
/* Pull writes a NEW handle holding the current value (caller frees). */
int MXTPUKVStorePull(MXTPUKVStoreHandle kv, int key, MXTPUNDArrayHandle* out);
int MXTPUKVStoreRank(MXTPUKVStoreHandle kv, int* rank);
int MXTPUKVStoreNumWorkers(MXTPUKVStoreHandle kv, int* n);
int MXTPUKVStoreFree(MXTPUKVStoreHandle kv);

/* --- Profiler (parity: MXSetProcessProfilerConfig/State, MXDumpProfile —
 * --- `src/c_api/c_api_profile.cc`, `python/mxnet/profiler.py:34,125`) -- */

int MXTPUProfilerStart(void);
int MXTPUProfilerStop(void);
/* Aggregate per-op table (pointer valid until the next call). `reset`
 * nonzero clears the accumulated stats after reading (the reference's
 * profiler.dumps(reset=...) — default there is a non-destructive read). */
int MXTPUProfilerDumps(const char** out, int reset);

/* --- Symbol construction (parity: MXSymbolCreateVariable,
 * --- MXSymbolCreateAtomicSymbol + MXSymbolCompose,
 * --- MXSymbolCreateFromFile/FromJSON, MXSymbolSaveToJSON,
 * --- MXSymbolListArguments/ListOutputs — `include/mxnet/c_api.h`
 * --- MXSymbol* family) ------------------------------------------------ */

typedef void* MXTPUSymbolHandle;

/* A free variable (input/parameter placeholder). */
int MXTPUSymbolCreateVariable(const char* name, MXTPUSymbolHandle* out);

/* One op applied to input symbols (atomic-symbol + compose in one step;
 * the graph is functional, so there is no separate mutation phase).
 * `kwargs_json` holds the op attrs; `name` may be NULL (auto-named). */
int MXTPUSymbolCreateFromOp(const char* op, const char* name,
                            MXTPUSymbolHandle* inputs, int n_in,
                            const char* kwargs_json, MXTPUSymbolHandle* out);

/* Load / parse the exported symbol-JSON graph. */
int MXTPUSymbolLoad(const char* path, MXTPUSymbolHandle* out);
int MXTPUSymbolLoadJSON(const char* json, MXTPUSymbolHandle* out);

/* Serialize to JSON (pointer valid until the next MXTPU* call). */
int MXTPUSymbolSaveJSON(MXTPUSymbolHandle sym, const char** out);

/* Argument/output names. On entry *n is the capacity of `name_buf`; on
 * exit the count. Pointers valid until the next MXTPU* call. */
int MXTPUSymbolListArguments(MXTPUSymbolHandle sym, const char** name_buf,
                             int* n);
int MXTPUSymbolListOutputs(MXTPUSymbolHandle sym, const char** name_buf,
                           int* n);

/* Bind `arg_names[i] = arg_vals[i]` and evaluate (Executor bind+forward).
 * On entry *n_out is the capacity of `outputs`; on exit the count. */
int MXTPUSymbolEval(MXTPUSymbolHandle sym, const char** arg_names,
                    MXTPUNDArrayHandle* arg_vals, int n_args,
                    MXTPUNDArrayHandle* outputs, int* n_out);

int MXTPUSymbolFree(MXTPUSymbolHandle sym);

/* --- Model (CachedOp) flags (parity: MXCreateCachedOpEx's flag pairs —
 * --- static_alloc/static_shape — and Block train/predict mode).
 * --- `flags_json` e.g. {"training": true, "hybridize": true}.
 * --- static_alloc/static_shape are always true on XLA (accepted for
 * --- parity; disabling them errors). ---------------------------------- */
int MXTPUModelSetFlags(MXTPUModelHandle model, const char* flags_json);
int MXTPUModelGetFlags(MXTPUModelHandle model, const char** out_json);

/* --- Data iterators (parity: MXListDataIters, MXDataIterCreateIter,
 * --- MXDataIterNext/BeforeFirst, MXDataIterGetData/GetLabel,
 * --- MXDataIterFree — `include/mxnet/c_api.h` MXDataIter* family) ----- */

typedef void* MXTPUDataIterHandle;

/* Comma-separated iterator type names (MNISTIter, ImageRecordIter,
 * CSVIter, LibSVMIter, NDArrayIter). Pointer valid until the next call. */
int MXTPUListDataIters(const char** out, int* n);

/* Create by type name with JSON params (the reference's key/value pairs),
 * e.g. MNISTIter: {"batch_size": 32, "shuffle": true} or CSVIter:
 * {"data_csv": "x.csv", "data_shape": [3], "batch_size": 4}. */
int MXTPUDataIterCreate(const char* type, const char* params_json,
                        MXTPUDataIterHandle* out);

/* In-memory iterator over existing arrays (NDArrayIter; label may be
 * NULL). */
int MXTPUDataIterCreateFromArrays(MXTPUNDArrayHandle data,
                                  MXTPUNDArrayHandle label, int batch_size,
                                  int shuffle, MXTPUDataIterHandle* out);

/* Advance; *more = 1 while a batch is available, 0 at epoch end. */
int MXTPUDataIterNext(MXTPUDataIterHandle it, int* more);

/* Rewind to the epoch start (MXDataIterBeforeFirst). */
int MXTPUDataIterReset(MXTPUDataIterHandle it);

/* Current batch's data/label (new handles; caller frees). */
int MXTPUDataIterGetData(MXTPUDataIterHandle it, MXTPUNDArrayHandle* out);
int MXTPUDataIterGetLabel(MXTPUDataIterHandle it, MXTPUNDArrayHandle* out);

int MXTPUDataIterFree(MXTPUDataIterHandle it);

#ifdef __cplusplus
}  /* extern "C" */
#endif

#endif  /* MXNET_TPU_CPP_C_API_H_ */
