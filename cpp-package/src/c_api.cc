/*
 * C ABI implementation: embedded CPython driving the mxnet_tpu runtime.
 * See c_api.h for the design rationale (single PjRt client per process).
 *
 * Python objects cross the ABI as opaque handles (owned references).
 * Every entry point takes the GIL, so the ABI is safe to call from any
 * thread; calls serialize like the reference engine's exclusive-write
 * semantics on a single var.
 */
#include "../include/mxnet-tpu-cpp/c_api.h"

#include <Python.h>

#include <cstring>
#include <string>

namespace {

PyObject* g_helpers = nullptr;   // module dict holding the helper funcs
bool g_initialized = false;
PyThreadState* g_main_state = nullptr;  // saved so the GIL is released
thread_local std::string tls_last_error;

// Helper functions injected at init. Kept in Python because the work —
// dtype plumbing, pytree flattening — is runtime logic, not ABI logic.
const char kBootstrap[] = R"PY(
import os, sys, json
_home = os.environ.get('MXTPU_HOME')
if _home and _home not in sys.path:
    sys.path.insert(0, _home)
import jax
_platform = os.environ.get('_MXTPU_CAPI_PLATFORM', '')
if _platform:
    jax.config.update('jax_platforms', _platform)
import numpy as _onp
import mxnet_tpu as mx

def nd_from_buffer(mv, shape):
    a = _onp.frombuffer(mv, dtype=_onp.float32)
    return mx.np.array(a.reshape(tuple(shape)).copy())

def nd_shape(nd):
    return tuple(int(d) for d in nd.shape)

def nd_bytes(nd):
    return nd.asnumpy().astype(_onp.float32, copy=False).tobytes()

def invoke(op, inputs, kwargs_json):
    ns = mx.np if hasattr(mx.np, op) else mx.npx
    if not hasattr(ns, op):
        raise AttributeError(f'no operator {op!r} in mx.np or mx.npx')
    kwargs = json.loads(kwargs_json) if kwargs_json else {}
    return getattr(ns, op)(*inputs, **kwargs)

def model_load(symbol_file, params_file):
    from mxnet_tpu.gluon.block import SymbolBlock
    return SymbolBlock.imports(symbol_file, param_file=params_file or None)

def model_forward(model, inputs):
    out = model(*inputs)
    return out if isinstance(out, tuple) else (out,)

def seed(s):
    mx.random.seed(s)

# --- training surface (parity: reference cpp-package Optimizer/Executor,
# --- `cpp-package/example/mlp.cpp` trains an MLP from C++) ---------------

def model_create(spec_json):
    """Build a trainable Gluon net from a JSON spec:
    {"type": "mlp", "in_units": N, "layers": [h1, ..., out],
     "activation": "relu"}."""
    spec = json.loads(spec_json)
    from mxnet_tpu.gluon import nn
    if spec.get('type', 'mlp') != 'mlp':
        raise ValueError(f"unknown model type {spec.get('type')!r}")
    net = nn.HybridSequential()
    first = in_units = int(spec['in_units'])
    act = spec.get('activation', 'relu')
    layers = [int(w) for w in spec['layers']]
    for i, width in enumerate(layers):
        net.add(nn.Dense(width, in_units=in_units,
                         activation=None if i == len(layers) - 1 else act))
        in_units = width
    net.initialize()
    net.hybridize()
    net(mx.np.zeros((1, first)))
    return net

def trainer_create(model, opt_name, opt_params_json):
    from mxnet_tpu import gluon
    kw = json.loads(opt_params_json) if opt_params_json else {}
    return gluon.Trainer(model.collect_params(), opt_name, kw)

_LOSSES = None

def train_step(model, trainer, inputs, label, loss_name):
    global _LOSSES
    from mxnet_tpu import autograd, gluon
    if _LOSSES is None:
        _LOSSES = {
            'softmax_ce': gluon.loss.SoftmaxCrossEntropyLoss,
            'sigmoid_bce': gluon.loss.SigmoidBinaryCrossEntropyLoss,
            'l2': gluon.loss.L2Loss,
            'l1': gluon.loss.L1Loss,
        }
    if loss_name not in _LOSSES:
        raise ValueError(
            f'unknown loss {loss_name!r}; one of {sorted(_LOSSES)}')
    loss_fn = _LOSSES[loss_name]()
    with autograd.record():
        out = model(*inputs)
        loss = loss_fn(out, label)
    loss.backward()
    trainer.step(int(label.shape[0]))
    return float(loss.mean().asnumpy())

def model_save_params(model, path):
    model.save_parameters(path)

def model_load_params(model, path):
    model.load_parameters(path)
)PY";

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  tls_last_error = "unknown python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* msg = PyUnicode_AsUTF8(s);
      if (msg) tls_last_error = msg;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

PyObject* helper(const char* name) {
  return PyDict_GetItemString(g_helpers, name);  // borrowed
}

// RAII GIL acquisition for every ABI entry point.
class GILGuard {
 public:
  GILGuard() : state_(PyGILState_Ensure()) {}
  ~GILGuard() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

#define MXTPU_REQUIRE_INIT()                                   \
  do {                                                         \
    if (!g_initialized) {                                      \
      tls_last_error = "MXTPUInit has not been called";        \
      return -1;                                               \
    }                                                          \
  } while (0)

}  // namespace

extern "C" {

int MXTPUInit(const char* platform) {
  if (g_initialized) return 0;
  if (platform && platform[0] != '\0') {
    setenv("_MXTPU_CAPI_PLATFORM", platform, 1);
  }
  bool fresh = !Py_IsInitialized();
  if (fresh) {
    Py_InitializeEx(0);
  }
  {
    GILGuard gil;
    PyObject* mod = PyModule_New("__mxtpu_capi__");
    if (!mod) { set_error_from_python(); return -1; }
    g_helpers = PyModule_GetDict(mod);  // borrowed; mod leaks on purpose
    PyDict_SetItemString(g_helpers, "__builtins__", PyEval_GetBuiltins());
    PyObject* r = PyRun_String(kBootstrap, Py_file_input, g_helpers,
                               g_helpers);
    if (!r) {
      set_error_from_python();
      g_helpers = nullptr;
      return -1;
    }
    Py_DECREF(r);
  }
  if (fresh) {
    // Py_InitializeEx leaves this thread holding the GIL; release it so
    // any thread (including this one, via GILGuard) can re-acquire
    g_main_state = PyEval_SaveThread();
  }
  g_initialized = true;
  return 0;
}

int MXTPUShutdown(void) {
  if (!g_initialized) return 0;
  g_initialized = false;
  g_helpers = nullptr;
  if (g_main_state != nullptr) {
    PyEval_RestoreThread(g_main_state);  // Finalize needs the GIL
    g_main_state = nullptr;
  }
  Py_Finalize();
  return 0;
}

const char* MXTPUGetLastError(void) { return tls_last_error.c_str(); }

int MXTPUNDArrayCreate(const float* data, const int64_t* shape, int ndim,
                       MXTPUNDArrayHandle* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  int64_t n = 1;
  PyObject* pyshape = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i) {
    n *= shape[i];
    PyTuple_SET_ITEM(pyshape, i, PyLong_FromLongLong(shape[i]));
  }
  PyObject* mv = PyMemoryView_FromMemory(
      reinterpret_cast<char*>(const_cast<float*>(data)),
      n * static_cast<int64_t>(sizeof(float)), PyBUF_READ);
  PyObject* r = PyObject_CallFunctionObjArgs(helper("nd_from_buffer"), mv,
                                             pyshape, nullptr);
  Py_DECREF(mv);
  Py_DECREF(pyshape);
  if (!r) { set_error_from_python(); return -1; }
  *out = r;  // ownership transferred to the handle
  return 0;
}

int MXTPUNDArrayShape(MXTPUNDArrayHandle handle, int64_t* shape, int* ndim) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* r = PyObject_CallFunctionObjArgs(
      helper("nd_shape"), static_cast<PyObject*>(handle), nullptr);
  if (!r) { set_error_from_python(); return -1; }
  Py_ssize_t k = PyTuple_Size(r);
  if (k > 8) { Py_DECREF(r); tls_last_error = "rank > 8"; return -1; }
  *ndim = static_cast<int>(k);
  for (Py_ssize_t i = 0; i < k; ++i) {
    shape[i] = PyLong_AsLongLong(PyTuple_GET_ITEM(r, i));
  }
  Py_DECREF(r);
  return 0;
}

int MXTPUNDArraySize(MXTPUNDArrayHandle handle, int64_t* size) {
  int64_t shape[8];
  int ndim = 0;
  if (MXTPUNDArrayShape(handle, shape, &ndim) != 0) return -1;
  int64_t n = 1;
  for (int i = 0; i < ndim; ++i) n *= shape[i];
  *size = n;
  return 0;
}

int MXTPUNDArrayCopyTo(MXTPUNDArrayHandle handle, float* buf, int64_t size) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* r = PyObject_CallFunctionObjArgs(
      helper("nd_bytes"), static_cast<PyObject*>(handle), nullptr);
  if (!r) { set_error_from_python(); return -1; }
  char* raw = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(r, &raw, &len) != 0) {
    Py_DECREF(r);
    set_error_from_python();
    return -1;
  }
  if (len != size * static_cast<int64_t>(sizeof(float))) {
    Py_DECREF(r);
    tls_last_error = "CopyTo: size mismatch";
    return -1;
  }
  std::memcpy(buf, raw, len);
  Py_DECREF(r);
  return 0;
}

int MXTPUNDArrayFree(MXTPUNDArrayHandle handle) {
  if (!g_initialized || handle == nullptr) return 0;
  GILGuard gil;
  Py_DECREF(static_cast<PyObject*>(handle));
  return 0;
}

int MXTPUInvoke(const char* op_name, MXTPUNDArrayHandle* inputs, int n_in,
                const char* kwargs_json, MXTPUNDArrayHandle* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* ins = PyTuple_New(n_in);
  for (int i = 0; i < n_in; ++i) {
    PyObject* o = static_cast<PyObject*>(inputs[i]);
    Py_INCREF(o);
    PyTuple_SET_ITEM(ins, i, o);
  }
  PyObject* r = PyObject_CallFunction(
      helper("invoke"), "sOs", op_name, ins,
      kwargs_json ? kwargs_json : "");
  Py_DECREF(ins);
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

int MXTPUModelLoad(const char* symbol_file, const char* params_file,
                   MXTPUModelHandle* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* r = PyObject_CallFunction(helper("model_load"), "ss", symbol_file,
                                      params_file ? params_file : "");
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

int MXTPUModelForward(MXTPUModelHandle model, MXTPUNDArrayHandle* inputs,
                      int n_in, MXTPUNDArrayHandle* outputs, int* n_out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* ins = PyTuple_New(n_in);
  for (int i = 0; i < n_in; ++i) {
    PyObject* o = static_cast<PyObject*>(inputs[i]);
    Py_INCREF(o);
    PyTuple_SET_ITEM(ins, i, o);
  }
  PyObject* r = PyObject_CallFunctionObjArgs(
      helper("model_forward"), static_cast<PyObject*>(model), ins, nullptr);
  Py_DECREF(ins);
  if (!r) { set_error_from_python(); return -1; }
  Py_ssize_t k = PyTuple_Size(r);
  if (k > *n_out) {
    Py_DECREF(r);
    tls_last_error = "Forward: output capacity too small";
    return -1;
  }
  for (Py_ssize_t i = 0; i < k; ++i) {
    PyObject* o = PyTuple_GET_ITEM(r, i);
    Py_INCREF(o);
    outputs[i] = o;
  }
  *n_out = static_cast<int>(k);
  Py_DECREF(r);
  return 0;
}

int MXTPUModelFree(MXTPUModelHandle handle) {
  return MXTPUNDArrayFree(handle);
}

int MXTPURandomSeed(int seed) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* r = PyObject_CallFunction(helper("seed"), "i", seed);
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

/* --- training (parity: reference cpp-package Optimizer/Executor) ------ */

int MXTPUModelCreate(const char* spec_json, MXTPUModelHandle* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* r = PyObject_CallFunction(helper("model_create"), "s", spec_json);
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

int MXTPUTrainerCreate(MXTPUModelHandle model, const char* optimizer,
                       const char* optimizer_params_json,
                       MXTPUTrainerHandle* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* r = PyObject_CallFunction(
      helper("trainer_create"), "Oss", static_cast<PyObject*>(model),
      optimizer, optimizer_params_json ? optimizer_params_json : "");
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

int MXTPUTrainerStep(MXTPUTrainerHandle trainer, MXTPUModelHandle model,
                     MXTPUNDArrayHandle* inputs, int n_in,
                     MXTPUNDArrayHandle label, const char* loss,
                     float* loss_out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* ins = PyTuple_New(n_in);
  for (int i = 0; i < n_in; ++i) {
    PyObject* o = static_cast<PyObject*>(inputs[i]);
    Py_INCREF(o);
    PyTuple_SET_ITEM(ins, i, o);
  }
  PyObject* r = PyObject_CallFunction(
      helper("train_step"), "OOOOs", static_cast<PyObject*>(model),
      static_cast<PyObject*>(trainer), ins,
      static_cast<PyObject*>(label), loss);
  Py_DECREF(ins);
  if (!r) { set_error_from_python(); return -1; }
  *loss_out = static_cast<float>(PyFloat_AsDouble(r));
  Py_DECREF(r);
  if (PyErr_Occurred()) { set_error_from_python(); return -1; }
  return 0;
}

int MXTPUTrainerFree(MXTPUTrainerHandle handle) {
  return MXTPUNDArrayFree(handle);
}

int MXTPUModelSaveParams(MXTPUModelHandle model, const char* path) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* r = PyObject_CallFunction(
      helper("model_save_params"), "Os", static_cast<PyObject*>(model), path);
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

int MXTPUModelLoadParams(MXTPUModelHandle model, const char* path) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* r = PyObject_CallFunction(
      helper("model_load_params"), "Os", static_cast<PyObject*>(model), path);
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

}  // extern "C"
