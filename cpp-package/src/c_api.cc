/*
 * C ABI implementation: embedded CPython driving the mxnet_tpu runtime.
 * See c_api.h for the design rationale (single PjRt client per process).
 *
 * Python objects cross the ABI as opaque handles (owned references).
 * Every entry point takes the GIL, so the ABI is safe to call from any
 * thread; calls serialize like the reference engine's exclusive-write
 * semantics on a single var.
 */
#include "../include/mxnet-tpu-cpp/c_api.h"

#include <Python.h>

#include <cstring>
#include <string>
#include <vector>

namespace {

PyObject* g_helpers = nullptr;   // module dict holding the helper funcs
bool g_initialized = false;
PyThreadState* g_main_state = nullptr;  // saved so the GIL is released
thread_local std::string tls_last_error;

// Helper functions injected at init. Kept in Python because the work —
// dtype plumbing, pytree flattening — is runtime logic, not ABI logic.
const char kBootstrap[] = R"PY(
import os, sys, json
_home = os.environ.get('MXTPU_HOME')
if _home and _home not in sys.path:
    sys.path.insert(0, _home)
import jax
_platform = os.environ.get('_MXTPU_CAPI_PLATFORM', '')
if _platform:
    # env var too: mxnet_tpu's import honors JAX_PLATFORMS and would
    # re-override a config-only choice with the ambient env value
    os.environ['JAX_PLATFORMS'] = _platform
    jax.config.update('jax_platforms', _platform)
import numpy as _onp
import mxnet_tpu as mx

def nd_from_buffer(mv, shape):
    a = _onp.frombuffer(mv, dtype=_onp.float32)
    return mx.np.array(a.reshape(tuple(shape)).copy())

def nd_shape(nd):
    return tuple(int(d) for d in nd.shape)

def nd_bytes(nd):
    return nd.asnumpy().astype(_onp.float32, copy=False).tobytes()

def invoke(op, inputs, kwargs_json):
    ns = mx.np if hasattr(mx.np, op) else mx.npx
    if not hasattr(ns, op):
        raise AttributeError(f'no operator {op!r} in mx.np or mx.npx')
    kwargs = json.loads(kwargs_json) if kwargs_json else {}
    return getattr(ns, op)(*inputs, **kwargs)

def model_load(symbol_file, params_file):
    from mxnet_tpu.gluon.block import SymbolBlock
    return SymbolBlock.imports(symbol_file, param_file=params_file or None)

def seed(s):
    mx.random.seed(s)

# --- training surface (parity: reference cpp-package Optimizer/Executor,
# --- `cpp-package/example/mlp.cpp` trains an MLP from C++) ---------------

def model_create(spec_json):
    """Build a trainable Gluon net from a JSON spec:
    {"type": "mlp", "in_units": N, "layers": [h1, ..., out],
     "activation": "relu"}."""
    spec = json.loads(spec_json)
    from mxnet_tpu.gluon import nn
    if spec.get('type', 'mlp') != 'mlp':
        raise ValueError(f"unknown model type {spec.get('type')!r}")
    net = nn.HybridSequential()
    first = in_units = int(spec['in_units'])
    act = spec.get('activation', 'relu')
    layers = [int(w) for w in spec['layers']]
    for i, width in enumerate(layers):
        net.add(nn.Dense(width, in_units=in_units,
                         activation=None if i == len(layers) - 1 else act))
        in_units = width
    net.initialize()
    net.hybridize()
    net(mx.np.zeros((1, first)))
    return net

def trainer_create(model, opt_name, opt_params_json):
    from mxnet_tpu import gluon
    kw = json.loads(opt_params_json) if opt_params_json else {}
    return gluon.Trainer(model.collect_params(), opt_name, kw)

_LOSSES = None

def train_step(model, trainer, inputs, label, loss_name):
    global _LOSSES
    from mxnet_tpu import autograd, gluon
    if _LOSSES is None:
        _LOSSES = {
            'softmax_ce': gluon.loss.SoftmaxCrossEntropyLoss,
            'sigmoid_bce': gluon.loss.SigmoidBinaryCrossEntropyLoss,
            'l2': gluon.loss.L2Loss,
            'l1': gluon.loss.L1Loss,
        }
    if loss_name not in _LOSSES:
        raise ValueError(
            f'unknown loss {loss_name!r}; one of {sorted(_LOSSES)}')
    loss_fn = _LOSSES[loss_name]()
    with autograd.record():
        out = model(*inputs)
        loss = loss_fn(out, label)
    loss.backward()
    trainer.step(int(label.shape[0]))
    return float(loss.mean().asnumpy())

def model_save_params(model, path):
    model.save_parameters(path)

def model_load_params(model, path):
    model.load_parameters(path)

# --- runtime introspection ----------------------------------------------

def version():
    parts = (mx.__version__.split('+')[0].split('.') + ['0', '0'])[:3]
    nums = [int(''.join(ch for ch in p if ch.isdigit()) or 0)
            for p in parts]
    return nums[0] * 10000 + nums[1] * 100 + nums[2]

def list_ops():
    names = set()
    for ns in (mx.np, mx.npx, getattr(mx, 'nd', None)):
        if ns is None:
            continue
        for n in dir(ns):
            if not n.startswith('_') and callable(getattr(ns, n, None)):
                names.add(n)
    return ','.join(sorted(names))

def feature_enabled(name):
    feats = mx.runtime.Features()
    return 1 if (name in feats and feats[name].enabled) else 0

# --- ndarray breadth ----------------------------------------------------

def nd_from_buffer_ex(mv, shape, dtype):
    return nd_from_buffer(mv, shape).astype(dtype)

def nd_dtype(nd):
    return str(nd.dtype)

def nd_save(path, arrays, names):
    mx.nd.save(path, {n: a for n, a in zip(names, arrays)})

def nd_load(path):
    d = mx.nd.load(path)
    if isinstance(d, dict):
        items = sorted(d.items())
    else:
        # list results keep their on-disk arr_N keys so a Save/Load
        # round-trip preserves the caller's names
        items = [(f'arr_{i}', a) for i, a in enumerate(d)]
    return [n for n, _ in items], [a for _, a in items]

def wait_all():
    mx.nd.waitall()

# --- autograd -----------------------------------------------------------

_record_scope = None

def record_begin():
    global _record_scope
    if _record_scope is not None:
        raise RuntimeError('a recording scope is already active')
    _record_scope = mx.autograd.record()
    _record_scope.__enter__()

def record_end():
    global _record_scope
    if _record_scope is None:
        raise RuntimeError('no active recording scope')
    scope, _record_scope = _record_scope, None
    scope.__exit__(None, None, None)

def attach_grad(nd):
    nd.attach_grad()

def backward(head):
    head.backward()

def get_grad(nd):
    if nd.grad is None:
        raise RuntimeError('array has no gradient (attach_grad + backward '
                           'inside a recording scope first)')
    return nd.grad

# --- kvstore ------------------------------------------------------------

def kv_create(kind):
    return mx.kv.create(kind)

def kv_init(kv, key, val):
    kv.init(key, val)

def kv_push(kv, key, val):
    kv.push(key, val)

def kv_pull(kv, key):
    # the reference's MXKVStorePull writes into caller NDArrays; C callers
    # here get the pulled copy AS the new handle, shaped off the stored
    # value. Shaping needs the built-in store's key table; plugin stores
    # (horovod/byteps-style) fail loudly rather than mis-shape.
    store = getattr(kv, '_store', None)
    if store is None or not hasattr(kv, '_key'):
        raise RuntimeError(
            f'MXTPUKVStorePull supports the built-in kvstore types; '
            f'{type(kv).__name__} does not expose a key table')
    kk = kv._key(key)
    if kk not in store:
        raise RuntimeError(f'key {key} has not been initialised')
    tmpl = store[kk]
    out = mx.np.zeros(tmpl.shape, dtype=str(tmpl.dtype))
    kv.pull(key, out=out)
    return out

def kv_rank(kv):
    return int(kv.rank)

def kv_num_workers(kv):
    return int(kv.num_workers)

# --- profiler -----------------------------------------------------------

def profiler_start():
    mx.profiler.set_config(aggregate_stats=True)
    mx.profiler.start()

def profiler_stop():
    mx.profiler.stop()

def profiler_dumps(reset):
    return mx.profiler.dumps(reset=bool(reset))

# --- symbol construction (parity: MXSymbolCreateVariable,
# --- MXSymbolCreateAtomicSymbol+Compose, MXSymbolCreateFromFile/JSON,
# --- MXSymbolSaveToJSON, MXSymbolListArguments/Outputs, MXSymbolFree —
# --- `include/mxnet/c_api.h` MXSymbol* family) --------------------------

def sym_variable(name):
    from mxnet_tpu import symbol
    return symbol.var(name)

def sym_from_op(op, name, inputs, kwargs_json):
    from mxnet_tpu.symbol.symbol import _resolve_op, Symbol
    kwargs = json.loads(kwargs_json) if kwargs_json else {}
    if _resolve_op(op) is None:   # fail now on unknown ops, not at eval
        raise ValueError(f'unknown symbol op {op!r}')
    return Symbol._node(op, list(inputs), kwargs, name or None)

def sym_load(path):
    from mxnet_tpu import symbol
    return symbol.load(path)

def sym_load_json(js):
    from mxnet_tpu.symbol.symbol import fromjson
    return fromjson(js)

def sym_to_json(sym):
    return sym.tojson()

def sym_list_arguments(sym):
    return list(sym.list_arguments())

def sym_list_outputs(sym):
    return list(sym.list_outputs())

def sym_eval(sym, names, vals):
    out = sym.eval(**{n: v for n, v in zip(names, vals)})
    return out if isinstance(out, (tuple, list)) else (out,)

# --- model (CachedOp) flags (parity: MXCreateCachedOpEx flag pairs —
# --- static_alloc/static_shape/data_indices — `include/mxnet/c_api.h`;
# --- here flags configure the jit cache + forward mode) -----------------

_KNOWN_FLAGS = {'training', 'hybridize', 'static_alloc', 'static_shape'}

def model_set_flags(model, flags_json):
    flags = json.loads(flags_json)
    unknown = set(flags) - _KNOWN_FLAGS
    if unknown:
        raise ValueError(f'unknown model flags {sorted(unknown)}; '
                         f'known: {sorted(_KNOWN_FLAGS)}')
    cur = dict(getattr(model, '_capi_flags', None) or {
        'training': False, 'hybridize': True,
        # XLA compiles statically always — accepted for parity, fixed True
        'static_alloc': True, 'static_shape': True})
    cur.update({k: bool(v) for k, v in flags.items()})
    # validate on the COPY: a rejected call must not corrupt stored state
    if not cur['static_alloc'] or not cur['static_shape']:
        raise ValueError('static_alloc/static_shape are always true on '
                         'the XLA runtime and cannot be disabled')
    model._capi_flags = cur
    if hasattr(model, 'hybridize'):
        model.hybridize(cur['hybridize'])

def model_get_flags(model):
    cur = getattr(model, '_capi_flags', None) or {
        'training': False, 'hybridize': True,
        'static_alloc': True, 'static_shape': True}
    return json.dumps(cur)

def model_forward(model, inputs):
    from mxnet_tpu import autograd
    flags = getattr(model, '_capi_flags', None)
    if flags and flags.get('training'):
        with autograd.train_mode():
            out = model(*inputs)
    else:
        out = model(*inputs)
    return out if isinstance(out, tuple) else (out,)

# --- data iterators (parity: MXListDataIters, MXDataIterCreateIter,
# --- MXDataIterNext/BeforeFirst, MXDataIterGetData/GetLabel, MXDataIterFree
# --- — `include/mxnet/c_api.h` MXDataIter* family; `src/io/iter_mnist.cc`
# --- and friends) -------------------------------------------------------

_ITER_TYPES = ('MNISTIter', 'ImageRecordIter', 'CSVIter', 'LibSVMIter',
               'NDArrayIter')

def list_data_iters():
    return ','.join(_ITER_TYPES)

def data_iter_create(kind, params_json):
    import mxnet_tpu.io as io
    if kind not in _ITER_TYPES:
        raise ValueError(f'unknown iterator {kind!r}; one of {_ITER_TYPES}')
    params = json.loads(params_json) if params_json else {}
    for k in ('data_shape', 'label_shape', 'input_shape'):
        if k in params and isinstance(params[k], list):
            params[k] = tuple(params[k])
    return [getattr(io, kind)(**params), None]   # [iter, current_batch]

def data_iter_from_arrays(data, label, batch_size, shuffle):
    import mxnet_tpu.io as io
    return [io.NDArrayIter(data, label=label, batch_size=int(batch_size),
                           shuffle=bool(shuffle)), None]

def data_iter_next(state):
    it = state[0]
    try:
        state[1] = it.next()
        return 1
    except StopIteration:
        state[1] = None
        return 0

def data_iter_reset(state):
    state[0].reset()
    state[1] = None

def _iter_part(state, what):
    b = state[1]
    if b is None:
        raise RuntimeError('no current batch: call MXTPUDataIterNext first '
                           '(and check it returned more=1)')
    part = getattr(b, what)
    if not part:
        raise RuntimeError(f'batch has no {what}')
    return part[0]

def data_iter_data(state):
    return _iter_part(state, 'data')

def data_iter_label(state):
    return _iter_part(state, 'label')
)PY";

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  tls_last_error = "unknown python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* msg = PyUnicode_AsUTF8(s);
      if (msg) tls_last_error = msg;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

PyObject* helper(const char* name) {
  return PyDict_GetItemString(g_helpers, name);  // borrowed
}

// RAII GIL acquisition for every ABI entry point.
class GILGuard {
 public:
  GILGuard() : state_(PyGILState_Ensure()) {}
  ~GILGuard() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

#define MXTPU_REQUIRE_INIT()                                   \
  do {                                                         \
    if (!g_initialized) {                                      \
      tls_last_error = "MXTPUInit has not been called";        \
      return -1;                                               \
    }                                                          \
  } while (0)

}  // namespace

extern "C" {

int MXTPUInit(const char* platform) {
  if (g_initialized) return 0;
  if (platform && platform[0] != '\0') {
    setenv("_MXTPU_CAPI_PLATFORM", platform, 1);
  }
  bool fresh = !Py_IsInitialized();
  if (fresh) {
    Py_InitializeEx(0);
  }
  {
    GILGuard gil;
    PyObject* mod = PyModule_New("__mxtpu_capi__");
    if (!mod) { set_error_from_python(); return -1; }
    g_helpers = PyModule_GetDict(mod);  // borrowed; mod leaks on purpose
    PyDict_SetItemString(g_helpers, "__builtins__", PyEval_GetBuiltins());
    PyObject* r = PyRun_String(kBootstrap, Py_file_input, g_helpers,
                               g_helpers);
    if (!r) {
      set_error_from_python();
      g_helpers = nullptr;
      return -1;
    }
    Py_DECREF(r);
  }
  if (fresh) {
    // Py_InitializeEx leaves this thread holding the GIL; release it so
    // any thread (including this one, via GILGuard) can re-acquire
    g_main_state = PyEval_SaveThread();
  }
  g_initialized = true;
  return 0;
}

int MXTPUShutdown(void) {
  if (!g_initialized) return 0;
  g_initialized = false;
  g_helpers = nullptr;
  if (g_main_state != nullptr) {
    PyEval_RestoreThread(g_main_state);  // Finalize needs the GIL
    g_main_state = nullptr;
  }
  Py_Finalize();
  return 0;
}

const char* MXTPUGetLastError(void) { return tls_last_error.c_str(); }

int MXTPUNDArrayCreate(const float* data, const int64_t* shape, int ndim,
                       MXTPUNDArrayHandle* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  int64_t n = 1;
  PyObject* pyshape = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i) {
    n *= shape[i];
    PyTuple_SET_ITEM(pyshape, i, PyLong_FromLongLong(shape[i]));
  }
  PyObject* mv = PyMemoryView_FromMemory(
      reinterpret_cast<char*>(const_cast<float*>(data)),
      n * static_cast<int64_t>(sizeof(float)), PyBUF_READ);
  PyObject* r = PyObject_CallFunctionObjArgs(helper("nd_from_buffer"), mv,
                                             pyshape, nullptr);
  Py_DECREF(mv);
  Py_DECREF(pyshape);
  if (!r) { set_error_from_python(); return -1; }
  *out = r;  // ownership transferred to the handle
  return 0;
}

int MXTPUNDArrayShape(MXTPUNDArrayHandle handle, int64_t* shape, int* ndim) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* r = PyObject_CallFunctionObjArgs(
      helper("nd_shape"), static_cast<PyObject*>(handle), nullptr);
  if (!r) { set_error_from_python(); return -1; }
  Py_ssize_t k = PyTuple_Size(r);
  if (k > 8) { Py_DECREF(r); tls_last_error = "rank > 8"; return -1; }
  *ndim = static_cast<int>(k);
  for (Py_ssize_t i = 0; i < k; ++i) {
    shape[i] = PyLong_AsLongLong(PyTuple_GET_ITEM(r, i));
  }
  Py_DECREF(r);
  return 0;
}

int MXTPUNDArraySize(MXTPUNDArrayHandle handle, int64_t* size) {
  int64_t shape[8];
  int ndim = 0;
  if (MXTPUNDArrayShape(handle, shape, &ndim) != 0) return -1;
  int64_t n = 1;
  for (int i = 0; i < ndim; ++i) n *= shape[i];
  *size = n;
  return 0;
}

int MXTPUNDArrayCopyTo(MXTPUNDArrayHandle handle, float* buf, int64_t size) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* r = PyObject_CallFunctionObjArgs(
      helper("nd_bytes"), static_cast<PyObject*>(handle), nullptr);
  if (!r) { set_error_from_python(); return -1; }
  char* raw = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(r, &raw, &len) != 0) {
    Py_DECREF(r);
    set_error_from_python();
    return -1;
  }
  if (len != size * static_cast<int64_t>(sizeof(float))) {
    Py_DECREF(r);
    tls_last_error = "CopyTo: size mismatch";
    return -1;
  }
  std::memcpy(buf, raw, len);
  Py_DECREF(r);
  return 0;
}

int MXTPUNDArrayFree(MXTPUNDArrayHandle handle) {
  if (!g_initialized || handle == nullptr) return 0;
  GILGuard gil;
  Py_DECREF(static_cast<PyObject*>(handle));
  return 0;
}

int MXTPUInvoke(const char* op_name, MXTPUNDArrayHandle* inputs, int n_in,
                const char* kwargs_json, MXTPUNDArrayHandle* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* ins = PyTuple_New(n_in);
  for (int i = 0; i < n_in; ++i) {
    PyObject* o = static_cast<PyObject*>(inputs[i]);
    Py_INCREF(o);
    PyTuple_SET_ITEM(ins, i, o);
  }
  PyObject* r = PyObject_CallFunction(
      helper("invoke"), "sOs", op_name, ins,
      kwargs_json ? kwargs_json : "");
  Py_DECREF(ins);
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

int MXTPUModelLoad(const char* symbol_file, const char* params_file,
                   MXTPUModelHandle* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* r = PyObject_CallFunction(helper("model_load"), "ss", symbol_file,
                                      params_file ? params_file : "");
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

int MXTPUModelForward(MXTPUModelHandle model, MXTPUNDArrayHandle* inputs,
                      int n_in, MXTPUNDArrayHandle* outputs, int* n_out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* ins = PyTuple_New(n_in);
  for (int i = 0; i < n_in; ++i) {
    PyObject* o = static_cast<PyObject*>(inputs[i]);
    Py_INCREF(o);
    PyTuple_SET_ITEM(ins, i, o);
  }
  PyObject* r = PyObject_CallFunctionObjArgs(
      helper("model_forward"), static_cast<PyObject*>(model), ins, nullptr);
  Py_DECREF(ins);
  if (!r) { set_error_from_python(); return -1; }
  Py_ssize_t k = PyTuple_Size(r);
  if (k > *n_out) {
    Py_DECREF(r);
    tls_last_error = "Forward: output capacity too small";
    return -1;
  }
  for (Py_ssize_t i = 0; i < k; ++i) {
    PyObject* o = PyTuple_GET_ITEM(r, i);
    Py_INCREF(o);
    outputs[i] = o;
  }
  *n_out = static_cast<int>(k);
  Py_DECREF(r);
  return 0;
}

int MXTPUModelFree(MXTPUModelHandle handle) {
  return MXTPUNDArrayFree(handle);
}

int MXTPURandomSeed(int seed) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* r = PyObject_CallFunction(helper("seed"), "i", seed);
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

/* --- training (parity: reference cpp-package Optimizer/Executor) ------ */

int MXTPUModelCreate(const char* spec_json, MXTPUModelHandle* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* r = PyObject_CallFunction(helper("model_create"), "s", spec_json);
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

int MXTPUTrainerCreate(MXTPUModelHandle model, const char* optimizer,
                       const char* optimizer_params_json,
                       MXTPUTrainerHandle* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* r = PyObject_CallFunction(
      helper("trainer_create"), "Oss", static_cast<PyObject*>(model),
      optimizer, optimizer_params_json ? optimizer_params_json : "");
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

int MXTPUTrainerStep(MXTPUTrainerHandle trainer, MXTPUModelHandle model,
                     MXTPUNDArrayHandle* inputs, int n_in,
                     MXTPUNDArrayHandle label, const char* loss,
                     float* loss_out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* ins = PyTuple_New(n_in);
  for (int i = 0; i < n_in; ++i) {
    PyObject* o = static_cast<PyObject*>(inputs[i]);
    Py_INCREF(o);
    PyTuple_SET_ITEM(ins, i, o);
  }
  PyObject* r = PyObject_CallFunction(
      helper("train_step"), "OOOOs", static_cast<PyObject*>(model),
      static_cast<PyObject*>(trainer), ins,
      static_cast<PyObject*>(label), loss);
  Py_DECREF(ins);
  if (!r) { set_error_from_python(); return -1; }
  *loss_out = static_cast<float>(PyFloat_AsDouble(r));
  Py_DECREF(r);
  if (PyErr_Occurred()) { set_error_from_python(); return -1; }
  return 0;
}

int MXTPUTrainerFree(MXTPUTrainerHandle handle) {
  return MXTPUNDArrayFree(handle);
}

int MXTPUModelSaveParams(MXTPUModelHandle model, const char* path) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* r = PyObject_CallFunction(
      helper("model_save_params"), "Os", static_cast<PyObject*>(model), path);
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

int MXTPUModelLoadParams(MXTPUModelHandle model, const char* path) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* r = PyObject_CallFunction(
      helper("model_load_params"), "Os", static_cast<PyObject*>(model), path);
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

/* --- runtime introspection -------------------------------------------- */

namespace {

// string results live here until the next call on the same thread
thread_local std::string tls_string_result;
thread_local std::vector<std::string> tls_name_results;

// Shared call driver. `has_args` distinguishes "helper takes no args"
// from "Py_BuildValue failed" (nullptr args with has_args=true must
// surface the pending build error, not call the helper argless).
PyObject* call_helper(const char* name, PyObject* args_owned, bool has_args) {
  if (has_args && args_owned == nullptr) {
    set_error_from_python();  // Py_BuildValue failure (bad UTF-8, OOM...)
    return nullptr;
  }
  PyObject* r = args_owned
      ? PyObject_CallObject(helper(name), args_owned)
      : PyObject_CallFunctionObjArgs(helper(name), nullptr);
  Py_XDECREF(args_owned);
  if (!r) set_error_from_python();
  return r;
}

// call a helper expecting an int result
int call_int_helper(const char* name, PyObject* args_owned, int* out) {
  PyObject* r = call_helper(name, args_owned, args_owned != nullptr ||
                            PyErr_Occurred());
  if (!r) return -1;
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  if (PyErr_Occurred()) { set_error_from_python(); return -1; }
  return 0;
}

// call a no-arg helper, discard the result
int call_void_helper(const char* name, PyObject* args_owned = nullptr) {
  PyObject* r = call_helper(name, args_owned, args_owned != nullptr ||
                            PyErr_Occurred());
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

// call a helper returning str; point *out at a thread-local copy
int call_str_helper(const char* name, PyObject* args_owned,
                    const char** out) {
  PyObject* r = call_helper(name, args_owned, args_owned != nullptr ||
                            PyErr_Occurred());
  if (!r) return -1;
  const char* s = PyUnicode_AsUTF8(r);
  if (!s) { Py_DECREF(r); set_error_from_python(); return -1; }
  tls_string_result = s;
  Py_DECREF(r);
  *out = tls_string_result.c_str();
  return 0;
}

}  // namespace

int MXTPUGetVersion(int* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_int_helper("version", nullptr, out);
}

int MXTPUListOps(const char** out, int* n_ops) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  if (call_str_helper("list_ops", nullptr, out) != 0) return -1;
  if (n_ops != nullptr) {
    int n = tls_string_result.empty() ? 0 : 1;
    for (char c : tls_string_result) n += (c == ',');
    *n_ops = n;
  }
  return 0;
}

int MXTPUFeatureIsEnabled(const char* name, int* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_int_helper("feature_enabled",
                         Py_BuildValue("(s)", name), out);
}

/* --- NDArray breadth --------------------------------------------------- */

int MXTPUNDArrayCreateEx(const float* data, const int64_t* shape, int ndim,
                         const char* dtype, MXTPUNDArrayHandle* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  int64_t n = 1;
  PyObject* pyshape = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i) {
    n *= shape[i];
    PyTuple_SET_ITEM(pyshape, i, PyLong_FromLongLong(shape[i]));
  }
  PyObject* mv = PyMemoryView_FromMemory(
      reinterpret_cast<char*>(const_cast<float*>(data)),
      n * static_cast<int64_t>(sizeof(float)), PyBUF_READ);
  PyObject* r = PyObject_CallFunction(helper("nd_from_buffer_ex"), "OOs",
                                      mv, pyshape, dtype);
  Py_DECREF(mv);
  Py_DECREF(pyshape);
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

int MXTPUNDArrayDType(MXTPUNDArrayHandle handle, const char** out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_str_helper(
      "nd_dtype",
      Py_BuildValue("(O)", static_cast<PyObject*>(handle)), out);
}

int MXTPUNDArraySave(const char* path, MXTPUNDArrayHandle* arrays,
                     const char** names, int n) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* arrs = PyList_New(n);
  PyObject* keys = PyList_New(n);
  for (int i = 0; i < n; ++i) {
    PyObject* o = static_cast<PyObject*>(arrays[i]);
    Py_INCREF(o);
    PyList_SET_ITEM(arrs, i, o);
    PyObject* name = PyUnicode_FromString(names[i]);
    if (name == nullptr) {  // e.g. invalid UTF-8 in the caller's key
      Py_DECREF(arrs);
      Py_DECREF(keys);
      set_error_from_python();
      return -1;
    }
    PyList_SET_ITEM(keys, i, name);
  }
  PyObject* r = PyObject_CallFunction(helper("nd_save"), "sOO", path,
                                      arrs, keys);
  Py_DECREF(arrs);
  Py_DECREF(keys);
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

int MXTPUNDArrayLoad(const char* path, MXTPUNDArrayHandle* arrays,
                     const char** name_buf, int* n) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* r = PyObject_CallFunction(helper("nd_load"), "s", path);
  if (!r) { set_error_from_python(); return -1; }
  PyObject* names = PyTuple_GetItem(r, 0);
  PyObject* arrs = PyTuple_GetItem(r, 1);
  Py_ssize_t k = PyList_Size(arrs);
  if (k > *n) {
    Py_DECREF(r);
    tls_last_error = "Load: output capacity too small";
    return -1;
  }
  tls_name_results.clear();
  for (Py_ssize_t i = 0; i < k; ++i) {
    tls_name_results.emplace_back(
        PyUnicode_AsUTF8(PyList_GET_ITEM(names, i)));
  }
  for (Py_ssize_t i = 0; i < k; ++i) {
    PyObject* o = PyList_GET_ITEM(arrs, i);
    Py_INCREF(o);
    arrays[i] = o;
    name_buf[i] = tls_name_results[i].c_str();
  }
  *n = static_cast<int>(k);
  Py_DECREF(r);
  return 0;
}

int MXTPUWaitAll(void) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_void_helper("wait_all");
}

/* --- autograd ---------------------------------------------------------- */

int MXTPUAutogradRecordBegin(void) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_void_helper("record_begin");
}

int MXTPUAutogradRecordEnd(void) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_void_helper("record_end");
}

int MXTPUNDArrayAttachGrad(MXTPUNDArrayHandle handle) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_void_helper(
      "attach_grad", Py_BuildValue("(O)", static_cast<PyObject*>(handle)));
}

int MXTPUAutogradBackward(MXTPUNDArrayHandle head) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_void_helper(
      "backward", Py_BuildValue("(O)", static_cast<PyObject*>(head)));
}

int MXTPUNDArrayGetGrad(MXTPUNDArrayHandle handle, MXTPUNDArrayHandle* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* r = PyObject_CallFunctionObjArgs(
      helper("get_grad"), static_cast<PyObject*>(handle), nullptr);
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

/* --- kvstore ----------------------------------------------------------- */

int MXTPUKVStoreCreate(const char* type, MXTPUKVStoreHandle* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* r = PyObject_CallFunction(helper("kv_create"), "s", type);
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

int MXTPUKVStoreInit(MXTPUKVStoreHandle kv, int key, MXTPUNDArrayHandle val) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_void_helper(
      "kv_init", Py_BuildValue("(OiO)", static_cast<PyObject*>(kv), key,
                               static_cast<PyObject*>(val)));
}

int MXTPUKVStorePush(MXTPUKVStoreHandle kv, int key, MXTPUNDArrayHandle val) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_void_helper(
      "kv_push", Py_BuildValue("(OiO)", static_cast<PyObject*>(kv), key,
                               static_cast<PyObject*>(val)));
}

int MXTPUKVStorePull(MXTPUKVStoreHandle kv, int key,
                     MXTPUNDArrayHandle* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* r = PyObject_CallFunction(helper("kv_pull"), "Oi",
                                      static_cast<PyObject*>(kv), key);
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

int MXTPUKVStoreRank(MXTPUKVStoreHandle kv, int* rank) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_int_helper(
      "kv_rank", Py_BuildValue("(O)", static_cast<PyObject*>(kv)), rank);
}

int MXTPUKVStoreNumWorkers(MXTPUKVStoreHandle kv, int* n) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_int_helper(
      "kv_num_workers", Py_BuildValue("(O)", static_cast<PyObject*>(kv)), n);
}

int MXTPUKVStoreFree(MXTPUKVStoreHandle kv) {
  return MXTPUNDArrayFree(kv);
}

/* --- profiler ---------------------------------------------------------- */

int MXTPUProfilerStart(void) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_void_helper("profiler_start");
}

int MXTPUProfilerStop(void) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_void_helper("profiler_stop");
}

int MXTPUProfilerDumps(const char** out, int reset) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_str_helper("profiler_dumps", Py_BuildValue("(i)", reset), out);
}

/* --- symbol ------------------------------------------------------------ */

namespace {

// helper returning a handle (new python reference becomes the handle)
int call_handle_helper(const char* name, PyObject* args_owned, void** out) {
  PyObject* r = call_helper(name, args_owned, true);
  if (!r) return -1;
  *out = r;
  return 0;
}

// helper returning list[str] -> thread-local name buffer
int call_names_helper(const char* name, PyObject* args_owned,
                      const char** name_buf, int* n) {
  PyObject* r = call_helper(name, args_owned, true);
  if (!r) return -1;
  Py_ssize_t k = PyList_Size(r);
  if (k > *n) {
    Py_DECREF(r);
    tls_last_error = "name buffer capacity too small";
    return -1;
  }
  tls_name_results.clear();
  for (Py_ssize_t i = 0; i < k; ++i) {
    const char* s = PyUnicode_AsUTF8(PyList_GetItem(r, i));
    tls_name_results.emplace_back(s ? s : "");
  }
  Py_DECREF(r);
  for (Py_ssize_t i = 0; i < k; ++i) name_buf[i] = tls_name_results[i].c_str();
  *n = static_cast<int>(k);
  return 0;
}

}  // namespace

int MXTPUSymbolCreateVariable(const char* name, MXTPUSymbolHandle* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_handle_helper("sym_variable", Py_BuildValue("(s)", name), out);
}

int MXTPUSymbolCreateFromOp(const char* op, const char* name,
                            MXTPUSymbolHandle* inputs, int n_in,
                            const char* kwargs_json, MXTPUSymbolHandle* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* ins = PyTuple_New(n_in);
  for (int i = 0; i < n_in; ++i) {
    PyObject* o = static_cast<PyObject*>(inputs[i]);
    Py_INCREF(o);
    PyTuple_SET_ITEM(ins, i, o);
  }
  int rc = call_handle_helper(
      "sym_from_op",
      Py_BuildValue("(ssOs)", op, name ? name : "", ins,
                    kwargs_json ? kwargs_json : ""),
      out);
  Py_DECREF(ins);
  return rc;
}

int MXTPUSymbolLoad(const char* path, MXTPUSymbolHandle* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_handle_helper("sym_load", Py_BuildValue("(s)", path), out);
}

int MXTPUSymbolLoadJSON(const char* json, MXTPUSymbolHandle* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_handle_helper("sym_load_json", Py_BuildValue("(s)", json), out);
}

int MXTPUSymbolSaveJSON(MXTPUSymbolHandle sym, const char** out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_str_helper(
      "sym_to_json", Py_BuildValue("(O)", static_cast<PyObject*>(sym)), out);
}

int MXTPUSymbolListArguments(MXTPUSymbolHandle sym, const char** name_buf,
                             int* n) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_names_helper(
      "sym_list_arguments",
      Py_BuildValue("(O)", static_cast<PyObject*>(sym)), name_buf, n);
}

int MXTPUSymbolListOutputs(MXTPUSymbolHandle sym, const char** name_buf,
                           int* n) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_names_helper(
      "sym_list_outputs",
      Py_BuildValue("(O)", static_cast<PyObject*>(sym)), name_buf, n);
}

int MXTPUSymbolEval(MXTPUSymbolHandle sym, const char** arg_names,
                    MXTPUNDArrayHandle* arg_vals, int n_args,
                    MXTPUNDArrayHandle* outputs, int* n_out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* names = PyTuple_New(n_args);
  PyObject* vals = PyTuple_New(n_args);
  for (int i = 0; i < n_args; ++i) {
    PyTuple_SET_ITEM(names, i, PyUnicode_FromString(arg_names[i]));
    PyObject* v = static_cast<PyObject*>(arg_vals[i]);
    Py_INCREF(v);
    PyTuple_SET_ITEM(vals, i, v);
  }
  PyObject* r = call_helper(
      "sym_eval",
      Py_BuildValue("(OOO)", static_cast<PyObject*>(sym), names, vals), true);
  Py_DECREF(names);
  Py_DECREF(vals);
  if (!r) return -1;
  Py_ssize_t k = PySequence_Size(r);
  if (k > *n_out) {
    Py_DECREF(r);
    tls_last_error = "SymbolEval: output capacity too small";
    return -1;
  }
  for (Py_ssize_t i = 0; i < k; ++i) {
    outputs[i] = PySequence_GetItem(r, i);  // new refs become handles
  }
  *n_out = static_cast<int>(k);
  Py_DECREF(r);
  return 0;
}

int MXTPUSymbolFree(MXTPUSymbolHandle sym) {
  return MXTPUNDArrayFree(sym);
}

/* --- model flags ------------------------------------------------------- */

int MXTPUModelSetFlags(MXTPUModelHandle model, const char* flags_json) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_void_helper(
      "model_set_flags",
      Py_BuildValue("(Os)", static_cast<PyObject*>(model),
                    flags_json ? flags_json : "{}"));
}

int MXTPUModelGetFlags(MXTPUModelHandle model, const char** out_json) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_str_helper(
      "model_get_flags",
      Py_BuildValue("(O)", static_cast<PyObject*>(model)), out_json);
}

/* --- data iterators ---------------------------------------------------- */

int MXTPUListDataIters(const char** out, int* n) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  if (call_str_helper("list_data_iters", nullptr, out) != 0) return -1;
  if (n) {
    int k = tls_string_result.empty() ? 0 : 1;
    for (char c : tls_string_result) k += (c == ',');
    *n = k;
  }
  return 0;
}

int MXTPUDataIterCreate(const char* type, const char* params_json,
                        MXTPUDataIterHandle* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_handle_helper(
      "data_iter_create",
      Py_BuildValue("(ss)", type, params_json ? params_json : ""), out);
}

int MXTPUDataIterCreateFromArrays(MXTPUNDArrayHandle data,
                                  MXTPUNDArrayHandle label, int batch_size,
                                  int shuffle, MXTPUDataIterHandle* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  PyObject* lab = label ? static_cast<PyObject*>(label) : Py_None;
  return call_handle_helper(
      "data_iter_from_arrays",
      Py_BuildValue("(OOii)", static_cast<PyObject*>(data), lab, batch_size,
                    shuffle),
      out);
}

int MXTPUDataIterNext(MXTPUDataIterHandle it, int* more) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_int_helper(
      "data_iter_next", Py_BuildValue("(O)", static_cast<PyObject*>(it)),
      more);
}

int MXTPUDataIterReset(MXTPUDataIterHandle it) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_void_helper(
      "data_iter_reset", Py_BuildValue("(O)", static_cast<PyObject*>(it)));
}

int MXTPUDataIterGetData(MXTPUDataIterHandle it, MXTPUNDArrayHandle* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_handle_helper(
      "data_iter_data", Py_BuildValue("(O)", static_cast<PyObject*>(it)),
      out);
}

int MXTPUDataIterGetLabel(MXTPUDataIterHandle it, MXTPUNDArrayHandle* out) {
  MXTPU_REQUIRE_INIT();
  GILGuard gil;
  return call_handle_helper(
      "data_iter_label", Py_BuildValue("(O)", static_cast<PyObject*>(it)),
      out);
}

int MXTPUDataIterFree(MXTPUDataIterHandle it) {
  return MXTPUNDArrayFree(it);
}

}  // extern "C"
