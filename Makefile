# Developer entry points (parity: reference Makefile/CMake targets, reduced
# to what a single-language-core framework needs).
PY ?= python

.PHONY: test test-dist lint bench cpp docs clean

test:
	$(PY) -m pytest tests/unittest -q --ignore=tests/unittest/test_dist_kvstore.py

test-dist:
	$(PY) -m pytest tests/unittest/test_dist_kvstore.py -q

lint:
	ruff check mxnet_tpu tests || true

bench:
	$(PY) bench.py

cpp:
	cmake -S cpp-package -B cpp-package/build && \
	cmake --build cpp-package/build

clean:
	rm -rf cpp-package/build .pytest_cache $(shell find . -name __pycache__)
