# Developer entry points (parity: reference Makefile/CMake targets, reduced
# to what a single-language-core framework needs).
PY ?= python

.PHONY: ci test test-all test-dist test-parity lint bench cpp docs clean opperf-check telemetry-smoke health-smoke chaos-smoke serve-smoke fleet-smoke procfleet-smoke kernels-smoke elastic-smoke export-smoke data-smoke trace-smoke quant-smoke spec-smoke disagg-smoke obsplane-smoke replay-smoke qos-smoke perf-gate

# the one-command gate CI runs (VERDICT round-2 next-step #7): lint +
# unit suite + 2-process dist tests + C++ package build/tests
ci: lint test-all test-dist cpp-test

cpp-test:
	$(PY) -m pytest tests/unittest/test_cpp_package.py -q

# fast default for local iteration (VERDICT r3 weak #5): skips the
# slow-marked tier (example subprocesses, op-sweep batteries,
# integration-scale training loops, scaling/large-tensor benches);
# `make test-all` runs everything.  -n auto parallelizes when xdist +
# cores are available: ~13.5 min serial on the 1-core builder VM,
# well under 10 min on any >=2-core box
test: telemetry-smoke health-smoke chaos-smoke serve-smoke fleet-smoke procfleet-smoke kernels-smoke elastic-smoke export-smoke data-smoke trace-smoke quant-smoke spec-smoke disagg-smoke obsplane-smoke replay-smoke qos-smoke
	$(PY) -m pytest tests/unittest -q -m "not slow" $$($(PY) -c 'import xdist, os; print("-n auto" if (os.cpu_count() or 1) > 1 else "")' 2>/dev/null) --ignore=tests/unittest/test_dist_kvstore.py

test-all:
	$(PY) -m pytest tests/unittest tests/parity -q --ignore=tests/unittest/test_dist_kvstore.py

# the reference-conformance tier alone (reference unit-test bodies run
# against this framework; see tests/parity/conftest.py)
test-parity:
	$(PY) -m pytest tests/parity -q

# op-microbenchmark regression gate (VERDICT r4 item 5): pinned subset
# vs bench_results/opperf_cpu.md, median-normalized so only RELATIVE
# single-kernel regressions trip it; refresh docs in tools/opperf_check.py
opperf-check:
	$(PY) tools/opperf_check.py

test-dist:
	$(PY) -m pytest tests/unittest/test_dist_kvstore.py -q

lint:
	@if $(PY) -m ruff --version >/dev/null 2>&1; then \
		$(PY) -m ruff check mxnet_tpu tests; \
	else echo "ruff not installed; lint skipped (CI installs it)"; fi

bench:
	$(PY) bench.py

# 5-step CPU training loop with the telemetry registry + run journal
# enabled; asserts the Prometheus exposition parses (pure-stdlib check,
# docs/observability.md)
telemetry-smoke:
	$(PY) tools/telemetry_smoke.py

# 12-step CPU run with a NaN injected through MXTPU_FAULT_SPEC, ending in
# a forced crash; asserts the numerics probes counted it, the anomaly
# journal event names the right step, and the crash flight-recorder
# bundle landed in MXTPU_CRASH_DIR (docs/observability.md)
health-smoke:
	$(PY) tools/health_smoke.py

# self-healing end-to-end: 40-step CPU run under MXTPU_RECOVERY with an
# injected NaN batch (tier-1 skip + loss-scale backoff), worker kills,
# a sustained divergence (tier-2 rollback to the newest healthy-tagged
# checkpoint), and a mid-run SIGTERM (grace-deadline emergency save);
# a second phase resumes from the marker and completes
# (docs/resilience.md, "Recovery policies & preemption")
chaos-smoke:
	$(PY) tools/chaos_smoke.py

# elastic mesh reformation end-to-end: an 8-virtual-device CPU run loses
# a simulated host mid-run (heartbeat stops) -> the mesh shrinks dp4xtp2
# -> dp2xtp2 and training resumes at the multi-host agreed checkpoint
# step WITHOUT a process restart; the host later rejoins and the mesh
# grows back.  Asserts step continuity (no lost batches), a bit-identical
# post-shrink loss trajectory vs an uninterrupted run restored from the
# same checkpoint on the same mesh, and trace_count==1 per topology
# (docs/resilience.md, "Elastic scale-out")
elastic-smoke:
	$(PY) tools/elastic_smoke.py

# deterministic data pipeline end-to-end (docs/data.md): a training
# child over mixture+packed RecordIO shards dies mid-epoch after 12
# batches; a FRESH process restores from the pipeline-attached
# CheckpointManager (O(1) manifest seek, no replay) and its stream must
# be bit-identical to an uninterrupted reference run.  Also proves a
# 1->2->1 host shrink/grow reform delivers every sample exactly once,
# and that the packed data path causes zero retraces (trace_count==1
# over 8 prefetched batches)
data-smoke:
	$(PY) tools/data_smoke.py

# serving-stack end-to-end: 8 staggered concurrent requests through the
# continuous-batching scheduler over a deliberately undersized paged KV
# pool (forced mid-stream eviction + re-admit); asserts streamed tokens
# are bit-identical to unbatched generate() and the per-request TTFT
# histograms / page-occupancy gauges landed in telemetry
# (docs/serving.md)
serve-smoke:
	$(PY) tools/serve_smoke.py

# serving-fleet robustness end-to-end (docs/serving.md "Fleet,
# failover & overload"): 3 supervised replicas under staggered
# mixed-length load — one killed mid-stream via the replica_step fault
# point (in-flight streams fail over and resume bit-identical on
# survivors), one drained gracefully (exits with an empty active set),
# and a pre-start overload burst proving the shed counter fires only
# once the bounded global queue is full.  Zero dropped requests; every
# streamed token identical to unbatched generate()
fleet-smoke:
	$(PY) tools/fleet_smoke.py

# process transport: real worker processes over the wire protocol,
# SIGKILL + respawn + wire drain + dropped-frame chaos
procfleet-smoke:
	$(PY) tools/procfleet_smoke.py

# disaggregated serving (docs/serving.md "Disaggregated serving"):
# 1 prefill + 2 tp=2 decode process replicas over 8 virtual devices,
# every stream crossing a binary-frame KV handoff, one decode worker
# SIGKILLed mid-stream — bit-identical streams, handoffs > 0, zero
# dropped requests, <60 s on CPU
disagg-smoke:
	$(PY) tools/disagg_smoke.py

# fleet observability plane (docs/observability.md "Fleet
# observability"): one trace id per request across router + prefill +
# decode processes with clock-rebased worker spans, merged Perfetto
# export via diagnose --trace, per-replica federated /metrics series
# present then retired on drain, and an SLO burn alert fired by
# SIGSTOP-induced failover latency (silent on the clean run)
obsplane-smoke:
	$(PY) tools/obsplane_smoke.py

# incident flight recorder (docs/serving.md "Flight recorder &
# replay"): a seeded bursty shared-prefix trace served on a 2-replica
# fleet with a traffic journal, a tight TTFT SLO, and a mid-burst
# replica kill — the burn alert auto-writes an incident capsule, the
# capsule window replays on a fresh fleet with every greedy stream
# bit-identical to the recorded digests AND the same objective
# re-entering burn, and diagnose --capsule renders it (rc 0); <60 s CPU
replay-smoke:
	$(PY) tools/replay_smoke.py

# per-tenant QoS (docs/serving.md "Per-tenant QoS"): a 2-replica fleet
# serves a protected tenant solo, then again while a noisy tenant
# floods the router behind a request-rate quota + bulkhead — every
# protected stream must stay bit-identical to its solo digest with a
# 0 shed rate while the noisy tenant absorbs 100% of the sheds, and
# shed journal rows must carry tenant + reason; <60 s CPU
qos-smoke:
	$(PY) tools/qos_smoke.py

# fused Pallas kernel set: CPU interpret-mode parity sweep over
# odd/padded shapes (norms, MoE dispatch/combine incl. overflow drops,
# multi-tensor optimizer incl. the skip guard) + one autotune round
# asserting the persisted config is reloaded with zero timed trials
# (docs/perf.md, "Fused kernels & autotuning")
kernels-smoke:
	$(PY) tools/kernels_smoke.py

# ahead-of-time export end-to-end (docs/export.md): capture a small GPT
# train step + serving step through the offline pass pipeline (remat
# policy search under a tight synthetic HBM budget + sharding retarget),
# reload BOTH in a fresh process, and assert bit-identical losses/
# tokens, trace_count==0 on the loaded path, and a non-default remat
# winner
export-smoke:
	$(PY) tools/export_smoke.py

# distributed tracing + FLOP attribution end-to-end
# (docs/observability.md, "Tracing & performance attribution"): 3 serve
# requests + 5 train steps in one process under MXTPU_TRACE; asserts a
# loadable Perfetto JSON with a complete nested request span tree
# (queue -> prefill -> decode -> stream), a decomposed TTFT, train spans
# correlated to journal step ids, distinct serve/train trace-id spaces,
# and a NONZERO mfu_estimate gauge from XLA cost_analysis flops on CPU
trace-smoke:
	$(PY) tools/trace_smoke.py

# quantization end-to-end (docs/quantization.md): f32 reference streams,
# then QuantizePass(int8) + QuantizePass(int4) serve exports reloaded in
# fresh processes — engine weight bytes shrink >=1.9x / >=3.5x, the
# freed bytes buy KV pages, loaded streams run ZERO transformer Python
# and stay within the pinned top-1 agreement of f32; plus interpret-mode
# fused dequant-matmul parity vs the jnp oracle and a 12-step
# int8-compressed-gradient convergence dryrun vs f32 all-reduce
quant-smoke:
	$(PY) tools/quant_smoke.py

# decode fast path end-to-end (docs/serving.md "Speculative decoding &
# prefix caching"): 6 requests with shared prompt prefixes under k=4
# speculation — streams bit-identical to unbatched generate(), measured
# fused-step launches per emitted token < 1.0, prefill tokens served
# from the cross-request prefix cache, at least one copy-on-write page
# fork exercised, and zero mid-run recompiles (one program per width)
spec-smoke:
	$(PY) tools/spec_smoke.py

# CPU-bench regression tripwire (ROADMAP item 5): median-of-3
# `bench.py --measure cpu` runs must stay within 15% of the checked-in
# budget (bench_results/cpu_budget.json); re-baseline deliberately with
# `python tools/perf_gate.py --rebaseline`
perf-gate:
	$(PY) tools/perf_gate.py

cpp:
	cmake -S cpp-package -B cpp-package/build && \
	cmake --build cpp-package/build

clean:
	rm -rf cpp-package/build .pytest_cache $(shell find . -name __pycache__)
