"""`mx.error` (parity: `python/mxnet/error.py`): typed error classes over
MXNetError with a registry keyed by error-type name."""
from .base import MXNetError

_ERROR_TYPES = {}


def register_error(name_or_cls=None, cls=None):
    """Register an error class: decorator (`@register_error`), named
    decorator factory (`@register_error("Name")`), or direct call
    (`register_error("Name", SomeError)`)."""
    if isinstance(name_or_cls, str):
        name = name_or_cls
        if cls is not None:
            _ERROR_TYPES[name] = cls
            return cls

        def _named(c):
            _ERROR_TYPES[name] = c
            return c
        return _named

    def _do(c):
        _ERROR_TYPES[c.__name__] = c
        return c
    return _do(name_or_cls) if name_or_cls is not None else _do


register = register_error


@register_error
class InternalError(MXNetError):
    """Framework-internal invariant violation."""


for _name, _cls in [("ValueError", ValueError), ("TypeError", TypeError),
                    ("AttributeError", AttributeError),
                    ("IndexError", IndexError),
                    ("NotImplementedError", NotImplementedError),
                    ("IOError", IOError),
                    ("FloatingPointError", FloatingPointError)]:
    register_error(_name, _cls)
