"""`mx.error` (parity: `python/mxnet/error.py`): typed error classes over
MXNetError with a registry keyed by error-type name."""
from .base import MXNetError

_ERROR_TYPES = {}


def register_error(name_or_cls=None, cls=None):
    """Register an error class: decorator (`@register_error`), named
    decorator factory (`@register_error("Name")`), or direct call
    (`register_error("Name", SomeError)`)."""
    if isinstance(name_or_cls, str):
        name = name_or_cls
        if cls is not None:
            _ERROR_TYPES[name] = cls
            return cls

        def _named(c):
            _ERROR_TYPES[name] = c
            return c
        return _named

    def _do(c):
        _ERROR_TYPES[c.__name__] = c
        return c
    return _do(name_or_cls) if name_or_cls is not None else _do


register = register_error


@register_error
class InternalError(MXNetError):
    """Framework-internal invariant violation."""


# typed duals (reference semantics): each subclasses BOTH MXNetError and
# the builtin, so `except mx.error.ValueError` and `except MXNetError`
# and `except ValueError` all catch it
for _builtin in (ValueError, TypeError, AttributeError, IndexError,
                 NotImplementedError, IOError, FloatingPointError):
    _typed = type(_builtin.__name__, (MXNetError, _builtin),
                  {"__doc__": f"MXNetError specialized as "
                              f"{_builtin.__name__}."})
    globals()[_builtin.__name__] = _typed
    register_error(_builtin.__name__, _typed)
