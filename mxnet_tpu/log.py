"""`mx.log` (parity: `python/mxnet/log.py`): logging helpers."""
import logging

DEBUG = logging.DEBUG
INFO = logging.INFO
WARNING = logging.WARNING
ERROR = logging.ERROR
CRITICAL = logging.CRITICAL


def get_logger(name=None, filename=None, filemode=None, level=None):
    logger = logging.getLogger(name)
    if getattr(logger, "_mxtpu_init_done", False):
        if level is not None:   # only an explicit level overrides
            logger.setLevel(level)
        return logger           # never stack another handler
    logger._mxtpu_init_done = True
    logger.propagate = False  # the handler added here is the only sink
    if filename:
        handler = logging.FileHandler(filename, filemode or "a")
    else:
        handler = logging.StreamHandler()
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s: %(message)s"))
    logger.addHandler(handler)
    logger.setLevel(WARNING if level is None else level)
    return logger
