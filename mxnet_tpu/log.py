"""`mx.log` (parity: `python/mxnet/log.py`): logging helpers."""
import logging

DEBUG = logging.DEBUG
INFO = logging.INFO
WARNING = logging.WARNING
ERROR = logging.ERROR
CRITICAL = logging.CRITICAL


def get_logger(name=None, filename=None, filemode=None, level=WARNING):
    logger = logging.getLogger(name)
    if getattr(logger, "_mxtpu_init_done", False):
        return logger  # don't stack handlers on repeated calls
    logger._mxtpu_init_done = True
    if filename:
        handler = logging.FileHandler(filename, filemode or "a")
    else:
        handler = logging.StreamHandler()
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s: %(message)s"))
    logger.addHandler(handler)
    logger.setLevel(level)
    return logger
