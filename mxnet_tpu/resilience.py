"""Shared resilience primitives: bounded retry + deterministic fault injection.

SURVEY §5.3 names fault tolerance as the capability this port adds over the
reference (a dead ps-lite node kills an MXNet job outright).  The recovery
code in `elastic.py`, `utils/checkpoint.py` and `gluon/data/_mp_loader.py`
shares two building blocks that live here so they stay dependency-free —
this module imports nothing heavyweight, which matters because spawned
DataLoader workers import it on their hot startup path:

* :func:`retry_with_backoff` — call a flaky operation with exponential
  backoff + jitter, retrying only an explicit exception allowlist.
* an env-driven fault-point registry — every recovery path in the
  framework passes through a **named injection point**
  (:func:`fault_point`), and ``MXTPU_FAULT_SPEC`` arms specific points to
  fail on specific hits.  Because the spec travels through the
  environment it crosses the ``spawn`` boundary into DataLoader workers,
  so an end-to-end test can corrupt a checkpoint read in the trainer AND
  kill a worker process in one run.  This generalizes the step-only
  `elastic.FailureInjector` (kept for back-compat).

Spec grammar (comma-separated entries)::

    MXTPU_FAULT_SPEC = entry[,entry...]
    entry            = point@hit[:action]
    point            = injection point name (ckpt_write, ckpt_read,
                       worker_exec, elastic_step, replica_step,
                       router_dispatch, router_admit, tenant_quota,
                       ... — full table in
                       docs/resilience.md)
    hit              = 1-based occurrence count, per process: the fault
                       fires the hit-th time the point is reached
    action           = builtin exception name (OSError, ValueError, ...)
                       | "exit"  (hard process exit after flushing the
                          result queue — simulates SIGKILL/OOM; only
                          meaningful inside DataLoader workers)
                       default: FaultInjected (a RuntimeError, so the
                       elastic retry path treats it as transient)

Example: ``MXTPU_FAULT_SPEC=ckpt_read@1,worker_exec@2:exit`` makes the
first checkpoint load raise (exercising the fallback chain) and every
DataLoader worker hard-exit on its second batch (exercising respawn).

Each armed entry fires **once per process**; hit counts are per point
name and only advance while a spec is armed, so production runs (no env
var) pay one dict lookup per fault point.
"""
from __future__ import annotations

import builtins
import logging
import os
import random
import time
from typing import Callable, Dict, Optional, Sequence

__all__ = ["retry_with_backoff", "FaultInjected", "FaultExit",
           "FaultRegistry", "fault_point", "fault_registry", "ENV_VAR"]

_log = logging.getLogger(__name__)

ENV_VAR = "MXTPU_FAULT_SPEC"

# distinctive exit code so a supervised worker killed by injection is
# distinguishable from a real crash in test assertions
EXIT_CODE = 86


class FaultInjected(RuntimeError):
    """Raised by an armed fault point. Subclasses RuntimeError so the
    elastic restore-retry path treats it like any transient step error."""


class FaultExit(BaseException):
    """Raised for the ``exit`` action. The site hosting the fault point
    (the DataLoader worker main loop) converts it into a hard
    ``os._exit(EXIT_CODE)`` after flushing its result queue — a process
    death the supervisor must recover from, without non-deterministically
    losing work that was already delivered (a raw mid-loop ``os._exit``
    can kill the queue feeder thread before a finished batch reaches the
    pipe). BaseException, so generic ``except Exception`` error-shipping
    cannot swallow it."""


# ---------------------------------------------------------------------------
# retry
# ---------------------------------------------------------------------------

def retry_with_backoff(fn: Callable[[], object], *, retries: int = 3,
                       base_delay: float = 0.05, max_delay: float = 2.0,
                       jitter: float = 0.5, full_jitter: bool = False,
                       max_elapsed: Optional[float] = None,
                       retry_on: Sequence[type] = (OSError,),
                       on_retry: Optional[Callable] = None,
                       sleep: Callable[[float], None] = time.sleep,
                       clock: Callable[[], float] = time.monotonic):
    """Call ``fn()`` retrying listed exceptions with exponential backoff.

    Only exceptions in `retry_on` are retried — anything else propagates
    immediately (a typo'd path must not be retried like a network blip).
    Even when `retry_on` names a broad base class, non-``Exception``
    ``BaseException``\\ s (`FaultExit`, ``KeyboardInterrupt``,
    ``SystemExit``) are NEVER retried: a fault-injected process exit or a
    user's Ctrl-C swallowed by a retry wrapper would defeat the very
    teardown it requested.

    Delay for attempt *k* is ``base_delay * 2**(k-1)`` capped at
    `max_delay`, plus up to ``jitter`` fraction of itself (decorrelates
    retry storms across hosts); ``full_jitter=True`` draws the whole
    delay uniformly from ``[0, capped)`` instead (the AWS "full jitter"
    policy — better decorrelation when many hosts retry the same shared
    service).  `max_elapsed` is an overall deadline in seconds: once the
    elapsed time plus the upcoming delay would exceed it, the last
    exception propagates instead of starting another sleep — a retry
    loop inside a preemption grace window must not outlive the window.
    After `retries` failed retries the last exception propagates
    unchanged. `on_retry(attempt, exc, delay)` is invoked before each
    sleep; `sleep`/`clock` are injectable for tests.
    """
    retry_on = tuple(retry_on)
    attempt = 0
    start = clock()
    while True:
        try:
            return fn()
        except retry_on as e:
            if not isinstance(e, Exception):
                raise  # BaseException-only (FaultExit, KeyboardInterrupt)
            attempt += 1
            if attempt > retries:
                raise
            delay = min(base_delay * (2.0 ** (attempt - 1)), max_delay)
            if full_jitter:
                delay = random.uniform(0.0, delay)
            else:
                delay += random.uniform(0.0, jitter * delay)
            if max_elapsed is not None and \
                    clock() - start + delay > max_elapsed:
                _log.warning(
                    "retry budget exhausted after %.3fs (max_elapsed "
                    "%.3fs); raising %s", clock() - start, max_elapsed,
                    type(e).__name__)
                raise
            if on_retry is not None:
                on_retry(attempt, e, delay)
            _log.warning("retry %d/%d after %s: %s (sleeping %.3fs)",
                         attempt, retries, type(e).__name__, e, delay)
            sleep(delay)


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------

def _resolve_action(token: str):
    if token in ("exit", "kill"):
        return "exit"
    exc = getattr(builtins, token, None)
    if isinstance(exc, type) and issubclass(exc, BaseException):
        return exc
    raise ValueError(
        f"{ENV_VAR}: unknown action {token!r} (expected a builtin "
        f"exception name or 'exit')")


class FaultRegistry:
    """Parsed ``MXTPU_FAULT_SPEC``: {point -> {hit_no -> action}} plus
    per-point hit counters. Parse errors raise ValueError eagerly — a
    typo'd spec silently injecting nothing would defeat the test using it.
    """

    def __init__(self, spec: str = ""):
        self.spec = spec
        self._plan: Dict[str, Dict[int, object]] = {}
        self._counts: Dict[str, int] = {}
        for raw in spec.split(","):
            entry = raw.strip()
            if not entry:
                continue
            if "@" not in entry:
                raise ValueError(f"{ENV_VAR}: bad entry {entry!r} "
                                 f"(expected point@hit[:action])")
            point, _, rest = entry.partition("@")
            hit_s, _, action_s = rest.partition(":")
            try:
                hit = int(hit_s)
            except ValueError:
                raise ValueError(f"{ENV_VAR}: bad hit count in {entry!r}")
            if hit < 1:
                raise ValueError(f"{ENV_VAR}: hit counts are 1-based "
                                 f"({entry!r})")
            action = _resolve_action(action_s) if action_s else FaultInjected
            self._plan.setdefault(point, {})[hit] = action

    @property
    def armed(self) -> bool:
        return bool(self._plan)

    def hits(self, name: str) -> int:
        return self._counts.get(name, 0)

    def fire(self, name: str) -> None:
        """Record a hit of injection point `name`; raise (or exit) if the
        spec arms this hit. Each armed hit fires at most once."""
        if not self._plan:
            return
        n = self._counts[name] = self._counts.get(name, 0) + 1
        action = self._plan.get(name, {}).pop(n, None)
        if action is None:
            return
        # telemetry is imported lazily HERE (armed-and-firing is the rare
        # path) so worker startup never pays for it; it is stdlib-only but
        # spawned workers should import the bare minimum
        try:
            from . import telemetry as _tele
            if _tele.enabled():
                _tele.counter(
                    "fault_triggers",
                    "Armed fault-injection points that fired",
                    labelnames=("point",)).inc(point=name)
                _tele.event(
                    "fault_trigger", point=name, hit=n,
                    action="exit" if action == "exit" else action.__name__)
        except Exception:  # telemetry must never mask the injected fault
            pass
        if action == "exit":
            _log.error("fault injection: exit requested at point %r "
                       "(hit %d)", name, n)
            raise FaultExit(name, n)
        _log.warning("fault injection: raising %s at point %r (hit %d)",
                     action.__name__, name, n)
        raise action(f"injected fault at point '{name}' (hit {n})")


_active: Optional[FaultRegistry] = None


def fault_registry() -> FaultRegistry:
    """The process-wide registry for the CURRENT value of the env var.
    Re-parsed (with fresh hit counters) whenever the env value changes, so
    tests get deterministic counts without explicit reset plumbing."""
    global _active
    spec = os.environ.get(ENV_VAR, "")
    if _active is None or _active.spec != spec:
        _active = FaultRegistry(spec)
    return _active


def fault_point(name: str) -> None:
    """Mark a named injection point. No-op (one env lookup) unless
    ``MXTPU_FAULT_SPEC`` arms this point."""
    fault_registry().fire(name)
