"""`mx.visualization` (parity: `python/mxnet/visualization.py`):
`print_summary` renders a layer table over the Symbol DAG;
`plot_network` emits graphviz when the library is present (not baked
into the TPU image — a documented error otherwise)."""
from __future__ import annotations

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def _walk(symbol):
    """Topological node order over the Symbol DAG (inputs first);
    synthetic group nodes are skipped (their inputs stand in for them,
    like `Symbol.get_internals`)."""
    seen, order = set(), []

    def visit(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for i in node.inputs:
            visit(i)
        if node.op != "_group":
            order.append(node)
    visit(symbol)
    return order


def print_summary(symbol, shape=None, line_length=120,
                  positions=(.44, .64, .74, 1.)):
    """Print a per-layer table: name(op), output shape (when input shapes
    are given), params, and predecessors (reference `visualization.py:46`
    layout)."""
    pos = [int(line_length * p) for p in positions]
    headers = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]
    shapes = {}
    if shape:
        try:
            args = symbol.list_arguments()
            inferred, _, _ = symbol.infer_shape(**shape)
            shapes = dict(zip(args, inferred))
        except Exception:
            shapes = dict(shape)

    def row(fields):
        line = ""
        for f, p in zip(fields, pos):
            line = (line + str(f))[:p - 1].ljust(p)
        print(line)

    # per-layer output shapes: evaluate each node on zeros of the
    # inferred argument shapes (graphs handed to summaries are small)
    node_out_shapes = {}
    if shapes:
        try:
            from . import numpy as _mnp
            from .device import cpu as _cpu
            zeros = {n: _mnp.zeros(s) for n, s in shapes.items()}
            for node in _walk(symbol):
                try:
                    args = {n: zeros[n] for n in node.list_arguments()}
                    outs = node.bind(_cpu(), args).forward()
                    first = outs[0] if isinstance(outs, (list, tuple)) \
                        else outs
                    node_out_shapes[node.name] = tuple(first.shape)
                except Exception:
                    pass
        except Exception:
            pass

    print("=" * line_length)
    row(headers)
    print("=" * line_length)
    total_params = 0
    for node in _walk(symbol):
        if node.op is None and node.name in shapes:
            import numpy as _onp
            n_par = int(_onp.prod(shapes[node.name])) \
                if node.name not in (shape or {}) else 0
        else:
            n_par = 0
        total_params += n_par
        out_shape = node_out_shapes.get(node.name,
                                        shapes.get(node.name, ""))
        prev = ",".join(i.name for i in node.inputs)
        kind = node.op or "null"
        row([f"{node.name}({kind})", out_shape, n_par, prev])
    print("=" * line_length)
    print(f"Total params: {total_params}")
    print("=" * line_length)


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 dtype=None, node_attrs=None, hide_weights=True):
    """Graphviz rendering of the Symbol DAG (reference plot_network).
    Requires the optional `graphviz` package."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise MXNetError(
            "plot_network requires the graphviz package, which is not "
            "baked into this image; use print_summary for a text view"
        ) from e
    dot = Digraph(name=title, format=save_format)
    for node in _walk(symbol):
        if hide_weights and node.op is None and \
                ("weight" in node.name or "bias" in node.name):
            continue
        dot.node(node.name, f"{node.name}\n{node.op or 'input'}")
        for i in node.inputs:
            if hide_weights and i.op is None and \
                    ("weight" in i.name or "bias" in i.name):
                continue
            dot.edge(i.name, node.name)
    return dot
