"""`mx.npx` — operator-level extension namespace.

Parity: `python/mxnet/numpy_extension/` plus the dense NN op corpus
(`src/operator/nn/`: convolution.cc:435, fully_connected.cc:251,
batch_norm.cc:582, pooling, dropout, softmax, rnn.cc:306) and the contrib
attention kernels (`src/operator/contrib/transformer.cc:675-1095`). Every op
is a pure function over `ndarray`s lowering to XLA; layout is NCHW/NCW/NCDHW
to match the reference's defaults, and the MXU-relevant ops (FC, conv,
attention) are expressed as single large contractions so XLA tiles them onto
the systolic array.
"""
from __future__ import annotations

import math
import os
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as _onp
from jax import lax

from ..base import MXNetError
from ..device import current_device
from ..ndarray.ndarray import ndarray, apply_op, from_jax, _write_out
from .. import random as _rng
from .. import _tape

__all__ = [
    "activation", "relu", "sigmoid", "tanh", "softrelu", "softsign", "gelu",
    "log_sigmoid", "mish", "hard_sigmoid",
    "silu", "leaky_relu", "elu", "selu", "prelu", "softmax", "log_softmax",
    "masked_softmax", "masked_log_softmax", "fully_connected", "convolution",
    "deconvolution", "pooling", "batch_norm", "layer_norm", "group_norm",
    "instance_norm", "l2_normalization", "dropout", "embedding", "one_hot",
    "pick", "topk", "slice", "reshape", "index_add", "index_update", "constraint_check", "sequence_mask", "arange_like", "shape_array",
    "reshape_like", "broadcast_like", "gamma", "gammaln", "erf", "erfinv",
    "smooth_l1", "gather_nd", "scatter_nd", "cast", "amp_cast", "amp_multicast",
    "interleaved_matmul_selfatt_qk", "interleaved_matmul_selfatt_valatt",
    "interleaved_matmul_encdec_qk", "interleaved_matmul_encdec_valatt",
    "sldwin_atten_mask_like", "sldwin_atten_score", "sldwin_atten_context",
    "multi_head_attention", "ctc_loss", "foreach", "while_loop", "cond",
    "remat_call",
    "resolve_remat_policy",
    "grid_generator", "bilinear_sampler", "spatial_transformer",
    "correlation", "im2col", "col2im", "deformable_convolution",
    "softmax_cross_entropy",
    "save", "load", "waitall", "set_np", "reset_np", "is_np_array",
    "seed", "rnn", "intgemm_fully_connected", "custom",
    "random", "image", "cpu", "gpu", "tpu", "num_gpus", "num_tpus",
    "batch_dot", "bernoulli", "from_numpy", "from_dlpack",
    "to_dlpack_for_read", "to_dlpack_for_write", "savez", "normal_n",
    "uniform_n",
]


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------

def _unary(fn, name):
    def op(data, **kwargs):
        return apply_op(lambda x: fn(x, **kwargs) if kwargs else fn(x),
                        (data,), {}, name=name)
    op.__name__ = name
    return op


relu = _unary(jax.nn.relu, "relu")
sigmoid = _unary(jax.nn.sigmoid, "sigmoid")
tanh = _unary(jnp.tanh, "tanh")
softsign = _unary(jax.nn.soft_sign, "softsign")
silu = _unary(jax.nn.silu, "silu")
erf = _unary(jax.scipy.special.erf, "erf")
erfinv = _unary(jax.scipy.special.erfinv, "erfinv")
gammaln = _unary(jax.scipy.special.gammaln, "gammaln")
gamma = _unary(lambda x: jnp.exp(jax.scipy.special.gammaln(x)), "gamma")
# standalone activation ops the reference registers alongside Activation's
# act_type modes (src/operator/nn/activation.cc; log_sigmoid/mish landed
# as first-class ops in 2.x)
log_sigmoid = _unary(jax.nn.log_sigmoid, "log_sigmoid")
mish = _unary(lambda x: x * jnp.tanh(jax.nn.softplus(x)), "mish")
hard_sigmoid = _unary(
    lambda x, alpha=0.2, beta=0.5: jnp.clip(alpha * x + beta, 0.0, 1.0),
    "hard_sigmoid")


def softrelu(data):
    return apply_op(jax.nn.softplus, (data,), {}, name="softrelu")


def gelu(data, approximation="erf"):
    approximate = approximation in ("tanh", "fast")
    return apply_op(lambda x: jax.nn.gelu(x, approximate=approximate), (data,),
                    {}, name="gelu")


def leaky_relu(data, gamma_=None, act_type="leaky", slope=0.25,
               lower_bound=0.125, upper_bound=0.334, **kwargs):
    if act_type == "leaky":
        return apply_op(lambda x: jnp.where(x >= 0, x, slope * x), (data,), {},
                        name="leaky_relu")
    if act_type == "elu":
        return apply_op(lambda x: jnp.where(x >= 0, x, slope * jnp.expm1(x)),
                        (data,), {}, name="elu")
    if act_type == "selu":
        return apply_op(_selu_j, (data,), {}, name="selu")
    if act_type == "gelu":
        # the reference's LeakyReLU gelu kernel is the tanh approximation
        # (leaky_relu-inl.h; its unit test asserts the tanh formula)
        return gelu(data, approximation="tanh")
    if act_type == "prelu":
        return prelu(data, gamma_)
    if act_type == "rrelu":
        # eval-mode rrelu: mean slope
        s = (lower_bound + upper_bound) / 2.0
        return apply_op(lambda x: jnp.where(x >= 0, x, s * x), (data,), {},
                        name="rrelu")
    raise MXNetError(f"unknown leaky_relu act_type {act_type}")


def elu(data, alpha=1.0):
    return apply_op(lambda x: jax.nn.elu(x, alpha), (data,), {}, name="elu")


def selu(data):
    return apply_op(_selu_j, (data,), {}, name="selu")


def prelu(data, gamma_):
    def fn(x, g):
        if g.ndim == 1 and x.ndim > 1:
            # gamma is per-CHANNEL (axis 1), as the reference's
            # LeakyReLU prelu kernel broadcasts it
            g = g.reshape((1, -1) + (1,) * (x.ndim - 2))
        return jnp.where(x >= 0, x, g * x)
    return apply_op(fn, (data, gamma_), {}, name="prelu")


_ACTS = {
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "log_sigmoid": jax.nn.log_sigmoid,
    "tanh": jnp.tanh,
    "softrelu": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
}



def _selu_j(x):
    # the reference kernel's exact arithmetic (leaky_relu.cc selu):
    # scale*x for x>=0, (scale*alpha)*expm1(x) otherwise, with the
    # scale*alpha product folded in f64 then rounded ONCE — the ported
    # test asserts bitwise equality against this order of operations
    scale = 1.0507009873554804934193349852946
    alpha = 1.6732632423543772848170429916717
    return jnp.where(x >= 0, scale * x, (scale * alpha) * jnp.expm1(x))

def activation(data, act_type="relu", **kwargs):
    if act_type not in _ACTS:
        raise MXNetError(f"unknown activation {act_type!r}")
    return apply_op(_ACTS[act_type], (data,), {}, name=act_type)


# ---------------------------------------------------------------------------
# softmax family
# ---------------------------------------------------------------------------

def softmax(data, length=None, axis=-1, temperature=None, use_length=False,
            dtype=None):
    t = temperature if temperature is not None else 1.0

    if use_length and length is not None:
        def fn(x, ln):
            idx = jnp.arange(x.shape[axis])
            shape = [1] * x.ndim
            shape[axis] = x.shape[axis]
            idx = idx.reshape(shape)
            mask = idx < jnp.expand_dims(ln, axis=axis % x.ndim)
            y = jax.nn.softmax(jnp.where(mask, x / t, -jnp.inf), axis=axis)
            y = jnp.where(mask, y, 0.0)
            return y.astype(dtype) if dtype else y
        return apply_op(fn, (data, length), {}, name="softmax")

    def fn(x):
        y = jax.nn.softmax(x / t, axis=axis)
        return y.astype(dtype) if dtype else y
    return apply_op(fn, (data,), {}, name="softmax")


def log_softmax(data, axis=-1, temperature=None, dtype=None, use_length=False,
                length=None):
    t = temperature if temperature is not None else 1.0

    if use_length and length is not None:
        def fn(x, ln):
            idx = jnp.arange(x.shape[axis])
            shape = [1] * x.ndim
            shape[axis] = x.shape[axis]
            idx = idx.reshape(shape)
            mask = idx < jnp.expand_dims(ln, axis=axis % x.ndim)
            y = jax.nn.log_softmax(jnp.where(mask, x / t, -jnp.inf), axis=axis)
            y = jnp.where(mask, y, -jnp.inf)
            return y.astype(dtype) if dtype else y
        return apply_op(fn, (data, length), {}, name="log_softmax")

    def fn(x):
        y = jax.nn.log_softmax(x / t, axis=axis)
        return y.astype(dtype) if dtype else y
    return apply_op(fn, (data,), {}, name="log_softmax")


def masked_softmax(data, mask=None, axis=-1, temperature=1.0, dtype=None):
    if mask is None:
        return softmax(data, axis=axis, temperature=temperature, dtype=dtype)

    def fn(x, m):
        y = jnp.where(m, x / temperature, -jnp.inf)
        y = jax.nn.softmax(y, axis=axis)
        y = jnp.where(m, y, 0.0)
        return y.astype(dtype) if dtype else y
    return apply_op(fn, (data, mask), {}, name="masked_softmax")


def masked_log_softmax(data, mask=None, axis=-1, temperature=1.0, dtype=None):
    if mask is None:
        return log_softmax(data, axis=axis, temperature=temperature, dtype=dtype)

    def fn(x, m):
        y = jnp.where(m, x / temperature, -jnp.inf)
        y = jax.nn.log_softmax(y, axis=axis)
        y = jnp.where(m, y, -jnp.inf)
        return y.astype(dtype) if dtype else y
    return apply_op(fn, (data, mask), {}, name="masked_log_softmax")


# ---------------------------------------------------------------------------
# dense layers
# ---------------------------------------------------------------------------

def fully_connected(x, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True):
    """y = x @ W^T + b (parity: `src/operator/nn/fully_connected.cc:251`).

    weight is (num_hidden, in_units) like the reference. `flatten=True`
    collapses all non-batch dims.
    """
    if no_bias or bias is None:
        def fn(xv, wv):
            xm = xv.reshape((xv.shape[0], -1)) if flatten else xv
            return jnp.matmul(xm, wv.T)
        return apply_op(fn, (x, weight), {}, name="fully_connected")

    def fn(xv, wv, bv):
        xm = xv.reshape((xv.shape[0], -1)) if flatten else xv
        return jnp.matmul(xm, wv.T) + bv
    return apply_op(fn, (x, weight, bias), {}, name="fully_connected")


def _tuplize(v, n):
    if v is None:
        return (0,) * n if n else None
    if isinstance(v, int):
        return (v,) * n
    t = tuple(v)
    if len(t) == 1:
        return t * n
    return t


def convolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                pad=None, num_filter=0, num_group=1, no_bias=False,
                layout=None, **kwargs):
    """N-D convolution, NC(D)HW layout (parity: `src/operator/nn/convolution.cc:435`).

    weight layout: (num_filter, in_channels/num_group, *kernel) — identical to
    the reference, mapped to `lax.conv_general_dilated` (MXU path on TPU).
    """
    nd = data.ndim - 2
    stride = _tuplize(stride or 1, nd)
    dilate = _tuplize(dilate or 1, nd)
    pad = _tuplize(pad or 0, nd)
    padding = [(p, p) for p in pad]
    spatial = "".join("DHW"[3 - nd + i] for i in range(nd))
    dn = lax.conv_dimension_numbers(
        data.shape, weight.shape,
        ("NC" + spatial, "OI" + spatial, "NC" + spatial))

    if no_bias or bias is None:
        def fn(x, w):
            return lax.conv_general_dilated(
                x, w, window_strides=stride, padding=padding,
                rhs_dilation=dilate, dimension_numbers=dn,
                feature_group_count=num_group)
        return apply_op(fn, (data, weight), {}, name="convolution")

    def fn(x, w, b):
        y = lax.conv_general_dilated(
            x, w, window_strides=stride, padding=padding,
            rhs_dilation=dilate, dimension_numbers=dn,
            feature_group_count=num_group)
        return y + b.reshape((1, -1) + (1,) * nd)
    return apply_op(fn, (data, weight, bias), {}, name="convolution")


def deconvolution(data, weight, bias=None, kernel=None, stride=None,
                  dilate=None, pad=None, adj=None, num_filter=0, num_group=1,
                  no_bias=True, layout=None, target_shape=None, **kwargs):
    """Transposed convolution (parity: `src/operator/nn/deconvolution.cc`).

    Implemented as the gradient of convolution (lax.conv_transpose with
    IOHW-style kernel flip), weight layout (in_channels, num_filter/group, *k).
    """
    nd = data.ndim - 2
    stride = _tuplize(stride or 1, nd)
    dilate = _tuplize(dilate or 1, nd)
    pad = _tuplize(pad or 0, nd)
    adj = _tuplize(adj or 0, nd)
    spatial = "".join("DHW"[3 - nd + i] for i in range(nd))
    # output padding semantics: out = (in-1)*s - 2p + dilate*(k-1) + 1 + adj
    padding = [(d * (k - 1) - p, d * (k - 1) - p + a)
               for p, a, d, k in zip(pad, adj, dilate,
                                     weight.shape[2:])]

    def _deconv(x, w):
        wf = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
        if num_group > 1:
            # reference weight layout (cin, cout/g, *k): group i maps rows
            # [i*cin/g, (i+1)*cin/g) -> outputs [i*co_g, (i+1)*co_g).
            # conv_general_dilated wants rhs I = cin/g with the O dim
            # spanning ALL outputs group-major — regroup accordingly
            cin, co_g = wf.shape[0], wf.shape[1]
            wf = wf.reshape((num_group, cin // num_group, co_g)
                            + wf.shape[2:])
            wf = jnp.moveaxis(wf, 0, 1)
            wf = wf.reshape((cin // num_group, num_group * co_g)
                            + wf.shape[3:])
        dn = lax.conv_dimension_numbers(
            x.shape, wf.shape,
            ("NC" + spatial, "IO" + spatial, "NC" + spatial))
        return lax.conv_general_dilated(
            x, wf, window_strides=(1,) * nd, padding=padding,
            lhs_dilation=stride, rhs_dilation=dilate,
            dimension_numbers=dn, feature_group_count=num_group)

    if no_bias or bias is None:
        return apply_op(_deconv, (data, weight), {}, name="deconvolution")

    def fn(x, w, b):
        return _deconv(x, w) + b.reshape((1, -1) + (1,) * nd)
    return apply_op(fn, (data, weight, bias), {}, name="deconvolution")


def pooling(data, kernel=None, stride=None, pad=None, pool_type="max",
            global_pool=False, pooling_convention="valid", count_include_pad=True,
            p_value=2, layout=None, **kwargs):
    """Pooling (parity: `src/operator/nn/pooling.cc`), NC* layout."""
    nd = data.ndim - 2
    if global_pool:
        axes = tuple(range(2, 2 + nd))
        if pool_type == "max":
            fn = lambda x: jnp.max(x, axis=axes, keepdims=True)
        elif pool_type == "avg":
            fn = lambda x: jnp.mean(x, axis=axes, keepdims=True)
        else:
            fn = lambda x: jnp.power(
                jnp.sum(jnp.power(jnp.abs(x), p_value), axis=axes,
                        keepdims=True), 1.0 / p_value)
        return apply_op(fn, (data,), {}, name="global_pool")

    kernel = _tuplize(kernel, nd)
    stride = _tuplize(stride or 1, nd)
    pad = _tuplize(pad or 0, nd)
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    padding = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)
    if pooling_convention == "full":
        # ceil-mode: extend padding on the right so the last window fits
        extra = []
        for i in range(nd):
            in_sz = data.shape[2 + i]
            out = math.ceil((in_sz + 2 * pad[i] - kernel[i]) / stride[i]) + 1
            need = (out - 1) * stride[i] + kernel[i] - (in_sz + 2 * pad[i])
            extra.append(max(0, need))
        padding = ((0, 0), (0, 0)) + tuple(
            (p, p + e) for p, e in zip(pad, extra))

    if pool_type == "max":
        init = -jnp.inf

        def fn(x):
            return lax.reduce_window(x, init, lax.max, window, strides, padding)
        return apply_op(fn, (data,), {}, name="max_pool")

    if pool_type in ("avg", "sum"):
        def fn(x):
            s = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
            if pool_type == "sum":
                return s
            if count_include_pad:
                denom = float(_onp.prod(kernel))
                return s / denom
            ones_ = jnp.ones(x.shape, x.dtype)
            cnt = lax.reduce_window(ones_, 0.0, lax.add, window, strides, padding)
            return s / cnt
        return apply_op(fn, (data,), {}, name="avg_pool")

    if pool_type == "lp":
        def fn(x):
            s = lax.reduce_window(jnp.power(jnp.abs(x), p_value), 0.0, lax.add,
                                  window, strides, padding)
            return jnp.power(s, 1.0 / p_value)
        return apply_op(fn, (data,), {}, name="lp_pool")
    raise MXNetError(f"unknown pool_type {pool_type}")


# ---------------------------------------------------------------------------
# normalisation
# ---------------------------------------------------------------------------

def batch_norm(x, gamma_, beta, running_mean, running_var, eps=1e-5,
               momentum=0.9, fix_gamma=False, use_global_stats=False,
               output_mean_var=False, axis=1, min_calib_range=None,
               max_calib_range=None, cudnn_off=False):
    """BatchNorm (parity: `src/operator/nn/batch_norm.cc:582`).

    Training-mode selection follows autograd state like the reference
    (train = autograd.is_training()); running stats are updated in-place on
    the aux `ndarray`s when training.
    """
    training = _tape.is_training() and not use_global_stats
    red_axes = tuple(i for i in range(x.ndim) if i != axis % x.ndim)
    bshape = [1] * x.ndim
    bshape[axis % x.ndim] = x.shape[axis % x.ndim]

    if training:
        def fn(xv, g, b):
            mean = jnp.mean(xv, axis=red_axes)
            var = jnp.var(xv, axis=red_axes)
            g_ = jnp.ones_like(g) if fix_gamma else g
            y = (xv - mean.reshape(bshape)) * jax.lax.rsqrt(
                var.reshape(bshape) + eps)
            y = y * g_.reshape(bshape) + b.reshape(bshape)
            return y, mean, var
        out, mean, var = apply_op(fn, (x, gamma_, beta), {}, name="batch_norm",
                                  n_out=3)
        # in-place running-stat update (aux state, outside autograd)
        m = momentum
        running_mean._data = m * running_mean._data + (1 - m) * mean._data
        running_var._data = m * running_var._data + (1 - m) * var._data
        if output_mean_var:
            return out, mean, var
        return out

    def fn(xv, g, b, rm, rv):
        g_ = jnp.ones_like(g) if fix_gamma else g
        y = (xv - rm.reshape(bshape)) * jax.lax.rsqrt(rv.reshape(bshape) + eps)
        return y * g_.reshape(bshape) + b.reshape(bshape)
    out = apply_op(fn, (x, gamma_, beta, running_mean, running_var), {},
                   name="batch_norm")
    if output_mean_var:
        return out, running_mean, running_var
    return out


def layer_norm(x, gamma_, beta, axis=-1, eps=1e-5):
    """LayerNorm (parity: `src/operator/nn/layer_norm.cc`).

    Last-axis normalisation dispatches the fused Pallas row kernel when
    the kernel path is active (`MXTPU_PALLAS`, docs/perf.md); the jnp
    math below is the reference everywhere else."""
    def fn(xv, g, b):
        from ..ops.pallas import fused_norm as _fnorm
        if _fnorm.kernel_eligible(xv, axis):
            return _fnorm.fused_layer_norm(xv, g, b, eps=eps)
        mean = jnp.mean(xv, axis=axis, keepdims=True)
        var = jnp.var(xv, axis=axis, keepdims=True)
        y = (xv - mean) * jax.lax.rsqrt(var + eps)
        shape = [1] * xv.ndim
        shape[axis % xv.ndim] = xv.shape[axis % xv.ndim]
        return y * g.reshape(shape) + b.reshape(shape)
    return apply_op(fn, (x, gamma_, beta), {}, name="layer_norm")


def layer_norm_residual(x, residual, gamma_, beta, axis=-1, eps=1e-5):
    """Fused pre-LN transformer step: ``s = residual + x; y = LN(s)``.

    Returns ``(y, s)`` — the normalised output AND the new residual
    stream, so the add never makes a separate HBM round-trip (one
    Pallas row kernel when active, jnp reference otherwise).  Only the
    last axis is supported (that is the transformer case; plain
    `layer_norm` covers the rest)."""
    if axis not in (-1, getattr(x, "ndim", 0) - 1):
        raise ValueError("layer_norm_residual normalises the last axis "
                         f"only, got axis={axis}")

    def fn(xv, rv, g, b):
        from ..ops.pallas import fused_norm as _fnorm
        if _fnorm.kernel_eligible(xv, -1):
            return _fnorm.layer_norm_residual(xv, rv, g, b, eps=eps)
        return _fnorm.layer_norm_reference(xv, g, b, eps=eps,
                                           residual=rv)
    return apply_op(fn, (x, residual, gamma_, beta), {},
                    name="layer_norm_residual", n_out=2)


def rms_norm(x, gamma_, axis=-1, eps=1e-6):
    """RMSNorm over the last axis: ``y = x * rsqrt(mean(x^2)+eps) * g``
    (fused Pallas row kernel when active)."""
    if axis not in (-1, getattr(x, "ndim", 0) - 1):
        raise ValueError(f"rms_norm normalises the last axis only, got "
                         f"axis={axis}")

    def fn(xv, g):
        from ..ops.pallas import fused_norm as _fnorm
        if _fnorm.kernel_eligible(xv, -1):
            return _fnorm.fused_rms_norm(xv, g, eps=eps)
        return _fnorm.rms_norm_reference(xv, g, eps=eps)
    return apply_op(fn, (x, gamma_), {}, name="rms_norm")


def rms_norm_residual(x, residual, gamma_, axis=-1, eps=1e-6):
    """Fused ``s = residual + x; y = RMSNorm(s)``; returns ``(y, s)``."""
    if axis not in (-1, getattr(x, "ndim", 0) - 1):
        raise ValueError("rms_norm_residual normalises the last axis "
                         f"only, got axis={axis}")

    def fn(xv, rv, g):
        from ..ops.pallas import fused_norm as _fnorm
        if _fnorm.kernel_eligible(xv, -1):
            return _fnorm.rms_norm_residual(xv, rv, g, eps=eps)
        return _fnorm.rms_norm_reference(xv, g, eps=eps, residual=rv)
    return apply_op(fn, (x, residual, gamma_), {},
                    name="rms_norm_residual", n_out=2)


def group_norm(x, gamma_, beta, num_groups=1, eps=1e-5):
    """GroupNorm over NC+ layout (parity: `src/operator/nn/group_norm.cc`)."""
    def fn(xv, g, b):
        n, c = xv.shape[0], xv.shape[1]
        rest = xv.shape[2:]
        xg = xv.reshape((n, num_groups, c // num_groups) + rest)
        axes = tuple(range(2, xg.ndim))
        mean = jnp.mean(xg, axis=axes, keepdims=True)
        var = jnp.var(xg, axis=axes, keepdims=True)
        y = ((xg - mean) * jax.lax.rsqrt(var + eps)).reshape(xv.shape)
        shape = (1, c) + (1,) * (xv.ndim - 2)
        return y * g.reshape(shape) + b.reshape(shape)
    return apply_op(fn, (x, gamma_, beta), {}, name="group_norm")


def instance_norm(x, gamma_, beta, eps=1e-5):
    def fn(xv, g, b):
        axes = tuple(range(2, xv.ndim))
        mean = jnp.mean(xv, axis=axes, keepdims=True)
        var = jnp.var(xv, axis=axes, keepdims=True)
        y = (xv - mean) * jax.lax.rsqrt(var + eps)
        shape = (1, xv.shape[1]) + (1,) * (xv.ndim - 2)
        return y * g.reshape(shape) + b.reshape(shape)
    return apply_op(fn, (x, gamma_, beta), {}, name="instance_norm")


def l2_normalization(data, eps=1e-10, mode="instance"):
    def fn(x):
        if mode == "instance":
            axes = tuple(range(1, x.ndim))
        elif mode == "channel":
            axes = (1,)
        else:  # spatial
            axes = tuple(range(2, x.ndim))
        n = jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=True) + eps)
        return x / n
    return apply_op(fn, (data,), {}, name="l2_normalization")


# ---------------------------------------------------------------------------
# dropout / embedding / misc
# ---------------------------------------------------------------------------

def dropout(data, p=0.5, mode="training", axes=(), cudnn_off=False):
    """Dropout (parity: `src/operator/nn/dropout.cc`): active iff
    autograd.is_training() or mode=='always'."""
    active = (_tape.is_training() or mode == "always") and p > 0
    if not active:
        return data
    key = _rng.next_key()

    def fn(x):
        shape = list(x.shape)
        for ax in axes:
            shape[ax] = 1
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)
    return apply_op(fn, (data,), {}, name="dropout")


def resolve_remat_policy(value, env_override: bool = True):
    """Resolve a remat knob (``GPTConfig.remat``-style) to ``(enabled,
    jax_policy)``.

    Accepted values: ``False``/``None``/``"none"``/``"off"`` (remat
    off), ``True``/``"full"`` (checkpoint everything — recompute the
    whole block in backward), or a named `jax.checkpoint_policies`
    entry (``"dots_saveable"``, ``"nothing_saveable"``,
    ``"dots_with_no_batch_dims_saveable"``, ``"everything_saveable"``,
    ...).  With ``env_override`` (the model-knob path),
    ``MXTPU_REMAT_POLICY`` wins over `value` — the hook the offline
    export remat-policy search and operators share (docs/export.md).
    Resolution happens at trace time: flipping the env mid-run does not
    retrace a live step.  Unknown names raise `MXNetError` (a typo must
    not silently train without remat)."""
    from ..base import MXNetError
    if env_override:
        env = os.environ.get("MXTPU_REMAT_POLICY", "").strip()
        if env:
            value = env
    if value is None or value is False:
        return False, None
    if value is True:
        return True, None
    name = str(value).strip().lower()
    if name in ("0", "off", "none", "false", "no"):
        return False, None
    if name in ("1", "true", "full"):
        return True, None       # jax.checkpoint default: save nothing
    pol = getattr(jax.checkpoint_policies, name, None)
    if pol is None or not callable(pol):
        known = sorted(n for n in dir(jax.checkpoint_policies)
                       if not n.startswith("_"))
        raise MXNetError(
            f"unknown remat policy {value!r}; expected 'none'/'full' or "
            f"a named jax.checkpoint_policies entry: {known}")
    return True, pol


def remat_call(fn, *args, policy=None):
    """Run `fn(*args)` under `jax.checkpoint`: its activations are
    recomputed during the backward pass instead of stored — the
    FLOPs-for-HBM trade that makes long-sequence training fit (SURVEY §7;
    the reference's closest knob is the mirror/memonger graph pass).

    `fn` takes and returns ndarrays (a Gluon block call is the intended
    use: ``npx.remat_call(lambda t: layer(t, mask), x)``). Effective under
    `hybridize`/`jit`/the sharded train step, where gradients flow through
    the parameters `fn` closes over. Under eager tape recording this calls
    `fn` directly — remat would detach closed-over parameters from the
    tape, and eager execution materializes per-op residuals anyway.

    `policy` selects WHAT the checkpoint saves: a
    `jax.checkpoint_policies` object, or its NAME as a string
    (``"dots_saveable"``, ...; see `resolve_remat_policy` — an explicit
    string here is taken literally, the env override applies to the
    model-config knob, not this argument).
    """
    if isinstance(policy, str):
        enabled, policy = resolve_remat_policy(policy, env_override=False)
        if not enabled:
            return fn(*args)
    if _tape.is_recording():
        return fn(*args)

    dev = next((a._device for a in args if isinstance(a, ndarray)),
               current_device())

    def pure(*vals):
        nds = [from_jax(v, dev) for v in vals]
        out = fn(*nds)
        return out._data if isinstance(out, ndarray) else out

    ck = jax.checkpoint(pure, policy=policy)
    return apply_op(ck, args, {}, name="remat")


def _embedding_grad_via_matmul(w) -> bool:
    """Policy for the embedding weight-grad strategy (flags.embedding_grad).
    XLA:TPU lowers scatter-add row-serially, so the dense embedding
    backward can dominate a step; a one-hot(tokens,V) @ cotangent matmul
    is MXU work instead. 'auto' enables it on TPU when the bf16 one-hot
    stays comfortably under HBM pressure."""
    from ..utils.config import flags
    mode = flags.embedding_grad
    if mode == "matmul":
        return True
    if mode == "auto":
        try:
            return jax.default_backend() == "tpu"
        except Exception:
            return False
    return False


def _embedding_matmul_grad(idx32, w):
    """take(w, idx) with a custom VJP: dW = one_hot(idx)^T @ cotangent.
    The one-hot is built at the cotangent's dtype (bf16 in AMP training)
    and the product accumulates in fp32 (MXU native)."""
    n_rows = w.shape[0]
    # guard the HBM cost of materializing the one-hot: fall back to the
    # scatter path above ~0.75 GB. The one-hot is built at the cotangent's
    # dtype, which for a jax VJP matches the primal's — use w's item size.
    itemsize = jnp.dtype(w.dtype).itemsize
    if int(idx32.size) * int(n_rows) * itemsize > 750_000_000:
        return jnp.take(w, idx32, axis=0, mode="clip")

    @jax.custom_vjp
    def emb(w):
        return jnp.take(w, idx32, axis=0, mode="clip")

    def fwd(w):
        return emb(w), None

    def bwd(_, cot):
        flat = jnp.clip(idx32.reshape(-1), 0, n_rows - 1)
        oh = jax.nn.one_hot(flat, n_rows, dtype=cot.dtype)       # (T, V)
        cot2 = cot.reshape((flat.shape[0], -1))                  # (T, E)
        g = jax.lax.dot_general(
            oh, cot2, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                  # (V, E)
        return (g.reshape(w.shape).astype(w.dtype),)

    emb.defvjp(fwd, bwd)
    return emb(w)


def embedding(data, weight, input_dim=None, output_dim=None, dtype=None,
              sparse_grad=False):
    """Embedding lookup (parity: `src/operator/tensor/indexing_op.cc`
    Embedding). With `sparse_grad=True` in eager autograd the weight
    gradient is produced as a `RowSparseNDArray` (index/value pairs, never
    densified) — the reference's row-sparse grad path; under jit/hybridize
    the dense scatter-add path is used (XLA fuses it; sparse storage would
    force dynamic shapes into the trace)."""
    def fn(idx, w):
        # mode='clip' matches the reference's index clipping and avoids
        # XLA's NaN-fill for out-of-bounds gathers under jit
        idx32 = idx.astype(jnp.int32)
        if _embedding_grad_via_matmul(w):
            out = _embedding_matmul_grad(idx32, w)
        else:
            out = jnp.take(w, idx32, axis=0, mode="clip")
        return out.astype(dtype) if dtype else out

    if sparse_grad and _tape.is_recording() \
            and not isinstance(weight._data, jax.core.Tracer) \
            and not isinstance(data._data, jax.core.Tracer) \
            and weight._ag_node is None and weight._grad_req != "null":
        # leaf weights only: a non-leaf weight (e.g. w*scale) would feed the
        # RowSparseNDArray cotangent into an upstream dense jax VJP — those
        # fall through to the dense scatter-add path below
        from ..ndarray.sparse import RowSparseNDArray
        idx_v, w_v = data._data, weight._data
        out_v = fn(idx_v, w_v)
        n_rows, row_shape = w_v.shape[0], w_v.shape[1:]

        def sparse_vjp(cot):
            flat_idx = jnp.clip(idx_v.astype(jnp.int32).reshape(-1),
                                0, n_rows - 1)
            vals = cot.reshape((-1,) + tuple(row_shape)).astype(w_v.dtype)
            return (RowSparseNDArray(flat_idx, vals, w_v.shape),)

        node = _tape.record_node(
            sparse_vjp, [weight], 1, name="embedding_sparse",
            out_avals=[(tuple(out_v.shape), out_v.dtype)])
        out = ndarray(out_v, weight._device, _no_copy=True)
        out._ag_node = node
        out._ag_out_index = 0
        return out
    return apply_op(fn, (data, weight), {}, name="embedding")


def one_hot(data, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    def fn(idx):
        oh = jax.nn.one_hot(idx.astype(jnp.int32), depth, dtype=jnp.dtype(dtype))
        return oh * (on_value - off_value) + off_value
    return apply_op(fn, (data,), {}, name="one_hot")


def pick(data, index, axis=-1, keepdims=False, mode="clip"):
    def fn(x, idx):
        idx = jnp.expand_dims(idx.astype(jnp.int32), axis=axis)
        out = jnp.take_along_axis(x, idx, axis=axis, mode="clip")
        if not keepdims:
            out = jnp.squeeze(out, axis=axis)
        return out
    return apply_op(fn, (data, index), {}, name="pick")


def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    def fn(x):
        xs = jnp.moveaxis(x, axis, -1)
        vals = -xs if is_ascend else xs
        v, i = jax.lax.top_k(vals, k)
        if is_ascend:
            v = -v
        v = jnp.moveaxis(v, -1, axis)
        i = jnp.moveaxis(i, -1, axis)
        if ret_typ == "value":
            return v
        if ret_typ == "both":
            return v, i.astype(jnp.dtype(dtype))
        if ret_typ == "mask":
            raise MXNetError("topk ret_typ='mask' not supported")
        return i.astype(jnp.dtype(dtype))
    n_out = 2 if ret_typ == "both" else 1
    return apply_op(fn, (data,), {}, name="topk", n_out=n_out)



def slice(data, begin, end, step=None):
    """Reference `slice` op (`src/operator/tensor/matrix_op.cc` Slice):
    per-axis begin/end/step with None meaning full range."""
    def fn(x):
        ixs = []
        nd_ = x.ndim
        b = tuple(begin) + (None,) * (nd_ - len(begin))
        e = tuple(end) + (None,) * (nd_ - len(end))
        st = tuple(step) + (None,) * (nd_ - len(step)) if step else (None,) * nd_
        for bi, ei, si in zip(b, e, st):
            ixs.append(builtins_slice(bi, ei, si))
        return x[tuple(ixs)]
    import builtins
    builtins_slice = builtins.slice
    return apply_op(fn, (data,), {}, name="slice")


def reshape(a, newshape, reverse=False, order="C"):
    """`npx.reshape` with the reference's special codes
    (`src/operator/numpy/np_matrix_op-inl.h` NumpyXReshapeInferShape):
    -1 infer, -2 copy one input dim, -3 drop a size-1 dim, -4 splice all
    remaining input dims, -5 merge two consecutive dims, -6 split one dim
    into the next two spec values; reverse=True matches from the right."""
    orig_shape = tuple(a.shape)
    in_shape = orig_shape
    spec = [newshape] if isinstance(newshape, int) else list(newshape)
    if reverse:
        in_shape = in_shape[::-1]
        spec = spec[::-1]

    def _need_dims(idx, code):
        # reference-style error instead of a raw IndexError when a
        # special code consumes more input dims than the array has
        if idx >= len(in_shape):
            raise MXNetError(
                f"npx.reshape {code}: special code consumes input dim "
                f"{idx} but input has only {len(in_shape)} dims "
                f"(shape {orig_shape})")

    out = []
    i = 0
    j = 0
    while j < len(spec):
        sv = spec[j]
        if sv == -4:
            out.extend(in_shape[i:])
            i = len(in_shape)
        elif sv == -2:
            _need_dims(i, -2)
            out.append(in_shape[i]); i += 1
        elif sv == -3:
            _need_dims(i, -3)
            if in_shape[i] != 1:
                raise MXNetError(
                    f"npx.reshape -3: input dim {i} is {in_shape[i]}, not 1")
            i += 1
        elif sv == -5:
            _need_dims(i + 1, -5)
            out.append(in_shape[i] * in_shape[i + 1]); i += 2
        elif sv == -6:
            _need_dims(i, -6)
            if j + 2 >= len(spec):
                raise MXNetError(
                    f"npx.reshape -6: needs two following spec values, "
                    f"got {spec[j + 1:]} (newshape {tuple(spec)})")
            d = in_shape[i]; i += 1
            av, bv = spec[j + 1], spec[j + 2]
            if av == -1:
                av = d // bv
            if bv == -1:
                bv = d // av
            if av * bv != d:
                raise MXNetError(f"npx.reshape -6: {av}*{bv} != {d}")
            out.extend([av, bv]); j += 2
        elif sv == -1:
            out.append(-1)
            i += 1
        else:
            out.append(sv)
            i += 1   # spec positions align 1:1 with input dims (the
            # reference's NumpyXReshapeInferShape walks both in step)
        j += 1
    if reverse:
        out = out[::-1]
    if -1 in out:
        if out.count(-1) > 1:
            raise MXNetError(
                "npx.reshape: one and only one dim can be inferred")
        import math as _math
        known = _math.prod(d for d in out if d != -1)
        total = _math.prod(in_shape)
        if known == 0 or total % known:
            raise MXNetError(
                f"npx.reshape: cannot infer -1 — {total} elements do "
                f"not divide by the known dims product {known}")
        out[out.index(-1)] = total // known
    else:
        # no inferred dim: the resolved output must cover the input
        # exactly — raise the reference-style error here instead of
        # letting jnp.reshape fail later inside the traced op
        import math as _math
        if _math.prod(out) != _math.prod(in_shape):
            raise MXNetError(
                f"npx.reshape: cannot reshape array of shape "
                f"{orig_shape} ({_math.prod(in_shape)} elements) into "
                f"shape {tuple(out)} ({_math.prod(out)} elements)")
    return apply_op(lambda x: jnp.reshape(x, tuple(out)), (a,), {},
                    name="npx.reshape")


def _index_scatter(name, method):
    def op(a, ind, val):
        def fn(av, iv, vv):
            iv = jnp.atleast_1d(iv.astype(jnp.int32))
            rows = (iv,) if iv.ndim == 1 else tuple(iv)
            k = len(rows)
            n = rows[0].shape[0]
            tail = av.shape[k:]
            vb = jnp.broadcast_to(vv, (n,) + tail)
            return getattr(av.at[rows], method)(vb)
        return apply_op(fn, (a, ind, val), {}, name=name)
    op.__name__ = name
    return op


# `npx.index_add(a, ind, val)`: scatter-add `val` at the
# (ind_ndim, ind_num) integer index matrix (parity:
# `src/operator/contrib/index_add.cc`); index_update overwrites.
index_add = _index_scatter("index_add", "add")
index_update = _index_scatter("index_update", "set")


def constraint_check(condition, msg="Constraint violated"):
    """`npx.constraint_check`: eager validation of a boolean tensor —
    raises ValueError when any element is False, else evaluates to True
    (parity: `src/operator/numpy/np_constraint_check.cc`)."""
    from ..ndarray.ndarray import is_tracer as _is_tracer
    cv = condition._data if isinstance(condition, ndarray) else condition
    if not _is_tracer(cv) and not bool(jnp.all(cv)):
        raise ValueError(msg)
    return apply_op(lambda c: jnp.all(c), (condition,), {},
                    name="constraint_check")

def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    if not use_sequence_length or sequence_length is None:
        return data

    def fn(x, ln):
        steps = jnp.arange(x.shape[axis])
        shape = [1] * x.ndim
        shape[axis] = x.shape[axis]
        steps = steps.reshape(shape)
        batch_axis = 1 - axis  # (T, N, ...) or (N, T, ...)
        lshape = [1] * x.ndim
        lshape[batch_axis] = x.shape[batch_axis]
        mask = steps < ln.reshape(lshape)
        return jnp.where(mask, x, value)
    return apply_op(fn, (data, sequence_length), {}, name="sequence_mask")


def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None, ctx=None):
    def fn(x):
        if axis is None:
            n = x.size
            out = start + step * jnp.arange(n)
            return out.reshape(x.shape)
        n = x.shape[axis]
        return start + step * jnp.arange(n).astype(x.dtype)
    return apply_op(fn, (data,), {}, name="arange_like")


def shape_array(data):
    return from_jax(jnp.asarray(data.shape, jnp.int64
                                if jax.config.jax_enable_x64 else jnp.int32),
                    data._device)


def reshape_like(lhs, rhs, lhs_begin=None, lhs_end=None, rhs_begin=None,
                 rhs_end=None):
    """Reshape `lhs` to `rhs`'s shape; the optional begin/end bounds
    splice only a sub-range of axes (reference
    `src/operator/tensor/elemwise_unary_op_basic.cc` ReshapeLike)."""
    def _rng_(n, b, e):
        b = 0 if b is None else (b + n if b < 0 else b)
        e = n if e is None else (e + n if e < 0 else e)
        return b, e

    def fn(a, b):
        if lhs_begin is None and lhs_end is None and rhs_begin is None \
                and rhs_end is None:
            return a.reshape(b.shape)
        lb, le = _rng_(a.ndim, lhs_begin, lhs_end)
        rb, re_ = _rng_(b.ndim, rhs_begin, rhs_end)
        new_shape = a.shape[:lb] + b.shape[rb:re_] + a.shape[le:]
        return a.reshape(new_shape)
    return apply_op(fn, (lhs, rhs), {}, name="reshape_like")


def broadcast_like(lhs, rhs, lhs_axes=None, rhs_axes=None):
    return apply_op(lambda a, b: jnp.broadcast_to(a, b.shape), (lhs, rhs), {},
                    name="broadcast_like")


def smooth_l1(data, scalar=1.0):
    s2 = scalar * scalar

    def fn(x):
        ax = jnp.abs(x)
        return jnp.where(ax < 1.0 / s2, 0.5 * s2 * x * x, ax - 0.5 / s2)
    return apply_op(fn, (data,), {}, name="smooth_l1")


def gather_nd(data, indices):
    def fn(x, idx):
        idx = idx.astype(jnp.int32)
        return x[tuple(idx[i] for i in range(idx.shape[0]))]
    return apply_op(fn, (data, indices), {}, name="gather_nd")


def scatter_nd(data, indices, shape):
    def fn(d, idx):
        idx = idx.astype(jnp.int32)
        out = jnp.zeros(shape, d.dtype)
        return out.at[tuple(idx[i] for i in range(idx.shape[0]))].set(d)
    return apply_op(fn, (data, indices), {}, name="scatter_nd")


def cast(data, dtype):
    return data.astype(dtype)


def amp_cast(data, dtype):
    """Cast BETWEEN float dtypes only: integer/bool inputs pass through
    unchanged (reference `src/operator/tensor/amp_cast.h` semantics —
    the AMP pass must not change integer-op results)."""
    if not jnp.issubdtype(jnp.asarray(data._data if isinstance(data, ndarray)
                                      else data).dtype, jnp.floating):
        return data
    return data.astype(dtype)


def amp_multicast(*data, num_outputs=None, cast_narrow=False):
    arrays = list(data)
    dtypes = [a.dtype for a in arrays]
    widest = jnp.result_type(*dtypes)
    target = min(dtypes, key=lambda d: jnp.finfo(d).bits) if cast_narrow \
        else widest
    return tuple(a.astype(target) for a in arrays)


# ---------------------------------------------------------------------------
# attention (parity: src/operator/contrib/transformer.cc:675-1095)
# ---------------------------------------------------------------------------

def interleaved_matmul_selfatt_qk(queries_keys_values, heads=1):
    """scores = Q K^T / sqrt(d) over interleaved (qlen, batch, 3*embed) input.

    Parity: `_contrib_interleaved_matmul_selfatt_qk`
    (`src/operator/contrib/transformer.cc:675`). Output
    (batch*heads, qlen, qlen)."""
    def fn(qkv):
        qlen, bsz, e3 = qkv.shape
        emb = e3 // 3
        hd = emb // heads
        x = qkv.reshape(qlen, bsz, heads, 3, hd)
        q = x[:, :, :, 0]  # (L, B, H, D)
        k = x[:, :, :, 1]
        q = q.transpose(1, 2, 0, 3).reshape(bsz * heads, qlen, hd)
        k = k.transpose(1, 2, 0, 3).reshape(bsz * heads, qlen, hd)
        return jnp.einsum("bqd,bkd->bqk", q, k) / jnp.sqrt(
            jnp.asarray(hd, q.dtype))
    return apply_op(fn, (queries_keys_values,), {}, name="selfatt_qk")


def interleaved_matmul_selfatt_valatt(queries_keys_values, attention, heads=1):
    """context = softmax(scores) V (parity: transformer.cc:760)."""
    def fn(qkv, att):
        qlen, bsz, e3 = qkv.shape
        emb = e3 // 3
        hd = emb // heads
        x = qkv.reshape(qlen, bsz, heads, 3, hd)
        v = x[:, :, :, 2].transpose(1, 2, 0, 3).reshape(bsz * heads, qlen, hd)
        ctx = jnp.einsum("bqk,bkd->bqd", att, v)
        ctx = ctx.reshape(bsz, heads, qlen, hd).transpose(2, 0, 1, 3)
        return ctx.reshape(qlen, bsz, emb)
    return apply_op(fn, (queries_keys_values, attention), {}, name="selfatt_valatt")


def interleaved_matmul_encdec_qk(queries, keys_values, heads=1):
    """Parity: transformer.cc:820 — queries (qlen,B,E), kv (klen,B,2E)."""
    def fn(q, kv):
        qlen, bsz, emb = q.shape
        klen = kv.shape[0]
        hd = emb // heads
        qh = q.reshape(qlen, bsz, heads, hd).transpose(1, 2, 0, 3)
        qh = qh.reshape(bsz * heads, qlen, hd)
        kvh = kv.reshape(klen, bsz, heads, 2, hd)
        kh = kvh[:, :, :, 0].transpose(1, 2, 0, 3).reshape(bsz * heads, klen, hd)
        return jnp.einsum("bqd,bkd->bqk", qh, kh) / jnp.sqrt(
            jnp.asarray(hd, q.dtype))
    return apply_op(fn, (queries, keys_values), {}, name="encdec_qk")


def interleaved_matmul_encdec_valatt(keys_values, attention, heads=1):
    def fn(kv, att):
        klen, bsz, e2 = kv.shape
        emb = e2 // 2
        hd = emb // heads
        kvh = kv.reshape(klen, bsz, heads, 2, hd)
        v = kvh[:, :, :, 1].transpose(1, 2, 0, 3).reshape(bsz * heads, klen, hd)
        qlen = att.shape[1]
        ctx = jnp.einsum("bqk,bkd->bqd", att, v)
        ctx = ctx.reshape(bsz, heads, qlen, hd).transpose(2, 0, 1, 3)
        return ctx.reshape(qlen, bsz, emb)
    return apply_op(fn, (keys_values, attention), {}, name="encdec_valatt")


def sldwin_atten_mask_like(score, dilation, valid_length, num_heads=1,
                           symmetric=True, w=1):
    """Sliding-window attention mask (parity: transformer.cc:887)."""
    def fn(s, vl):
        bh, qlen, wlen = s.shape
        i = jnp.arange(qlen)[:, None]
        j = jnp.arange(wlen)[None, :]
        # window offsets relative to i: j maps to absolute position
        offs = (j - (wlen // 2)) * dilation
        absj = i + offs
        ok = (absj >= 0) & (absj < qlen)
        if not symmetric:
            ok = ok & (offs <= 0)
        b = bh // num_heads
        vl_ = jnp.repeat(vl, num_heads)
        ok = ok[None] & (absj[None] < vl_[:, None, None]) & \
            (i[None] < vl_[:, None, None])
        return ok.astype(s.dtype)
    return apply_op(fn, (score, valid_length), {}, name="sldwin_mask_like")


def _sldwin_indices(qlen, w, dilation, symmetric):
    wlen = (2 * w + 1) if symmetric else (w + 1)
    i = jnp.arange(qlen)[:, None]
    off = (jnp.arange(wlen)[None, :] - (w if symmetric else w)) * dilation
    j = i + off
    valid = (j >= 0) & (j < qlen)
    return jnp.clip(j, 0, qlen - 1), valid, wlen


def sldwin_atten_score(query, key, dilation, w=1, symmetric=True):
    """Banded QK^T: out (B*H, L, W) (parity: transformer.cc:960).

    query/key: (B*H, L, D). Computed by gathering the key window per
    position — O(L*W*D), never materialising the (L,L) matrix."""
    def fn(q, k):
        bh, qlen, hd = q.shape
        j, valid, wlen = _sldwin_indices(qlen, w, int(dilation), symmetric)
        kg = k[:, j.reshape(-1), :].reshape(bh, qlen, wlen, hd)
        s = jnp.einsum("bld,blwd->blw", q, kg) / jnp.sqrt(
            jnp.asarray(hd, q.dtype))
        return jnp.where(valid[None], s, s)
    return apply_op(fn, (query, key), {}, name="sldwin_score")


def sldwin_atten_context(score, value, dilation, w=1, symmetric=True):
    """Banded attention context (parity: transformer.cc:1030)."""
    def fn(s, v):
        bh, qlen, wlen = s.shape
        j, valid, _ = _sldwin_indices(qlen, w, int(dilation), symmetric)
        vg = v[:, j.reshape(-1), :].reshape(bh, qlen, wlen, v.shape[-1])
        return jnp.einsum("blw,blwd->bld", s, vg)
    return apply_op(fn, (score, value), {}, name="sldwin_context")


def multi_head_attention(query, key, value, num_heads, mask=None,
                         dropout_p=0.0, causal=False, use_flash=True,
                         window=None, window_symmetric=True,
                         rope_theta=None, num_kv_heads=None):
    """Fused multi-head attention over (B, L, E) tensors.

    New-capability op (the reference only has the interleaved primitives):
    lowers to the Pallas flash-attention kernel on TPU when available,
    otherwise a jnp reference path. `window=w` runs fused sliding-window
    (local) attention — O(L·w), out-of-band blocks skipped in-kernel.
    `rope_theta` applies rotary position embeddings to q/k per head.
    `num_kv_heads=g` selects grouped-query attention (GQA/MQA).
    See `mxnet_tpu.ops.attention`."""
    from ..ops import attention as _att
    return _att.multi_head_attention(query, key, value, num_heads, mask=mask,
                                     dropout_p=dropout_p, causal=causal,
                                     use_flash=use_flash, window=window,
                                     window_symmetric=window_symmetric,
                                     rope_theta=rope_theta,
                                     num_kv_heads=num_kv_heads)


# ---------------------------------------------------------------------------
# losses / sequence ops
# ---------------------------------------------------------------------------

def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             use_data_lengths=False, use_label_lengths=False, blank_label="first"):
    """CTC loss (parity: `src/operator/nn/ctc_loss.cc`).

    data: (T, B, C) alphabet scores (pre-softmax); label: (B, L) padded with
    -1 (or 0 when blank_label='first' and labels are 1-based)."""
    import optax

    def fn(d, lbl, *rest):
        t, b, c = d.shape
        logits = jnp.transpose(d, (1, 0, 2))  # (B, T, C)
        if use_data_lengths and rest:
            dl = rest[0]
            logit_pad = (jnp.arange(t)[None, :] >= dl[:, None]).astype(d.dtype)
        else:
            logit_pad = jnp.zeros((b, t), d.dtype)
        lbl = lbl.astype(jnp.int32)
        if blank_label == "first":
            blank_id = 0
        else:
            blank_id = c - 1
        if use_label_lengths and len(rest) == 2:
            ll = rest[-1]
            label_pad = (jnp.arange(lbl.shape[1])[None, :] >= ll[:, None])
        else:
            label_pad = lbl < 0
        labels = jnp.where(label_pad, 0, lbl)
        loss = optax.ctc_loss(logits, logit_pad, labels,
                              label_pad.astype(d.dtype), blank_id=blank_id)
        return loss

    args = [data, label]
    if use_data_lengths and data_lengths is not None:
        args.append(data_lengths)
    if use_label_lengths and label_lengths is not None:
        args.append(label_lengths)
    return apply_op(fn, tuple(args), {}, name="ctc_loss")


# ---------------------------------------------------------------------------
# control flow (parity: src/operator/control_flow.cc:1075,1134,1195)
# ---------------------------------------------------------------------------

def foreach(body, data, init_states):
    """`lax.scan`-backed foreach. body(step_data, states) -> (out, states)."""
    single_data = isinstance(data, ndarray)
    single_state = isinstance(init_states, ndarray)
    datas = [data] if single_data else list(data)
    states = [init_states] if single_state else list(init_states)
    dev = datas[0]._device

    def step(carry, xs):
        st = [from_jax(c, dev) for c in carry]
        xv = [from_jax(x, dev) for x in xs]
        out, new_st = body(xv[0] if single_data else xv,
                           st[0] if single_state else st)
        outs = [out] if isinstance(out, ndarray) else list(out)
        new_states = [new_st] if isinstance(new_st, ndarray) else list(new_st)
        return tuple(s._data for s in new_states), \
            tuple(o._data for o in outs)

    arrs = datas + states
    nd_ = len(datas)

    def fn(*vals):
        xs = tuple(vals[:nd_])
        init = tuple(vals[nd_:])
        final, ys = lax.scan(step, init, xs)
        return tuple(ys) + tuple(final)

    res = apply_op(fn, tuple(arrs), {}, name="foreach",
                   n_out=2)
    res = list(res) if isinstance(res, tuple) else [res]
    # partition: ys first, then final states — count from body signature
    # run body once abstractly? simpler: scan returned len(ys)+len(final)
    n_states = len(states)
    outs = res[:-n_states] if n_states else res
    fstates = res[-n_states:] if n_states else []
    out = outs[0] if len(outs) == 1 else tuple(outs)
    fst = fstates[0] if single_state else list(fstates)
    return out, fst


def while_loop(cond_fn, func, loop_vars, max_iterations=None):
    """Bounded while loop (parity: `_while_loop`); max_iterations is required
    under jit for fixed output shape; here state-only (no per-step outputs)."""
    single = isinstance(loop_vars, ndarray)
    lvs = [loop_vars] if single else list(loop_vars)
    dev = lvs[0]._device

    def jcond(carry):
        st = [from_jax(c, dev) for c in carry]
        r = cond_fn(st[0] if single else st)
        return r._data.reshape(()) if isinstance(r, ndarray) else jnp.asarray(r)

    def jbody(carry):
        st = [from_jax(c, dev) for c in carry]
        r = func(st[0] if single else st)
        rl = [r] if isinstance(r, ndarray) else list(r)
        return tuple(x._data for x in rl)

    def fn(*vals):
        return lax.while_loop(jcond, jbody, tuple(vals))

    res = apply_op(fn, tuple(lvs), {}, name="while_loop")
    if single:
        return res if isinstance(res, ndarray) else res[0]
    return list(res) if isinstance(res, tuple) else [res]


def cond(pred, then_func, else_func, inputs=()):
    """Conditional (parity: `_cond`)."""
    single = isinstance(inputs, ndarray)
    ins = [inputs] if single else list(inputs)
    dev = ins[0]._device if ins else current_device()
    pv = pred._data.reshape(()) if isinstance(pred, ndarray) else jnp.asarray(pred)

    def branch(f):
        def g(vals):
            nd_ = [from_jax(v, dev) for v in vals]
            r = f(*(nd_ if not single else nd_))
            rl = [r] if isinstance(r, ndarray) else list(r)
            return tuple(x._data for x in rl)
        return g

    def fn(*vals):
        return lax.cond(pv.astype(bool), branch(then_func), branch(else_func),
                        tuple(vals))

    res = apply_op(fn, tuple(ins), {}, name="cond")
    if isinstance(res, tuple) and len(res) == 1:
        return res[0]
    return res


# ---------------------------------------------------------------------------
# fused RNN op (parity: src/operator/rnn.cc:306) — see gluon.rnn for layers
# ---------------------------------------------------------------------------

def rnn(data, parameters, state, state_cell=None, mode="lstm", state_size=1,
        num_layers=1, bidirectional=False, p=0.0, state_outputs=True,
        projection_size=None, use_sequence_length=False, sequence_length=None,
        **kwargs):
    from ..gluon.rnn import _fused_rnn_op
    return _fused_rnn_op(data, parameters, state, state_cell, mode, state_size,
                         num_layers, bidirectional, p, state_outputs)


def intgemm_fully_connected(data, weight, scaling=1.0, bias=None, **kwargs):
    """int8 GEMM parity (`src/operator/contrib/intgemm/`): delegated to XLA
    int8 dot with dequant scaling."""
    def fn(x, w):
        y = jnp.matmul(x.astype(jnp.int32), w.T.astype(jnp.int32))
        return y.astype(jnp.float32) * scaling
    if bias is None:
        return apply_op(fn, (data, weight), {}, name="intgemm_fc")

    def fnb(x, w, b):
        y = jnp.matmul(x.astype(jnp.int32), w.T.astype(jnp.int32))
        return y.astype(jnp.float32) * scaling + b
    return apply_op(fnb, (data, weight, bias), {}, name="intgemm_fc")


# ---------------------------------------------------------------------------
# serialization / session utils
# ---------------------------------------------------------------------------

def save(fname, data):
    """Save dict/list of ndarrays (parity: `mx.npx.save` / NDArray save in
    `src/ndarray/ndarray.cc`). Uses `.npz` container (cnpy parity)."""
    from ..util import save_arrays
    save_arrays(fname, data)


def load(fname):
    """Load `.npz` saves AND reference binary NDArray files (sniffed) —
    `npx.load` in the reference likewise reads both its own and legacy
    formats."""
    from ..ndarray.legacy_serialization import is_legacy_ndarray_file
    if is_legacy_ndarray_file(fname):
        from ..ndarray import load as _nd_load
        out = _nd_load(fname)
        return out if isinstance(out, dict) else \
            {f"arr_{i}": a for i, a in enumerate(out)}
    from ..util import load_arrays
    return load_arrays(fname)


def waitall():
    from ..ndarray import waitall as _w
    _w()


# shape semantics are real scoped state shared with mx.util (the legacy
# `mx.nd` surface consults it); array semantics are always-on (one
# ndarray type)
from ..util import set_np, reset_np, is_np_shape, set_np_shape  # noqa: F401,E402


def is_np_array():
    return True


def is_np_default_dtype():
    return False


def seed(s):
    _rng.seed(s)


def custom(*inputs, op_type, **kwargs):
    """Invoke a registered `mx.operator.CustomOpProp` op (parity:
    `mx.nd.Custom`, `src/operator/custom/custom.cc`)."""
    from ..operator import custom as _custom
    return _custom(*inputs, op_type=op_type, **kwargs)



# ---------------------------------------------------------------------------
# spatial / warping ops (ref `src/operator/spatial_transformer.cc`,
# `bilinear_sampler.cc`, `grid_generator.cc`, `correlation.cc`,
# `src/operator/nn/im2col.h`; jax-level math in `mxnet_tpu/ops/spatial.py`)
# ---------------------------------------------------------------------------

def softmax_cross_entropy(logits, labels, reduction="none"):
    """Fused sparse-label cross entropy (ref `mx.nd.softmax_cross_entropy`,
    `src/operator/softmax_output.cc`). On TPU this streams the logits
    through a Pallas kernel without materialising fp32 (N, V) log-probs
    (`ops/pallas/softmax_xent.py`).

    reduction='none' (default) returns per-row loss with the label shape;
    reduction='sum' matches the reference op's summed (1,) output."""
    from ..ops.pallas.softmax_xent import softmax_cross_entropy as _sce
    if reduction == "sum":      # the reference op's contract
        return apply_op(lambda x, l: _sce(x, l).sum().reshape(1),
                        (logits, labels), {}, name="softmax_cross_entropy")
    return apply_op(lambda x, l: _sce(x, l), (logits, labels), {},
                    name="softmax_cross_entropy")


def grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    from ..ops import spatial as _sp
    return apply_op(
        lambda d: _sp.grid_generator(d, transform_type, target_shape),
        (data,), {}, name="grid_generator")


def bilinear_sampler(data, grid):
    from ..ops import spatial as _sp
    return apply_op(lambda d, g: _sp.bilinear_sample(d, g), (data, grid),
                    {}, name="bilinear_sampler")


def spatial_transformer(data, loc, target_shape=None,
                        transform_type="affine", sampler_type="bilinear"):
    from ..ops import spatial as _sp
    return apply_op(
        lambda d, l: _sp.spatial_transformer(d, l, target_shape,
                                             transform_type, sampler_type),
        (data, loc), {}, name="spatial_transformer")


def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    from ..ops import spatial as _sp
    return apply_op(
        lambda a, b: _sp.correlation(a, b, kernel_size, max_displacement,
                                     stride1, stride2, pad_size,
                                     is_multiply),
        (data1, data2), {}, name="correlation")


def im2col(data, kernel, stride=(1, 1), dilate=(1, 1), pad=(0, 0)):
    from ..ops import spatial as _sp
    return apply_op(lambda d: _sp.im2col(d, kernel, stride, dilate, pad),
                    (data,), {}, name="im2col")


def col2im(data, output_size, kernel, stride=(1, 1), dilate=(1, 1),
           pad=(0, 0)):
    from ..ops import spatial as _sp
    return apply_op(
        lambda c: _sp.col2im(c, output_size, kernel, stride, dilate, pad),
        (data,), {}, name="col2im")


def deformable_convolution(data, offset, weight, bias=None, kernel=(3, 3),
                           stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                           num_filter=None, num_group=1,
                           num_deformable_group=1):
    from ..ops import spatial as _sp
    args = (data, offset, weight) + (() if bias is None else (bias,))

    def fn(d, o, w, *rest):
        return _sp.deformable_convolution(
            d, o, w, rest[0] if rest else None, kernel, stride, dilate,
            pad, num_filter, num_group, num_deformable_group)
    return apply_op(fn, args, {}, name="deformable_convolution")


# submodule re-exports (parity: `python/mxnet/numpy_extension/__init__.py`
# exposes npx.random, npx.image, and the device helpers)
from ..numpy import random  # noqa: E402,F401
from ..image import _npx_image as image  # noqa: E402,F401
from ..device import cpu, gpu, tpu, num_gpus, num_tpus  # noqa: E402,F401


# ---------------------------------------------------------------------------
# npx tail parity (`python/mxnet/numpy_extension/__init__.py` __all__):
# batch_dot, dlpack/numpy interop, savez, and the *_n samplers
# ---------------------------------------------------------------------------

def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Batched matmul over leading batch dims (`npx.batch_dot`)."""
    from ..ndarray.legacy_ops import batch_dot as _bd
    return _bd(lhs, rhs, transpose_a=transpose_a, transpose_b=transpose_b)


def bernoulli(prob=None, logit=None, size=None, dtype=None, device=None,
              ctx=None):
    """`npx.random.bernoulli` surface (also exported at npx top level)."""
    from ..numpy.random import bernoulli as _b
    return _b(prob=prob, logit=logit, size=size, dtype=dtype,
              device=device, ctx=ctx)


def from_numpy(ndarray, zero_copy=True):
    """Host numpy -> device array (`npx.from_numpy`; dtype-preserving up
    to jax's x64 policy, and the device transfer copies regardless — XLA
    owns its buffers).  A float64 HOST array converts like the implicit
    default (f32) when x64 is off: it is the array's ambient dtype, not
    an explicit user request, so the loud f64 check does not apply."""
    from ..numpy import array as _array
    import numpy as _np
    if _np.dtype(ndarray.dtype) in (_np.float64, _np.complex128) \
            and not jax.config.jax_enable_x64:
        return _array(ndarray)
    return _array(ndarray, dtype=ndarray.dtype)


# DLPack interop: one implementation, mx.dlpack (protocol objects +
# legacy-capsule adaptation + read/write sync) — re-exported here
from ..dlpack import (from_dlpack, to_dlpack_for_read,  # noqa: E402,F401
                      to_dlpack_for_write)


def savez(file, *args, **kwargs):
    """numpy-style savez (`npx.savez`): positional arrays land under
    arr_0..arr_{n-1}, keywords under their names."""
    from ..util import save_arrays
    data = {f"arr_{i}": a for i, a in enumerate(args)}
    overlap = set(data) & set(kwargs)
    if overlap:
        raise ValueError(f"savez name collision: {sorted(overlap)}")
    data.update(kwargs)
    save_arrays(file, data)


# *_n leading-batch samplers live in numpy/random.py (npx.random IS that
# module — numpy_extension re-exports it); top-level npx aliases:
from ..numpy.random import normal_n, uniform_n  # noqa: E402,F401
