"""`mx.rtc` (parity surface for `python/mxnet/rtc.py`): CUDA runtime
compilation has no TPU analog — XLA owns codegen (SURVEY §7 maps RTC to
XLA fusion; custom kernels are Pallas, `mxnet_tpu/ops/pallas/`)."""
from .base import MXNetError

__all__ = ["CudaModule"]


class CudaModule:
    def __init__(self, *args, **kwargs):
        raise MXNetError(
            "CUDA RTC is not available on the TPU backend: XLA compiles "
            "all kernels. For custom kernels write Pallas "
            "(mxnet_tpu/ops/pallas) or use mx.operator.CustomOp.")
