"""Execution-engine facade.

The reference's dependency engine (`src/engine/threaded_engine.cc`,
`include/mxnet/engine.h:253-437`) schedules every op asynchronously with
read/write variable lists. On TPU, XLA/PjRt *is* the engine: dispatch is async,
ordering is dataflow, exceptions surface at synchronisation. This module keeps
the user-facing control surface (`waitall`, bulking knobs, engine-type query)
as no-op/diagnostic parity.
"""
from __future__ import annotations

import contextlib

import jax

from .utils.config import flags

__all__ = ["waitall", "engine_type", "bulk", "set_bulk_size"]


def waitall():
    """Barrier over outstanding async work (parity: `Engine::WaitForAll`)."""
    jax.effects_barrier()


def engine_type() -> str:
    return flags.engine_type  # 'xla'


_bulk_size = [15]


def set_bulk_size(size: int) -> int:
    """Parity: `mx.engine.set_bulk_size` / MXNET_EXEC_BULK_EXEC_*; XLA fuses
    at compile time so this only records the setting."""
    prev = _bulk_size[0]
    _bulk_size[0] = size
    return prev


@contextlib.contextmanager
def bulk(size: int):
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
