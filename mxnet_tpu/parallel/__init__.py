"""`mxnet_tpu.parallel` — meshes, shardings, collectives, sequence/tensor/
pipeline/expert parallelism (SURVEY.md §2.4 checklist, rebuilt TPU-native).

The reference's distributed story (KVStore over comm trees/NCCL/ps-lite) is
replaced by GSPMD: pick a mesh, annotate shardings, let XLA insert ICI/DCN
collectives. Multi-host bootstrap maps `tools/launch.py` env
(`DMLC_PS_ROOT_URI` etc.) onto `jax.distributed.initialize`.
"""
from __future__ import annotations

import os

import jax

from .mesh import (make_mesh, auto_mesh, fit_axes, MeshConfig, Mesh,
                   NamedSharding, PartitionSpec)
from .sharding import (ShardingRules, default_tp_rules, param_sharding,
                       shard_parameter_tree, replicated, retarget_spec)
from .elastic_mesh import (ElasticMeshController, TopologyChange,
                           member_sync)
from . import collectives
from . import compress
from .collectives import (allreduce, allgather, reduce_scatter, broadcast,
                          ppermute_shift, all_to_all)
from .ring_attention import ring_attention, ring_attention_sharded
from .ulysses import ulysses_attention, ulysses_attention_sharded
from .moe import MoEFeedForward, switch_moe
from .pipeline import pipeline_apply, gpipe_sharded
from .train import ShardedTrainStep, StepHandle, make_sharded_train_step
from .prefetch import (DevicePrefetcher, AsyncMetricBuffer,
                       default_prefetch_depth)

__all__ = [
    "make_mesh", "auto_mesh", "fit_axes", "MeshConfig", "Mesh",
    "NamedSharding",
    "PartitionSpec", "ShardingRules", "default_tp_rules", "param_sharding",
    "shard_parameter_tree", "replicated", "retarget_spec",
    "ElasticMeshController", "TopologyChange", "member_sync",
    "collectives", "compress", "allreduce",
    "allgather", "reduce_scatter", "broadcast", "ppermute_shift", "all_to_all",
    "ring_attention", "ring_attention_sharded", "ulysses_attention",
    "ulysses_attention_sharded", "MoEFeedForward", "switch_moe",
    "pipeline_apply", "gpipe_sharded",
    "ShardedTrainStep", "StepHandle", "DevicePrefetcher",
    "AsyncMetricBuffer", "default_prefetch_depth",
    "make_sharded_train_step", "initialize", "rank", "num_workers",
]


def initialize(coordinator_address=None, num_processes=None, process_id=None):
    """Multi-host bootstrap (parity: dmlc tracker env `DMLC_PS_ROOT_URI`/
    `DMLC_NUM_WORKER`/`DMLC_WORKER_ID` from `tools/launch.py`)."""
    coordinator_address = coordinator_address or os.environ.get(
        "MXTPU_COORDINATOR") or _dmlc_coordinator()
    if coordinator_address is None:
        return  # single process
    num_processes = num_processes or int(
        os.environ.get("MXTPU_NUM_PROCESSES")
        or os.environ.get("MXTPU_NUM_WORKERS")
        or os.environ.get("DMLC_NUM_WORKER", "1"))
    process_id = process_id if process_id is not None else int(
        os.environ.get("MXTPU_PROCESS_ID")
        or os.environ.get("MXTPU_WORKER_ID")
        or os.environ.get("DMLC_WORKER_ID", "0"))
    jax.distributed.initialize(coordinator_address, num_processes, process_id)


def _dmlc_coordinator():
    uri = os.environ.get("DMLC_PS_ROOT_URI")
    port = os.environ.get("DMLC_PS_ROOT_PORT", "9000")
    if uri:
        return f"{uri}:{port}"
    return None


def rank() -> int:
    try:
        return jax.process_index()
    except Exception:
        return 0


def num_workers() -> int:
    try:
        return jax.process_count()
    except Exception:
        return 1
