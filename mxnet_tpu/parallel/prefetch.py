"""Async input pipeline — the ThreadedEngine analogue for the JAX runtime.

The reference overlaps H2D copies, compute, and host work through its
dependency engine (`Engine::PushAsync`) plus the IO prefetcher
(`src/io/iter_prefetcher.cc`: a background thread keeps a bounded buffer of
decoded batches ahead of the consumer).  XLA already overlaps compute via
async dispatch; what the host loop still serializes is (a) the H2D placement
of every batch (`jax.device_put` / `make_array_from_callback` runs inline in
the training loop) and (b) the D2H `float(loss)` fetch that blocks the host
on the device every step.  Two pieces here remove both stalls:

* :class:`DevicePrefetcher` — wraps any iterator/`DataLoader` and performs
  device placement on a background thread with depth-N double buffering, so
  batch *k+1* (and beyond) is already device-resident when the step for
  batch *k* is dispatched.  Pair with ``ShardedTrainStep.place_batch`` to
  land batches directly on their target `NamedSharding` (works on single-
  and multi-process meshes — placement is addressable-shard-local).
* :class:`AsyncMetricBuffer` — defers the per-step scalar fetch; losses
  accumulate as async device scalars and are fetched in one batched
  `device_get` every ``drain_every`` steps, keeping several steps in
  flight between host syncs.

Both are fault-aware: the prefetch thread passes through the
``prefetch_next`` injection point (``MXTPU_FAULT_SPEC``, see
`docs/resilience.md`), and any error — injected or real — tears the
pipeline down cleanly and re-raises in the consumer (no hang, no batch
buffers stranded in the queue).
"""
from __future__ import annotations

import os
import queue as _queue
import threading
import time
from typing import Callable, Iterable, Optional

import jax

from ..base import MXNetError
from ..resilience import fault_point
from .. import health as _health
from .. import telemetry as _tele
from .. import tracing as _trace

__all__ = ["DevicePrefetcher", "AsyncMetricBuffer", "default_prefetch_depth"]

ENV_DEPTH = "MXTPU_PREFETCH_DEPTH"


def default_prefetch_depth() -> int:
    """Depth-N double buffering default: ``MXTPU_PREFETCH_DEPTH`` (>= 1),
    else 2 — one batch being consumed, one staged ahead."""
    try:
        depth = int(os.environ.get(ENV_DEPTH, "2"))
    except ValueError:
        depth = 2
    return max(1, depth)


class DevicePrefetcher:
    """Iterate `source`, device-placing each batch on a background thread.

    `place` maps one source item to its device-resident form; batches that
    are tuples/lists are splatted (``place(*item)``), so
    ``ShardedTrainStep.place_batch`` plugs in directly.  The default places
    every leaf on the default device with `jax.device_put` (unwrapping
    mx ndarrays).  The bounded queue (``depth``) gives backpressure: the
    thread stays at most ``depth + 1`` batches ahead (``depth`` queued
    plus the one it placed and is waiting to enqueue), so prefetch memory
    is capped at ``(depth + 1) x batch_bytes`` on the device.

    Iteration yields placed batches in source order.  An exception on the
    prefetch thread (dataset bug, placement failure, injected fault) is
    re-raised to the consumer on its next ``next()``; the thread and queue
    are torn down first.  `close()` (also via context-manager exit) stops
    the thread and drops buffered batches — safe to call mid-epoch.
    """

    def __init__(self, source: Iterable, place: Optional[Callable] = None,
                 depth: Optional[int] = None, timeout: float = 120.0):
        self._source = source
        self._place = place if place is not None else self._default_place
        self._depth = default_prefetch_depth() if depth is None else int(depth)
        if self._depth < 1:
            raise MXNetError(f"prefetch depth must be >= 1, got {self._depth}")
        # timeout bounds each consumer wait: a wedged source raises instead
        # of deadlocking the training loop (DataLoader timeout semantics)
        self._timeout = timeout
        self._q: _queue.Queue = _queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._exhausted = False
        # occupancy stats (read via stats()): how full the window was at
        # each hand-out, and how long the consumer waited — the two numbers
        # that say whether depth is too small (drained window, long waits)
        self._occ_sum = 0
        self._batches = 0
        self._wait_s = 0.0
        # checkpoint-lag accounting (data.DataPipeline): how far this
        # prefetcher has pulled the source AHEAD of the consumer — the
        # number of batches a naive "current source state" checkpoint
        # would skip on resume (the pipeline's state ring exists to make
        # that lag harmless; pending() makes it observable)
        self._pulled = 0
        self._delivered = 0
        # cross-thread span handoff (mx.tracing): the prefetch worker's
        # placement spans parent under whatever span was open on the
        # CONSTRUCTING (consumer) thread — e.g. a training loop's outer
        # span — so the H2D work nests in the consumer's trace instead
        # of starting orphan traces on the worker thread
        self._trace_ctx = (_trace.get_tracer("data").current_context()
                           if _trace.enabled() else None)
        self._thread = threading.Thread(target=self._worker,
                                        name="mxtpu-prefetch", daemon=True)
        self._thread.start()

    # -- producer (background thread) ----------------------------------
    @staticmethod
    def _default_place(*items):
        placed = tuple(jax.device_put(getattr(b, "_data", b)) for b in items)
        return placed if len(placed) != 1 else placed[0]

    def _apply_place(self, item):
        if isinstance(item, (tuple, list)):
            return self._place(*item)
        placed = self._place(item)
        # a bare (non-tuple) source item comes back bare whatever the
        # hook returns: ShardedTrainStep.place_batch always returns a
        # tuple, and without the unwrap swapping it in would silently
        # turn every yielded batch into a 1-tuple
        if isinstance(placed, tuple) and len(placed) == 1:
            return placed[0]
        return placed

    def _put(self, entry) -> bool:
        """Stop-aware bounded put; False when closed mid-wait."""
        while not self._stop.is_set():
            try:
                self._q.put(entry, timeout=0.05)
                return True
            except _queue.Full:
                continue
        return False

    def _worker(self):
        try:
            for item in self._source:
                if self._stop.is_set():
                    return
                fault_point("prefetch_next")
                self._pulled += 1
                # named heartbeat for the hang watchdog (mx.health): a
                # wedged placement/source stops touching it and shows up
                # by name in the stall dump
                _health.beat("prefetch")
                # H2D overlap shows up in the XPlane trace under this span
                p_span = _trace.get_tracer("data").start_span(
                    "prefetch.place", parent=self._trace_ctx,
                    track="prefetch", batch=self._pulled) \
                    if _trace.enabled() else None
                with jax.profiler.TraceAnnotation("mxtpu.prefetch"):
                    placed = self._apply_place(item)
                if p_span is not None:
                    p_span.finish()
                if not self._put(("item", placed)):
                    return
            self._put(("end", None))
        except BaseException as e:  # incl. FaultExit — consumer decides
            self._put(("error", e))

    # -- consumer -------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._exhausted or self._stop.is_set():
            raise StopIteration
        t0 = time.perf_counter()
        # wait in short slices so a close() from another thread (elastic
        # shutdown, supervisor teardown) wakes this consumer promptly
        # instead of stalling it for the full timeout
        while True:
            try:
                kind, payload = self._q.get(timeout=0.05)
                break
            except _queue.Empty:
                if self._stop.is_set():
                    raise StopIteration
                if time.perf_counter() - t0 > self._timeout:
                    self.close()
                    raise MXNetError(
                        f"DevicePrefetcher: no batch arrived within "
                        f"{self._timeout}s (source iterator or device "
                        "placement is stuck); raise `timeout=` or debug "
                        "the input pipeline")
        wait = time.perf_counter() - t0
        self._wait_s += wait
        if kind == "item":
            self._batches += 1
            self._delivered += 1
            occ = self._q.qsize()
            self._occ_sum += occ
            if _tele.enabled():
                _tele.histogram(
                    "prefetch_wait_ms",
                    "Consumer wait per prefetched batch (ms); long waits "
                    "with low occupancy mean the source is the bottleneck"
                ).observe(wait * 1e3)
                _tele.gauge(
                    "prefetch_occupancy",
                    "Prefetch queue depth at hand-out (near depth = "
                    "prefetch is ahead)").set(occ)
                _tele.gauge(
                    "prefetch_pending",
                    "Batches pulled from the source but not yet "
                    "delivered to the consumer — the checkpoint-lag "
                    "window DataPipeline.state_at rewinds (docs/data.md)"
                ).set(self.pending())
            if _trace.enabled():
                _trace.get_tracer("data").record_span(
                    "prefetch.wait", t0, time.perf_counter(),
                    track="prefetch consumer", batch=self._delivered,
                    occupancy=occ)
            return payload
        self._exhausted = True
        self.close()
        if kind == "error":
            raise payload
        raise StopIteration

    def skip(self, n: int = 1) -> int:
        """Fast-forward: consume and drop up to `n` batches (device
        buffers are released immediately).  The recovery rollback path
        uses this to step a forward-only stream past the poison window —
        the batches that fed anomalies on the abandoned timeline — so the
        replay does not re-train on them.  Returns the number actually
        dropped (short when the source ends first)."""
        dropped = 0
        for _ in range(int(n)):
            try:
                next(self)
            except StopIteration:
                # only a genuinely exhausted source ends the skip; a
                # pipeline error (worker failure, placement fault, wait
                # timeout) propagates — swallowing it here would leave a
                # dead prefetcher whose root cause surfaces nowhere
                break
            dropped += 1
        if dropped and _tele.enabled():
            _tele.counter(
                "prefetch_skipped_batches",
                "Prefetched batches dropped by recovery fast-forward"
            ).inc(dropped)
        return dropped

    # -- lifecycle ------------------------------------------------------
    def _drain_queue(self):
        try:
            while True:
                self._q.get_nowait()
        except _queue.Empty:
            pass

    def close(self, timeout: float = 5.0):
        """Stop the prefetch thread and drop buffered batches. Idempotent;
        never hangs: the producer's puts are stop-aware, and the queue is
        drained so a blocked put wakes immediately.  Drained again AFTER
        the join: a producer woken from its blocked put can deposit one
        last batch after the first drain saw Empty — without the re-drain
        that device buffer would stay pinned in the dead queue."""
        self._stop.set()
        self._drain_queue()
        t = self._thread
        if t is not threading.current_thread() and t.is_alive():
            t.join(timeout)
        self._drain_queue()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close(timeout=0.2)
        except Exception:
            pass

    def pending(self) -> int:
        """Batches pulled from the source but not yet delivered to the
        consumer (buffered + in placement).  This is the gap between
        "where the source is" and "where training is" — exactly the
        number of batches `data.DataPipeline.state_at` rewinds when a
        checkpoint lands while the window is full (docs/data.md)."""
        return max(0, self._pulled - self._delivered)

    def stats(self) -> dict:
        """Pipeline health: {'depth', 'batches', 'mean_occupancy',
        'mean_wait_ms', 'pending'}. mean_occupancy near 0 with long waits
        means the source (not the consumer) is the bottleneck — raise
        depth or speed up the loader; occupancy near depth means prefetch
        is ahead."""
        n = max(1, self._batches)
        return {
            "depth": self._depth,
            "batches": self._batches,
            "mean_occupancy": round(self._occ_sum / n, 3),
            "mean_wait_ms": round(self._wait_s * 1e3 / n, 3),
            "pending": self.pending(),
        }


class AsyncMetricBuffer:
    """Deferred scalar-metric fetches: append async device scalars (or
    ``StepHandle``s), fetch them in ONE batched `device_get` every
    ``drain_every`` appends.  Between drains the host never blocks on the
    device, so up to ``drain_every`` steps stay in flight — the reference's
    ``metric.update`` every-k-batches idiom, made explicit.

    ``values`` holds the fetched floats in append order; ``drain()`` forces
    the fetch (call once after the loop).  ``max_in_flight`` records the
    deepest the pipeline ran — the bench reports it as ``steps_in_flight``.
    """

    def __init__(self, drain_every: int = 8):
        if drain_every < 1:
            raise MXNetError(
                f"drain_every must be >= 1, got {drain_every}")
        self.drain_every = int(drain_every)
        self._pending: list = []
        self.values: list = []
        self.max_in_flight = 0

    def append(self, value):
        self._pending.append(getattr(value, "loss", value))
        if len(self._pending) > self.max_in_flight:
            self.max_in_flight = len(self._pending)
        if len(self._pending) >= self.drain_every:
            self.drain()
        return self

    @property
    def in_flight(self) -> int:
        return len(self._pending)

    def drain(self) -> list:
        if self._pending:
            fetched = jax.device_get(self._pending)
            self.values.extend(float(v) for v in fetched)
            self._pending.clear()
        return self.values

    def mean(self, last_n: Optional[int] = None) -> float:
        vals = self.drain()
        if last_n is not None:
            vals = vals[-last_n:]
        if not vals:
            raise MXNetError("AsyncMetricBuffer.mean() on an empty buffer")
        return sum(vals) / len(vals)

    def __len__(self):
        return len(self.values) + len(self._pending)
