"""Pipeline parallelism over a 'pp' mesh axis (SPMD collective-permute
GPipe).

New capability beyond the reference (SURVEY.md §2.4: PP absent upstream).
TPU-native formulation — no per-stage processes or schedulers: all stages
run the SAME program under `shard_map`; each device holds one stage's
parameters (stacked on a leading stage dim, sharded over 'pp'), and a
`lax.scan` over ticks shifts in-flight microbatch activations one stage
forward per tick with `lax.ppermute`. After S + M - 1 ticks every
microbatch has flowed through all S stages. Differentiable end-to-end
(jax reverses the ppermutes in the backward pass), so it composes with
`jax.grad`/`jit` and the dp/tp/sp axes.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import shard_map_nocheck

from ..base import MXNetError

__all__ = ["pipeline_apply", "gpipe_sharded"]


def gpipe_sharded(stage_fn: Callable, stage_params, x_mb,
                  axis_name: str = "pp"):
    """Run microbatches through the stage pipeline — call INSIDE shard_map.

    stage_fn(params_leaf_tree, x) -> y, same activation shape in and out.
    stage_params: pytree whose leaves have a leading LOCAL stage dim of 1
      (the global stacked dim S is sharded over `axis_name`).
    x_mb: (M, ...) microbatched input, replicated over `axis_name`.
    Returns (M, ...) outputs of the LAST stage, replicated (psum-gathered).
    """
    from .collectives import axis_size
    s = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    m = x_mb.shape[0]
    params_local = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    is_first = my == 0
    is_last = my == s - 1

    perm = [(i, (i + 1) % s) for i in range(s)]
    # the scan carry must carry the 'pp'-varying manual-axes type (same
    # trick as ring_attention's carries): tie it to the local params
    seed = jax.tree_util.tree_leaves(params_local)[0]
    zero = jnp.zeros_like(x_mb[0]) + \
        (0.0 * jnp.sum(seed)).astype(x_mb.dtype)

    def tick(carry, t):
        inflight = carry                       # activation entering my stage
        # stage 0 ingests microbatch t while t < M; later stages take the
        # activation permuted from the previous stage
        mb_idx = jnp.clip(t, 0, m - 1)
        fed = jnp.where(is_first, x_mb[mb_idx], inflight)
        y = stage_fn(params_local, fed)
        # collect the last stage's result for microbatch t - (S - 1)
        out_valid = is_last & (t >= s - 1)
        out = jnp.where(out_valid, y, zero)
        nxt = lax.ppermute(y, axis_name, perm)
        return nxt, out

    _, outs = lax.scan(tick, zero, jnp.arange(s + m - 1))
    # outs[t] is microbatch t-(S-1) on the last stage, zero elsewhere —
    # select the valid window and broadcast to every stage
    outs = outs[s - 1:]
    return lax.psum(outs, axis_name) if s > 1 else outs


def pipeline_apply(stage_fn: Callable, stacked_params, x, mesh: Mesh,
                   num_microbatches: int, axis_name: str = "pp"):
    """Top-level GPipe: stage_fn(params, x)->y applied through S stages.

    stacked_params: pytree whose leaves have leading dim S (= size of the
      `axis_name` mesh axis) — stage i uses leaf[i].
    x: (B, ...) batch; B must divide into `num_microbatches`.
    Returns (B, ...) outputs of the final stage.
    """
    # make_mesh drops size-1 axes, so a degenerate pp=1 mesh has no
    # `axis_name` at all — run the single stage directly (microbatching
    # and the shard_map specs would otherwise name a nonexistent axis)
    if axis_name not in mesh.axis_names:
        single = jax.tree_util.tree_map(lambda leaf: leaf[0], stacked_params)
        for leaf in jax.tree_util.tree_leaves(stacked_params):
            if leaf.shape[0] != 1:
                raise MXNetError(
                    f"stacked parameter leading dim {leaf.shape[0]} != 1 "
                    f"but mesh has no {axis_name!r} axis")
        return stage_fn(single, x)
    s = mesh.shape[axis_name]
    b = x.shape[0]
    if b % num_microbatches != 0:
        raise MXNetError(f"batch {b} not divisible into "
                         f"{num_microbatches} microbatches")
    leaves = jax.tree_util.tree_leaves(stacked_params)
    for leaf in leaves:
        if leaf.shape[0] != s:
            raise MXNetError(
                f"stacked parameter leading dim {leaf.shape[0]} != pipeline "
                f"stages {s} (mesh axis {axis_name!r})")
    x_mb = x.reshape((num_microbatches, b // num_microbatches) +
                     tuple(x.shape[1:]))

    pspec = jax.tree_util.tree_map(
        lambda leaf: P(axis_name, *([None] * (leaf.ndim - 1))),
        stacked_params)
    # true data parallelism: shard the per-microbatch batch dim over 'dp'
    # when the mesh has it and it divides; otherwise replicate
    mb = b // num_microbatches
    if "dp" in mesh.axis_names and mesh.shape["dp"] > 1 \
            and mb % mesh.shape["dp"] == 0:
        xspec = P(None, "dp")
    else:
        xspec = P()
    fn = functools.partial(gpipe_sharded, stage_fn, axis_name=axis_name)
    mapped = shard_map_nocheck(fn, mesh, (pspec, xspec), xspec)
    out_mb = mapped(stacked_params, x_mb)
    return out_mb.reshape((b,) + tuple(out_mb.shape[2:]))
