"""Ring attention — sequence/context parallelism over the ICI ring.

Headline new capability (SURVEY.md §5.7: the reference has NO sequence
parallelism; its longest-context artifact is sliding-window attention,
`src/operator/contrib/transformer.cc:887-1095`). Design follows the public
ring-attention recipe: shard the sequence axis over the 'sp' mesh axis; each
device keeps its Q shard resident and streams K/V shards around the ring via
`lax.ppermute`, accumulating blockwise online-softmax partial results, so the
full (L, L) score matrix never exists and per-device memory is O(L/n · L/n).
Communication overlaps compute (XLA schedules the ppermute alongside the
block matmuls).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import shard_map_nocheck

__all__ = ["ring_attention", "ring_attention_sharded", "seq_sharded_call"]

NEG_INF = -1e30


def _block_attn(q, k, v, mask):
    """Unnormalised blockwise attention: returns (acc, m, l).

    q: (B,H,Lq,D); k,v: (B,Hkv,Lk,D) with Hkv == H or Hkv == g < H (GQA:
    each kv head serves H//g query heads — K/V ride the ring at g heads,
    an ICI-bandwidth saving of H/g on top of the memory one).  `mask` is
    broadcastable (B,1|H,1|Lq,Lk) or None.  Masked entries contribute
    exactly zero (a fully-masked row yields l = 0 → zero output),
    matching the flash kernel's masked-softmax semantics."""
    b, h, lq, dd = q.shape
    g, lk = k.shape[1], k.shape[2]
    if g != h and (g == 0 or h % g):
        raise ValueError(f"query heads ({h}) must be a multiple of kv "
                         f"heads ({g})")
    if g != h:
        rep = h // g
        qg = q.reshape(b, g, rep, lq, dd)
        s = jnp.einsum("bgrqd,bgkd->bgrqk", qg, k,
                       preferred_element_type=jnp.float32)
        s = s.reshape(b, h, lq, lk)
    else:
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                       preferred_element_type=jnp.float32)
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)                      # (B,H,Lq)
    p = jnp.exp(s - m[..., None])
    if mask is not None:
        # an all-masked row has m == NEG_INF and would otherwise give
        # p == 1 uniformly (the exp(NEG_INF - NEG_INF) trap)
        p = jnp.where(s > 0.5 * NEG_INF, p, 0.0)
    l = jnp.sum(p, axis=-1)                      # (B,H,Lq)
    if g != h:
        pg = p.reshape(b, g, h // g, lq, lk)
        acc = jnp.einsum("bgrqk,bgkd->bgrqd", pg,
                         v.astype(jnp.float32)).reshape(b, h, lq, dd)
    else:
        acc = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return acc, m, l


def ring_attention_sharded(q, k, v, kv_mask=None, axis_name: str = "sp",
                           causal: bool = False, scale: Optional[float] = None):
    """Attention over sequence-sharded q/k/v — call INSIDE shard_map.

    q, k, v: local shards (B, H, L_local, D); the sequence axis is sharded
    over `axis_name`. `kv_mask` is the LOCAL key-validity shard (B,
    L_local) bool — it rides the ring alongside its keys, so padded
    long-context batches stay O(L/n · L/n) per device. Returns the local
    output shard (B, H, L_local, D).
    """
    from .collectives import axis_size
    n = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    q = (q * s).astype(q.dtype)
    lq = q.shape[2]
    b, h = q.shape[0], q.shape[1]

    # init carries as data-dependent on q so they carry the same
    # varying-manual-axes ('sp') type as the scan body's outputs
    zq = (q * 0).astype(jnp.float32)
    m0 = zq[..., 0] + NEG_INF
    l0 = zq[..., 0]
    acc0 = zq
    has_mask = kv_mask is not None
    # the dummy all-valid mask derives from k so it carries the same
    # sp-varying manual-axes type as the rotated carries (see zq above)
    mk0 = kv_mask if has_mask else (k[:, 0, :, 0] * 0 == 0)

    def step(carry, t):
        acc, m, l, kk, vv, mk = carry
        src = (my - t) % n  # which global shard kk currently holds
        mask = None
        if causal:
            qpos = my * lq + jnp.arange(lq)
            kpos = src * kk.shape[2] + jnp.arange(kk.shape[2])
            mask = (qpos[:, None] >= kpos[None, :])[None, None]
        if has_mask:
            kvm = mk[:, None, None, :]           # (B,1,1,Lk)
            mask = kvm if mask is None else (mask & kvm)
        a, bm, bl = _block_attn(q, kk, vv, mask)
        m_new = jnp.maximum(m, bm)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(bm - m_new)
        l = l * alpha + bl * beta
        acc = acc * alpha[..., None] + a * beta[..., None]
        m = m_new
        # rotate k/v (+ their validity mask) to the next device (skip the
        # final rotate's result use, but keep it unconditional so the comm
        # schedule is static)
        perm = [(i, (i + 1) % n) for i in range(n)]
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        mk = lax.ppermute(mk, axis_name, perm)
        return (acc, m, l, kk, vv, mk), None

    (acc, m, l, _, _, _), _ = lax.scan(step, (acc0, m0, l0, k, v, mk0),
                                       jnp.arange(n))
    # explicit zero guard: a subnormal epsilon (1e-38) flushes to zero
    # under f32 FTZ, turning fully-masked rows into 0/0 = NaN
    out = acc / jnp.where(l[..., None] > 0, l[..., None], 1.0)
    return out.astype(q.dtype)


def seq_sharded_call(fn, q, k, v, mesh: Mesh, axis_name: str = "sp",
                     batch_axis: Optional[str] = "dp"):
    """shard_map a per-shard attention fn over (B, H, L, D) arrays with L
    sharded on `axis_name` (and B on `batch_axis` when present). Shared by
    the ring and Ulysses sequence-parallel strategies."""
    axes = set(mesh.axis_names)
    bspec = batch_axis if (batch_axis and batch_axis in axes) else None
    spec = P(bspec, None, axis_name, None)
    mapped = shard_map_nocheck(fn, mesh, (spec, spec, spec), spec)
    return mapped(q, k, v)


def ring_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                   causal: bool = False, scale: Optional[float] = None,
                   batch_axis: Optional[str] = "dp", kv_mask=None):
    """Top-level ring attention over (B, H, L, D) jax arrays; composes
    under jit/pjit. `kv_mask` is a (B, L) bool key-validity mask (padded
    long-context batches), sequence-sharded like k/v."""
    if kv_mask is None:
        fn = functools.partial(ring_attention_sharded, axis_name=axis_name,
                               causal=causal, scale=scale)
        return seq_sharded_call(fn, q, k, v, mesh, axis_name, batch_axis)
    axes = set(mesh.axis_names)
    bspec = batch_axis if (batch_axis and batch_axis in axes) else None
    spec = P(bspec, None, axis_name, None)
    mspec = P(bspec, axis_name)

    def fn(qq, kk, vv, mm):
        return ring_attention_sharded(qq, kk, vv, kv_mask=mm,
                                      axis_name=axis_name, causal=causal,
                                      scale=scale)

    mapped = shard_map_nocheck(fn, mesh, (spec, spec, spec, mspec), spec)
    return mapped(q, k, v, kv_mask)
