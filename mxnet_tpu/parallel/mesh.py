"""Device-mesh construction for dp/tp/sp/pp/ep parallelism.

New first-class capability (SURVEY.md §2.4): the reference scales only by data
parallelism (KVStore comm trees / ps-lite); here every strategy is a named
mesh axis consumed by `NamedSharding` rules and `shard_map` collectives:

- 'dp' — data parallel (batch axis; gradient psum rides ICI)
- 'tp' — tensor parallel (Dense/attention weight sharding)
- 'sp' — sequence/context parallel (ring attention over `ppermute`)
- 'pp' — pipeline stages (shard_map + collective_permute microbatching)
- 'ep' — expert parallel (MoE all-to-all)
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as _onp
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..base import MXNetError

__all__ = ["make_mesh", "auto_mesh", "MeshConfig", "Mesh", "NamedSharding",
           "shard_map_nocheck", "fit_axes",
           "PartitionSpec"]

AXES = ("dp", "sp", "tp", "pp", "ep")


def fit_axes(n_devices: int, tp: int = 1, sp: int = 1, pp: int = 1,
             ep: int = 1) -> Dict[str, int]:
    """Clamp a model-axis plan to a (possibly changed) device count —
    the elastic-reform companion to `auto_mesh`: each requested model
    axis is reduced to its largest divisor compatible with the devices
    that remain (gcd), claimed in tp → sp → pp → ep order, and dp
    absorbs whatever is left.  ``fit_axes(4, tp=2)`` keeps tp=2 with
    dp=2; ``fit_axes(3, tp=2)`` degrades to tp=1, dp=3 — the mesh
    re-forms at ANY surviving device count instead of refusing."""
    import math
    out: Dict[str, int] = {}
    rem = int(n_devices)
    if rem < 1:
        raise MXNetError(f"fit_axes needs >= 1 device, got {n_devices}")
    for name, want in (("tp", tp), ("sp", sp), ("pp", pp), ("ep", ep)):
        got = math.gcd(max(int(want), 1), rem)
        out[name] = got
        rem //= got
    out["dp"] = rem
    return out


class MeshConfig:
    def __init__(self, dp: int = 1, sp: int = 1, tp: int = 1, pp: int = 1,
                 ep: int = 1):
        self.sizes = {"dp": dp, "sp": sp, "tp": tp, "pp": pp, "ep": ep}

    @property
    def n_devices(self) -> int:
        n = 1
        for v in self.sizes.values():
            n *= v
        return n

    def axis_names(self) -> Tuple[str, ...]:
        return tuple(a for a in AXES if self.sizes[a] > 1) or ("dp",)


def make_mesh(axis_sizes: Dict[str, int], devices=None) -> Mesh:
    """Build a Mesh with named axes from {'dp': 4, 'tp': 2, ...}."""
    devices = list(devices if devices is not None else jax.devices())
    names = [a for a in AXES if axis_sizes.get(a, 1) > 1]
    if not names:
        names = ["dp"]
        axis_sizes = {"dp": len(devices)}
    shape = [axis_sizes[a] for a in names]
    total = int(_onp.prod(shape))
    if total != len(devices):
        raise MXNetError(f"mesh {dict(zip(names, shape))} needs {total} "
                         f"devices, got {len(devices)}")
    arr = _onp.array(devices).reshape(shape)
    return Mesh(arr, tuple(names))


def auto_mesh(n_devices: Optional[int] = None, tp: int = 1, sp: int = 1,
              pp: int = 1, ep: int = 1, devices=None) -> Mesh:
    """Mesh with dp absorbing whatever is left after tp/sp/pp/ep."""
    devices = list(devices if devices is not None else jax.devices())
    n = n_devices or len(devices)
    denom = tp * sp * pp * ep
    if n % denom:
        raise MXNetError(f"{n} devices not divisible by tp*sp*pp*ep={denom}")
    return make_mesh({"dp": n // denom, "sp": sp, "tp": tp, "pp": pp,
                      "ep": ep}, devices[:n])


def shard_map_nocheck(fn, mesh, in_specs, out_specs):
    """`shard_map` with the vma/replication checker off: the Pallas flash
    kernel's `pallas_call` output ShapeDtypeStructs carry no `vma`
    annotation, which jax's `check_vma=True` default rejects inside a
    mapped body (the kernel would silently fall back to O(L²) reference
    attention on the SP path). Single switch point for every SP/PP
    shard_map in the package; older jax without the kwarg falls through.

    TRADE-OFF (ADVICE r3): the switch is body-wide — it also silences
    the replication checker for the collectives surrounding the kernel
    call, so an out_specs/replication bug in an SP/PP body surfaces as
    wrong numerics, not a trace-time error.  jax has no narrower scope
    today; the compensating control is tests that pin numerics against
    the single-device path (tests/unittest/test_parallel.py ring/Ulysses
    equivalence, tests/dist/).  Revisit if jax grows per-region vma
    control."""
    try:
        from jax import shard_map
    except ImportError:
        # jax 0.4.x keeps shard_map under experimental (the top-level
        # name landed later) — this was the "shard_map incompat" tier-1
        # failure class carried since the seed
        from jax.experimental.shard_map import shard_map
    try:
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
    except TypeError:
        pass
    try:
        # jax 0.4.x spells the same switch check_rep (the rename came
        # with the vma terminology); without it the Pallas flash kernel
        # trips "No replication rule for pallas_call" under shard_map
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)
    except TypeError:
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs)
