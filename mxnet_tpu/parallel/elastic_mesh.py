"""Elastic mesh reformation: survive host loss and host join without a
restart (ROADMAP item 4 — the piece that turns PR 5's "hard to kill on a
fixed topology" into "hard to kill, period").

The reference framework's ps-lite KVStore tolerates worker churn — a
data-parallel job keeps training when a worker drops — but a GSPMD mesh
is frozen at construction: until this layer, a single preempted host
turned the whole multi-host job into a cold restart.  This module closes
that gap with three cooperating pieces:

* **Topology-change detection** — a heartbeat/membership layer
  (:class:`ElasticMeshController`).  Three signals feed it:

  1. a **heartbeat** that goes stale past ``MXTPU_ELASTIC_HEARTBEAT``
     seconds (host loss — preemption without notice, kernel panic),
  2. a **suspected host loss** surfaced by any timeout-bounded
     coordination round (`elastic.sync_flags` / `recovery.agree_step` /
     :func:`member_sync` now raise `SuspectedHostLoss` instead of
     stalling until the hang watchdog fires),
  3. an **explicit request** — a planned drain (`request_leave`) or a
     capacity join (`request_join`).

* **Re-sharding** — `ShardedTrainStep.reshard(new_mesh)`: drain
  in-flight step handles, gather the full param + optimizer-state tree
  to host, re-run `ShardingRules` (and the ZeRO dp-absorption / 1-D
  bucket planning) against the new axes, re-place, and reset the
  compiled step so ``trace_count`` restarts cleanly on the new topology.
  For host loss the live gather is impossible (the dead host's shards
  are gone), so the reform re-plans placements only
  (``reshard(gather=False)``) and restores the multi-host **agreed
  step** through `CheckpointManager`'s topology-agnostic restore path —
  checkpoints always store logical (unsharded, unpadded) values.

* **Resumption** — :meth:`ElasticMeshController.reform` returns the step
  to resume from; `ElasticLoop.run` (``mesh_controller=``) consumes
  topology changes between steps exactly like recovery remediations.

**Host simulation.**  jax's multi-controller runtime cannot today admit
a NEW process into an initialized distributed job, so true process-level
join still needs the cluster scheduler.  What this layer makes
restart-free is everything else: the mesh, the sharded state, and the
compiled step re-form **in process** at the new device count.  The
controller therefore models membership as *named hosts owning device
lists* — on a real multi-host job each process registers its own
addressable devices; in tests and the ``elastic-smoke`` chaos run the
hosts are simulated partitions of one process's devices, which exercises
the identical control path (detect → drain → re-shard → agree → resume).

Fault points: ``member_sync`` (the membership round), ``mesh_reform``
(entering `reshard`), ``reshard_gather`` (the host gather inside it).
See docs/resilience.md ("Elastic scale-out").
"""
from __future__ import annotations

import logging
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

from ..base import MXNetError, SuspectedHostLoss
from ..resilience import fault_point
from .. import recovery as _recovery
from .. import telemetry as _tele
from .. import tracing as _trace
from .mesh import Mesh, fit_axes, make_mesh

__all__ = ["ElasticMeshController", "TopologyChange", "MemberView",
           "member_sync", "heartbeat_timeout", "min_devices",
           "ENV_HEARTBEAT", "ENV_MIN_DEVICES"]

_log = logging.getLogger(__name__)

ENV_HEARTBEAT = "MXTPU_ELASTIC_HEARTBEAT"
ENV_MIN_DEVICES = "MXTPU_ELASTIC_MIN_DEVICES"

DEFAULT_HEARTBEAT = 60.0


def heartbeat_timeout() -> float:
    """``MXTPU_ELASTIC_HEARTBEAT`` parsed to seconds (default 60): how
    stale a host's heartbeat may grow before the controller declares it
    lost.  0/negative/invalid falls back to the default."""
    raw = os.environ.get(ENV_HEARTBEAT, "").strip()
    if not raw:
        return DEFAULT_HEARTBEAT
    try:
        val = float(raw)
    except ValueError:
        _log.warning("ignoring non-numeric %s=%r", ENV_HEARTBEAT, raw)
        return DEFAULT_HEARTBEAT
    return val if val > 0 else DEFAULT_HEARTBEAT


def min_devices() -> int:
    """``MXTPU_ELASTIC_MIN_DEVICES`` (default 1): the floor below which
    a reform refuses to shrink — losing your last tp group is a job
    failure, not an elasticity event."""
    raw = os.environ.get(ENV_MIN_DEVICES, "").strip()
    if not raw:
        return 1
    try:
        return max(1, int(raw))
    except ValueError:
        _log.warning("ignoring non-integer %s=%r", ENV_MIN_DEVICES, raw)
        return 1


class MemberView:
    """Result of one membership round: how many processes answered and
    the OR-reduced join/leave intents."""

    __slots__ = ("processes", "alive", "join", "leave")

    def __init__(self, processes: int, alive: bool = True,
                 join: bool = False, leave: bool = False):
        self.processes = int(processes)
        self.alive = bool(alive)
        self.join = bool(join)
        self.leave = bool(leave)

    def __repr__(self):
        return (f"MemberView(processes={self.processes}, "
                f"join={self.join}, leave={self.leave})")


def member_sync(alive: bool = True, join: bool = False,
                leave: bool = False,
                timeout: Optional[float] = None) -> MemberView:
    """One membership round across all processes: everyone contributes
    ``(alive, join, leave)``; the reduce is an OR per flag.  Layered on
    the PR-5 packed-collective flag sync, with the crucial difference
    that the round is **timeout-bounded** (default
    ``MXTPU_ELASTIC_SYNC_TIMEOUT``): a peer that never enters the
    collective surfaces as `SuspectedHostLoss` — the topology-change
    signal — instead of a silent stall only the hang watchdog can see.

    Single-process: identity (the simulated-host registry in
    `ElasticMeshController` carries membership instead)."""
    fault_point("member_sync")
    import jax
    if jax.process_count() == 1:
        return MemberView(1, alive, join, leave)
    if timeout is None:
        timeout = _recovery.sync_timeout()

    def _gather():
        import numpy as onp
        import jax.numpy as jnp
        from jax.experimental import multihost_utils
        v = onp.asarray(multihost_utils.process_allgather(
            jnp.asarray([1 if alive else 0, 1 if join else 0,
                         1 if leave else 0])))
        v = v.reshape(-1, 3)
        return MemberView(v.shape[0], bool(v[:, 0].max()),
                          bool(v[:, 1].max()), bool(v[:, 2].max()))

    try:
        return _recovery.coordinated_round(
            _gather, timeout=timeout, name="mxtpu-member-sync",
            timeout_msg=
            f"elastic_mesh.member_sync: membership round did not complete "
            f"within {timeout or 0:g}s — a peer host is suspected lost")
    except SuspectedHostLoss:
        raise
    except Exception as e:
        raise MXNetError(
            f"elastic_mesh.member_sync: membership round failed "
            f"({e})") from e


class TopologyChange:
    """One detected topology transition, consumed by :meth:`reform`.

    ``kind``: ``"shrink"`` or ``"grow"``; ``reason``: ``"host_loss"``,
    ``"suspected_host_loss"``, ``"host_leave"`` (planned drain) or
    ``"host_join"``; ``hosts``: the host names involved; ``devices``:
    the device list of the NEW topology; ``live``: whether the old
    state is fully gatherable (planned transitions) or must come from a
    checkpoint (loss)."""

    __slots__ = ("kind", "reason", "hosts", "devices", "live")

    def __init__(self, kind: str, reason: str, hosts: Sequence[str],
                 devices: list, live: bool):
        self.kind = kind
        self.reason = reason
        self.hosts = tuple(hosts)
        self.devices = list(devices)
        self.live = bool(live)

    def __repr__(self):
        return (f"TopologyChange({self.kind}, reason={self.reason}, "
                f"hosts={list(self.hosts)}, "
                f"n_devices={len(self.devices)}, live={self.live})")


class _Host:
    __slots__ = ("name", "devices", "alive", "last_beat")

    def __init__(self, name: str, devices: list):
        self.name = name
        self.devices = list(devices)
        self.alive = True
        self.last_beat = time.monotonic()


class ElasticMeshController:
    """Detect topology changes and re-form a `ShardedTrainStep`'s mesh.

    ``hosts`` maps host names to the devices they own (ordered; the mesh
    is rebuilt over the concatenation of live hosts' devices in
    registration order).  Defaults to one host ``"host0"`` owning the
    step's current devices — the controller then only reacts to explicit
    requests and suspected-loss notes.

    ``manager`` (a `CheckpointManager`) is required for the host-loss
    path — state that died with a host can only come back from a
    checkpoint; `ElasticLoop` wires its own manager in automatically.

    The model-axis plan (tp/sp/pp/ep) defaults to the step's current
    mesh and is re-fit to every new device count via
    `mesh.fit_axes` — dp absorbs whatever the surviving model axes
    leave over, so the mesh re-forms at ANY surviving count.

    Thread-safety: `heartbeat`, `request_*`, and `note_suspected_loss`
    may be called from any thread (signal handlers, watchdog callbacks);
    `poll`/`reform` belong to the training loop's thread.
    """

    def __init__(self, step, manager=None,
                 hosts: Optional[Dict[str, list]] = None,
                 heartbeat_timeout_s: Optional[float] = None,
                 axis_plan: Optional[Dict[str, int]] = None,
                 min_devices_n: Optional[int] = None):
        self.step = step
        self.manager = manager
        self._lock = threading.Lock()
        self._hosts: Dict[str, _Host] = {}
        if hosts:
            for name, devs in hosts.items():
                self._hosts[name] = _Host(name, devs)
        else:
            self._hosts["host0"] = _Host(
                "host0", list(step.mesh.devices.flat))
        self.heartbeat_timeout_s = (
            heartbeat_timeout() if heartbeat_timeout_s is None
            else float(heartbeat_timeout_s))
        self.min_devices = (min_devices() if min_devices_n is None
                            else max(1, int(min_devices_n)))
        if axis_plan is None:
            shape = dict(step.mesh.shape)
            axis_plan = {a: int(shape.get(a, 1))
                         for a in ("tp", "sp", "pp", "ep")}
        self.axis_plan = dict(axis_plan)
        self._pending: List[TopologyChange] = []
        self._all_stale_since: Optional[float] = None
        self.reforms = 0

    # -- membership signals ----------------------------------------------
    def heartbeat(self, host: str) -> None:
        """A host (or its health monitor) reports liveness."""
        with self._lock:
            h = self._hosts.get(host)
            if h is not None:
                h.last_beat = time.monotonic()

    def hosts(self) -> Dict[str, bool]:
        """{host: alive} snapshot."""
        with self._lock:
            return {n: h.alive for n, h in self._hosts.items()}

    def live_devices(self) -> list:
        with self._lock:
            return [d for h in self._hosts.values() if h.alive
                    for d in h.devices]

    def request_join(self, host: str, devices: Optional[list] = None) -> None:
        """A host (back) in service: re-form the mesh to include its
        devices.  `devices` is required the first time a host is seen;
        a re-join reuses the registered list."""
        with self._lock:
            h = self._hosts.get(host)
            if h is None:
                if devices is None:
                    raise MXNetError(
                        f"elastic_mesh: unknown host {host!r} joining "
                        f"without a device list")
                h = self._hosts[host] = _Host(host, devices)
                h.alive = False
            elif devices is not None:
                h.devices = list(devices)
            if h.alive:
                return  # already in the mesh
            h.alive = True
            h.last_beat = time.monotonic()
            new = [d for hh in self._hosts.values() if hh.alive
                   for d in hh.devices]
            self._pending.append(TopologyChange(
                "grow", "host_join", (host,), new, live=True))
        self._note_membership(host, "join")

    def request_leave(self, host: str) -> None:
        """Planned drain (e.g. a maintenance notice): shrink the mesh
        with a LIVE reshard — state is gathered before the host goes."""
        self._mark_lost(host, "host_leave", live=True)

    def note_suspected_loss(self, host: Optional[str] = None,
                            exc: Optional[BaseException] = None) -> None:
        """A bounded coordination round timed out (`SuspectedHostLoss`).
        With a host name, that host is declared lost; without one, every
        host whose heartbeat is already stale is — a timeout with no
        stale heartbeat stays queued as evidence but triggers nothing
        (poll returns None and the caller re-raises)."""
        if host is not None:
            self._mark_lost(host, "suspected_host_loss", live=False)
            return
        stale = self._stale_hosts()
        for name in stale:
            self._mark_lost(name, "suspected_host_loss", live=False)
        if not stale:
            _log.warning(
                "elastic_mesh: suspected host loss (%s) but no stale "
                "heartbeat to attribute it to; not reforming", exc)

    def _stale_hosts(self) -> List[str]:
        if self.heartbeat_timeout_s <= 0:
            return []
        now = time.monotonic()
        with self._lock:
            alive = [h for h in self._hosts.values() if h.alive]
            stale = [h.name for h in alive
                     if now - h.last_beat > self.heartbeat_timeout_s]
            # never declare EVERY host lost: the one running this code
            # is alive by construction.  EVERY beat lapsing at once is
            # the signature of a local pause (reform, restore, compile,
            # GC) rather than mass death — and right after one, beat
            # timestamps are near-identical, so any immediate pick risks
            # sparing the corpse.  Defer one full window instead: the
            # live hosts beat again, the dead one stays stale, and the
            # NEXT round names it.  Only if staleness stays unanimous a
            # whole extra window (nobody is pumping beats at all) fall
            # back to sparing the freshest-beating host
            if stale and len(stale) == len(alive):
                if self._all_stale_since is None:
                    self._all_stale_since = now
                    return []
                if now - self._all_stale_since <= self.heartbeat_timeout_s:
                    return []
                freshest = max(alive, key=lambda h: h.last_beat).name
                stale = [n for n in stale if n != freshest]
            self._all_stale_since = None
        return stale

    def _mark_lost(self, host: str, reason: str, live: bool) -> None:
        with self._lock:
            h = self._hosts.get(host)
            if h is None or not h.alive:
                return
            h.alive = False
            new = [d for hh in self._hosts.values() if hh.alive
                   for d in hh.devices]
            if len(new) < self.min_devices:
                h.alive = True  # refuse: below the survivable floor
                raise MXNetError(
                    f"elastic_mesh: losing host {host!r} leaves "
                    f"{len(new)} device(s) < MXTPU_ELASTIC_MIN_DEVICES="
                    f"{self.min_devices}; cannot re-form")
            self._pending.append(TopologyChange(
                "shrink", reason, (host,), new, live=live))
        self._note_membership(host, reason)

    def _note_membership(self, host: str, change: str) -> None:
        if _tele.enabled():
            _tele.event("membership", host=host, change=change)
        _log.warning("elastic_mesh: membership change — host %s: %s",
                     host, change)

    # -- the poll/reform cycle -------------------------------------------
    def has_pending(self) -> bool:
        """Peek: fold stale heartbeats into the membership and report
        whether a topology change is queued — WITHOUT consuming it.
        `ElasticLoop` packs this into the per-iteration flag sync so
        every host agrees a reform is due before any host enters
        `reform()`'s collectives."""
        for name in self._stale_hosts():
            self._mark_lost(name, "host_loss", live=False)
        with self._lock:
            return bool(self._pending)

    def poll(self) -> Optional[TopologyChange]:
        """Consume the next pending topology change, first folding in
        hosts whose heartbeat went stale.  Consecutive pending changes
        collapse into one (the LAST pending change's device list already
        reflects every membership edit)."""
        if not self.has_pending():
            return None
        with self._lock:
            if not self._pending:
                return None
            pending, self._pending = self._pending, []
        if len(pending) == 1:
            return pending[0]
        last = pending[-1]
        live = all(c.live for c in pending)
        kind = ("shrink" if len(last.devices)
                < self.step.mesh.size else "grow")
        return TopologyChange(
            kind, "+".join(dict.fromkeys(c.reason for c in pending)),
            tuple(h for c in pending for h in c.hosts),
            last.devices, live)

    def plan_mesh(self, devices: list) -> Mesh:
        """Build the new mesh: model axes re-fit to the device count
        (`fit_axes` — gcd clamp, dp absorbs the rest)."""
        axes = fit_axes(len(devices), **self.axis_plan)
        return make_mesh(axes, devices)

    def reform(self, change: TopologyChange,
               current_step: int) -> int:
        """Execute one topology change; returns the step to resume from.

        Planned/live transitions reshard the live state and resume at
        `current_step`; loss transitions re-plan placements, agree the
        restore step across hosts (`recovery.agree_step` min-reduce over
        each host's newest checkpoint), and restore it through the
        topology-agnostic checkpoint path.  Either way the caller's loop
        continues without a process restart and the next dispatch traces
        exactly once on the new topology."""
        t0 = time.monotonic()
        new_mesh = self.plan_mesh(change.devices)
        old = self.step.topology()
        live = change.live
        # reform phase spans (mx.tracing): the lexical "elastic.reform"
        # root nests member_sync/restore here plus the drain/gather
        # spans ShardedTrainStep.reshard opens on the same tracer+thread
        r_span = _trace.get_tracer("elastic").span(
            "elastic.reform", track="elastic", kind=change.kind,
            reason=change.reason, step=int(current_step)) \
            if _trace.enabled() else None
        try:
            resume, live = self._reform_body(change, current_step,
                                             new_mesh, live)
        except BaseException:
            if r_span is not None:
                r_span.__exit__(*sys.exc_info())
            raise
        if r_span is not None:
            r_span.set_tag("resume_step", resume)
            r_span.__exit__(None, None, None)
        elapsed = time.monotonic() - t0
        if _tele.enabled():
            _tele.counter(
                "elastic_reforms_total",
                "Mesh reformations executed (shrink/grow)",
                labelnames=("kind",)).inc(kind=change.kind)
            _tele.event("mesh_reform", step=resume, kind=change.kind,
                        reason=change.reason, hosts=list(change.hosts),
                        old_axes=old["axes"],
                        new_axes=self.step.topology()["axes"],
                        live=live, from_step=int(current_step),
                        elapsed_s=round(elapsed, 3))
        _log.warning(
            "elastic_mesh: %s reform (%s) %s -> %s in %.2fs; resuming at "
            "step %d%s", change.kind, change.reason, old["axes"],
            self.step.topology()["axes"], elapsed, resume,
            "" if live else " (restored from checkpoint)")
        return resume

    def _reform_body(self, change: TopologyChange, current_step: int,
                     new_mesh: Mesh, live: bool) -> tuple:
        """The phases of one reform; returns ``(resume_step, live)``
        (`live` can degrade to a live gather below)."""
        tr = _trace.get_tracer("elastic") if _trace.enabled() else None
        # membership barrier: every process must enter the reform
        # together (single-process: identity).  A peer that never shows
        # up here means the runtime cannot collectivize at all — surface
        # that as the restart case below rather than deadlocking in the
        # reshard collectives
        try:
            if tr is not None:
                with tr.span("elastic.member_sync", kind=change.kind):
                    member_sync(join=change.kind == "grow",
                                leave=change.kind == "shrink")
            else:
                member_sync(join=change.kind == "grow",
                            leave=change.kind == "shrink")
        except SuspectedHostLoss as e:
            raise MXNetError(
                f"elastic_mesh: the {change.kind} reform's membership "
                f"round timed out — the surviving processes cannot "
                f"collectivize without the lost peer (jax collectives "
                f"span the full initialized process set).  Cross-process "
                f"loss cannot re-form in place: restart the job and every "
                f"host resumes from its newest checkpoint.  In-process "
                f"reformation covers hosts simulated as device "
                f"partitions of live processes") from e
        if not live and self.manager is None:
            _log.warning(
                "elastic_mesh: host-loss reform without a checkpoint "
                "manager; falling back to a live gather (single-process "
                "simulations only — on a real multi-host job the dead "
                "host's shards are gone)")
            live = True
        if not live and self.manager.latest() is None:
            # nothing durable yet: a live gather is strictly better than
            # refusing (the simulated-loss case; a real dead host means
            # the job had no checkpoint to lose either)
            _log.warning("elastic_mesh: no checkpoint on disk for the "
                         "host-loss reform; gathering live state")
            live = True
        self.step.reshard(new_mesh, gather=live)
        if live:
            resume = int(current_step)
        else:
            newest = self.manager.latest()
            try:
                agreed = _recovery.agree_step(newest[0])
            except SuspectedHostLoss as e:
                raise MXNetError(
                    f"elastic_mesh: the restore-step consensus timed out "
                    f"mid-reform — a peer process died and the runtime "
                    f"cannot collectivize without it.  Restart the job; "
                    f"every host resumes from its newest checkpoint") \
                    from e
            fault_point("rollback_restore")
            if tr is not None:
                with tr.span("elastic.restore", step=agreed):
                    resume = self.manager.restore(self.step, step=agreed)
            else:
                resume = self.manager.restore(self.step, step=agreed)
            # checkpoints newer than the agreed step belong to the
            # pre-loss timeline (old mesh, possibly ahead of peers): a
            # crash before the next periodic save must not resume INTO
            # the state we just reformed away from (mirrors the tier-2
            # rollback path)
            self.manager.discard_newer(resume)
        self.reforms += 1
        # the reform itself (gather, re-place, restore) can outlast the
        # heartbeat budget, and every host in the new mesh is current as
        # of this decision — refresh their beats so reform latency is
        # never misread as a fresh loss
        now = time.monotonic()
        with self._lock:
            for h in self._hosts.values():
                if h.alive:
                    h.last_beat = now
        return resume, live
