"""Sharded training step — the GSPMD replacement for the reference's
KVStore data-parallel pipeline (`src/kvstore/`, `gluon/trainer.py` push/pull).

One jitted function carries forward + backward + optimizer update for the
whole model, with parameters/optimizer state laid out by `ShardingRules` over
a named mesh (dp/tp/sp/...). XLA inserts the gradient psum over 'dp'
(all-reduce riding ICI), TP collectives around row/column-parallel matmuls,
and ring-attention ppermutes when sequence parallelism is active. Buffers are
donated, so weights update in place — the `static_alloc` end-state.
"""
from __future__ import annotations

import collections
import concurrent.futures as _cf
import functools
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import logging

import numpy as onp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError
from ..gluon.block import Block, functional_call
from ..gluon.parameter import Parameter
from ..optimizer import Optimizer
from ..ops.fused_optim import HpScalarCache
from ..ops.pallas import fused_optimizer as _fused_opt
from .. import health as _health
from .. import profiler as _profiler
from .. import recovery as _recovery
from .. import telemetry as _tele
from .. import tracing as _trace
from .sharding import ShardingRules, default_tp_rules

__all__ = ["ShardedTrainStep", "StepHandle", "make_sharded_train_step"]

_log = logging.getLogger(__name__)


def _spec_axes(spec):
    """Flatten a PartitionSpec's entries to the set of mesh-axis names."""
    return {a for e in spec
            for a in ((e,) if isinstance(e, str) else (e or ()))}


def _put_global(x, sharding):
    """Place a host value onto a (possibly multi-process) sharding.

    Single-process meshes use plain device_put. When the mesh spans
    processes (SURVEY §5.8: one controller per host, SPMD over the global
    mesh), every process holds the identical GLOBAL value and contributes
    its addressable shards — the multi-controller idiom that replaces the
    reference's worker-local batch + ps-lite aggregation."""
    if getattr(sharding, "is_fully_addressable", True):
        return jax.device_put(x, sharding)
    arr = onp.asarray(x)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


class ShardedTrainStep:
    """Compiled data/tensor/sequence-parallel training step for a Gluon block.

    loss_fn(out, *batch_rest) -> scalar jax value, where `out` is the
    block's (jax-valued) output tree.
    """

    def __init__(self, block: Block, optimizer: Optimizer,
                 loss_fn: Callable, mesh: Mesh,
                 rules: Optional[ShardingRules] = None,
                 batch_specs: Optional[Tuple] = None,
                 num_model_args: Optional[int] = None,
                 grad_accum_dtype=jnp.float32, grad_accum: int = 1,
                 zero: bool = False, fsdp: bool = False,
                 donate: bool = True, grad_compress: Optional[str] = None):
        # ZeRO stage 1: shard optimizer state over the 'dp' axis instead
        # of replicating it (params stay replicated; XLA inserts the
        # reduce-scatter/all-gather around the sharded update). Cuts
        # optimizer-state HBM by the dp degree — for Adam on bf16 weights
        # that's 4x the weight bytes saved per extra dp shard.
        self.zero = zero
        # donate=True (default) updates weights in place — the
        # static_alloc end-state, halving peak param+state HBM.  CPU
        # caveat: the CPU runtime blocks a dispatch whose DONATED input is
        # still the in-flight output of the previous step, serializing
        # back-to-back dispatch()es; donate=False restores deep host-side
        # pipelining there (at 2x transient param footprint) — the CPU
        # overlap smoke uses it (docs/perf.md).
        self.donate = donate
        # FSDP (ZeRO stage 3): ALSO shard the parameters themselves over
        # 'dp' (first free divisible dim); XLA all-gathers each weight
        # just-in-time at its use and keeps gradients reduce-scattered.
        # Implies zero (sharded params get matching sharded state).
        self.fsdp = fsdp
        if fsdp:
            self.zero = True
        # accumulate gradients over this many microbatches per step (the
        # global batch splits on its leading dim; must divide it)
        if grad_accum < 1:
            raise MXNetError(f"grad_accum must be >= 1, got {grad_accum}")
        self.grad_accum = int(grad_accum)
        self.grad_accum_dtype = grad_accum_dtype
        # int8 gradient compression on the dp-axis reduction
        # (parallel/compress.py; MXNet survey layer-8 gradient-
        # compression parity).  Resolved ONCE at construction like the
        # probes — the quantize-dequantize round is traced into the
        # step, so flipping MXTPU_GRAD_COMPRESS mid-run never retraces.
        # Off by default: it deliberately trades bit-exactness with f32
        # training for 4x less gradient wire traffic.
        from . import compress as _compress
        self._grad_compress = _compress.resolve_grad_compress(
            grad_compress)
        self.block = block
        # how many leading batch args feed block.forward; the rest (labels
        # etc.) only reach loss_fn. None = all.
        self.num_model_args = num_model_args
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.rules = rules or default_tp_rules()
        self.batch_specs = batch_specs
        # the caller's ORIGINAL specs: reshard re-targets from these, so
        # a shrink that drops an axis doesn't ratchet the spec toward
        # replicated when the mesh later grows the axis back
        self._orig_batch_specs = batch_specs
        self._step_fn = None
        self._n_batch_args = None
        self._build_lock = threading.Lock()
        # async pipeline state: AOT-compiled executable (warmup()), trace
        # counter + last-seen avals (retrace guard), device-resident
        # hyperparameter cache, dispatch latencies, in-flight losses
        self._exec = None
        self._trace_count = 0
        self._trace_avals = None
        self._hp_cache = HpScalarCache()
        self._t_dev = None
        self._t_mirror = -1
        self._dispatch_s = collections.deque(maxlen=1024)
        self._inflight = collections.deque(maxlen=256)
        self.compile_seconds = None
        # performance attribution (mx.tracing): cost features are
        # recorded under this key at every compile site (AOT warmup,
        # export load) and combined with measured wall time at retire
        # into the mfu_estimate/step_flops/hbm_bytes_est gauges
        self._cost_key = f"train_step@{id(self):x}"
        self._last_retire_t: Optional[float] = None
        # numerics probes (MXTPU_HEALTH / health.enable): captured ONCE at
        # construction so the probe branch is a fixed part of the traced
        # program — with health off it is traced out entirely (zero extra
        # device computations, trace_count unchanged); enabling health
        # after construction requires a new step object
        self._health_probes = _health.probes_enabled()
        # tier-1 remediation (MXTPU_RECOVERY / recovery.enable): guard the
        # optimizer update with the non-finite probe INSIDE the jitted
        # step — a NaN/Inf gradient (or loss) applies the identity update
        # instead of poisoning the weights, and the host-side
        # RecoveryPolicy accounts the skip from the anomaly the probes
        # raise.  Captured once at construction like the probes: the
        # guard is a fixed part of the traced program (zero retraces,
        # and with recovery off it is traced out entirely).
        self._skip_nonfinite = (self._health_probes
                                and _recovery.skip_enabled())
        # stall-suppression guard entered at TRACE time (_note_trace) and
        # released when the triggering call returns: any path that
        # compiles — cold start, AOT fallback, mid-run aval-drift
        # retrace — blocks for up to minutes, and the hang watchdog must
        # not declare (or raise on) that expected silence
        self._trace_guard = None
        # stall dumps / crash bundles report this step's in-flight ids
        _health.register_inflight_source(self)

        params = {n: p for n, p in block.collect_params().items()
                  if p._data is not None}
        if not params:
            raise MXNetError("block has no initialized parameters; call "
                             "initialize() (and one forward for deferred "
                             "shapes) first")
        self.param_names = sorted(params)
        self.params = params
        self.diff_names = [n for n in self.param_names
                           if params[n].grad_req != "null"]

        # place parameters + optimizer state on the mesh. An explicit
        # Parameter(sharding=...) annotation wins over the rules table; a
        # large parameter matching no rule logs a warning instead of
        # silently replicating (round-1 verdict: silent fall-through).
        self.param_shardings = {
            n: self._resolve_sharding(n, params[n]) for n in self.param_names}
        # donation safety: device_put may ALIAS a same-device source
        # buffer (the CPU replicated-placement path does) — donating an
        # alias at step 1 would delete the caller's own param array out
        # from under every other holder (an InferenceEngine's extracted
        # weights, user references).  The step must OWN what it donates,
        # so the initial placement goes through an explicit copy.
        def _owned(x):
            return jnp.copy(x) if self.donate and isinstance(x, jax.Array) \
                else x
        self.pvals = {n: _put_global(_owned(params[n]._data._data),
                                     self.param_shardings[n])
                      for n in self.param_names}
        # optimizer state: each leaf shards like its parameter, ZeRO adds
        # a 'dp' axis where a dim allows it, and leaves with NO free
        # divisible dim (bias/scale vectors whose only dim is already
        # tp-sharded) are stored as a flattened dp-sharded BUCKET instead
        # of silently replicating (see _state_placement)
        self._state_buckets: Dict[str, Dict[int, Tuple[Tuple[int, ...],
                                                       int]]] = {}
        self.opt_state = {
            n: self._place_state_tree(
                n, optimizer.create_state_jax(_master_dtype(self.pvals[n])))
            for n in self.diff_names}
        self._t = 0
        # True when batch specs are derived (and re-derived on reshard)
        # from the mesh axes rather than caller-supplied
        self._auto_batch_specs = batch_specs is None
        # fused-optimizer route (captured ONCE, like the probes: the
        # choice is baked into the traced program, so flipping
        # MXTPU_PALLAS mid-run can never retrace a live step)
        self._fused_opt_kernel = self._resolve_fused_kernel()

    def _resolve_fused_kernel(self) -> bool:
        """Use the Pallas fused-optimizer kernels inside the jitted
        step?  Requires kernel mode + a kernel-eligible optimizer, and
        nothing sharded: the chunk pack concatenates leaves, which on a
        sharded layout would make GSPMD all-gather the tree every step
        (TODO(tpu): a segment-aware sharded pack, ROADMAP §5)."""
        if not _fused_opt.kernel_route(self.optimizer):
            return False
        if self.mesh.size == 1:
            return True
        if self.zero or self.fsdp:
            return False
        from jax.sharding import PartitionSpec as _P
        return all(s.spec == _P()
                   for s in self.param_shardings.values())

    # parameters below this size stay replicated under fsdp (per-use
    # all-gathers of tiny biases cost more than they save)
    FSDP_MIN_SIZE = 8192

    def _maybe_fsdp(self, sharding: NamedSharding, param) -> NamedSharding:
        if not self.fsdp or \
                int(onp.prod(param.shape)) < self.FSDP_MIN_SIZE:
            return sharding
        ns = _with_dp_axis(self.mesh, sharding.spec, param.shape)
        return ns if ns is not None else sharding

    def _state_placement(self, name, state_leaf):
        """``(sharding, bucket)`` for one optimizer-state leaf: like the
        parameter — plus, under ZeRO, the first unsharded divisible dim
        spread over 'dp' (the reduce-scatter/all-gather pattern XLA then
        emits is exactly ZeRO stage 1).

        When no dim can take the 'dp' axis (the 1-D gap the MULTICHIP
        logs showed: bias/scale vectors whose only dim is already
        tp-sharded, or dims dp doesn't divide), the leaf is stored as a
        **flattened concatenation bucket**: raveled, zero-padded to a
        multiple of dp, and sharded ``P('dp')``.  ``bucket`` is then
        ``(logical_shape, padded_size)``; the jitted step unpacks the
        logical view before the optimizer rule and repacks after, and
        checkpoints always store the logical (unpadded) value so the
        format stays topology-agnostic.  Scalars stay replicated (nothing
        to shard)."""
        param_sharding = self.param_shardings[name]
        param = self.params[name]
        base = _like_sharding(param_sharding, state_leaf, param)
        if not self.zero or "dp" not in self.mesh.axis_names:
            return base, None
        shape = tuple(getattr(state_leaf, "shape", ()))
        ns = _with_dp_axis(self.mesh, base.spec, shape)
        if ns is not None:
            return ns, None
        dp = dict(self.mesh.shape).get("dp", 1)
        if dp > 1 and shape and "dp" not in _spec_axes(base.spec):
            size = int(onp.prod(shape))
            padded = -(-size // dp) * dp
            return (NamedSharding(self.mesh, P("dp")),
                    (tuple(int(d) for d in shape), padded))
        return base, None

    def _place_state_tree(self, name, tree):
        """Device-place one parameter's optimizer-state tree (logical
        leaves), recording bucket metadata and packing bucketed leaves."""
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        buckets: Dict[int, Tuple[Tuple[int, ...], int]] = {}
        placed = []
        for i, leaf in enumerate(leaves):
            sharding, bucket = self._state_placement(name, leaf)
            if bucket is not None:
                buckets[i] = bucket
                leaf = _pack_bucket(leaf, bucket)
            placed.append(_put_global(leaf, sharding))
        self._state_buckets[name] = buckets
        return jax.tree_util.tree_unflatten(treedef, placed)

    def _unpack_state_tree(self, name, tree):
        """Bucketed (packed) leaves -> logical shapes.  jit-safe: slices
        and reshapes trace into the step program."""
        buckets = self._state_buckets.get(name)
        if not buckets:
            return tree
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        for i, (shape, _padded) in buckets.items():
            size = int(onp.prod(shape)) if shape else 1
            leaves[i] = leaves[i][:size].reshape(shape)
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def _pack_state_tree(self, name, tree, constrain=False):
        """Logical leaves -> packed dp-sharded buckets (inverse of
        `_unpack_state_tree`).  `constrain=True` adds a sharding
        constraint inside jit so GSPMD keeps the bucket on 'dp'."""
        buckets = self._state_buckets.get(name)
        if not buckets:
            return tree
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        for i, bucket in buckets.items():
            leaf = _pack_bucket(leaves[i], bucket)
            if constrain:
                leaf = jax.lax.with_sharding_constraint(
                    leaf, NamedSharding(self.mesh, P("dp")))
            leaves[i] = leaf
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def _logical_state_leaves(self, name):
        """The flat leaf list of `opt_state[name]` with bucketed leaves
        unpacked to their logical shapes — what checkpoints store."""
        return jax.tree_util.tree_leaves(
            self._unpack_state_tree(name, self.opt_state[name]))

    def _resolve_sharding(self, name: str, param) -> NamedSharding:
        mesh = self.mesh
        ann = getattr(param, "sharding", None)
        if ann is not None:
            # explicit annotations are validated strictly: a typo must not
            # silently replicate a deliberately-sharded parameter
            if isinstance(ann, str):
                ann = (ann,)
            spec = ann if isinstance(ann, P) else P(*ann)
            if len(spec) > len(param.shape):
                raise MXNetError(
                    f"parameter {name}: sharding annotation {tuple(spec)} "
                    f"has rank {len(spec)} > parameter rank "
                    f"{len(param.shape)} (shape {tuple(param.shape)})")
            names = set(mesh.axis_names)
            from .mesh import AXES as _KNOWN_AXES
            from .sharding import retarget_spec
            for a in spec:
                for ax in ((a,) if isinstance(a, str) else tuple(a or ())):
                    # a standard parallelism axis this mesh runs at size 1
                    # (make_mesh drops those) degrades to replicated via
                    # retarget_spec, so the same model code works when the
                    # mesh shrinks; anything else is a typo
                    if ax not in names and ax not in _KNOWN_AXES:
                        raise MXNetError(
                            f"parameter {name}: sharding annotation names "
                            f"mesh axis {ax!r} but this mesh has axes "
                            f"{sorted(names)}")
            spec = retarget_spec(spec, mesh)
            return self._maybe_fsdp(NamedSharding(mesh, spec), param)
        sharding = self.rules.sharding_for(mesh, name, param.shape)
        # 'dp' replicates params by design; 'sp' shards activations, never
        # params — only true model axes (tp/ep/...) make replication a smell.
        # Checked BEFORE the fsdp augment: fsdp's dp axis doesn't cure
        # replication across tp/ep
        model_axes = [a for a in mesh.axis_names if a not in ("dp", "sp")
                      and mesh.shape[a] > 1]
        if sharding.spec == P() and model_axes and \
                int(onp.prod(param.shape)) >= 1_000_000:
            _log.warning(
                "parameter %s %s matched no sharding rule and will be "
                "REPLICATED across the %s mesh axes; annotate it with "
                "Parameter(sharding=...) or extend ShardingRules",
                name, tuple(param.shape), model_axes)
        return self._maybe_fsdp(sharding, param)

    # ------------------------------------------------------------------
    def _build(self, batch_vals, rng_key):
        mesh = self.mesh
        if self.batch_specs is None:
            # default: shard leading batch dim over 'dp' (+'sp' on axis 1 if
            # the mesh has it and the arg is rank>=2)
            axes = set(mesh.axis_names)
            specs = []
            for b in batch_vals:
                spec = [None] * b.ndim
                if b.ndim >= 1 and "dp" in axes:
                    spec[0] = "dp"
                if b.ndim >= 2 and "sp" in axes:
                    spec[1] = "sp"
                specs.append(P(*spec))
            self.batch_specs = tuple(specs)
        batch_shardings = tuple(NamedSharding(mesh, s)
                                for s in self.batch_specs)
        self._batch_shardings = batch_shardings

        block, loss_fn, optimizer = self.block, self.loss_fn, self.optimizer
        diff_names = self.diff_names

        n_model = self.num_model_args

        k = self.grad_accum
        accum_dtype = self.grad_accum_dtype

        outer = self

        def step(pvals, opt_state, hp, key, *batch):
            # this body runs once per TRACE of the jitted step — the hook
            # counts compilations and warns (with the drifted avals) on a
            # silent retrace, the dtype-drift failure mode noted below
            outer._note_trace((pvals, opt_state, hp, key) + tuple(batch))

            def compute_loss(diff_vals, mkey, *mb):
                pv = dict(pvals)
                pv.update(diff_vals)
                model_args = mb if n_model is None else mb[:n_model]
                out, aux = functional_call(block, pv, *model_args,
                                           training=True, rng_key=mkey)
                loss = loss_fn(out, *mb)
                # a loss_fn written in mx.np ops returns a wrapped scalar;
                # unwrap so value_and_grad sees a jax value
                loss = getattr(loss, "_data", loss)
                return loss, aux

            diff_vals = {n: pvals[n] for n in diff_names}
            if k == 1:
                (loss, aux), grads = jax.value_and_grad(
                    compute_loss, has_aux=True)(diff_vals, key, *batch)
            else:
                # gradient accumulation: scan over k microbatches,
                # accumulating mean-of-means grads at accum_dtype — the
                # large-effective-batch path (reference Trainer's
                # update-skipping idiom, compiled into one program)
                micro = []
                for bi, b in enumerate(batch):
                    if b.ndim < 1 or b.shape[0] % k:
                        raise MXNetError(
                            f"grad_accum={k} must divide every batch "
                            f"arg's leading dim; got shape "
                            f"{tuple(b.shape)}")
                    mb = b.reshape((k, b.shape[0] // k)
                                   + tuple(b.shape[1:]))
                    # keep each microbatch dp-sharded on ITS batch dim —
                    # without the constraint GSPMD can move 'dp' onto the
                    # scan axis and every iteration pays a reshard
                    spec = (self.batch_specs[bi]
                            if self.batch_specs else None)
                    if spec is not None and "dp" in _spec_axes(spec):
                        mb = jax.lax.with_sharding_constraint(
                            mb, NamedSharding(mesh, P(None, *spec)))
                    micro.append(mb)
                micro = tuple(micro)
                keys = jax.random.split(key, k)

                def body(carry, xs):
                    acc, lsum = carry
                    mkey, mb = xs[0], xs[1:]
                    (loss, aux), grads = jax.value_and_grad(
                        compute_loss, has_aux=True)(diff_vals, mkey, *mb)
                    acc = jax.tree_util.tree_map(
                        lambda a, g: a + g.astype(accum_dtype), acc, grads)
                    return (acc, lsum + loss), aux

                init = (jax.tree_util.tree_map(
                    lambda v: jnp.zeros(v.shape, accum_dtype), diff_vals),
                    jnp.zeros((), accum_dtype))
                (acc, lsum), auxes = jax.lax.scan(
                    body, init, (keys,) + micro)
                grads = jax.tree_util.tree_map(
                    lambda a, v: (a / k).astype(v.dtype), acc, diff_vals)
                loss = (lsum / k).astype(jnp.float32)
                # running-stat writebacks: keep the final microbatch's
                aux = jax.tree_util.tree_map(lambda x: x[-1], auxes)
            probes = None
            if outer._health_probes:
                # numerics probes (docs/observability.md): cheap fused
                # reductions XLA folds into the step program — grad global
                # L2 norm + non-finite element count over the whole grad
                # tree.  Returned as async device scalars alongside the
                # loss, so they ride dispatch() with no extra device sync.
                leaves = jax.tree_util.tree_leaves(grads)
                gnorm = jnp.sqrt(sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in leaves))
                # count in f32, not i32: an all-NaN gradient tree on a
                # >=2^31-element model would WRAP an int32 sum negative
                # and poison the host-side counter; f32 loses exactness
                # past 2^24 but stays positive, which is what the
                # anomaly rule needs (int64 needs x64 mode)
                nonfinite = sum(
                    jnp.sum((~jnp.isfinite(g)).astype(jnp.float32))
                    for g in leaves)
                probes = {"grad_norm": gnorm, "nonfinite": nonfinite}
            if outer._grad_compress == "int8":
                # int8 grad compression (parallel/compress.py): per-
                # bucket symmetric scale + stochastic rounding, f32
                # master accumulate.  AFTER the probes (they must see
                # the raw gradients) and BEFORE the skip guard reads
                # them for the update.  The rounding key folds off the
                # step key, so replicas stay deterministic and no two
                # steps share noise.
                from .compress import compress_tree
                grads = compress_tree(
                    grads, jax.random.fold_in(key, 0x67c8))
            skip = None
            if outer._skip_nonfinite:
                # tier-1 recovery: a non-finite gradient tree (or loss)
                # turns the whole update into the identity — weights,
                # optimizer state, and running stats all keep their
                # pre-step values.  jnp.where on a traced scalar, so the
                # skip costs one select per leaf and never a retrace.
                skip = jnp.logical_or(
                    probes["nonfinite"] > 0,
                    ~jnp.isfinite(loss.astype(jnp.float32)))
            # fused multi-tensor optimizer update (ops/pallas/
            # fused_optimizer, MXTPU_PALLAS): same-dtype leaves pack
            # into contiguous chunks with ONE kernel launch each (skip
            # guard applied in-register) when the kernel path is
            # active; otherwise the per-leaf reference applies
            # `optimizer._rule` + the identity-on-skip select with the
            # exact semantics the former inline ladder had (dtype
            # cast-backs included — donation still never retraces)
            new_p = dict(pvals)
            # ZeRO 1-D buckets: the rule sees logical shapes; the packed
            # dp-sharded representation is storage-only
            states = {n: outer._unpack_state_tree(n, opt_state[n])
                      for n in diff_names}
            upd_p, new_s = _fused_opt.apply_updates(
                optimizer, {n: pvals[n] for n in diff_names}, grads,
                states, hp, skip,
                use_kernel=outer._fused_opt_kernel)
            new_s = {n: outer._pack_state_tree(n, new_s[n], constrain=True)
                     for n in new_s}
            new_p.update(upd_p)
            if skip is not None:
                aux = {k: jnp.where(skip, pvals[k], v) if k in pvals else v
                       for k, v in aux.items()}
            new_p.update(aux)  # running-stat writebacks
            if probes is not None:
                return new_p, new_s, loss, probes
            return new_p, new_s, loss

        pspec = {n: self.param_shardings[n] for n in self.param_names}
        # state shardings come straight off the placed arrays — the
        # single source of truth `_place_state_tree` established (bucket
        # leaves carry their packed P('dp') sharding)
        sspec = {
            n: jax.tree_util.tree_map(lambda x: x.sharding,
                                      self.opt_state[n])
            for n in self.diff_names}
        repl = NamedSharding(mesh, P())
        out_shardings = (pspec, sspec, repl)
        if self._health_probes:
            out_shardings += ({"grad_norm": repl, "nonfinite": repl},)
        self._step_fn = jax.jit(
            step,
            in_shardings=(pspec, sspec, None, None) + batch_shardings,
            out_shardings=out_shardings,
            donate_argnums=(0, 1) if self.donate else ())

    def _check_global_batch(self, batch_vals) -> None:
        """First-step guard: on a mesh spanning processes, assert every
        process passed the same global batch (cheap checksum allgather)."""
        if all(getattr(s, "is_fully_addressable", True)
               for s in self._batch_shardings):
            return
        from jax.experimental import multihost_utils
        sums = onp.asarray(
            [float(jnp.sum(jnp.abs(jnp.asarray(b, jnp.float32))))
             for b in batch_vals], onp.float32)
        gathered = multihost_utils.process_allgather(sums)
        if not onp.allclose(gathered, gathered[0], rtol=1e-5):
            raise MXNetError(
                "ShardedTrainStep on a multi-process mesh requires every "
                "process to pass the IDENTICAL global batch (each host "
                "contributes its addressable shards). Got differing batch "
                f"checksums across processes: {gathered.tolist()}. If each "
                "worker loads its own shard, concatenate/allgather to the "
                "global batch first (or give every worker the same data "
                "stream + global indices).")

    # -- async step pipeline -------------------------------------------
    # The reference hides per-step host latency behind its dependency
    # engine (Engine::PushAsync).  Here the jitted step is already async
    # on the device side; the pieces below remove the HOST serialization
    # around it: batch placement moves to DevicePrefetcher threads
    # (place_batch), hyperparameter scalars stay device-resident (_hp),
    # dispatch() returns without fetching the loss, and warmup() AOT-
    # compiles so step 1 (and, with MXTPU_COMPILE_CACHE, a restarted
    # process) never trace-compiles inline.

    def _note_trace(self, args) -> None:
        """Runs at trace time (the step body is python-executed once per
        jit compilation).  Counts traces; on any trace after the first,
        warns with the argument avals that drifted — a silent retrace
        re-pays compile AND breaks donation (see the dtype note in the
        optimizer-update loop)."""
        # a trace is always followed by an XLA compile before the
        # triggering call returns: suppress stall detection until then
        # (released in dispatch/warmup's finally)
        if self._trace_guard is None:
            self._trace_guard = _health.suppress_stalls("trace_compile")
            self._trace_guard.__enter__()
        leaves = jax.tree_util.tree_flatten_with_path(args)[0]
        avals = {
            jax.tree_util.keystr(path): (
                tuple(getattr(leaf, "shape", ())),
                str(getattr(leaf, "dtype", type(leaf).__name__)))
            for path, leaf in leaves}
        prev, self._trace_avals = self._trace_avals, avals
        self._trace_count += 1
        if _tele.enabled():
            _tele.counter(
                "trace_count",
                "Step-function traces/compilations (1 = healthy "
                "steady state)").inc()
            _tele.event("compile", step=self._t,
                        trace_count=self._trace_count)
        if self._trace_count <= 1 or prev is None:
            return
        drift = [f"{k}: {prev[k][0]}/{prev[k][1]} -> {v[0]}/{v[1]}"
                 for k, v in avals.items()
                 if k in prev and prev[k] != v]
        drift += [f"{k}: (new input)" for k in avals if k not in prev]
        drift += [f"{k}: (dropped)" for k in prev if k not in avals]
        if _tele.enabled():
            _tele.event("retrace", step=self._t,
                        trace_count=self._trace_count,
                        drift=drift[:8])
        _log.warning(
            "ShardedTrainStep RETRACE #%d: the step function compiled "
            "again (every retrace re-pays XLA compile and allocates a "
            "second executable). Drifted avals (%d): %s",
            self._trace_count, len(drift),
            "; ".join(drift[:8]) + ("; ..." if len(drift) > 8 else "")
            if drift else "<none — new static closure?>")

    def _release_trace_guard(self) -> None:
        """Exit the stall-suppression window a trace opened (no-op when
        no trace ran)."""
        guard, self._trace_guard = self._trace_guard, None
        if guard is not None:
            guard.__exit__(None, None, None)

    @property
    def trace_count(self) -> int:
        """How many times the step function has been traced/compiled.
        Stays 1 for a healthy steady-state run (assert on it in tests)."""
        return self._trace_count

    # -- performance attribution (mx.tracing) ---------------------------
    def _record_cost(self, compiled, source: str) -> None:
        """Capture `compiled`'s XLA cost/memory analysis into the
        process cost registry (once per compile; never on the hot
        path)."""
        _trace.record_executable(
            self._cost_key, compiled, kind="train_step", source=source,
            axes=self.topology()["axes"])

    def cost_features(self) -> Optional[dict]:
        """The step executable's XLA cost-feature vector (flops, bytes
        accessed, argument/output/temp bytes, hbm_bytes_est), or None
        before any AOT compile/export load recorded one (the live-jit
        path exposes no compiled object to analyze — run `warmup()`)."""
        return _trace.account().features(self._cost_key)

    def mfu_estimate(self, measured_step_s: float) -> Optional[dict]:
        """MFU of one step taking `measured_step_s` wall seconds, from
        the recorded cost features (projected peak on non-TPU backends;
        docs/observability.md)."""
        return _trace.account().mfu(self._cost_key, measured_step_s)

    def _prepare_batch(self, batch):
        """Unwrap mx ndarrays, build the step on first use, and place every
        batch arg on its target sharding — skipping the copy for args that
        already sit there (a DevicePrefetcher hand-off)."""
        batch_vals = [b._data if hasattr(b, "_data")
                      else b if isinstance(b, jax.Array)
                      else onp.asarray(b)
                      for b in batch]
        if self._step_fn is None and self._exec is None:
            with self._build_lock:
                if self._step_fn is None and self._exec is None:
                    self._build(batch_vals, None)
                    self._check_global_batch(batch_vals)
        # remembered for batch-less `export()` calls (avals only)
        self._last_batch_avals = [
            (tuple(b.shape), onp.dtype(b.dtype)) for b in batch_vals]
        return [b if isinstance(b, jax.Array) and b.sharding == s
                else _put_global(b, s)
                for b, s in zip(batch_vals, self._batch_shardings)]

    def place_batch(self, *batch):
        """Device-place one batch onto the step's batch shardings (built
        from this batch if needed).  This is the `place=` hook for
        `DevicePrefetcher`: calling it on the prefetch thread moves the
        H2D copy off the training loop; `dispatch`/`__call__` then detect
        the placement and skip their own copy."""
        return tuple(self._prepare_batch(batch))

    def _hp(self):
        """Device-resident hyperparameter scalars (shared `HpScalarCache`:
        lr/wd/rescale/clip uploads happen only when the host-side values
        actually change, instead of five H2D transfers per step); the
        step counter `t` advances by a device-side add, so steady-state
        dispatch enqueues zero transfers.  A checkpoint load (or external
        _t rewrite) makes the mirror mismatch and forces a host rebuild."""
        hp = self._hp_cache.get(self.optimizer)
        if self._t_dev is not None and self._t_mirror == self._t:
            pass  # same step (repeated warmup) — reuse
        elif self._t_dev is not None and self._t_mirror + 1 == self._t \
                and self._t % self._T_HOST_REFRESH:
            # device-side increment; periodically re-seeded from the host
            # counter because f32 `x + 1.0` saturates at 2**24 — a pure
            # device chain would silently freeze t on very long runs
            self._t_dev = self._t_dev + 1.0
        else:
            self._t_dev = jnp.asarray(self._t, jnp.float32)
        self._t_mirror = self._t
        hp["t"] = self._t_dev
        return hp

    # re-upload `t` from the host every this many steps (guards the f32
    # device-add saturation at 2**24; one tiny H2D per window otherwise)
    _T_HOST_REFRESH = 4096

    def warmup(self, *batch, rng_key=None, artifact=None):
        """AOT warm start: trace + compile the step for this batch's avals
        WITHOUT executing it (`.lower().compile()`), so the first real
        step runs at steady-state speed.  With ``MXTPU_COMPILE_CACHE`` set
        (see `runtime.enable_compile_cache`) the XLA binary is served from
        the persistent cache on a restart — the multi-minute BERT compile
        happens once per cluster, not once per process.  Returns the
        compile wall-time in seconds (also kept as `compile_seconds`).

        ``artifact=<path>`` skips tracing entirely: the step loads the
        export artifact (`load_export`), so ``trace_count`` stays 0.
        With ``MXTPU_EXPORT=1`` and an export dir configured
        (docs/export.md) the lookup is automatic — a matching artifact
        is loaded, a missing one is captured+saved after the live
        compile, so replica N>1 of a fleet never traces.

        Does not consume an RNG draw: the key is only used for its aval."""
        if artifact is not None:
            return self.load_export(artifact, *batch)
        auto_path = self._auto_artifact_path(batch)
        if auto_path is not None:
            import os as _os
            if _os.path.isfile(_os.path.join(auto_path, "manifest.json")):
                try:
                    return self.load_export(auto_path, *batch)
                except MXNetError as e:
                    _log.warning(
                        "export artifact %s unusable (%s); tracing live",
                        auto_path, str(e).splitlines()[0])
        secs = self._warmup_live(batch, rng_key)
        if auto_path is not None:
            try:
                self.export(auto_path, *batch)
            except Exception:
                _log.exception("auto-capture to %s failed (training "
                               "continues uncaptured)", auto_path)
        return secs

    def _warmup_live(self, batch, rng_key=None):
        batch_vals = self._prepare_batch(batch)
        if self._step_fn is None:
            # artifact-loaded step being re-warmed live (new batch
            # shape, or the export flag dropped): _prepare_batch skipped
            # its build because _exec was set — build the jit now
            with self._build_lock:
                if self._step_fn is None:
                    self._build([onp.asarray(b) for b in batch_vals],
                                None)
        hp = self._hp()
        key = rng_key if rng_key is not None else jax.random.PRNGKey(0)
        args = (self.pvals, self.opt_state, hp, key) + tuple(batch_vals)
        avals = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), args)
        if _tele.enabled():
            _tele.event("compile_start", step=self._t, kind="aot_warmup")
        t0 = time.perf_counter()
        # a multi-minute XLA compile is expected silence, not a hang —
        # keep the stall watchdog quiet for its duration (explicitly:
        # `.compile()` still runs even when `.lower()` skipped the trace
        # that would have armed the _note_trace guard)
        c_span = _trace.get_tracer("train").span(
            "train.compile", step=self._t, kind="aot_warmup") \
            if _trace.enabled() else None
        try:
            with _health.suppress_stalls("aot_compile"):
                self._exec = self._step_fn.lower(*avals).compile()
        finally:
            self._release_trace_guard()
            if c_span is not None:
                c_span.__exit__(None, None, None)
        self.compile_seconds = time.perf_counter() - t0
        self._record_cost(self._exec, source="aot_warmup")
        if _tele.enabled():
            _tele.event("compile_end", step=self._t, kind="aot_warmup",
                        seconds=round(self.compile_seconds, 4))
        return self.compile_seconds

    def dispatch(self, *batch, rng_key=None) -> "StepHandle":
        """Non-blocking step: enqueue forward+backward+update and return a
        `StepHandle` whose `.loss` is the still-async device scalar —
        `float()`/`.result()` blocks, `AsyncMetricBuffer` defers the fetch
        so multiple steps stay in flight.  The step boundary is marked
        with `jax.profiler.StepTraceAnnotation`, so Perfetto/TensorBoard
        segment the XPlane trace per step and show prefetch overlap."""
        from .. import random as _rng
        _health.beat("train_step.dispatch")
        t0 = time.perf_counter()
        # span pair (mx.tracing): "train.dispatch" covers the host-side
        # enqueue, "train.device" the dispatch -> retire window (finished
        # in steps_in_flight).  Both tagged with the journal step id.
        # manual span (not the thread-local stack): an exception mid-
        # dispatch must not strand an open span under later dispatches
        d_span = _trace.get_tracer("train").start_span(
            "train.dispatch", track="train host", step=self._t + 1) \
            if _trace.enabled() else None
        batch_vals = self._prepare_batch(batch)
        self._t += 1
        hp = self._hp()
        key = rng_key if rng_key is not None else _rng.next_key()
        # any (re)trace inside these calls enters the stall-suppression
        # guard via _note_trace; the finally releases it once the
        # triggering call (trace + XLA compile) has returned
        try:
            with _profiler.step_annotation("mxtpu.train_step",
                                           step_num=self._t):
                if self._exec is not None:
                    try:
                        out = self._exec(self.pvals, self.opt_state, hp,
                                         key, *batch_vals)
                    except TypeError as e:
                        # aval drift vs the AOT executable: fall back to
                        # the jit path (which retraces — _note_trace warns
                        # with the diff). Input buffers are intact: the
                        # AOT call validates avals before launching, so
                        # donation has not consumed them yet.
                        _log.warning(
                            "AOT-compiled step rejected inputs (%s); "
                            "falling back to jit",
                            str(e).splitlines()[0])
                        self._exec = None
                        if self._step_fn is None:
                            # artifact-loaded step (load_export): there
                            # is no jit to fall back to yet — build one
                            # (a LIVE trace; loud, since the zero-
                            # retrace contract just broke on aval drift)
                            self._build(batch_vals, None)
                        out = self._step_fn(self.pvals, self.opt_state,
                                            hp, key, *batch_vals)
                else:
                    out = self._step_fn(self.pvals, self.opt_state, hp,
                                        key, *batch_vals)
        finally:
            self._release_trace_guard()
        if self._health_probes:
            self.pvals, self.opt_state, loss, probes = out
        else:
            self.pvals, self.opt_state, loss = out
            probes = None
        # rebind block Parameters to the fresh (non-donated) buffers so
        # eager reads (p.data()) stay valid — pointer update only
        self.sync_params_to_block()
        dt = time.perf_counter() - t0
        self._dispatch_s.append(dt)
        x_span = None
        if d_span is not None:
            x_span = _trace.get_tracer("train").start_span(
                "train.device", parent=d_span.context(),
                track="train device", step=self._t)
            d_span.finish(dispatch_ms=round(dt * 1e3, 3))
        self._inflight.append((self._t, loss, probes,
                               time.perf_counter(), x_span))
        if _tele.enabled():
            _tele.histogram(
                "step_dispatch_ms",
                "Host time per dispatch() call (not device step time; "
                "overlap works when this sits far below step time)"
            ).observe(dt * 1e3)
            _tele.event("step_dispatched", step=self._t,
                        dispatch_ms=round(dt * 1e3, 3))
            _tele.gauge(
                "steps_in_flight",
                "Dispatched steps whose loss has not landed on the host"
            ).set(self.steps_in_flight())
        elif self._health_probes:
            self.steps_in_flight()   # retire → feed the health monitor
        return StepHandle(loss, self._t, dt, probes=probes)

    def steps_in_flight(self) -> int:
        """Dispatched steps whose loss has not yet landed on the host —
        non-blocking (`jax.Array.is_ready`), pruning finished entries.
        Retired steps feed their (now host-cheap) probe values to the
        health monitor when numerics probes are on."""
        q = self._inflight
        batch = []
        while q:
            entry = q[0]
            try:
                ready = bool(entry[1].is_ready())
            except Exception:
                ready = True
            if not ready:
                break
            q.popleft()
            batch.append(entry)
        if batch:
            now = time.perf_counter()
            # measured step wall: retire-to-retire cadence in a
            # pipelined steady state (first-ever retire falls back to
            # dispatch->retire).  Steps retiring in the SAME poll share
            # the interval since the previous retire — a per-entry
            # timestamp would divide full step flops by microseconds
            # and write garbage MFU rows into the corpus.
            prev, self._last_retire_t = self._last_retire_t, now
            base = prev if prev is not None else batch[0][3]
            measured_s = max(0.0, now - base) / len(batch)
            for step_id, loss, probes, _t_disp, x_span in batch:
                _health.beat("train_step.retire")
                if x_span is not None:
                    x_span.finish(t1=now)
                if probes is not None:
                    self._observe_health(step_id, loss, probes)
                if _tele.enabled():
                    # each step record carries the executable's cost-
                    # feature vector + the measured wall time — the
                    # (features, ms) corpus a learned performance model
                    # trains on — and updates the always-on
                    # mfu_estimate/step_flops/hbm_bytes_est gauges
                    cost = _trace.note_step_cost(
                        self._cost_key, measured_s) \
                        if measured_s > 0 else None
                    if cost is not None:
                        _tele.event("step_retired", step=step_id,
                                    cost=cost)
                    else:
                        _tele.event("step_retired", step=step_id)
        return len(q)

    def drain(self, timeout: Optional[float] = None) -> int:
        """Block until every dispatched step has retired (its loss landed
        on the host and, with health probes on, fed the monitor), or the
        `timeout` deadline passes.  Returns the number of steps still in
        flight (0 = fully drained).

        The recovery paths call this before acting on training state: a
        rollback restore or an emergency preemption save under
        outstanding donated buffers would race the in-flight steps, and
        the retirements carry the probe values the health monitor (and
        the anomaly→remediation policy behind it) still needs to see."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._inflight:
            loss = self._inflight[0][1]
            if deadline is None:
                try:
                    jax.block_until_ready(loss)
                except Exception:
                    pass
            else:
                while True:
                    try:
                        ready = bool(loss.is_ready())
                    except Exception:
                        ready = True
                    if ready:
                        break
                    if time.monotonic() >= deadline:
                        return self.steps_in_flight()
                    time.sleep(0.002)
            before = len(self._inflight)
            self.steps_in_flight()   # retires the ready head(s)
            if len(self._inflight) >= before:
                break  # no progress — avoid spinning on a wedged entry
        return self.steps_in_flight()

    @staticmethod
    def _observe_health(step_id, loss, probes) -> None:
        """Hand one retired step's probe scalars to the health monitor.
        The arrays are ready (the retire check just passed), so the
        device_get is a host copy, not a sync."""
        mon = _health.monitor()
        if mon is None:
            return
        try:
            mon.observe(step_id,
                        loss=float(jax.device_get(loss)),
                        grad_norm=float(jax.device_get(probes["grad_norm"])),
                        nonfinite=int(jax.device_get(probes["nonfinite"])))
        except Exception:   # monitoring must never take the step down
            _log.exception("health probe observation failed")

    def dispatch_stats(self) -> dict:
        """Host-side dispatch latency over the last <=1024 steps: the time
        the training loop spent per `dispatch()` call (NOT device step
        time — overlap is working when this is far below step time)."""
        d = list(self._dispatch_s)
        if not d:
            return {"dispatches": 0, "mean_ms": 0.0, "max_ms": 0.0}
        return {"dispatches": len(d),
                "mean_ms": round(sum(d) * 1e3 / len(d), 4),
                "max_ms": round(max(d) * 1e3, 4)}

    def __call__(self, *batch, rng_key=None):
        """Run one step; returns the (replicated) scalar loss as jax array
        (async — `float(loss)` blocks; prefer `dispatch()` +
        `AsyncMetricBuffer` in throughput loops).

        Multi-process meshes: every process must pass the identical GLOBAL
        batch (each contributes its addressable shards — see `_put_global`);
        the first step cross-checks this so the per-host-shard habit from
        the reference's KVStore path fails loudly instead of training on a
        silent patchwork of half-dropped data."""
        return self.dispatch(*batch, rng_key=rng_key).loss

    def sync_params_to_block(self):
        """Write the (sharded) trained values back into the Parameters."""
        for n in self.param_names:
            self.params[n]._data._data = self.pvals[n]

    # -- checkpoint/resume ----------------------------------------------
    # Parity: `gluon/trainer.py:510,537` (save_states/load_states) widened
    # to the full sharded training state — params + optimizer state + step
    # counter + host RNG — so a killed job resumes bit-exact (the recovery
    # story SURVEY.md §5.3 plans as a new capability).

    def save(self, path: str) -> None:
        """Checkpoint params, optimizer state, step count, and RNG to `path`
        (.npz). Sharded arrays are gathered to host; `load` re-shards."""
        self._drain_async_save()
        self._write_checkpoint(path, self._snapshot())

    def save_async(self, path: str):
        """Non-blocking checkpoint: snapshot the training state as
        device-side COPIES (async dispatches — cheap to enqueue) and
        gather + write in a background thread while training continues.
        Returns a handle; call `.result()` to wait and re-raise any
        writer error.  Copies, not references: the jitted step donates
        its param/state buffers (`donate_argnums`), so the next step()
        would invalidate snapshotted originals on TPU — the private
        copies are untouched by donation.  Costs one transient extra
        params+opt-state footprint in HBM until the write drains.  The
        reference has no analogue — its NDArrays are mutable, so
        `save_states` must stop the engine (SURVEY §5.4's recovery story
        without the stall).

        Only one async save runs at a time: a second call waits for the
        first.  Multi-process meshes fall back to a synchronous save —
        the cross-host allgather must not race training collectives."""
        multi = any(not getattr(s, "is_fully_addressable", True)
                    for s in self.param_shardings.values())
        if multi:
            self.save(path)
            done: _cf.Future = _cf.Future()
            done.set_result(path)
            return done
        return self._submit_async_save(path)

    def _submit_async_save(self, path: str):
        self._drain_async_save()
        snap = self._snapshot(copy=True)
        raw = _ckpt_pool().submit(self._write_checkpoint, path, snap)
        fut = _ObservedFuture()

        def _relay(f):
            e = f.exception()  # retrieves — the raw future never warns
            try:
                if e is None:
                    fut.set_result(path)
                else:
                    fut.set_exception(e)
            finally:
                fut.settled.set()

        raw.add_done_callback(_relay)
        self._ckpt_last = fut
        return fut

    _ckpt_last = None

    def _drain_async_save(self):
        """Wait for any in-flight async save; re-raise its error ONLY if
        no holder of the returned future retrieved it yet (backstop for
        saves the caller never polled).  An error that `CheckpointManager`
        (or any `.result()` caller) already consumed is NOT raised again —
        otherwise one failed background write would abort the NEXT
        save/save_async synchronously, escaping ElasticLoop's tolerant
        drain and defeating its documented max_restores failure budget."""
        fut, self._ckpt_last = self._ckpt_last, None
        if fut is None:
            return
        fut.settled.wait()
        if fut.error_retrieved:
            return
        fut.result()

    def _snapshot(self, copy: bool = False):
        """Consistent view of the current training state.  With
        `copy=True` every device array is copied (async dispatch) so the
        snapshot survives the next step's buffer donation."""
        from .. import random as _rng
        g = _rng.generator
        dup = (lambda x: jnp.copy(x)) if copy else (lambda x: x)
        # bucketed ZeRO leaves are snapshotted at their LOGICAL (unpadded)
        # shape, so the checkpoint format is topology-agnostic: the same
        # file restores under any mesh/dp (load re-packs for its layout)
        return {
            "pvals": {n: dup(v) for n, v in self.pvals.items()},
            "opt_state": {n: [dup(leaf) for leaf in
                              self._logical_state_leaves(n)]
                          for n in self.diff_names},
            "t": self._t,
            "rng_seed": g._seed,
            "rng_key": g._key,
        }

    def _write_checkpoint(self, path: str, snap) -> str:
        from ..util import npz_encode_entry

        def put(out, key, val):
            npz_encode_entry(out, key, onp.asarray(_gather_to_host(val)))

        out = {}
        for n in self.param_names:
            put(out, "p:" + n, snap["pvals"][n])
        for n in self.diff_names:
            for i, leaf in enumerate(snap["opt_state"][n]):
                put(out, f"s:{n}:{i}", leaf)
        out["meta:t"] = onp.asarray(snap["t"], onp.int64)
        out["meta:rng_seed"] = onp.asarray(snap["rng_seed"], onp.int64)
        if snap["rng_key"] is not None:
            put(out, "meta:rng_key", snap["rng_key"])
        # Multi-process meshes: every rank gathered the identical global
        # payload above (collectives), and every rank writes it — to a
        # pid-suffixed tmp so concurrent writers never interleave within
        # one file; the atomic replaces then race benignly (identical
        # content, last one wins, `path` is always complete). Skipping
        # the write on rank != 0 would break callers that hand each rank
        # its own tmp path and replace afterwards (CheckpointManager).
        import os
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            onp.savez(f, **out)
        os.replace(tmp, path)   # atomic: a crash never truncates `path`
        return path

    def load(self, path: str) -> None:
        """Restore a `save` checkpoint; arrays are re-placed with this
        step's shardings (the mesh/topology may differ from save time)."""
        from .. import random as _rng

        from ..util import npz_decode_entry
        with onp.load(path, allow_pickle=False) as z:
            raw = dict(npz_decode_entry(k, z[k]) for k in z.files)

        for n in self.param_names:
            if "p:" + n not in raw:
                raise MXNetError(f"checkpoint {path} missing parameter {n}")
            self.pvals[n] = _shard_from_host(raw["p:" + n],
                                             self.param_shardings[n])
        for n in self.diff_names:
            leaves, treedef = jax.tree_util.tree_flatten(self.opt_state[n])
            buckets = self._state_buckets.get(n, {})
            new_leaves = []
            for i, old in enumerate(leaves):
                key = f"s:{n}:{i}"
                if key not in raw:
                    raise MXNetError(
                        f"checkpoint {path} missing optimizer state {key} "
                        f"(optimizer type changed since save?)")
                val = raw[key]
                # restore at the CURRENT state dtype: a checkpoint written
                # before the fp32-master-state default would otherwise pin
                # bf16 m/v back onto a step compiled for fp32 state
                if hasattr(old, "dtype") and val.dtype != old.dtype:
                    val = val.astype(old.dtype)
                # checkpoints store the LOGICAL value; this step's layout
                # decides the on-device representation — so a file written
                # under any topology restores under this one
                bucket = buckets.get(i)
                if bucket is not None:
                    val = onp.asarray(_pack_bucket(onp.asarray(val),
                                                   bucket))
                    sharding = NamedSharding(self.mesh, P("dp"))
                else:
                    sharding, _ = self._state_placement(n, val)
                new_leaves.append(_shard_from_host(val, sharding))
            self.opt_state[n] = jax.tree_util.tree_unflatten(
                treedef, new_leaves)
        self._t = int(raw["meta:t"])
        g = _rng.generator
        g._seed = int(raw.get("meta:rng_seed", g._seed))
        if "meta:rng_key" in raw:
            g._key = jnp.asarray(raw["meta:rng_key"])
        else:
            # checkpoint predates any RNG draw: clear this process's
            # (possibly advanced) key so draws restart from PRNGKey(seed)
            g._key = None
        self.sync_params_to_block()

    # -- ahead-of-time export (docs/export.md) ---------------------------

    def export(self, path: str, *batch, passes=None) -> str:
        """Capture this step's FULL jitted program (forward + backward +
        optimizer update, grad-accum scan and skip-guard included) to a
        versioned StableHLO artifact at `path`, optionally running an
        offline rewrite pipeline (`export.passes`) first.  `batch`: an
        example batch; omitted, the last dispatched batch's avals are
        reused.  The live step is untouched (capture builds scratch
        programs and restores every piece of compiled-step state)."""
        from ..export import capture_train_step, PassManager
        cap = capture_train_step(self, *batch)
        if passes:
            cap = PassManager(passes).run(cap)
        return cap.save(path)

    def load_export(self, path: str, *batch) -> float:
        """Warm-start from an export artifact WITHOUT tracing: the
        module for this step's current topology is deserialized and
        AOT-compiled (the persistent compile cache serves the binary
        when warm), so ``trace_count`` stays 0.  Fails fast with a
        clear `MXNetError` on version / topology / aval / step-flag
        mismatches (docs/export.md failure matrix).  Returns the
        compile wall seconds (also kept as `compile_seconds`)."""
        import os as _os
        from ..export import load as _load, spec_from_json
        from ..export.capture import _train_avals, _step_flags
        la = _load(path)
        if la.kind != "train_step":
            raise MXNetError(
                f"load_export: artifact at {path} is kind={la.kind!r}, "
                "not a train_step capture")
        topo = self.topology()
        rec = la.artifact.module_record(topo)
        flags = _step_flags(self)
        for k, want in rec["meta"].items():
            # remat is NOT an equality gate: the artifact's baked policy
            # is authoritative (replicas can't know an offline search's
            # winner up front) — it is warned about and adopted below.
            # It IS part of export_signature, so the auto-capture path
            # never silently matches across differing local knobs.
            if k == "remat_policy":
                continue
            if k in flags and flags[k] != want:
                raise MXNetError(
                    f"export artifact {path} was captured with {k}="
                    f"{want!r} but this step runs {k}={flags[k]!r}; the "
                    "compiled program would not match — re-capture or "
                    "construct the step with matching settings")
        # flags the artifact's meta may simply not RECORD (captured by
        # an older build): absence means the capture ran the default,
        # so a step running non-default must still refuse — the loop
        # above only sees the artifact's keys, and silence here would
        # e.g. train uncompressed under grad_compress="int8"
        for k, default in (("grad_compress", "none"),):
            if k not in rec["meta"] and flags.get(k, default) != default:
                raise MXNetError(
                    f"export artifact {path} predates the {k} step flag "
                    f"(captured running the default {default!r}) but "
                    f"this step runs {k}={flags[k]!r}; the compiled "
                    "program would not match — re-capture")
        art_remat = rec["meta"].get("remat_policy")
        # batch specs/shardings come from the manifest (no _build runs).
        # Everything below validates into LOCALS first: a failed load
        # must leave the step untouched, or warmup()'s live-trace
        # fallback would build against the artifact's stale specs.
        if rec.get("batch_specs") is not None:
            specs = tuple(spec_from_json(s) for s in rec["batch_specs"])
        else:
            specs = self.batch_specs
        if specs is None:
            raise MXNetError(
                f"export artifact {path} predates batch_specs recording; "
                "re-capture it")
        shardings = tuple(NamedSharding(self.mesh, s) for s in specs)
        if batch:
            batch_vals = [b._data if hasattr(b, "_data")
                          else b if isinstance(b, jax.Array)
                          else onp.asarray(b) for b in batch]
        else:
            batch_vals = [onp.zeros(tuple(s), onp.dtype(d))
                          for s, d in rec["batch_avals"]]
        live = (self.pvals, self.opt_state, self._hp(),
                jax.random.PRNGKey(0)) + tuple(
                    jax.ShapeDtypeStruct(tuple(b.shape), b.dtype)
                    for b in batch_vals)
        la.artifact.check_avals(topo, live)
        exported = la.exported_for(topo)   # deserialize failure raises
        # aval/flag validation passed.  The remaining steps (global-
        # batch cross-check, AOT compile of the deserialized module)
        # need the loaded specs installed, but can still fail — e.g. a
        # module captured for another platform raising from lower() —
        # so roll the step back to its prior state on ANY failure:
        # warmup()'s live-trace fallback must never build against a
        # half-loaded artifact's specs.
        saved = (self.batch_specs,
                 getattr(self, "_batch_shardings", None),
                 getattr(self, "_last_batch_avals", None))
        self.batch_specs = specs
        self._batch_shardings = shardings
        self._last_batch_avals = [
            (tuple(b.shape), onp.dtype(b.dtype)) for b in batch_vals]
        try:
            # the live path's first _build runs the identical-global-
            # batch cross-check; the artifact path must too (a fleet
            # cold-starting from artifacts is exactly where a per-host-
            # shard data bug would otherwise train on a patchwork)
            if batch:
                self._check_global_batch(batch_vals)
            avals = _train_avals(self, batch_vals)
            if _tele.enabled():
                _tele.event("compile_start", step=self._t,
                            kind="export_load")
            t0 = time.perf_counter()
            with _health.suppress_stalls("export_load_compile"):
                compiled = jax.jit(
                    exported.call,
                    donate_argnums=(0, 1) if self.donate else ()
                ).lower(*avals).compile()
        except BaseException:
            (self.batch_specs, self._batch_shardings,
             self._last_batch_avals) = saved
            raise
        self.compile_seconds = time.perf_counter() - t0
        self._exec = compiled
        self._record_cost(compiled, source="export_load")
        self._step_fn = None     # no live jit: the artifact IS the program
        # adopt the artifact's baked remat policy into the model knob so
        # any LATER live retrace (aval drift, reshard) lowers the same
        # program — and warn when it differs from the local setting
        # (e.g. an artifact captured without remat loaded into a step
        # whose operator set remat to fit HBM: the loaded program wins)
        if art_remat is not None:
            from ..export.capture import _find_cfg, _resolved_remat
            local = _resolved_remat(self)
            if local != art_remat:
                _log.warning(
                    "export artifact %s bakes remat policy %r but this "
                    "model is configured %r; the artifact's program "
                    "wins (cfg.remat updated to match — watch HBM if "
                    "you relied on the local setting)",
                    path, art_remat, local)
            cfg = _find_cfg(self.block)
            if cfg is not None and hasattr(cfg, "remat"):
                cfg.remat = False if art_remat == "none" else art_remat
        if _tele.enabled():
            _tele.event("compile_end", step=self._t, kind="export_load",
                        seconds=round(self.compile_seconds, 4),
                        artifact=_os.path.basename(_os.path.abspath(path)))
        return self.compile_seconds

    def export_signature(self, batch=()) -> str:
        """Deterministic identity for auto-capture artifact names: the
        program is a function of param/state avals, batch avals, mesh
        topology, optimizer, step flags, backend, and jax version."""
        from ..export import signature
        from ..export.capture import _step_flags
        import jax as _jax
        pav = [(n, tuple(v.shape), str(v.dtype))
               for n, v in sorted(self.pvals.items())]
        if batch:
            bav = [(tuple(b.shape), str(onp.asarray(
                        b._data if hasattr(b, "_data") else b).dtype))
                   for b in batch]
        else:
            bav = [(tuple(s), str(d))
                   for s, d in getattr(self, "_last_batch_avals", ())]
        return signature([
            pav, bav, sorted(self.topology()["axes"].items()),
            self.topology()["devices"], _step_flags(self),
            _jax.__version__, _jax.default_backend()])

    def _auto_artifact_path(self, batch):
        """MXTPU_EXPORT=1 + an export dir -> this step's auto artifact
        directory; None when auto capture is off."""
        import os as _os
        from ..export import auto_capture_enabled, export_dir
        if not auto_capture_enabled():
            return None
        d = export_dir()
        if not d:
            return None
        return _os.path.join(d, f"train-{self.export_signature(batch)}")

    # -- elastic mesh reformation ----------------------------------------

    def topology(self) -> dict:
        """Topology descriptor stamped into checkpoint manifests (and
        compared by `CheckpointManager.restore` to announce a
        topology-agnostic restore): device count + named axis sizes."""
        return {"devices": int(self.mesh.size),
                "axes": {str(k): int(v)
                         for k, v in dict(self.mesh.shape).items()},
                "processes": int(jax.process_count())}

    def reshard(self, new_mesh: Mesh, rules=None,
                gather: bool = True) -> None:
        """Re-form this step onto `new_mesh` IN PLACE — the elastic
        mesh-reformation primitive (`parallel.elastic_mesh`): the
        Trainer / model / optimizer objects survive, only the device
        layout and the compiled executable change.

        1. drains in-flight dispatched steps and any async checkpoint
           write (donated buffers must settle before re-placement),
        2. with ``gather=True`` gathers the FULL param + optimizer-state
           tree to host (fault point ``reshard_gather``; bucketed ZeRO
           leaves are unpacked to their logical shapes first),
        3. swaps the mesh, re-runs `ShardingRules`/annotations against
           the new axes (`auto_mesh` dp absorption happened in the
           caller's mesh build; ZeRO dp-axis augments and 1-D buckets
           are re-planned for the new dp), re-derives auto batch specs,
           and re-places the state,
        4. resets the compiled-step state — ``trace_count`` restarts at 0
           so the first dispatch on the new topology traces exactly
           once; the AOT executable, aval guard, and device-resident hp
           cache are dropped (they referenced the old devices).

        ``gather=False`` is the **host-loss** path: a dead host's shards
        cannot be gathered, so placements/buckets are re-planned but the
        live values are left stale — the caller MUST restore a
        checkpoint into the step before dispatching (the
        topology-agnostic `load` re-places every array)."""
        from ..resilience import fault_point
        fault_point("mesh_reform")
        tr = _trace.get_tracer("elastic") if _trace.enabled() else None
        if tr is not None:
            with tr.span("elastic.drain", step=self._t):
                self.drain()
                self._drain_async_save()
        else:
            self.drain()
            self._drain_async_save()
        host_p = host_s = None
        if gather:
            fault_point("reshard_gather")

            def _gather_all():
                hp = {n: onp.asarray(_gather_to_host(v))
                      for n, v in self.pvals.items()}
                hs = {n: [onp.asarray(_gather_to_host(leaf))
                          for leaf in self._logical_state_leaves(n)]
                      for n in self.diff_names}
                return hp, hs

            if tr is not None:
                # with-block, not a bare __exit__: a SuspectedHostLoss
                # mid-gather must not strand an open span on the stack
                # (every later span would parent under the corpse)
                with tr.span("elastic.gather", step=self._t):
                    host_p, host_s = _gather_all()
            else:
                host_p, host_s = _gather_all()
        old_axes = {k: int(v) for k, v in dict(self.mesh.shape).items()}
        self.mesh = new_mesh
        if rules is not None:
            self.rules = rules
        if self._auto_batch_specs:
            self.batch_specs = None      # re-derived for the new axes
        elif self._orig_batch_specs is not None:
            from .sharding import retarget_spec
            self.batch_specs = tuple(
                retarget_spec(s, new_mesh)
                for s in self._orig_batch_specs)
        self.param_shardings = {
            n: self._resolve_sharding(n, self.params[n])
            for n in self.param_names}
        if gather:
            self.pvals = {
                n: _shard_from_host(host_p[n], self.param_shardings[n])
                for n in self.param_names}
            new_state = {}
            for n in self.diff_names:
                _, treedef = jax.tree_util.tree_flatten(self.opt_state[n])
                new_state[n] = self._place_state_tree(
                    n, jax.tree_util.tree_unflatten(treedef, host_s[n]))
            self.opt_state = new_state
        else:
            self._replan_state_buckets()
        # compiled-step reset: everything tied to the old topology
        self._step_fn = None
        self._exec = None
        self._trace_count = 0
        self._trace_avals = None
        self._hp_cache = HpScalarCache()
        self._t_dev = None
        self._t_mirror = -1
        self.compile_seconds = None
        # attribution state from the old topology: the cost features
        # describe the OLD program (re-recorded at the next warmup/
        # compile), and retire-to-retire cadence restarts
        _trace.account().discard(self._cost_key)
        self._last_retire_t = None
        self._fused_opt_kernel = self._resolve_fused_kernel()
        if gather:
            self.sync_params_to_block()
        if _tele.enabled():
            _tele.event("mesh_reshard", step=self._t, gather=gather,
                        old_axes=old_axes,
                        new_axes=self.topology()["axes"])

    def _replan_state_buckets(self) -> None:
        """Recompute bucket metadata for the current mesh WITHOUT moving
        data (the gather=False reshard): logical shapes come from the
        old bucket records / leaf shapes, so the following `load` places
        every leaf correctly for the new dp."""
        for n in self.diff_names:
            leaves, _ = jax.tree_util.tree_flatten(self.opt_state[n])
            old = self._state_buckets.get(n, {})
            new: Dict[int, Tuple[Tuple[int, ...], int]] = {}
            for i, leaf in enumerate(leaves):
                shape = old[i][0] if i in old else tuple(leaf.shape)
                aval = jax.ShapeDtypeStruct(shape, leaf.dtype)
                _, bucket = self._state_placement(n, aval)
                if bucket is not None:
                    new[i] = bucket
            self._state_buckets[n] = new


class StepHandle:
    """Async result of `ShardedTrainStep.dispatch`.

    `loss` is the not-yet-fetched replicated device scalar; `step` the
    1-based step index; `dispatch_s` the host time the dispatch call took.
    `result()` blocks and returns the float; `is_ready()` polls without
    blocking.  Feed handles straight into `AsyncMetricBuffer.append`.
    `probes` carries the async numerics-probe scalars
    (``{"grad_norm", "nonfinite"}``) when health probes are enabled,
    else None (docs/observability.md).
    """

    __slots__ = ("loss", "step", "dispatch_s", "probes")

    def __init__(self, loss, step: int, dispatch_s: float, probes=None):
        self.loss = loss
        self.step = step
        self.dispatch_s = dispatch_s
        self.probes = probes

    def is_ready(self) -> bool:
        try:
            return bool(self.loss.is_ready())
        except AttributeError:
            return True

    def result(self) -> float:
        return float(jax.device_get(self.loss))

    def __repr__(self):
        return (f"StepHandle(step={self.step}, "
                f"dispatch_ms={self.dispatch_s * 1e3:.3f})")


class _ObservedFuture(_cf.Future):
    """Future that records whether its exception was ever retrieved
    (`result()` raised it or `exception()` returned it).  Lets
    `_drain_async_save` deliver a failed write's error exactly once:
    consumers like CheckpointManager retrieve it through the future, and
    the drain backstop raises only for never-polled failures."""

    error_retrieved = False

    def __init__(self):
        super().__init__()
        # set by the producer AFTER set_result/set_exception returns, i.e.
        # after done-callbacks ran — the drain waits on this, not on the
        # future's state, so it can't observe a failure mid-delivery
        self.settled = threading.Event()

    def result(self, timeout=None):
        try:
            return super().result(timeout)
        except BaseException as e:
            # only the future's OWN error counts as retrieved — a wait
            # timeout / interrupt (even one racing the completion) must
            # not swallow the real failure from the later drain backstop
            try:
                own = super().exception(timeout=0) if self.done() else None
            except BaseException:
                own = None
            if own is not None and e is own:
                self.error_retrieved = True
            raise

    def exception(self, timeout=None):
        e = super().exception(timeout)
        if e is not None:
            self.error_retrieved = True
        return e

    def cancel(self):
        # the background write is not cancellable: a True here would let
        # _relay's set_result/set_exception raise InvalidStateError and
        # lose the write's real outcome
        return False


_CKPT_POOL = None


def _ckpt_pool():
    """Process-wide single-worker writer pool: shared across every
    ShardedTrainStep so repeated step construction (elastic restarts,
    sweeps) doesn't accumulate idle checkpoint threads."""
    global _CKPT_POOL
    if _CKPT_POOL is None:
        _CKPT_POOL = _cf.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="mxtpu-ckpt")
    return _CKPT_POOL


def _gather_to_host(x):
    """Fetch a (possibly multi-process-sharded) jax array to host numpy.
    Single-process arrays are fully addressable; multi-process global arrays
    need the allgather helper."""
    if not isinstance(x, jax.Array) or x.is_fully_addressable:
        return jax.device_get(x)
    from jax.experimental import multihost_utils
    return multihost_utils.process_allgather(x, tiled=True)


def _shard_from_host(arr, sharding):
    """Place a host array with `sharding`; works when the mesh spans
    multiple processes (each process fills only its addressable shards)."""
    a = jnp.asarray(arr) if jax.process_count() == 1 else arr
    if jax.process_count() == 1:
        return jax.device_put(a, sharding)
    arr = onp.asarray(arr)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def _pack_bucket(leaf, bucket):
    """Flatten + zero-pad a logical optimizer-state leaf into its
    dp-bucket representation ``(padded_size,)``.  Works on host numpy
    (checkpoint load) and on traced jax values (inside the step)."""
    shape, padded = bucket
    size = int(onp.prod(shape)) if shape else 1
    if isinstance(leaf, onp.ndarray):
        flat = leaf.reshape(-1)
        if padded == size:
            return flat
        return onp.concatenate(
            [flat, onp.zeros(padded - size, leaf.dtype)])
    flat = jnp.ravel(leaf)
    if padded == size:
        return flat
    return jnp.pad(flat, (0, padded - size))


def _with_dp_axis(mesh: Mesh, spec, shape):
    """Add 'dp' to the first free divisible dim of `spec`; None when the
    mesh has no dp>1 axis, 'dp' is already used, or no dim divides."""
    dp = dict(mesh.shape).get("dp", 1)
    if dp <= 1 or not shape:
        return None
    spec = list(spec) + [None] * (len(shape) - len(spec))
    if "dp" in _spec_axes(spec):
        return None
    for i, dim in enumerate(shape):
        if spec[i] is None and dim % dp == 0:
            spec[i] = "dp"
            return NamedSharding(mesh, P(*spec))
    return None


def _master_dtype(w):
    """Optimizer state for 16-bit weights accumulates in fp32 (the
    multi-precision default; bf16 m/v drifts) — hand `create_state_jax` an
    fp32 ShapeDtypeStruct so `zeros_like` state comes out fp32 WITHOUT
    materializing an fp32 copy of the parameter (2x HBM spike at init)."""
    if jnp.issubdtype(w.dtype, jnp.floating) and \
            jnp.dtype(w.dtype).itemsize < 4:
        return jax.ShapeDtypeStruct(w.shape, jnp.float32)
    return w


def _like_sharding(param_sharding: NamedSharding, state_leaf, param):
    """Optimizer state shards like its parameter when shapes match, else
    replicated (e.g. row-wise accumulators)."""
    if hasattr(state_leaf, "shape") and tuple(state_leaf.shape) == \
            tuple(param.shape):
        return param_sharding
    return NamedSharding(param_sharding.mesh, P())


def make_sharded_train_step(block, optimizer, loss_fn, mesh, rules=None,
                            batch_specs=None, num_model_args=None,
                            zero=False, fsdp=False,
                            grad_accum=1, donate=True,
                            grad_compress=None) -> ShardedTrainStep:
    return ShardedTrainStep(block, optimizer, loss_fn, mesh, rules,
                            batch_specs, num_model_args, zero=zero,
                            fsdp=fsdp, grad_accum=grad_accum, donate=donate,
                            grad_compress=grad_compress)
