"""Sharded training step — the GSPMD replacement for the reference's
KVStore data-parallel pipeline (`src/kvstore/`, `gluon/trainer.py` push/pull).

One jitted function carries forward + backward + optimizer update for the
whole model, with parameters/optimizer state laid out by `ShardingRules` over
a named mesh (dp/tp/sp/...). XLA inserts the gradient psum over 'dp'
(all-reduce riding ICI), TP collectives around row/column-parallel matmuls,
and ring-attention ppermutes when sequence parallelism is active. Buffers are
donated, so weights update in place — the `static_alloc` end-state.
"""
from __future__ import annotations

import concurrent.futures as _cf
import functools
import threading
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import logging

import numpy as onp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError
from ..gluon.block import Block, functional_call
from ..gluon.parameter import Parameter
from ..optimizer import Optimizer
from .sharding import ShardingRules, default_tp_rules

__all__ = ["ShardedTrainStep", "make_sharded_train_step"]

_log = logging.getLogger(__name__)


def _spec_axes(spec):
    """Flatten a PartitionSpec's entries to the set of mesh-axis names."""
    return {a for e in spec
            for a in ((e,) if isinstance(e, str) else (e or ()))}


def _put_global(x, sharding):
    """Place a host value onto a (possibly multi-process) sharding.

    Single-process meshes use plain device_put. When the mesh spans
    processes (SURVEY §5.8: one controller per host, SPMD over the global
    mesh), every process holds the identical GLOBAL value and contributes
    its addressable shards — the multi-controller idiom that replaces the
    reference's worker-local batch + ps-lite aggregation."""
    if getattr(sharding, "is_fully_addressable", True):
        return jax.device_put(x, sharding)
    arr = onp.asarray(x)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


class ShardedTrainStep:
    """Compiled data/tensor/sequence-parallel training step for a Gluon block.

    loss_fn(out, *batch_rest) -> scalar jax value, where `out` is the
    block's (jax-valued) output tree.
    """

    def __init__(self, block: Block, optimizer: Optimizer,
                 loss_fn: Callable, mesh: Mesh,
                 rules: Optional[ShardingRules] = None,
                 batch_specs: Optional[Tuple] = None,
                 num_model_args: Optional[int] = None,
                 grad_accum_dtype=jnp.float32, grad_accum: int = 1,
                 zero: bool = False, fsdp: bool = False):
        # ZeRO stage 1: shard optimizer state over the 'dp' axis instead
        # of replicating it (params stay replicated; XLA inserts the
        # reduce-scatter/all-gather around the sharded update). Cuts
        # optimizer-state HBM by the dp degree — for Adam on bf16 weights
        # that's 4x the weight bytes saved per extra dp shard.
        self.zero = zero
        # FSDP (ZeRO stage 3): ALSO shard the parameters themselves over
        # 'dp' (first free divisible dim); XLA all-gathers each weight
        # just-in-time at its use and keeps gradients reduce-scattered.
        # Implies zero (sharded params get matching sharded state).
        self.fsdp = fsdp
        if fsdp:
            self.zero = True
        self._zero_warned = set()
        # accumulate gradients over this many microbatches per step (the
        # global batch splits on its leading dim; must divide it)
        if grad_accum < 1:
            raise MXNetError(f"grad_accum must be >= 1, got {grad_accum}")
        self.grad_accum = int(grad_accum)
        self.grad_accum_dtype = grad_accum_dtype
        self.block = block
        # how many leading batch args feed block.forward; the rest (labels
        # etc.) only reach loss_fn. None = all.
        self.num_model_args = num_model_args
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.rules = rules or default_tp_rules()
        self.batch_specs = batch_specs
        self._step_fn = None
        self._n_batch_args = None

        params = {n: p for n, p in block.collect_params().items()
                  if p._data is not None}
        if not params:
            raise MXNetError("block has no initialized parameters; call "
                             "initialize() (and one forward for deferred "
                             "shapes) first")
        self.param_names = sorted(params)
        self.params = params
        self.diff_names = [n for n in self.param_names
                           if params[n].grad_req != "null"]

        # place parameters + optimizer state on the mesh. An explicit
        # Parameter(sharding=...) annotation wins over the rules table; a
        # large parameter matching no rule logs a warning instead of
        # silently replicating (round-1 verdict: silent fall-through).
        self.param_shardings = {
            n: self._resolve_sharding(n, params[n]) for n in self.param_names}
        self.pvals = {n: _put_global(params[n]._data._data,
                                     self.param_shardings[n])
                      for n in self.param_names}
        self.opt_state = {
            n: jax.tree_util.tree_map(
                lambda s, _n=n: _put_global(s, self._state_sharding(
                    self.param_shardings[_n], s, params[_n])),
                optimizer.create_state_jax(_master_dtype(self.pvals[n])))
            for n in self.diff_names}
        self._t = 0

    # parameters below this size stay replicated under fsdp (per-use
    # all-gathers of tiny biases cost more than they save)
    FSDP_MIN_SIZE = 8192

    def _maybe_fsdp(self, sharding: NamedSharding, param) -> NamedSharding:
        if not self.fsdp or \
                int(onp.prod(param.shape)) < self.FSDP_MIN_SIZE:
            return sharding
        ns = _with_dp_axis(self.mesh, sharding.spec, param.shape)
        return ns if ns is not None else sharding

    def _state_sharding(self, param_sharding, state_leaf, param):
        """Placement for one optimizer-state leaf: like the parameter —
        plus, under ZeRO, the first unsharded divisible dim spread over
        'dp' (the reduce-scatter/all-gather pattern XLA then emits is
        exactly ZeRO stage 1)."""
        base = _like_sharding(param_sharding, state_leaf, param)
        if not self.zero or "dp" not in self.mesh.axis_names:
            return base
        shape = getattr(state_leaf, "shape", ())
        ns = _with_dp_axis(self.mesh, base.spec, shape)
        if ns is not None:
            return ns
        key = (tuple(param.shape), tuple(shape))
        if "dp" not in _spec_axes(base.spec) and shape \
                and self.mesh.shape["dp"] > 1 \
                and key not in self._zero_warned:
            self._zero_warned.add(key)
            _log.warning(
                "zero=True: optimizer-state leaf %s for parameter of "
                "shape %s cannot shard over dp=%d (no free divisible "
                "dim); it stays replicated", tuple(shape),
                tuple(param.shape), self.mesh.shape["dp"])
        return base

    def _resolve_sharding(self, name: str, param) -> NamedSharding:
        mesh = self.mesh
        ann = getattr(param, "sharding", None)
        if ann is not None:
            # explicit annotations are validated strictly: a typo must not
            # silently replicate a deliberately-sharded parameter
            if isinstance(ann, str):
                ann = (ann,)
            spec = ann if isinstance(ann, P) else P(*ann)
            if len(spec) > len(param.shape):
                raise MXNetError(
                    f"parameter {name}: sharding annotation {tuple(spec)} "
                    f"has rank {len(spec)} > parameter rank "
                    f"{len(param.shape)} (shape {tuple(param.shape)})")
            names = set(mesh.axis_names)
            from .mesh import AXES as _KNOWN_AXES
            cleaned = []
            for a in spec:
                axes = (a,) if isinstance(a, str) else tuple(a or ())
                kept = []
                for ax in axes:
                    if ax in names:
                        kept.append(ax)
                    elif ax in _KNOWN_AXES:
                        # a standard parallelism axis this mesh runs at
                        # size 1 (make_mesh drops those): the annotation
                        # degrades to replicated on that axis, so the same
                        # model code works when the mesh shrinks
                        continue
                    else:
                        raise MXNetError(
                            f"parameter {name}: sharding annotation names "
                            f"mesh axis {ax!r} but this mesh has axes "
                            f"{sorted(names)}")
                cleaned.append(kept[0] if len(kept) == 1
                               else (tuple(kept) if kept else None))
            spec = P(*cleaned)
            return self._maybe_fsdp(NamedSharding(mesh, spec), param)
        sharding = self.rules.sharding_for(mesh, name, param.shape)
        # 'dp' replicates params by design; 'sp' shards activations, never
        # params — only true model axes (tp/ep/...) make replication a smell.
        # Checked BEFORE the fsdp augment: fsdp's dp axis doesn't cure
        # replication across tp/ep
        model_axes = [a for a in mesh.axis_names if a not in ("dp", "sp")
                      and mesh.shape[a] > 1]
        if sharding.spec == P() and model_axes and \
                int(onp.prod(param.shape)) >= 1_000_000:
            _log.warning(
                "parameter %s %s matched no sharding rule and will be "
                "REPLICATED across the %s mesh axes; annotate it with "
                "Parameter(sharding=...) or extend ShardingRules",
                name, tuple(param.shape), model_axes)
        return self._maybe_fsdp(sharding, param)

    # ------------------------------------------------------------------
    def _build(self, batch_vals, rng_key):
        mesh = self.mesh
        if self.batch_specs is None:
            # default: shard leading batch dim over 'dp' (+'sp' on axis 1 if
            # the mesh has it and the arg is rank>=2)
            axes = set(mesh.axis_names)
            specs = []
            for b in batch_vals:
                spec = [None] * b.ndim
                if b.ndim >= 1 and "dp" in axes:
                    spec[0] = "dp"
                if b.ndim >= 2 and "sp" in axes:
                    spec[1] = "sp"
                specs.append(P(*spec))
            self.batch_specs = tuple(specs)
        batch_shardings = tuple(NamedSharding(mesh, s)
                                for s in self.batch_specs)
        self._batch_shardings = batch_shardings

        block, loss_fn, optimizer = self.block, self.loss_fn, self.optimizer
        diff_names = self.diff_names

        n_model = self.num_model_args

        k = self.grad_accum
        accum_dtype = self.grad_accum_dtype

        def step(pvals, opt_state, hp, key, *batch):
            def compute_loss(diff_vals, mkey, *mb):
                pv = dict(pvals)
                pv.update(diff_vals)
                model_args = mb if n_model is None else mb[:n_model]
                out, aux = functional_call(block, pv, *model_args,
                                           training=True, rng_key=mkey)
                loss = loss_fn(out, *mb)
                # a loss_fn written in mx.np ops returns a wrapped scalar;
                # unwrap so value_and_grad sees a jax value
                loss = getattr(loss, "_data", loss)
                return loss, aux

            diff_vals = {n: pvals[n] for n in diff_names}
            if k == 1:
                (loss, aux), grads = jax.value_and_grad(
                    compute_loss, has_aux=True)(diff_vals, key, *batch)
            else:
                # gradient accumulation: scan over k microbatches,
                # accumulating mean-of-means grads at accum_dtype — the
                # large-effective-batch path (reference Trainer's
                # update-skipping idiom, compiled into one program)
                micro = []
                for bi, b in enumerate(batch):
                    if b.ndim < 1 or b.shape[0] % k:
                        raise MXNetError(
                            f"grad_accum={k} must divide every batch "
                            f"arg's leading dim; got shape "
                            f"{tuple(b.shape)}")
                    mb = b.reshape((k, b.shape[0] // k)
                                   + tuple(b.shape[1:]))
                    # keep each microbatch dp-sharded on ITS batch dim —
                    # without the constraint GSPMD can move 'dp' onto the
                    # scan axis and every iteration pays a reshard
                    spec = (self.batch_specs[bi]
                            if self.batch_specs else None)
                    if spec is not None and "dp" in _spec_axes(spec):
                        mb = jax.lax.with_sharding_constraint(
                            mb, NamedSharding(mesh, P(None, *spec)))
                    micro.append(mb)
                micro = tuple(micro)
                keys = jax.random.split(key, k)

                def body(carry, xs):
                    acc, lsum = carry
                    mkey, mb = xs[0], xs[1:]
                    (loss, aux), grads = jax.value_and_grad(
                        compute_loss, has_aux=True)(diff_vals, mkey, *mb)
                    acc = jax.tree_util.tree_map(
                        lambda a, g: a + g.astype(accum_dtype), acc, grads)
                    return (acc, lsum + loss), aux

                init = (jax.tree_util.tree_map(
                    lambda v: jnp.zeros(v.shape, accum_dtype), diff_vals),
                    jnp.zeros((), accum_dtype))
                (acc, lsum), auxes = jax.lax.scan(
                    body, init, (keys,) + micro)
                grads = jax.tree_util.tree_map(
                    lambda a, v: (a / k).astype(v.dtype), acc, diff_vals)
                loss = (lsum / k).astype(jnp.float32)
                # running-stat writebacks: keep the final microbatch's
                aux = jax.tree_util.tree_map(lambda x: x[-1], auxes)
            new_p = dict(pvals)
            new_s = {}
            for n in diff_names:
                w, s = optimizer._rule(pvals[n], grads[n], opt_state[n], hp)
                # low-precision training: fp32 hyperparameter scalars
                # promote the update math (desired — that's the implicit
                # master-weight path; state was created fp32 above), but
                # the stored weight/state dtypes must stay EXACTLY as
                # declared or donation breaks and every step retraces
                if w.dtype != pvals[n].dtype:
                    w = w.astype(pvals[n].dtype)
                s = jax.tree_util.tree_map(
                    lambda new, old: new.astype(old.dtype)
                    if hasattr(new, "dtype") and new.dtype != old.dtype
                    else new, s, opt_state[n])
                new_p[n] = w
                new_s[n] = s
            new_p.update(aux)  # running-stat writebacks
            return new_p, new_s, loss

        pspec = {n: self.param_shardings[n] for n in self.param_names}
        sspec = {
            n: jax.tree_util.tree_map(
                lambda s, _n=n: self._state_sharding(
                    self.param_shardings[_n], s, self.params[_n]),
                self.opt_state[n])
            for n in self.diff_names}
        repl = NamedSharding(mesh, P())
        self._step_fn = jax.jit(
            step,
            in_shardings=(pspec, sspec, None, None) + batch_shardings,
            out_shardings=(pspec, sspec, repl),
            donate_argnums=(0, 1))

    def _check_global_batch(self, batch_vals) -> None:
        """First-step guard: on a mesh spanning processes, assert every
        process passed the same global batch (cheap checksum allgather)."""
        if all(getattr(s, "is_fully_addressable", True)
               for s in self._batch_shardings):
            return
        from jax.experimental import multihost_utils
        sums = onp.asarray(
            [float(jnp.sum(jnp.abs(jnp.asarray(b, jnp.float32))))
             for b in batch_vals], onp.float32)
        gathered = multihost_utils.process_allgather(sums)
        if not onp.allclose(gathered, gathered[0], rtol=1e-5):
            raise MXNetError(
                "ShardedTrainStep on a multi-process mesh requires every "
                "process to pass the IDENTICAL global batch (each host "
                "contributes its addressable shards). Got differing batch "
                f"checksums across processes: {gathered.tolist()}. If each "
                "worker loads its own shard, concatenate/allgather to the "
                "global batch first (or give every worker the same data "
                "stream + global indices).")

    # ------------------------------------------------------------------
    def __call__(self, *batch, rng_key=None):
        """Run one step; returns the (replicated) scalar loss as jax array.

        Multi-process meshes: every process must pass the identical GLOBAL
        batch (each contributes its addressable shards — see `_put_global`);
        the first step cross-checks this so the per-host-shard habit from
        the reference's KVStore path fails loudly instead of training on a
        silent patchwork of half-dropped data."""
        from .. import random as _rng
        batch_vals = [b._data if hasattr(b, "_data") else jnp.asarray(b)
                      for b in batch]
        if self._step_fn is None:
            self._build(batch_vals, rng_key)
            self._check_global_batch(batch_vals)
        self._t += 1
        o = self.optimizer
        hp = {"lr": jnp.asarray(o.learning_rate, jnp.float32),
              "wd": jnp.asarray(o.wd, jnp.float32),
              "rescale_grad": jnp.asarray(o.rescale_grad, jnp.float32),
              "clip_gradient": o.clip_gradient,
              "t": jnp.asarray(self._t, jnp.float32)}
        key = rng_key if rng_key is not None else _rng.next_key()
        batch_vals = [_put_global(b, s)
                      for b, s in zip(batch_vals, self._batch_shardings)]
        self.pvals, self.opt_state, loss = self._step_fn(
            self.pvals, self.opt_state, hp, key, *batch_vals)
        # rebind block Parameters to the fresh (non-donated) buffers so
        # eager reads (p.data()) stay valid — pointer update only
        self.sync_params_to_block()
        return loss

    def sync_params_to_block(self):
        """Write the (sharded) trained values back into the Parameters."""
        for n in self.param_names:
            self.params[n]._data._data = self.pvals[n]

    # -- checkpoint/resume ----------------------------------------------
    # Parity: `gluon/trainer.py:510,537` (save_states/load_states) widened
    # to the full sharded training state — params + optimizer state + step
    # counter + host RNG — so a killed job resumes bit-exact (the recovery
    # story SURVEY.md §5.3 plans as a new capability).

    def save(self, path: str) -> None:
        """Checkpoint params, optimizer state, step count, and RNG to `path`
        (.npz). Sharded arrays are gathered to host; `load` re-shards."""
        self._drain_async_save()
        self._write_checkpoint(path, self._snapshot())

    def save_async(self, path: str):
        """Non-blocking checkpoint: snapshot the training state as
        device-side COPIES (async dispatches — cheap to enqueue) and
        gather + write in a background thread while training continues.
        Returns a handle; call `.result()` to wait and re-raise any
        writer error.  Copies, not references: the jitted step donates
        its param/state buffers (`donate_argnums`), so the next step()
        would invalidate snapshotted originals on TPU — the private
        copies are untouched by donation.  Costs one transient extra
        params+opt-state footprint in HBM until the write drains.  The
        reference has no analogue — its NDArrays are mutable, so
        `save_states` must stop the engine (SURVEY §5.4's recovery story
        without the stall).

        Only one async save runs at a time: a second call waits for the
        first.  Multi-process meshes fall back to a synchronous save —
        the cross-host allgather must not race training collectives."""
        multi = any(not getattr(s, "is_fully_addressable", True)
                    for s in self.param_shardings.values())
        if multi:
            self.save(path)
            done: _cf.Future = _cf.Future()
            done.set_result(path)
            return done
        return self._submit_async_save(path)

    def _submit_async_save(self, path: str):
        self._drain_async_save()
        snap = self._snapshot(copy=True)
        raw = _ckpt_pool().submit(self._write_checkpoint, path, snap)
        fut = _ObservedFuture()

        def _relay(f):
            e = f.exception()  # retrieves — the raw future never warns
            try:
                if e is None:
                    fut.set_result(path)
                else:
                    fut.set_exception(e)
            finally:
                fut.settled.set()

        raw.add_done_callback(_relay)
        self._ckpt_last = fut
        return fut

    _ckpt_last = None

    def _drain_async_save(self):
        """Wait for any in-flight async save; re-raise its error ONLY if
        no holder of the returned future retrieved it yet (backstop for
        saves the caller never polled).  An error that `CheckpointManager`
        (or any `.result()` caller) already consumed is NOT raised again —
        otherwise one failed background write would abort the NEXT
        save/save_async synchronously, escaping ElasticLoop's tolerant
        drain and defeating its documented max_restores failure budget."""
        fut, self._ckpt_last = self._ckpt_last, None
        if fut is None:
            return
        fut.settled.wait()
        if fut.error_retrieved:
            return
        fut.result()

    def _snapshot(self, copy: bool = False):
        """Consistent view of the current training state.  With
        `copy=True` every device array is copied (async dispatch) so the
        snapshot survives the next step's buffer donation."""
        from .. import random as _rng
        g = _rng.generator
        dup = (lambda x: jnp.copy(x)) if copy else (lambda x: x)
        return {
            "pvals": {n: dup(v) for n, v in self.pvals.items()},
            "opt_state": {n: [dup(leaf) for leaf in
                              jax.tree_util.tree_leaves(self.opt_state[n])]
                          for n in self.diff_names},
            "t": self._t,
            "rng_seed": g._seed,
            "rng_key": g._key,
        }

    def _write_checkpoint(self, path: str, snap) -> str:
        from ..util import npz_encode_entry

        def put(out, key, val):
            npz_encode_entry(out, key, onp.asarray(_gather_to_host(val)))

        out = {}
        for n in self.param_names:
            put(out, "p:" + n, snap["pvals"][n])
        for n in self.diff_names:
            for i, leaf in enumerate(snap["opt_state"][n]):
                put(out, f"s:{n}:{i}", leaf)
        out["meta:t"] = onp.asarray(snap["t"], onp.int64)
        out["meta:rng_seed"] = onp.asarray(snap["rng_seed"], onp.int64)
        if snap["rng_key"] is not None:
            put(out, "meta:rng_key", snap["rng_key"])
        # Multi-process meshes: every rank gathered the identical global
        # payload above (collectives), and every rank writes it — to a
        # pid-suffixed tmp so concurrent writers never interleave within
        # one file; the atomic replaces then race benignly (identical
        # content, last one wins, `path` is always complete). Skipping
        # the write on rank != 0 would break callers that hand each rank
        # its own tmp path and replace afterwards (CheckpointManager).
        import os
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            onp.savez(f, **out)
        os.replace(tmp, path)   # atomic: a crash never truncates `path`
        return path

    def load(self, path: str) -> None:
        """Restore a `save` checkpoint; arrays are re-placed with this
        step's shardings (the mesh/topology may differ from save time)."""
        from .. import random as _rng

        from ..util import npz_decode_entry
        with onp.load(path, allow_pickle=False) as z:
            raw = dict(npz_decode_entry(k, z[k]) for k in z.files)

        for n in self.param_names:
            if "p:" + n not in raw:
                raise MXNetError(f"checkpoint {path} missing parameter {n}")
            self.pvals[n] = _shard_from_host(raw["p:" + n],
                                             self.param_shardings[n])
        for n in self.diff_names:
            leaves, treedef = jax.tree_util.tree_flatten(self.opt_state[n])
            new_leaves = []
            for i, old in enumerate(leaves):
                key = f"s:{n}:{i}"
                if key not in raw:
                    raise MXNetError(
                        f"checkpoint {path} missing optimizer state {key} "
                        f"(optimizer type changed since save?)")
                val = raw[key]
                # restore at the CURRENT state dtype: a checkpoint written
                # before the fp32-master-state default would otherwise pin
                # bf16 m/v back onto a step compiled for fp32 state
                if hasattr(old, "dtype") and val.dtype != old.dtype:
                    val = val.astype(old.dtype)
                sharding = self._state_sharding(self.param_shardings[n],
                                                val, self.params[n])
                new_leaves.append(_shard_from_host(val, sharding))
            self.opt_state[n] = jax.tree_util.tree_unflatten(
                treedef, new_leaves)
        self._t = int(raw["meta:t"])
        g = _rng.generator
        g._seed = int(raw.get("meta:rng_seed", g._seed))
        if "meta:rng_key" in raw:
            g._key = jnp.asarray(raw["meta:rng_key"])
        else:
            # checkpoint predates any RNG draw: clear this process's
            # (possibly advanced) key so draws restart from PRNGKey(seed)
            g._key = None
        self.sync_params_to_block()


class _ObservedFuture(_cf.Future):
    """Future that records whether its exception was ever retrieved
    (`result()` raised it or `exception()` returned it).  Lets
    `_drain_async_save` deliver a failed write's error exactly once:
    consumers like CheckpointManager retrieve it through the future, and
    the drain backstop raises only for never-polled failures."""

    error_retrieved = False

    def __init__(self):
        super().__init__()
        # set by the producer AFTER set_result/set_exception returns, i.e.
        # after done-callbacks ran — the drain waits on this, not on the
        # future's state, so it can't observe a failure mid-delivery
        self.settled = threading.Event()

    def result(self, timeout=None):
        try:
            return super().result(timeout)
        except BaseException as e:
            # only the future's OWN error counts as retrieved — a wait
            # timeout / interrupt (even one racing the completion) must
            # not swallow the real failure from the later drain backstop
            try:
                own = super().exception(timeout=0) if self.done() else None
            except BaseException:
                own = None
            if own is not None and e is own:
                self.error_retrieved = True
            raise

    def exception(self, timeout=None):
        e = super().exception(timeout)
        if e is not None:
            self.error_retrieved = True
        return e

    def cancel(self):
        # the background write is not cancellable: a True here would let
        # _relay's set_result/set_exception raise InvalidStateError and
        # lose the write's real outcome
        return False


_CKPT_POOL = None


def _ckpt_pool():
    """Process-wide single-worker writer pool: shared across every
    ShardedTrainStep so repeated step construction (elastic restarts,
    sweeps) doesn't accumulate idle checkpoint threads."""
    global _CKPT_POOL
    if _CKPT_POOL is None:
        _CKPT_POOL = _cf.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="mxtpu-ckpt")
    return _CKPT_POOL


def _gather_to_host(x):
    """Fetch a (possibly multi-process-sharded) jax array to host numpy.
    Single-process arrays are fully addressable; multi-process global arrays
    need the allgather helper."""
    if not isinstance(x, jax.Array) or x.is_fully_addressable:
        return jax.device_get(x)
    from jax.experimental import multihost_utils
    return multihost_utils.process_allgather(x, tiled=True)


def _shard_from_host(arr, sharding):
    """Place a host array with `sharding`; works when the mesh spans
    multiple processes (each process fills only its addressable shards)."""
    a = jnp.asarray(arr) if jax.process_count() == 1 else arr
    if jax.process_count() == 1:
        return jax.device_put(a, sharding)
    arr = onp.asarray(arr)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def _with_dp_axis(mesh: Mesh, spec, shape):
    """Add 'dp' to the first free divisible dim of `spec`; None when the
    mesh has no dp>1 axis, 'dp' is already used, or no dim divides."""
    dp = dict(mesh.shape).get("dp", 1)
    if dp <= 1 or not shape:
        return None
    spec = list(spec) + [None] * (len(shape) - len(spec))
    if "dp" in _spec_axes(spec):
        return None
    for i, dim in enumerate(shape):
        if spec[i] is None and dim % dp == 0:
            spec[i] = "dp"
            return NamedSharding(mesh, P(*spec))
    return None


def _master_dtype(w):
    """Optimizer state for 16-bit weights accumulates in fp32 (the
    multi-precision default; bf16 m/v drifts) — hand `create_state_jax` an
    fp32 ShapeDtypeStruct so `zeros_like` state comes out fp32 WITHOUT
    materializing an fp32 copy of the parameter (2x HBM spike at init)."""
    if jnp.issubdtype(w.dtype, jnp.floating) and \
            jnp.dtype(w.dtype).itemsize < 4:
        return jax.ShapeDtypeStruct(w.shape, jnp.float32)
    return w


def _like_sharding(param_sharding: NamedSharding, state_leaf, param):
    """Optimizer state shards like its parameter when shapes match, else
    replicated (e.g. row-wise accumulators)."""
    if hasattr(state_leaf, "shape") and tuple(state_leaf.shape) == \
            tuple(param.shape):
        return param_sharding
    return NamedSharding(param_sharding.mesh, P())


def make_sharded_train_step(block, optimizer, loss_fn, mesh, rules=None,
                            batch_specs=None, num_model_args=None,
                            zero=False, fsdp=False,
                            grad_accum=1) -> ShardedTrainStep:
    return ShardedTrainStep(block, optimizer, loss_fn, mesh, rules,
                            batch_specs, num_model_args, zero=zero,
                            fsdp=fsdp, grad_accum=grad_accum)
