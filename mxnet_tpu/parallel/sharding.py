"""Sharding rules: map parameter names/shapes to `PartitionSpec`s.

The TP/SP design (SURVEY.md §5.7, §2.4): instead of the reference's per-key
KVStore placement, parameters carry logical-axis annotations; a rule table
resolves logical axes to mesh axes. Megatron-style defaults for transformer
blocks: column-parallel qkv/ffn-in (shard output dim on 'tp'),
row-parallel proj/ffn-out (shard input dim on 'tp'), embeddings sharded on
vocab, everything replicated over 'dp'.
"""
from __future__ import annotations

import re
from typing import Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["ShardingRules", "default_tp_rules", "param_sharding",
           "shard_parameter_tree", "replicated", "retarget_spec"]


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def retarget_spec(spec: PartitionSpec, mesh: Mesh) -> PartitionSpec:
    """Re-target a `PartitionSpec` at a (possibly differently-shaped)
    mesh: axes the new mesh doesn't carry are dropped element-wise, so
    the same logical spec degrades gracefully when the mesh shrinks
    (e.g. ``P('dp', 'sp')`` on a dp-only mesh becomes ``P('dp', None)``).
    The elastic reshard path uses this for caller-supplied batch specs —
    rule-derived shardings re-run `ShardingRules.sharding_for` instead."""
    names = set(mesh.axis_names)
    clean = []
    for a in spec:
        axes = (a,) if isinstance(a, str) else tuple(a or ())
        kept = tuple(ax for ax in axes if ax in names)
        clean.append(kept[0] if len(kept) == 1
                     else (kept if kept else None))
    return PartitionSpec(*clean)


class ShardingRules:
    """Ordered (regex -> PartitionSpec) table over parameter names."""

    def __init__(self, rules: Sequence[Tuple[str, PartitionSpec]],
                 default: PartitionSpec = PartitionSpec()):
        self.rules = [(re.compile(p), spec) for p, spec in rules]
        self.default = default

    def spec_for(self, name: str, shape=None) -> PartitionSpec:
        for pat, spec in self.rules:
            if pat.search(name):
                if shape is not None and len(spec) > len(shape):
                    continue
                return spec
        return self.default

    def sharding_for(self, mesh: Mesh, name: str, shape=None) -> NamedSharding:
        spec = self.spec_for(name, shape)
        # drop axes not present in the mesh (tuple entries element-wise:
        # a partial match keeps only the mesh's axes)
        clean = list(retarget_spec(spec, mesh))
        # a dim the mesh axes don't divide evenly falls back to replicated
        # (e.g. an odd vocab over tp=2) instead of crashing at device_put
        if shape is not None:
            for i, a in enumerate(clean):
                if a is None:
                    continue
                axes = (a,) if isinstance(a, str) else tuple(a)
                ways = 1
                for ax in axes:
                    ways *= mesh.shape[ax]
                if shape[i] % ways != 0:
                    clean[i] = None
        return NamedSharding(mesh, PartitionSpec(*clean))


def default_tp_rules() -> ShardingRules:
    """Megatron-style TP rules for this package's layer naming.

    Weight layouts are (out, in) for Dense (reference FC layout), so
    column-parallel layers shard dim 0 on 'tp' and row-parallel shard dim 1.
    """
    return ShardingRules([
        # attention: qkv projections column-parallel, out proj row-parallel
        (r"(attn|attention).*(query|key|value|qkv|kv).*weight", PartitionSpec("tp", None)),
        (r"(attn|attention).*(query|key|value|qkv|kv).*bias", PartitionSpec("tp")),
        (r"(attn|attention).*(proj|out).*weight", PartitionSpec(None, "tp")),
        # mlp/ffn: in column-parallel, out row-parallel
        (r"(ffn|mlp|intermediate|fc1|dense1).*weight", PartitionSpec("tp", None)),
        (r"(ffn|mlp|intermediate|fc1|dense1).*bias", PartitionSpec("tp")),
        (r"(ffn_out|output|fc2|dense2|proj).*weight", PartitionSpec(None, "tp")),
        # embeddings: vocab-sharded
        (r"(word_embed|embedding|embed).*weight", PartitionSpec("tp", None)),
        # norms / scalars replicated
        (r"(gamma|beta|norm)", PartitionSpec()),
    ])


def param_sharding(mesh: Mesh, name: str, shape, rules: Optional[ShardingRules]
                   = None) -> NamedSharding:
    rules = rules or default_tp_rules()
    return rules.sharding_for(mesh, name, shape)


def shard_parameter_tree(params: Dict[str, jax.Array], mesh: Mesh,
                         rules: Optional[ShardingRules] = None):
    """Device-put a {name: jax.Array} tree with rule-derived shardings."""
    rules = rules or default_tp_rules()
    out = {}
    for name, v in params.items():
        sh = rules.sharding_for(mesh, name, v.shape)
        out[name] = jax.device_put(v, sh)
    return out
