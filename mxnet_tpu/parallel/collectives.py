"""Collective communication (parity: `src/kvstore/comm.h` reduce trees, NCCL
`kvstore_nccl.h`, ps-lite — all replaced by XLA collectives over ICI/DCN).

These wrappers are usable inside `shard_map`/`pjit` bodies; outside a mapped
context they degrade to identity (single device).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["allreduce", "allgather", "reduce_scatter", "broadcast",
           "ppermute_shift", "all_to_all", "axis_index", "axis_size"]


def allreduce(x, axis_name: str = "dp", op: str = "sum"):
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(f"unknown reduce op {op}")


def allgather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, axis: int = 0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def broadcast(x, axis_name: str, src: int = 0):
    idx = lax.axis_index(axis_name)
    n = axis_size(axis_name)
    perm = [(src, i) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def ppermute_shift(x, axis_name: str, shift: int = 1):
    """Ring shift: device i sends to (i+shift) mod n (ring-attention hop)."""
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name: str, split_axis: int, concat_axis: int):
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def axis_index(axis_name: str):
    return lax.axis_index(axis_name)


def axis_size(axis_name: str):
    """Static size of a named mesh axis, trace-safe inside shard_map.

    jax 0.4.x has no ``lax.axis_size``; ``lax.psum(1, axis)`` of a
    Python literal folds to a concrete int (usable in ``range()`` for
    ppermute permutations), which is the classic idiom the newer API
    replaced.  One compat point for every SP/PP collective."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)
