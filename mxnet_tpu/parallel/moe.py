"""Mixture-of-Experts with expert parallelism (the 'ep' mesh axis).

New capability beyond the reference (SURVEY.md §2.4 lists EP as absent
upstream): a Switch-Transformer-style top-1 routed FFN whose expert
weights are stacked on a leading expert dim and sharded over 'ep'. The
dispatch/combine are dense einsums over static capacity buffers — the
GSPMD-friendly formulation: with tokens sharded over 'dp' and experts over
'ep', XLA lowers the dispatch einsum to the expert all-to-all over ICI.

Everything is static-shaped (capacity_factor bounds tokens/expert; overflow
tokens are dropped, underflow is zero-padded) so the layer jits and
composes with the sharded train step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..gluon.block import HybridBlock
from ..gluon.parameter import Parameter
from ..ndarray.ndarray import apply_op

__all__ = ["MoEFeedForward", "switch_moe"]


def switch_moe(x, router_w, w_up, w_down, capacity_factor=1.25,
               activation="gelu", router_noise=0.0, rng_key=None):
    """Functional top-1 MoE over jax values.

    x: (B, L, H); router_w: (E, H); w_up: (E, I, H); w_down: (E, H, I).
    Returns (out (B, L, H), aux_loss scalar). Pure jax — safe under jit.
    """
    b, l, h = x.shape
    e = router_w.shape[0]
    tokens = b * l
    xt = x.reshape(tokens, h)

    logits = jnp.einsum("th,eh->te", xt.astype(jnp.float32),
                        router_w.astype(jnp.float32))
    if router_noise > 0.0 and rng_key is not None:
        logits = logits + router_noise * jax.random.normal(
            rng_key, logits.shape, logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1)            # (T, E)
    expert = jnp.argmax(probs, axis=-1)                # (T,)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]

    # load-balance aux loss (Switch eq. 4): E * sum_e f_e * p_e
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)   # (T, E)
    density = onehot.mean(0)
    density_proxy = probs.mean(0)
    aux_loss = e * jnp.sum(density * density_proxy)

    capacity = max(1, int(capacity_factor * tokens / e))
    # position of each token within its expert's buffer
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot       # (T, E)
    in_cap = (pos < capacity) & (onehot > 0)
    pos = jnp.sum(pos * in_cap, axis=-1).astype(jnp.int32)  # (T,)
    kept = jnp.any(in_cap, axis=-1)

    from ..ops.pallas import pallas_mode
    if pallas_mode() == "off":
        # legacy dense formulation: a one-hot (T, E, C) dispatch tensor
        # contracted twice — O(T·E·C·H) for what is a permutation.
        # Kept as the escape hatch and the overflow-semantics oracle.
        disp = (onehot * kept[:, None])[:, :, None] * jax.nn.one_hot(
            pos, capacity, dtype=x.dtype)[:, None, :]
        disp = disp.astype(x.dtype)
        buf = jnp.einsum("tec,th->ech", disp, xt)           # (E, C, H)
    else:
        # blockwise path (ops/pallas/moe_dispatch): scatter tokens to
        # their capacity cells — cost scales with T·H, not T·E·C·H
        from ..ops.pallas import moe_dispatch as _moed
        buf = _moed.moe_dispatch(xt, expert, pos, kept, e, capacity)

    # expert FFN (batched over E; sharded on 'ep' when annotated)
    up = jnp.einsum("ech,eih->eci", buf, w_up.astype(buf.dtype))
    if activation == "gelu":
        up = jax.nn.gelu(up)
    else:
        up = jax.nn.relu(up)
    down = jnp.einsum("eci,ehi->ech", up, w_down.astype(up.dtype))

    # combine weighted by the gate
    if pallas_mode() == "off":
        out = jnp.einsum("tec,ech->th", disp * gate[:, None, None].astype(
            x.dtype), down)
    else:
        from ..ops.pallas import moe_dispatch as _moed
        out = _moed.moe_combine(down, expert, pos, kept, gate)
    return out.reshape(b, l, h), aux_loss


class MoEFeedForward(HybridBlock):
    """Routed FFN layer for transformer blocks.

    Expert weights are stacked (E, ...) with `Parameter(sharding=('ep',
    ...))` annotations so `ShardedTrainStep` places one expert group per
    'ep' mesh slice. `forward` returns `(out, aux_loss)` — add the
    load-balance aux loss to the training loss scaled by e.g. 0.01
    (Switch Transformer's alpha). Returning it (rather than stashing it on
    an attribute) keeps the layer usable under jit/ShardedTrainStep, where
    a side-effect attribute would leak a tracer."""

    def __init__(self, hidden_size: int, intermediate_size: int,
                 num_experts: int, capacity_factor: float = 1.25,
                 activation: str = "gelu", dtype="float32"):
        super().__init__()
        if num_experts < 2:
            raise MXNetError("MoEFeedForward needs num_experts >= 2")
        self._cf = capacity_factor
        self._act = activation
        self.router = Parameter("router", shape=(num_experts, hidden_size),
                                dtype=dtype)
        self.expert_up = Parameter(
            "expert_up", shape=(num_experts, intermediate_size, hidden_size),
            dtype=dtype, sharding=("ep", None, None))
        self.expert_down = Parameter(
            "expert_down", shape=(num_experts, hidden_size,
                                  intermediate_size),
            dtype=dtype, sharding=("ep", None, None))

    def forward(self, x):
        def fn(xv, rw, wu, wd):
            out, aux = switch_moe(xv, rw, wu, wd,
                                  capacity_factor=self._cf,
                                  activation=self._act)
            return out, aux

        return apply_op(fn, (x, self.router.data(),
                             self.expert_up.data(),
                             self.expert_down.data()), {}, name="moe")
