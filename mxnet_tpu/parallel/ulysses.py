"""Ulysses (DeepSpeed-style) sequence parallelism: all-to-all head scatter.

The second sequence-parallel strategy SURVEY.md §5.7 plans (alongside ring
attention): instead of streaming K/V around the ring, one `all_to_all`
re-shards activations from sequence-sharded (B, H, L/n, D) to head-sharded
(B, H/n, L, D), runs FULL-sequence attention locally on the head subset
(any kernel — including the Pallas flash kernel — works unchanged because
each device sees the whole sequence), and a second `all_to_all` restores
sequence sharding.

Trade-off vs ring attention (public recipe): two all-to-alls of the
activations per attention call instead of n ppermutes of K/V — cheaper
when heads >> devices and ICI all-to-all bandwidth is good; requires
num_heads % n == 0 while ring requires seq % n == 0.
"""
from __future__ import annotations

import functools
from typing import Optional

from jax import lax
from jax.sharding import Mesh

from ..base import MXNetError

__all__ = ["ulysses_attention", "ulysses_attention_sharded"]


def ulysses_attention_sharded(q, k, v, kv_mask=None, axis_name: str = "sp",
                              causal: bool = False,
                              scale: Optional[float] = None,
                              attn_fn=None):
    """Attention over sequence-sharded q/k/v — call INSIDE shard_map.

    q, k, v: local shards (B, H, L_local, D) with the sequence axis sharded
    over `axis_name`. `kv_mask` is the LOCAL (B, L_local) key-validity
    shard; an all_gather over the tiny bool vector rebuilds the full-
    sequence mask each device needs after the head scatter. Returns the
    local (B, H, L_local, D) output shard.

    `attn_fn(q, k, v, mask=..., causal=..., scale=...)` runs on
    full-sequence, head-sharded blocks; defaults to the flash/reference
    dispatcher (masks stay on the Pallas kernel as its bias input).
    """
    from .collectives import axis_size
    n = axis_size(axis_name)
    b, h, l_loc, d = q.shape
    if h % n != 0:
        raise MXNetError(
            f"ulysses attention needs num_heads ({h}) divisible by the "
            f"'{axis_name}' axis size ({n}); use ring attention for "
            "head counts that don't divide")
    g = k.shape[1]
    if g != h and (g == 0 or h % g):
        raise MXNetError(f"query heads ({h}) must be a multiple of kv "
                         f"heads ({g})")
    if g != h and g % n != 0:
        # GQA with fewer kv heads than the axis can split: expand K/V to
        # full heads BEFORE the scatter (correct, but forfeits the GQA
        # all-to-all saving — ring attention keeps it for this shape)
        from ..ops.pallas.flash_attention import _expand_kv
        k, v = _expand_kv(k, v, h)
    if attn_fn is None:
        from ..ops.attention import dot_product_attention
        attn_fn = dot_product_attention

    # (B, H, L/n, D) -> tiled all_to_all swaps a head tile against the
    # sequence tiles: every device ends up with the FULL sequence for H/n
    # heads.
    qh, kh, vh = (lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                 tiled=True) for x in (q, k, v))

    kwargs = {}
    if kv_mask is not None:
        # (B, L_local) -> (B, L): bool gather is L bytes, negligible next
        # to the activation all-to-alls
        full = lax.all_gather(kv_mask, axis_name, axis=1, tiled=True)
        kwargs["mask"] = full[:, None, None, :]
    out = attn_fn(qh, kh, vh, causal=causal, scale=scale,
                  **kwargs)                                  # (B, H/n, L, D)

    # inverse: scatter sequence back, gather heads
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def ulysses_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                      causal: bool = False, scale: Optional[float] = None,
                      batch_axis: Optional[str] = "dp", attn_fn=None,
                      kv_mask=None):
    """Top-level Ulysses attention over (B, H, L, D) jax arrays; composes
    under jit/pjit like `ring_attention`. `kv_mask` is a (B, L) bool
    key-validity mask, sequence-sharded like k/v."""
    from .ring_attention import seq_sharded_call
    if kv_mask is None:
        fn = functools.partial(ulysses_attention_sharded,
                               axis_name=axis_name, causal=causal,
                               scale=scale, attn_fn=attn_fn)
        return seq_sharded_call(fn, q, k, v, mesh, axis_name, batch_axis)
    from jax.sharding import PartitionSpec as P
    from .mesh import shard_map_nocheck
    axes = set(mesh.axis_names)
    bspec = batch_axis if (batch_axis and batch_axis in axes) else None
    spec = P(bspec, None, axis_name, None)
    mspec = P(bspec, axis_name)

    def fn(qq, kk, vv, mm):
        return ulysses_attention_sharded(qq, kk, vv, kv_mask=mm,
                                         axis_name=axis_name, causal=causal,
                                         scale=scale, attn_fn=attn_fn)

    mapped = shard_map_nocheck(fn, mesh, (spec, spec, spec, mspec), spec)
    return mapped(q, k, v, kv_mask)
