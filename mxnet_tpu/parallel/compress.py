"""int8 gradient compression for the dp-axis all-reduce.

MXNet survey layer-8 parity (KVStore ``gradient_compression``): the
data-parallel gradient reduction carries int8 payloads instead of f32 —
on a real fabric that is 4x fewer wire bytes per step, the classic
bandwidth lever for large-dp training.

Scheme (the 1-bit/terngrad family's well-conditioned member):

- **per-bucket symmetric scale** — each gradient leaf is flattened and
  cut into fixed-size buckets (default 2048 elements); every bucket
  gets one f32 scale ``amax/127``, so a single outlier only damages its
  own bucket, not the whole tensor;
- **stochastic rounding** — ``q = floor(g/scale + u)``, u ~ U[0,1) from
  the step's PRNG key: quantization noise is zero-mean, so compressed
  SGD stays an unbiased estimator and converges at the f32 rate in
  expectation (the convergence dryrun in ``make quant-smoke`` checks
  exactly this);
- **f32 master accumulate** — dequantization and every optimizer-side
  use happen in f32; only the wire format narrows.

Placement note (docs/quantization.md): inside the GSPMD step the
gradient tree this module sees is already dp-reduced — XLA fuses the
cross-replica psum into the backward.  The compressor therefore models
the *numerics* of quantize → integer-accumulate → dequantize exactly
(per-bucket scale, stochastic rounding, f32 master), while the
wire-level int8 collective itself needs the explicit-collective step
variant — a TPU-validation item (ROADMAP §5): XLA:CPU would simulate,
not measure, the bandwidth win.  The knob is **off by default** because
it deliberately breaks bit-exactness with f32 training
(``MXTPU_GRAD_COMPRESS=int8`` / ``ShardedTrainStep(grad_compress=...)``).
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError

__all__ = ["DEFAULT_BUCKET", "resolve_grad_compress",
           "quantize_bucketed", "dequantize_bucketed", "compress_tree"]

DEFAULT_BUCKET = 2048
_INT8_MAX = 127.0


def resolve_grad_compress(value=None) -> str:
    """Resolve the compression knob: explicit ``value`` wins, else the
    ``MXTPU_GRAD_COMPRESS`` env, else ``"none"``.  Only ``"none"`` and
    ``"int8"`` exist today; unknown spellings raise (a typo must not
    silently train uncompressed)."""
    v = value if value is not None else \
        os.environ.get("MXTPU_GRAD_COMPRESS", "")
    v = str(v).strip().lower()
    if v in ("", "0", "none", "off", "false", "no"):
        return "none"
    if v == "int8":
        return "int8"
    raise MXNetError(
        f"unknown gradient compression {v!r}; supported: none, int8 "
        "(MXTPU_GRAD_COMPRESS / ShardedTrainStep(grad_compress=...))")


def quantize_bucketed(g, key, bucket: int = DEFAULT_BUCKET):
    """One leaf -> (q int8 (nb, bucket), scale f32 (nb,), meta).

    jit-safe; `meta` is the (static) original shape + element count for
    :func:`dequantize_bucketed`.  An all-zero (or non-finite-scaled)
    bucket quantizes to zeros with scale 0."""
    shape = tuple(g.shape)
    size = int(onp.prod(shape)) if shape else 1
    gf = g.astype(jnp.float32).reshape(-1)
    nb = -(-size // bucket)
    pad = nb * bucket - size
    if pad:
        gf = jnp.pad(gf, (0, pad))
    gb = gf.reshape(nb, bucket)
    amax = jnp.max(jnp.abs(gb), axis=1)
    # a non-finite bucket keeps scale 0 -> dequantizes to zeros; the
    # step's own non-finite probes/skip-guard own that failure mode
    amax = jnp.where(jnp.isfinite(amax), amax, 0.0)
    scale = amax / _INT8_MAX
    inv = jnp.where(scale > 0.0, 1.0 / jnp.maximum(scale, 1e-30), 0.0)
    u = jax.random.uniform(key, gb.shape, jnp.float32)
    q = jnp.clip(jnp.floor(gb * inv[:, None] + u),
                 -_INT8_MAX, _INT8_MAX).astype(jnp.int8)
    return q, scale, (shape, size)


def dequantize_bucketed(q, scale, meta, dtype=jnp.float32):
    """Inverse of :func:`quantize_bucketed` (f32 master values)."""
    shape, size = meta
    flat = (q.astype(jnp.float32) * scale[:, None]).reshape(-1)[:size]
    return flat.reshape(shape).astype(dtype)


def compress_tree(grads, key, bucket: int = DEFAULT_BUCKET):
    """Quantize-dequantize round over a whole gradient pytree — what
    the jitted train step applies between backward and the optimizer
    when ``grad_compress="int8"``.  Each leaf folds its index into the
    step key, so no two leaves (or steps) share rounding noise."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    out = []
    for i, g in enumerate(leaves):
        if not jnp.issubdtype(g.dtype, jnp.floating):
            out.append(g)
            continue
        lk = jax.random.fold_in(key, i)
        q, scale, meta = quantize_bucketed(g, lk, bucket)
        out.append(dequantize_bucketed(q, scale, meta, g.dtype))
    return jax.tree_util.tree_unflatten(treedef, out)
