"""Elastic training / fault tolerance (SURVEY.md §5.3 — NEW capability).

The reference has no recovery story: a dead ps-lite server or worker kills
the whole job (`src/kvstore/kvstore_dist.h` — no rejoin path; SURVEY §5.3).
On TPU the failure model is different and simpler to cover:

* **preemption** — Cloud TPU sends SIGTERM with a grace window; the right
  response is save-and-exit, then the scheduler restarts the job and it
  resumes from the newest checkpoint.
* **transient runtime errors** — tunnel/network hiccups or collective
  timeouts surface as ``RuntimeError`` / ``MXNetError`` at the sync point
  (XLA's async dispatch defers errors, like the reference engine's
  exception propagation, `src/engine/threaded_engine.h:67`). Recovery is
  restore-from-checkpoint and retry.
* **hangs** — a stuck collective never raises. A watchdog thread detects a
  step that stopped completing, dumps all-thread stacks, and (optionally)
  kills the process so the supervisor can restart it.

`ElasticLoop` composes these around any step callable and any checkpoint
target exposing ``save(path)``/``load(path)`` (canonically
`parallel.ShardedTrainStep`, via `utils.CheckpointManager`).

Usage::

    step = make_sharded_train_step(model, opt, loss_fn, mesh)
    loop = ElasticLoop(step, directory="/ckpts", save_every=500)
    loop.run(lambda i: step(*batch(i)), total_steps=10_000)
"""
from __future__ import annotations

import faulthandler
import logging
import os
import signal
import sys
import threading
import time
from typing import Callable, Optional, Sequence

import jax

from . import health as _health
from .base import MXNetError
from .resilience import fault_point, retry_with_backoff
from .utils.checkpoint import CheckpointManager

__all__ = ["PreemptionGuard", "Watchdog", "FailureInjector", "ElasticLoop",
           "sync_flag"]

_log = logging.getLogger(__name__)


class PreemptionGuard:
    """Convert termination signals into a cooperative stop flag.

    Installs handlers for `signals` (default SIGTERM — what Cloud TPU
    preemption delivers) that set :attr:`preempted` instead of killing the
    process, giving the training loop a grace window to checkpoint. Restores
    the previous handlers on exit. Signal handlers only work on the main
    thread; elsewhere the guard degrades to a manual flag
    (:meth:`request_stop`).
    """

    def __init__(self, signals: Sequence[int] = (signal.SIGTERM,)):
        self._signals = tuple(signals)
        self._prev = {}
        self._event = threading.Event()
        self._installed = False

    @property
    def preempted(self) -> bool:
        return self._event.is_set()

    def request_stop(self) -> None:
        """Manually trigger the stop flag (tests, custom schedulers)."""
        self._event.set()

    def _handler(self, signum, frame):
        _log.warning("received signal %d: requesting checkpoint-and-exit",
                     signum)
        self._event.set()

    def __enter__(self):
        if threading.current_thread() is threading.main_thread():
            for s in self._signals:
                self._prev[s] = signal.signal(s, self._handler)
            self._installed = True
        return self

    def __exit__(self, *exc):
        if self._installed:
            for s, h in self._prev.items():
                signal.signal(s, h)
            self._prev.clear()
            self._installed = False
        return False


class Watchdog:
    """Hang detector: a daemon thread that fires if :meth:`ping` is not
    called within `timeout` seconds.

    On expiry it dumps every thread's stack to stderr (the evidence a hung
    collective leaves nowhere else), invokes `on_hang`, and — when
    `kill=True` — SIGABRTs the process so a supervisor can restart it. The
    default is detect-and-report only.

    This is the LOOP-level detector (one ping per completed step).  The
    process-wide generalization lives in `mx.health.HangWatchdog`: every
    hot path (dispatch/retire, prefetch, DataLoader) touches a named
    heartbeat and one monitor covers them all, with a flight-recorder
    bundle on stall.  `ping` here also touches the ``elastic_step``
    heartbeat so both detectors share one liveness signal, and a firing
    expiry flushes a post-mortem bundle when the health subsystem is up.
    """

    def __init__(self, timeout: float, on_hang: Optional[Callable] = None,
                 kill: bool = False):
        if timeout <= 0:
            raise MXNetError("watchdog timeout must be positive")
        self.timeout = timeout
        self.on_hang = on_hang
        self.kill = kill
        self.fired = False
        self._bundle_dumped = False
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._thread = None

    def ping(self) -> None:
        self._last = time.monotonic()
        # progress since the last expiry: the next one is a NEW hang
        # episode and deserves a fresh post-mortem bundle
        self._bundle_dumped = False
        _health.beat("elastic_step")

    def _watch(self):
        while not self._stop.wait(min(self.timeout / 4, 1.0)):
            if _health.stalls_suppressed():
                # an announced long block (cold-start XLA compile inside
                # step_fn) produces no pings but is not a hang — mirror
                # the process-wide watchdog and restart the clock
                self._last = time.monotonic()
                continue
            if time.monotonic() - self._last > self.timeout:
                self.fired = True
                _log.error("watchdog: no step completion in %.1fs — "
                           "dumping stacks", self.timeout)
                try:
                    faulthandler.dump_traceback(file=sys.stderr)
                except Exception:
                    pass
                try:
                    # shared stall accounting (counter + journal event
                    # with heartbeats/in-flight ids); one bundle per
                    # hang episode (a persistent hang refires every
                    # window; ping() resets the flag)
                    _health.record_stall("elastic_watchdog", self.timeout,
                                         dump=not self._bundle_dumped)
                    self._bundle_dumped = True
                except Exception:
                    pass
                if self.on_hang is not None:
                    try:
                        self.on_hang()
                    except Exception:
                        _log.exception("watchdog on_hang callback failed")
                if self.kill:
                    os.kill(os.getpid(), signal.SIGABRT)
                self._last = time.monotonic()  # avoid refiring every poll

    def __enter__(self):
        self.ping()
        self._stop.clear()
        self._thread = threading.Thread(target=self._watch, daemon=True,
                                        name="mxtpu-watchdog")
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        return False


class FailureInjector:
    """Deterministic fault injection (SURVEY §5.3 names fault *injection*
    as part of the recovery test strategy). Raises `exc_type` the first
    time each step in `at_steps` is reached.

    Kept for programmatic use; the env-driven registry in
    `mxnet_tpu.resilience` (``MXTPU_FAULT_SPEC=elastic_step@N,...``)
    generalizes this to named points across the whole framework
    (checkpoint write/read, DataLoader worker execution) and crosses the
    spawn boundary into worker processes."""

    def __init__(self, at_steps: Sequence[int],
                 exc_type=RuntimeError):
        self._pending = set(at_steps)
        self._exc_type = exc_type
        self.injected = []

    def check(self, step: int) -> None:
        if step in self._pending:
            self._pending.discard(step)
            self.injected.append(step)
            raise self._exc_type(f"injected failure at step {step}")


# sync_flag's allgather retry budget: a collective that fails 3 times over
# ~1s of backoff is a down host, not a blip
_SYNC_RETRIES = 2
_SYNC_BASE_DELAY = 0.25


def sync_flag(flag: bool) -> bool:
    """Agree on a boolean across all processes (logical OR), so e.g. a
    preemption notice on one host checkpoints every host at the same step.
    Single-process: identity.

    Failure mode (multi-host): a transient collective error (tunnel reset,
    coordination-service hiccup) is retried with backoff
    (`resilience.retry_with_backoff`); once the budget is exhausted the
    hosts can no longer agree on a common step, so this raises
    `MXNetError` — the right response is to let the job die and resume
    every host from the newest checkpoint rather than checkpoint a
    diverged state.

    Caveat: the retry only helps for errors raised while *entering* the
    collective (before any peer commits to it — the common shape of
    coordination-service hiccups, which fail symmetrically). If one host
    errors after the others completed, its retried allgather pairs with
    the peers' NEXT collective (collectives match by program order) and
    the program is already lost to a hang or garbage — exactly the case
    the `MXNetError` path exists for: kill the job, restore all hosts
    from the newest checkpoint."""
    if jax.process_count() == 1:
        return bool(flag)
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    def _gather():
        v = multihost_utils.process_allgather(
            jnp.asarray([1 if flag else 0]))
        return bool(v.max())

    try:
        return retry_with_backoff(_gather, retries=_SYNC_RETRIES,
                                  base_delay=_SYNC_BASE_DELAY,
                                  retry_on=(RuntimeError, OSError))
    except (RuntimeError, OSError) as e:
        raise MXNetError(
            f"elastic.sync_flag: multi-host allgather failed after "
            f"{_SYNC_RETRIES} retries ({e}); hosts cannot agree on a "
            f"common step — restart the job and resume from the newest "
            f"checkpoint") from e


class ElasticLoop:
    """Checkpointed, preemption-aware, self-restoring training loop.

    Composes `CheckpointManager` (periodic atomic saves + resume),
    `PreemptionGuard` (SIGTERM → save-and-exit), `Watchdog` (hang report)
    and restore-retry on transient step failures around a user step
    function ``step_fn(i) -> loss``. Restores go through the manager's
    verified fallback chain: a corrupt latest checkpoint is quarantined
    and the rollback lands on the newest intact one, so bit-rot costs one
    (deeper) rollback instead of failing every restore-retry.

    The `target` must expose ``save(path)``/``load(path)``. Returns a dict
    with the exit status — ``"completed"``, ``"preempted"`` (checkpoint
    written; rerun to resume), or raises after `max_restores` failed
    recoveries.
    """

    def __init__(self, target, directory: str, save_every: int = 100,
                 keep: int = 3, max_restores: int = 3,
                 watchdog_timeout: Optional[float] = None,
                 retry_on=(RuntimeError, MXNetError),
                 failure_injector: Optional[FailureInjector] = None,
                 async_save: bool = False):
        self.target = target
        self.manager = CheckpointManager(directory, keep=keep)
        self.save_every = save_every
        self.max_restores = max_restores
        # MXTPU_STALL_TIMEOUT arms the loop-level watchdog too, so one
        # env var covers both the per-step and process-wide detectors
        if watchdog_timeout is None:
            watchdog_timeout = _health.stall_timeout()
        self.watchdog_timeout = watchdog_timeout
        self.retry_on = tuple(retry_on)
        self.failure_injector = failure_injector
        # periodic saves overlap training (ShardedTrainStep.save_async);
        # preemption/rollback/final saves stay synchronous — those must
        # be on disk before the process acts on them
        self.async_save = async_save

    _deferred_failures = 0

    def _drain_async_tolerant(self):
        """Surface-but-survive a deferred async-write failure: the loop's
        recovery/preemption/final paths must not let an OLD write error
        mask the operation they're about to perform (the last COMPLETE
        checkpoint on disk is still valid).  CONSECUTIVE failures are
        bounded like step failures — a full disk must not let the job
        run for days producing no durable checkpoints."""
        try:
            self.manager.wait_async()
            self._deferred_failures = 0
        except Exception as e:   # noqa: BLE001 — deliberately broad
            self._deferred_failures += 1
            if self._deferred_failures > self.max_restores:
                raise MXNetError(
                    f"elastic: {self._deferred_failures} consecutive async "
                    f"checkpoint writes failed; aborting rather than "
                    f"training without durable checkpoints") from e
            _log.warning(
                "elastic: a deferred async checkpoint write failed (%s); "
                "continuing from the last complete checkpoint "
                "(%d/%d consecutive)", e, self._deferred_failures,
                self.max_restores)

    def run(self, step_fn: Callable[[int], object], total_steps: int,
            on_step: Optional[Callable[[int, object], None]] = None) -> dict:
        restores = 0       # total, reported in the result
        consecutive = 0    # failed recoveries in a row, bounds the retry
        start = self.manager.restore(self.target)
        if start:
            _log.info("elastic: resumed from checkpoint at step %d", start)
        elif self.manager.latest() is None:
            # anchor checkpoint so a failure before the first periodic save
            # still has a consistent state to roll back to
            self.manager.save(self.target, 0)
        guard = PreemptionGuard()
        watchdog = (Watchdog(self.watchdog_timeout)
                    if self.watchdog_timeout else None)
        last_loss = None
        i = start
        with guard:
            ctx = watchdog if watchdog is not None else _null_ctx()
            with ctx:
                while i < total_steps:
                    if sync_flag(guard.preempted):
                        self._drain_async_tolerant()
                        path = self.manager.save(self.target, i)
                        _log.warning("elastic: preempted at step %d; "
                                     "checkpoint %s written", i, path)
                        return {"status": "preempted", "step": i,
                                "checkpoint": path, "restores": restores}
                    try:
                        # env-driven injection (MXTPU_FAULT_SPEC
                        # elastic_step@N — Nth step ATTEMPT, replays
                        # included, so a recovered run replays clean);
                        # generalizes the programmatic FailureInjector
                        fault_point("elastic_step")
                        if self.failure_injector is not None:
                            self.failure_injector.check(i)
                        last_loss = step_fn(i)
                        # a completed step proves the recovery worked;
                        # max_restores bounds CONSECUTIVE failed recoveries,
                        # not total hiccups over a long job's lifetime
                        consecutive = 0
                    except self.retry_on as e:
                        restores += 1
                        consecutive += 1
                        if consecutive > self.max_restores:
                            raise MXNetError(
                                f"elastic: step {i} failed after "
                                f"{self.max_restores} restores") from e
                        self._drain_async_tolerant()
                        rollback = self.manager.restore(self.target)
                        _log.warning(
                            "elastic: step %d failed (%s); restored "
                            "checkpoint at step %d (restore %d/%d)",
                            i, e, rollback, consecutive, self.max_restores)
                        i = rollback
                        continue
                    i += 1
                    if watchdog is not None:
                        watchdog.ping()
                    if on_step is not None:
                        on_step(i, last_loss)
                    # drain only when a save is DUE: draining every step
                    # would cap write/compute overlap at one step
                    if self.save_every > 0 and i % self.save_every == 0:
                        self._drain_async_tolerant()
                        self.manager.maybe_save(self.target, i,
                                                every=self.save_every,
                                                async_save=self.async_save)
        self._drain_async_tolerant()
        final = self.manager.save(self.target, total_steps)
        return {"status": "completed", "step": total_steps,
                "checkpoint": final, "restores": restores,
                "loss": last_loss}


class _null_ctx:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
