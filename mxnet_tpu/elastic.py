"""Elastic training / fault tolerance (SURVEY.md §5.3 — NEW capability).

The reference has no recovery story: a dead ps-lite server or worker kills
the whole job (`src/kvstore/kvstore_dist.h` — no rejoin path; SURVEY §5.3).
On TPU the failure model is different and simpler to cover:

* **preemption** — Cloud TPU sends SIGTERM with a grace window; the right
  response is save-and-exit, then the scheduler restarts the job and it
  resumes from the newest checkpoint.
* **transient runtime errors** — tunnel/network hiccups or collective
  timeouts surface as ``RuntimeError`` / ``MXNetError`` at the sync point
  (XLA's async dispatch defers errors, like the reference engine's
  exception propagation, `src/engine/threaded_engine.h:67`). Recovery is
  restore-from-checkpoint and retry.
* **hangs** — a stuck collective never raises. A watchdog thread detects a
  step that stopped completing, dumps all-thread stacks, and (optionally)
  kills the process so the supervisor can restart it.

`ElasticLoop` composes these around any step callable and any checkpoint
target exposing ``save(path)``/``load(path)`` (canonically
`parallel.ShardedTrainStep`, via `utils.CheckpointManager`).

Usage::

    step = make_sharded_train_step(model, opt, loss_fn, mesh)
    loop = ElasticLoop(step, directory="/ckpts", save_every=500)
    loop.run(lambda i: step(*batch(i)), total_steps=10_000)
"""
from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Callable, Optional, Sequence

import jax

from . import health as _health
from . import recovery as _recovery
from . import telemetry as _tele
from .base import MXNetError, SuspectedHostLoss
from .resilience import fault_point
from .utils.checkpoint import CheckpointManager

__all__ = ["PreemptionGuard", "Watchdog", "FailureInjector", "ElasticLoop",
           "sync_flag", "sync_flags"]

_log = logging.getLogger(__name__)


class PreemptionGuard:
    """Convert termination signals into a cooperative stop flag, with a
    grace-deadline emergency-checkpoint path.

    Installs handlers for `signals` (default SIGTERM — what Cloud TPU
    preemption delivers) that set :attr:`preempted` instead of killing the
    process, giving the training loop a grace window to checkpoint. Restores
    the previous handlers on exit. Signal handlers only work on the main
    thread; elsewhere the guard degrades to a manual flag
    (:meth:`request_stop`).

    `grace` (default ``MXTPU_PREEMPT_GRACE``) is the seconds between the
    signal and the scheduler's SIGKILL; when set, the signal arms a
    deadline and :meth:`emergency_checkpoint` budgets its work against it:
    cancel the prefetcher, drain in-flight steps (bounded), run a
    deadline-bounded save, and — when even that cannot fit — fall back to
    a partial-state resume marker naming the newest complete checkpoint,
    so the restart resumes from durable state instead of whatever a
    truncated write left behind.  With no grace configured the emergency
    path degrades to the classic unbounded save-and-exit.

    `manager`: a `CheckpointManager` whose in-flight async save the
    guard's exit path waits out (:meth:`__exit__` calls ``wait_async()``)
    — a background checkpoint write must never be truncated by process
    teardown racing the writer thread.
    """

    def __init__(self, signals: Sequence[int] = (signal.SIGTERM,),
                 grace: Optional[float] = None, manager=None):
        self._signals = tuple(signals)
        self._prev = {}
        self._event = threading.Event()
        self._installed = False
        self.grace = _recovery.preempt_grace() if grace is None else grace
        self.manager = manager
        self._deadline: Optional[float] = None

    @property
    def preempted(self) -> bool:
        return self._event.is_set()

    def request_stop(self) -> None:
        """Manually trigger the stop flag (tests, custom schedulers).
        Arms the grace deadline exactly like the signal path."""
        self._arm()

    def _arm(self) -> None:
        if self.grace and self._deadline is None:
            self._deadline = time.monotonic() + self.grace
        self._event.set()

    def _handler(self, signum, frame):
        _log.warning("received signal %d: requesting checkpoint-and-exit"
                     "%s", signum,
                     f" (grace {self.grace:g}s)" if self.grace else "")
        self._arm()

    def deadline_remaining(self) -> Optional[float]:
        """Seconds left in the grace window; None when no grace is
        configured or no signal has arrived yet (unbounded)."""
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    def emergency_checkpoint(self, manager=None, target=None,
                             step: int = 0, prefetcher=None,
                             drain_fraction: float = 0.5) -> dict:
        """Best-possible durable state inside the grace window.
        `manager` defaults to the guard's own (the one whose async saves
        `__exit__` waits out) — passing a different one would drain one
        manager while saving through another.

        1. cancel the prefetcher (buffered batches are lost by design —
           they will be re-read on resume; keeping H2D traffic alive
           only steals deadline from the save),
        2. drain in-flight dispatched steps, bounded to `drain_fraction`
           of the remaining deadline (``target.drain(timeout=...)`` when
           the target supports it),
        3. wait out any background async save (never truncate one),
        4. run ``manager.save`` on a worker thread with the remaining
           deadline; on timeout or error, fall back to a partial-state
           marker naming the newest *complete* checkpoint on disk,
        5. write the resumable marker `ElasticLoop.run` honors on
           restart.

        Returns ``{"step", "checkpoint", "complete", "partial"}``.
        """
        if manager is None:
            manager = self.manager
        if manager is None or target is None:
            raise MXNetError("emergency_checkpoint needs a manager "
                             "(constructor or argument) and a target")
        t0 = time.monotonic()
        fault_point("preempt_save")
        info = {"step": int(step), "checkpoint": None,
                "complete": False, "partial": False}
        if prefetcher is not None:
            try:
                prefetcher.close()
            except Exception:
                _log.exception("preemption: prefetcher cancel failed")
        remaining = self.deadline_remaining()
        drain = getattr(target, "drain", None)
        if callable(drain):
            try:
                left = drain(None if remaining is None
                             else max(0.1, remaining * drain_fraction))
                if left:
                    _log.warning("preemption: %d step(s) still in flight "
                                 "at the drain deadline", left)
            except Exception:
                _log.exception("preemption: in-flight drain failed")
        try:
            manager.wait_async()
        except Exception as e:
            _log.warning("preemption: deferred async save failed (%s); "
                         "the newest complete checkpoint stands", e)
        remaining = self.deadline_remaining()
        if remaining is None:
            # no grace window: the classic unbounded save-and-exit — a
            # failure here propagates (pre-deadline behavior), so a
            # supervisor never mistakes a failed save for a clean preempt
            info["checkpoint"] = manager.save(target, step)
            info["complete"] = True
        else:
            done: dict = {}

            def _save():
                try:
                    done["path"] = manager.save(target, step)
                except BaseException as e:
                    done["error"] = e

            t = threading.Thread(target=_save, daemon=True,
                                 name="mxtpu-preempt-save")
            t.start()
            t.join(max(0.1, remaining))
            if "path" in done:
                info["checkpoint"] = done["path"]
                info["complete"] = True
            else:
                # deadline too tight (or the write failed): fall back to
                # a partial-state manifest — the marker records the
                # newest COMPLETE checkpoint so the restart restores
                # durable state, and never a half-written file (the
                # atomic tmp+rename means the aborted save left no
                # visible checkpoint at all)
                info["partial"] = True
                newest = manager.latest()
                if newest is not None:
                    info["step"], info["checkpoint"] = newest
                else:
                    info["step"] = None
                _log.error(
                    "preemption: emergency save did not complete inside "
                    "the %.1fs grace remainder (%s); resume marker points "
                    "at the newest complete checkpoint (step %s)",
                    remaining,
                    done.get("error", "still writing"), info["step"])
        _tele.counter(
            "recovery_preempt_saves_total",
            "Emergency preemption checkpoints attempted",
            labelnames=("outcome",)).inc(
                outcome="complete" if info["complete"] else "partial")
        _tele.event("remediation", step=info["step"], kind="preempt_save",
                    complete=info["complete"], partial=info["partial"],
                    checkpoint=info["checkpoint"],
                    elapsed_s=round(time.monotonic() - t0, 3))
        _recovery.write_resume_marker(manager.directory, info)
        return info

    def __enter__(self):
        if threading.current_thread() is threading.main_thread():
            for s in self._signals:
                self._prev[s] = signal.signal(s, self._handler)
            self._installed = True
        return self

    def __exit__(self, *exc):
        if self._installed:
            for s, h in self._prev.items():
                signal.signal(s, h)
            self._prev.clear()
            self._installed = False
        if self.manager is not None:
            # a background save_async must finish before teardown can
            # truncate it; errors were/will be surfaced by the manager's
            # own drain paths — here completion is what matters
            try:
                self.manager.wait_async()
            except Exception as e:
                _log.warning("preemption guard: deferred async save "
                             "failed during exit (%s)", e)
        return False


class Watchdog:
    """Loop-level hang detector: fires if :meth:`ping` is not called
    within `timeout` seconds.

    .. deprecated:: PR 5
        This is now a thin shim over `mx.health.HangWatchdog`, scoped to
        the shared ``elastic_step`` heartbeat — detection, stall
        accounting (``health_stalls_total``, ``stall`` journal events,
        one flight-recorder bundle per hang episode), stack dumps, and
        stall suppression during compile windows all live in ONE place.
        New code should arm ``MXTPU_STALL_TIMEOUT`` (or
        `health.enable(stall_timeout_s=...)`) and let the process-wide
        watchdog cover every hot path; this class remains for the
        loop-scoped ``on_hang``/``kill`` contract.

    On expiry the underlying watchdog dumps every thread's stack to
    stderr, records the stall, and this shim invokes `on_hang` and —
    when `kill=True` — SIGABRTs the process so a supervisor can restart
    it. The default is detect-and-report only.
    """

    def __init__(self, timeout: float, on_hang: Optional[Callable] = None,
                 kill: bool = False):
        if timeout <= 0:
            raise MXNetError("watchdog timeout must be positive")
        self.timeout = timeout
        self.on_hang = on_hang
        self.kill = kill
        self.fired = False
        self._wd: Optional[_health.HangWatchdog] = None

    def ping(self) -> None:
        # the shared heartbeat IS the liveness state: the shim's private
        # HangWatchdog watches only this name, and a fresh beat both
        # resets its clock and starts a new bundle episode
        _health.beat("elastic_step")

    def _on_stall(self, info: dict) -> None:
        self.fired = True
        if self.on_hang is not None:
            try:
                self.on_hang()
            except Exception:
                _log.exception("watchdog on_hang callback failed")
        if self.kill:
            os.kill(os.getpid(), signal.SIGABRT)

    def __enter__(self):
        self.ping()
        self._wd = _health.HangWatchdog(
            self.timeout, action="record", on_stall=self._on_stall,
            names=("elastic_step",), source="elastic_watchdog").start()
        return self

    def __exit__(self, *exc):
        if self._wd is not None:
            self._wd.stop()
            self._wd = None
        return False


class FailureInjector:
    """Deterministic fault injection (SURVEY §5.3 names fault *injection*
    as part of the recovery test strategy). Raises `exc_type` the first
    time each step in `at_steps` is reached.

    Kept for programmatic use; the env-driven registry in
    `mxnet_tpu.resilience` (``MXTPU_FAULT_SPEC=elastic_step@N,...``)
    generalizes this to named points across the whole framework
    (checkpoint write/read, DataLoader worker execution) and crosses the
    spawn boundary into worker processes."""

    def __init__(self, at_steps: Sequence[int],
                 exc_type=RuntimeError):
        self._pending = set(at_steps)
        self._exc_type = exc_type
        self.injected = []

    def check(self, step: int) -> None:
        if step in self._pending:
            self._pending.discard(step)
            self.injected.append(step)
            raise self._exc_type(f"injected failure at step {step}")


# sync_flag's allgather retry budget: a collective that fails 3 times over
# ~1s of backoff is a down host, not a blip
_SYNC_RETRIES = 2
_SYNC_BASE_DELAY = 0.25


def sync_flag(flag: bool) -> bool:
    """Agree on a boolean across all processes (logical OR), so e.g. a
    preemption notice on one host checkpoints every host at the same step.
    Single-process: identity.

    Failure mode (multi-host): a transient collective error (tunnel reset,
    coordination-service hiccup) is retried with backoff
    (`resilience.retry_with_backoff`); once the budget is exhausted the
    hosts can no longer agree on a common step, so this raises
    `MXNetError` — the right response is to let the job die and resume
    every host from the newest checkpoint rather than checkpoint a
    diverged state.

    Caveat: the retry only helps for errors raised while *entering* the
    collective (before any peer commits to it — the common shape of
    coordination-service hiccups, which fail symmetrically). If one host
    errors after the others completed, its retried allgather pairs with
    the peers' NEXT collective (collectives match by program order) and
    the program is already lost to a hang or garbage — exactly the case
    the `MXNetError` path exists for: kill the job, restore all hosts
    from the newest checkpoint."""
    return sync_flags(flag)[0]


def sync_flags(*flags: bool, timeout: Optional[float] = None) -> tuple:
    """OR-reduce several booleans across all processes in ONE allgather
    (same collective, retry policy, and failure semantics as
    `sync_flag`).  The recovery-enabled loop syncs its preemption, exit,
    and rollback decisions per iteration — packing them keeps that at a
    single host-coordination round-trip instead of three.

    The collective is **timeout-bounded** (default
    ``MXTPU_ELASTIC_SYNC_TIMEOUT``, 120 s; 0 disables): a peer that died
    before entering the round used to stall every surviving host until
    the hang watchdog noticed — now the stall surfaces as
    `SuspectedHostLoss`, the signal the elastic mesh-reformation layer
    (`parallel.elastic_mesh`) consumes to re-form the mesh at the
    surviving size instead of restarting the job."""
    if jax.process_count() == 1:
        return tuple(bool(f) for f in flags)
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    def _gather():
        import numpy as onp
        v = onp.asarray(multihost_utils.process_allgather(
            jnp.asarray([1 if f else 0 for f in flags])))
        # (nproc, k) from the real collective; a single host's (k,)
        # (tests mock the gather) reshapes to one row
        v = v.reshape(-1, len(flags))
        return tuple(bool(x) for x in v.max(axis=0))

    if timeout is None:
        timeout = _recovery.sync_timeout()
    try:
        # each retry attempt runs on its own bounded worker thread
        # (recovery.coordinated_round): a dead peer never ANSWERS the
        # collective, so the bound has to come from outside it
        return _recovery.coordinated_round(
            _gather, timeout=timeout, name="mxtpu-flag-sync",
            retries=_SYNC_RETRIES, base_delay=_SYNC_BASE_DELAY,
            timeout_msg=
            f"elastic.sync_flags: multi-host flag sync did not "
            f"complete within {timeout or 0:g}s — a peer host is "
            f"suspected lost.  Attach an ElasticMeshController "
            f"(parallel.elastic_mesh) to re-form the mesh at the "
            f"surviving size, or restart the job and resume from the "
            f"newest checkpoint")
    except (RuntimeError, OSError) as e:
        if isinstance(e, SuspectedHostLoss):
            raise
        raise MXNetError(
            f"elastic.sync_flag: multi-host allgather failed after "
            f"{_SYNC_RETRIES} retries ({e}); hosts cannot agree on a "
            f"common step — restart the job and resume from the newest "
            f"checkpoint") from e


class ElasticLoop:
    """Checkpointed, preemption-aware, self-restoring training loop.

    Composes `CheckpointManager` (periodic atomic saves + resume),
    `PreemptionGuard` (SIGTERM → save-and-exit), `Watchdog` (hang report)
    and restore-retry on transient step failures around a user step
    function ``step_fn(i) -> loss``. Restores go through the manager's
    verified fallback chain: a corrupt latest checkpoint is quarantined
    and the rollback lands on the newest intact one, so bit-rot costs one
    (deeper) rollback instead of failing every restore-retry.

    The `target` must expose ``save(path)``/``load(path)``. Returns a dict
    with the exit status — ``"completed"``, ``"preempted"`` (checkpoint
    written; rerun to resume), ``"aborted"`` (the recovery policy's
    tier-3 exit: rollback budget exhausted, crash bundle flushed) — or
    raises after `max_restores` failed recoveries.

    **Self-healing** (``MXTPU_RECOVERY`` / `recovery`): a
    `recovery.RecoveryPolicy` subscribed to the health monitor turns
    anomalies into remediation the loop executes between steps — in-graph
    non-finite skips (tier 1, accounted by the policy), rollback to the
    newest healthy-tagged checkpoint with the poison window fast-forwarded
    (tier 2; on multi-host meshes the restore step is agreed via
    `recovery.agree_step` so every host restores the same step or none
    do), and a clean budgeted stop (tier 3).  `recovery=None` auto-builds
    the default policy when the env var is set; pass ``recovery=False``
    to opt out explicitly.

    `pipeline` (optional): a `data.DataPipeline` — attaching it couples
    the input stream to the checkpoint manager (`attach_pipeline`): every
    manifest carries the stream position and every restore — initial
    resume, preemption marker, tier-2 rollback, step-failure retry, and
    the mesh controller's host-loss path (it rides this loop's manager) —
    O(1)-seeks the stream instead of replaying it.  Pair with
    `data_reset`, called after every restore/reform with the resumed
    step: rebuild whatever wraps the (already re-seeked) pipeline — the
    loop closes the old `prefetcher` first, and adopts the hook's return
    value as the new one when it returns a prefetcher.

    `prefetcher` (optional): a `DevicePrefetcher` the preemption path
    cancels and the rollback path fast-forwards (`data_skip` overrides
    the per-step fast-forward hook).

    `mesh_controller` (optional): a
    `parallel.elastic_mesh.ElasticMeshController` — topology changes
    (host loss, host join, planned drains) are consumed between steps:
    the mesh re-forms at the new device count, live state is re-sharded
    (or, after a host loss, restored from the multi-host agreed
    checkpoint step), and the loop continues WITHOUT a process restart.
    A `SuspectedHostLoss` raised by the per-iteration flag sync feeds
    the same path.
    """

    def __init__(self, target, directory: str, save_every: int = 100,
                 keep: int = 3, max_restores: int = 3,
                 watchdog_timeout: Optional[float] = None,
                 retry_on=(RuntimeError, MXNetError),
                 failure_injector: Optional[FailureInjector] = None,
                 async_save: bool = False,
                 recovery=None, prefetcher=None,
                 preempt_grace: Optional[float] = None,
                 data_skip: Optional[Callable[[int], None]] = None,
                 mesh_controller=None, pipeline=None,
                 data_reset: Optional[Callable[[int], object]] = None):
        self.target = target
        self.manager = CheckpointManager(directory, keep=keep)
        self.save_every = save_every
        self.max_restores = max_restores
        # MXTPU_STALL_TIMEOUT arms the loop-level watchdog too, so one
        # env var covers both the per-step and process-wide detectors
        if watchdog_timeout is None:
            watchdog_timeout = _health.stall_timeout()
        self.watchdog_timeout = watchdog_timeout
        self.retry_on = tuple(retry_on)
        self.failure_injector = failure_injector
        # periodic saves overlap training (ShardedTrainStep.save_async);
        # preemption/rollback/final saves stay synchronous — those must
        # be on disk before the process acts on them
        self.async_save = async_save
        if recovery is None and _recovery.enabled():
            recovery = _recovery.RecoveryPolicy()
        self.recovery = recovery or None   # False -> None
        self.prefetcher = prefetcher
        self.preempt_grace = preempt_grace
        self.pipeline = pipeline
        self.data_reset = data_reset
        if pipeline is not None:
            # checkpoints now carry the stream position; every restore
            # below seeks instead of replaying (docs/data.md)
            self.manager.attach_pipeline(pipeline)
            if prefetcher is not None and data_reset is None:
                # every restore quiesces the prefetcher before the seek;
                # without a rebuild hook the loop would run on from a
                # permanently dead window — refuse up front, not at the
                # first post-restore next()
                raise MXNetError(
                    "ElasticLoop(pipeline=..., prefetcher=...) needs "
                    "data_reset= too: restores close the prefetch window "
                    "around the pipeline seek, and the hook rebuilds it "
                    "(return the new DevicePrefetcher) — docs/data.md")
        if data_skip is None and (prefetcher is not None
                                  or pipeline is not None):
            # reads self.prefetcher/self.pipeline at CALL time: after a
            # restore the data_reset hook may have swapped the
            # prefetcher, and skipping on the closed old one would drop
            # nothing while reporting the poison batch skipped
            data_skip = self._default_data_skip
        self.data_skip = data_skip
        # elastic mesh reformation (parallel.elastic_mesh): topology
        # changes are consumed between steps like recovery remediations;
        # the controller's host-loss restore path rides this loop's own
        # checkpoint manager unless the caller wired a different one
        self.mesh_controller = mesh_controller
        if mesh_controller is not None and mesh_controller.manager is None:
            mesh_controller.manager = self.manager
        # step ids (1-based, = the monitor's/journal's step-id space) the
        # post-rollback replay fast-forwards over.  The spaces stay
        # aligned across rollbacks because the dispatch counter is
        # checkpointed state: `ShardedTrainStep.load` resets `_t` to the
        # restored step exactly when the loop resets `i` to it.
        self._replay_skip: set = set()

    _deferred_failures = 0

    def _default_data_skip(self, _step: int) -> None:
        """Poison fast-forward: drop one batch from the CURRENT
        prefetcher (it may have been rebuilt since construction), else
        advance the pipeline directly."""
        if self.prefetcher is not None:
            self.prefetcher.skip(1)
        elif self.pipeline is not None:
            self.pipeline.skip_batches(1)

    def _quiesce_data(self) -> None:
        """Stop the prefetch thread before a restore re-seeks the
        attached pipeline: a producer pulling batches concurrently with
        `load_state` would interleave pre- and post-seek reads.  Buffered
        batches are dropped by design — the seek makes them reachable
        again in O(1), which is the whole point."""
        if self.pipeline is not None and self.prefetcher is not None:
            try:
                self.prefetcher.close()
            except Exception:
                _log.exception("elastic: prefetcher quiesce failed")

    def _reset_data(self, step: int) -> None:
        """After a restore/reform landed on `step`: let the owner rebuild
        whatever wraps the (already re-seeked) pipeline.  A hook that
        returns a `DevicePrefetcher` becomes the loop's new one (the old
        window was dropped by `_quiesce_data`)."""
        if self.data_reset is None:
            return
        try:
            pf = self.data_reset(step)
            if pf is not None:
                self.prefetcher = pf
        except Exception:
            _log.exception("elastic: data_reset hook failed at step %d "
                           "(continuing with the current data path)", step)

    def _drain_async_tolerant(self):
        """Surface-but-survive a deferred async-write failure: the loop's
        recovery/preemption/final paths must not let an OLD write error
        mask the operation they're about to perform (the last COMPLETE
        checkpoint on disk is still valid).  CONSECUTIVE failures are
        bounded like step failures — a full disk must not let the job
        run for days producing no durable checkpoints."""
        try:
            self.manager.wait_async()
            self._deferred_failures = 0
        except Exception as e:   # noqa: BLE001 — deliberately broad
            self._deferred_failures += 1
            if self._deferred_failures > self.max_restores:
                raise MXNetError(
                    f"elastic: {self._deferred_failures} consecutive async "
                    f"checkpoint writes failed; aborting rather than "
                    f"training without durable checkpoints") from e
            _log.warning(
                "elastic: a deferred async checkpoint write failed (%s); "
                "continuing from the last complete checkpoint "
                "(%d/%d consecutive)", e, self._deferred_failures,
                self.max_restores)

    def _maybe_periodic_save(self, i: int) -> None:
        """Periodic checkpoint when one is due at step `i`.  Drains only
        then: draining every step would cap write/compute overlap at one
        step."""
        if self.save_every > 0 and i % self.save_every == 0:
            self._drain_async_tolerant()
            self.manager.maybe_save(self.target, i, every=self.save_every,
                                    async_save=self.async_save)

    def _resume_start(self) -> int:
        """Initial restore, honoring a preemption resume marker when one
        is present: a marker naming a complete emergency checkpoint pins
        the resume to exactly that step (the marker is cleared either
        way — it describes one preemption, not a standing instruction)."""
        self._quiesce_data()
        marker = _recovery.read_resume_marker(self.manager.directory)
        if marker is not None:
            _recovery.clear_resume_marker(self.manager.directory)
            step = marker.get("step")
            if marker.get("complete") and step is not None:
                try:
                    start = self.manager.restore(self.target,
                                                 step=int(step))
                    _tele.event("remediation", step=start,
                                kind="preempt_resume",
                                checkpoint=marker.get("checkpoint"))
                    _log.info("elastic: resumed from emergency "
                              "preemption checkpoint at step %d", start)
                    return start
                except Exception as e:
                    _log.warning(
                        "elastic: resume marker points at step %s but the "
                        "restore failed (%s); falling back to the "
                        "checkpoint chain", step, e)
            else:
                _log.warning(
                    "elastic: preemption left a partial-state marker "
                    "(grace window too tight for a full save); resuming "
                    "from the newest complete checkpoint")
        return self.manager.restore(self.target)

    def _perform_rollback(self, action: dict, current: int,
                          restores: int) -> int:
        """Tier-2 remediation: restore the newest healthy-tagged
        checkpoint (cluster-agreed on multi-host meshes) and arm the
        poison-window fast-forward.  Returns the step to resume from."""
        reason = action.get("reason", "?")
        _log.warning("elastic: recovery rollback requested at step %d "
                     "(%s)", current, reason)
        # drain in-flight dispatched steps first: their retirements feed
        # the monitor, and a rollback under outstanding donated buffers
        # would race the restore's device placement
        drain = getattr(self.target, "drain", None)
        if callable(drain):
            try:
                drain(timeout=60.0)
            except Exception:
                _log.exception("elastic: in-flight drain before rollback "
                               "failed")
        self._drain_async_tolerant()
        self._quiesce_data()
        multi = jax.process_count() > 1
        if multi:
            cand = self.manager.newest_healthy()
            agreed = _recovery.agree_step(cand[0] if cand is not None
                                          else 0)
            if agreed == 0:
                # some host has NO healthy-tagged candidate (margin can
                # disqualify every retained checkpoint after a long
                # divergence).  Mirror the single-host fallback — a
                # suspect restore beats resetting a long run to the
                # step-0 anchor — by agreeing on the newest checkpoint
                # regardless of tag.  Same collective program order on
                # every host: all of them observed agreed == 0.
                newest = self.manager.latest()
                agreed = _recovery.agree_step(
                    newest[0] if newest is not None else 0)
                _log.error(
                    "elastic: no cluster-wide healthy rollback "
                    "candidate; agreed on newest checkpoint step %d "
                    "regardless of health tag", agreed)
            fault_point("rollback_restore")
            # all hosts restore the agreed step or none do: an explicit
            # restore raises on corruption (or on a missing agreed
            # checkpoint) instead of silently falling back to a step the
            # peers did not agree on; the raise kills the job and every
            # host restarts from its verified chain
            restored = self.manager.restore(self.target, step=agreed)
        else:
            fault_point("rollback_restore")
            restored = self.manager.restore(self.target,
                                            healthy_only=True)
        poison = []
        if self.recovery is not None:
            self.recovery.note_rollback(restored)
            poison = self.recovery.consume_poison(restored)
        self._replay_skip.update(poison)
        # checkpoints newer than the restore point belong to the
        # abandoned (diverged) timeline: a crash before the next periodic
        # save must not resume INTO the state we just rolled away from
        discarded = self.manager.discard_newer(restored)
        _tele.event("remediation", step=restored, tier=action.get("tier", 2),
                    kind="rollback", reason=reason, from_step=current,
                    restored_step=restored, poison=poison[:32],
                    discarded=discarded[:32], restores=restores)
        _log.warning(
            "elastic: rolled back from step %d to healthy checkpoint at "
            "step %d (%s); fast-forwarding %d poison step(s)%s",
            current, restored, reason, len(poison),
            f", discarded {len(discarded)} newer checkpoint(s)"
            if discarded else "")
        self._reset_data(restored)
        return restored

    def _perform_reform(self, change, current: int) -> int:
        """Execute one topology change (host loss / join / planned
        drain) via the attached `ElasticMeshController` and return the
        step to resume from — live reshards resume where they left off,
        loss reforms at the multi-host agreed checkpoint step."""
        self._drain_async_tolerant()
        self._quiesce_data()
        resume = self.mesh_controller.reform(change, current)
        # the reform may have restored a checkpoint through this loop's
        # manager (host-loss path) — with a pipeline attached, that
        # restore already re-seeked the stream; the hook re-derives the
        # host view (`pipeline.set_hosts`) for the new topology and
        # rebuilds the prefetch window
        self._reset_data(resume)
        _tele.event("remediation", step=resume, kind="mesh_reform",
                    reason=change.reason, tier=0, from_step=current)
        return resume

    def _on_suspected_loss(self, exc: SuspectedHostLoss,
                           current: int) -> int:
        """A bounded coordination round timed out mid-loop.  With a mesh
        controller attached the suspicion becomes a topology change
        (stale-heartbeat hosts are declared lost) and the loop re-forms;
        without one — or when no host can be blamed — the exception
        propagates and the job dies for a classic full restart."""
        if self.mesh_controller is None:
            raise exc
        self.mesh_controller.note_suspected_loss(exc=exc)
        change = self.mesh_controller.poll()
        if change is None:
            raise exc
        return self._perform_reform(change, current)

    def run(self, step_fn: Callable[[int], object], total_steps: int,
            on_step: Optional[Callable[[int, object], None]] = None) -> dict:
        restores = 0       # total, reported in the result
        consecutive = 0    # failed recoveries in a row, bounds the retry
        rollbacks = 0      # policy-driven (tier-2) rollbacks
        start = self._resume_start()
        self._reset_data(start)
        if start:
            _log.info("elastic: resumed from checkpoint at step %d", start)
        elif self.manager.latest() is None:
            # anchor checkpoint so a failure before the first periodic save
            # still has a consistent state to roll back to
            self.manager.save(self.target, 0)
        guard = PreemptionGuard(grace=self.preempt_grace,
                                manager=self.manager)
        watchdog = (Watchdog(self.watchdog_timeout)
                    if self.watchdog_timeout else None)
        if self.recovery is not None:
            self.recovery.attach()
        last_loss = None
        i = start
        try:
            with guard:
                ctx = watchdog if watchdog is not None else _null_ctx()
                with ctx:
                    while i < total_steps:
                        # remediation decisions are host-local (anomalies
                        # retire on host-local timing, budget windows are
                        # host-local wall-clock), so on multi-host meshes
                        # ALL of them — preemption, tier-3 exit, tier-2
                        # rollback, AND a pending topology change — are
                        # OR-reduced in one packed collective before
                        # anyone acts: a host entering reform()'s
                        # membership round (or agree_step, or returning)
                        # while a peer sits in this iteration's flag sync
                        # would mismatch collective program order and
                        # wedge the fleet.  A dead peer never enters the
                        # sync at all — that surfaces as the bounded
                        # round's SuspectedHostLoss below, the already-
                        # coordinated-by-failure path into a reform
                        action = (self.recovery.poll()
                                  if self.recovery is not None else None)
                        want_exit = (action is not None
                                     and action["kind"] == "exit")
                        want_rb = (action is not None
                                   and action["kind"] == "rollback")
                        want_reform = (self.mesh_controller is not None
                                       and self.mesh_controller
                                       .has_pending())
                        try:
                            preempted, want_exit, want_rb, want_reform = \
                                sync_flags(guard.preempted, want_exit,
                                           want_rb, want_reform)
                        except SuspectedHostLoss as e:
                            i = self._on_suspected_loss(e, i)
                            continue
                        if preempted:
                            self._drain_async_tolerant()
                            info = guard.emergency_checkpoint(
                                target=self.target, step=i,
                                prefetcher=self.prefetcher)
                            _log.warning(
                                "elastic: preempted at step %d; %s "
                                "checkpoint %s written", i,
                                "emergency" if info["complete"]
                                else "PARTIAL (marker only)",
                                info.get("checkpoint"))
                            return {"status": "preempted", "step": i,
                                    "checkpoint": info.get("checkpoint"),
                                    "restores": restores,
                                    "emergency": info}
                        if want_exit:
                            if action is None or action["kind"] != "exit":
                                action = {"kind": "exit",
                                          "reason": "peer_request",
                                          "tier": 3, "step": i}
                            return self._tier3_exit(action, i, restores)
                        if want_rb:
                            if action is None \
                                    or action["kind"] != "rollback":
                                action = {"kind": "rollback",
                                          "reason": "peer_request",
                                          "tier": 2, "step": i}
                            restores += 1
                            rollbacks += 1
                            try:
                                i = self._perform_rollback(action, i,
                                                           restores)
                            except SuspectedHostLoss as e:
                                # a peer died mid-rollback-consensus:
                                # same conversion as the flag sync —
                                # reform at the surviving size when a
                                # stale heartbeat names the culprit
                                i = self._on_suspected_loss(e, i)
                            continue
                        if want_reform:
                            change = (self.mesh_controller.poll()
                                      if self.mesh_controller is not None
                                      else None)
                            if change is not None:
                                i = self._perform_reform(change, i)
                            else:
                                # a PEER reported the pending change;
                                # its reform()'s membership round is the
                                # coordination point (and, on a real
                                # cross-process loss, the documented
                                # fast-fail into a restart)
                                _log.warning(
                                    "elastic: peer host reported a "
                                    "pending topology change; no local "
                                    "change to apply")
                            continue
                        if self._replay_skip and (i + 1) in \
                                self._replay_skip:
                            # fast-forward the poison window: this
                            # attempt's data fed an anomaly on the
                            # abandoned timeline — skip it rather than
                            # re-train on it (index-based sources skip
                            # the index; stream sources drop one batch
                            # via the data_skip hook)
                            self._replay_skip.discard(i + 1)
                            if self.data_skip is not None:
                                try:
                                    self.data_skip(i + 1)
                                except Exception:
                                    _log.exception(
                                        "elastic: data_skip hook failed")
                            _tele.event("remediation", step=i + 1,
                                        tier=2, kind="data_skip")
                            _log.warning("elastic: skipping poison step "
                                         "%d after rollback", i + 1)
                            i += 1
                            # a skipped step still honors a due periodic
                            # save (the state — restored + clean replays —
                            # is valid; silently missing the boundary
                            # would double the next failure's rollback
                            # distance).  on_step is NOT called: no step
                            # ran, and reporting a phantom loss would be
                            # worse than a gap in the step indices.
                            self._maybe_periodic_save(i)
                            continue
                        try:
                            # env-driven injection (MXTPU_FAULT_SPEC
                            # elastic_step@N — Nth step ATTEMPT, replays
                            # included, so a recovered run replays clean);
                            # generalizes the programmatic FailureInjector
                            fault_point("elastic_step")
                            if self.failure_injector is not None:
                                self.failure_injector.check(i)
                            last_loss = step_fn(i)
                            # a completed step proves the recovery worked;
                            # max_restores bounds CONSECUTIVE failed
                            # recoveries, not total hiccups over a long
                            # job's lifetime
                            consecutive = 0
                        except self.retry_on as e:
                            restores += 1
                            consecutive += 1
                            if consecutive > self.max_restores:
                                raise MXNetError(
                                    f"elastic: step {i} failed after "
                                    f"{self.max_restores} restores") from e
                            self._drain_async_tolerant()
                            self._quiesce_data()
                            rollback = self.manager.restore(self.target)
                            self._reset_data(rollback)
                            _log.warning(
                                "elastic: step %d failed (%s); restored "
                                "checkpoint at step %d (restore %d/%d)",
                                i, e, rollback, consecutive,
                                self.max_restores)
                            i = rollback
                            continue
                        i += 1
                        if watchdog is not None:
                            watchdog.ping()
                        if on_step is not None:
                            on_step(i, last_loss)
                        self._maybe_periodic_save(i)
        finally:
            if self.recovery is not None:
                self.recovery.detach()
        self._drain_async_tolerant()
        final = self.manager.save(self.target, total_steps)
        return {"status": "completed", "step": total_steps,
                "checkpoint": final, "restores": restores,
                "rollbacks": rollbacks, "loss": last_loss,
                "reforms": (self.mesh_controller.reforms
                            if self.mesh_controller is not None else 0)}

    def _tier3_exit(self, action: dict, step: int, restores: int) -> dict:
        """Tier-3 remediation: the rollback budget is exhausted — flush a
        post-mortem bundle and stop cleanly rather than burn the
        reservation on a rollback loop."""
        reason = action.get("reason", "rollback_budget_exhausted")
        self._drain_async_tolerant()
        bundle = _health.dump_bundle(f"recovery_exit:{reason}")
        _tele.counter(
            "recovery_exits_total",
            "Tier-3 clean stops (rollback budget exhausted)").inc()
        _tele.event("remediation", step=step, tier=3, kind="exit",
                    reason=reason, bundle=bundle)
        _log.error(
            "elastic: recovery policy requested a tier-3 exit at step %d "
            "(%s); post-mortem bundle: %s", step, reason, bundle)
        return {"status": "aborted", "step": step, "reason": reason,
                "restores": restores, "bundle": bundle}


class _null_ctx:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
