"""Unified runtime telemetry: metrics registry, device-memory monitor, and a
structured training-run journal.

The reference's engine profiler + aggregate-stats table (`src/profiler/`)
gave operators one place to see what the runtime was doing.  This module is
that place for the TPU build: a process-wide, thread-safe
:class:`MetricsRegistry` of labeled :class:`Counter`/:class:`Gauge`/
:class:`Histogram` primitives (ms-oriented fixed buckets), exportable as a
plain dict (`snapshot()`), Prometheus text exposition, or JSON — optionally
served from a stdlib ``http.server`` thread (``MXTPU_METRICS_PORT``).  A
:class:`MemoryMonitor` samples per-device live-array bytes
(`jax.live_arrays()` grouped by device, plus ``device.memory_stats()`` when
the backend provides it) and host RSS into gauges.  A :class:`RunJournal`
writes structured JSONL events (step dispatched/retired, retrace, compile
start/end, checkpoint write/restore/quarantine, worker death/respawn, fault
triggers) with monotonic step ids, so journal rows correlate with
`profiler.step_annotation` spans in the XPlane trace.

Gating contract: the registry and journal classes always work when used
directly, but the framework's *instrumentation sites* (`ShardedTrainStep`,
`DevicePrefetcher`, the DataLoader pools, `CheckpointManager`, the fault
registry, the compile cache) all guard on :func:`enabled` — one module-level
bool read — so a run without telemetry pays nothing.  Enable with
``MXTPU_TELEMETRY=1`` (or ``=<path.jsonl>`` to also open a journal there),
or programmatically via :func:`enable`.  See `docs/observability.md`.

This module imports only the stdlib at import time (jax is pulled lazily by
the memory monitor), so spawned DataLoader workers can import it on their
hot startup path for free.
"""
from __future__ import annotations

import atexit
import json
import logging
import math
import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "MemoryMonitor",
    "RunJournal", "MetricsServer", "registry", "counter", "gauge",
    "histogram", "enabled", "enable", "disable", "event", "journal",
    "add_event_tap", "remove_event_tap", "json_safe",
    "snapshot", "to_prometheus", "to_json", "serve_metrics",
    "install_compile_cache_listener", "DEFAULT_MS_BUCKETS",
    "ENV_ENABLE", "ENV_PORT", "ENV_MEMMON",
]

_log = logging.getLogger(__name__)

ENV_ENABLE = "MXTPU_TELEMETRY"
ENV_PORT = "MXTPU_METRICS_PORT"
ENV_MEMMON = "MXTPU_MEMMON_INTERVAL"

# histogram defaults are millisecond-oriented: sub-ms dispatch latencies up
# through multi-minute XLA compiles all land in a meaningful bucket
DEFAULT_MS_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                      100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0,
                      10000.0, 30000.0, 60000.0)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


# ---------------------------------------------------------------------------
# metric primitives
# ---------------------------------------------------------------------------

class _Metric:
    """Base: name + help + fixed label names; per-metric lock (updates may
    come from DataLoader supervisor threads, the prefetch thread, and the
    memory monitor concurrently)."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r} for {name}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, object]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name} takes labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}")
        return tuple(str(labels[ln]) for ln in self.labelnames)

    def remove(self, **labels) -> bool:
        """Drop one labeled series (True if it existed).  For gauges
        describing a RETIRED entity — a dead fleet replica's queue-depth
        series must not report its last value on /metrics forever.
        Counters are cumulative history and should normally be kept."""
        key = self._key(labels)
        with self._lock:
            return self._values.pop(key, None) is not None


class Counter(_Metric):
    """Monotonically increasing count (events, retries, cache hits)."""

    kind = "counter"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(
                f"counter {self.name} cannot decrease (inc({amount}))")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def _series(self):
        with self._lock:
            return [(dict(zip(self.labelnames, k)), v)
                    for k, v in sorted(self._values.items())]


class Gauge(_Metric):
    """Point-in-time value (steps in flight, occupancy, live bytes)."""

    kind = "gauge"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def _series(self):
        with self._lock:
            return [(dict(zip(self.labelnames, k)), v)
                    for k, v in sorted(self._values.items())]


class Histogram(_Metric):
    """Fixed-bucket distribution (latencies in ms). Buckets are cumulative
    upper bounds, Prometheus-style; an implicit +Inf bucket is always
    appended, so `observe` never drops a sample."""

    kind = "histogram"

    def __init__(self, name, help="", labelnames=(),
                 buckets: Sequence[float] = DEFAULT_MS_BUCKETS):
        super().__init__(name, help, labelnames)
        self.buckets = _normalize_buckets(name, buckets)
        # key -> [per-bucket counts (non-cumulative), sum, count]
        self._values: Dict[Tuple[str, ...], list] = {}

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        value = float(value)
        with self._lock:
            st = self._values.get(key)
            if st is None:
                st = self._values[key] = [[0] * len(self.buckets), 0.0, 0]
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    st[0][i] += 1
                    break
            st[1] += value
            st[2] += 1

    def count(self, **labels) -> int:
        with self._lock:
            st = self._values.get(self._key(labels))
            return st[2] if st else 0

    def sum(self, **labels) -> float:
        with self._lock:
            st = self._values.get(self._key(labels))
            return st[1] if st else 0.0

    def _series(self):
        """[(labels, {"buckets": {le: cumulative}, "sum": s, "count": n})]"""
        with self._lock:
            out = []
            for k, (counts, total, n) in sorted(self._values.items()):
                cum, acc = {}, 0
                for ub, c in zip(self.buckets, counts):
                    acc += c
                    cum[_fmt_le(ub)] = acc
                out.append((dict(zip(self.labelnames, k)),
                            {"buckets": cum, "sum": total, "count": n}))
            return out


def _normalize_buckets(name: str, buckets: Sequence[float]) -> tuple:
    """Validate + canonicalize histogram buckets: strictly increasing
    finite upper bounds (an unordered list is a caller bug that would
    silently misroute samples, not something to quietly sort away), with
    the implicit +Inf bucket appended."""
    bs = [float(b) for b in buckets]
    if not bs:
        raise ValueError(f"histogram {name} needs at least one bucket")
    if any(b2 <= b1 for b1, b2 in zip(bs, bs[1:])):
        raise ValueError(
            f"histogram {name}: buckets must be strictly increasing, "
            f"got {tuple(buckets)}")
    if bs[-1] != float("inf"):
        bs.append(float("inf"))
    return tuple(bs)


def _fmt_le(ub: float) -> str:
    if ub == float("inf"):
        return "+Inf"
    return repr(ub) if ub != int(ub) else str(int(ub))


def _escape_label(v: str) -> str:
    """Label-value escaping per the text exposition format: backslash,
    double-quote, and newline (in that order — escaping the escapes
    first)."""
    return v.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _escape_help(v: str) -> str:
    """HELP-text escaping: only backslash and newline (quotes are legal
    verbatim in help text, unlike label values)."""
    return v.replace("\\", r"\\").replace("\n", r"\n")


def _labels_str(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(str(v))}"' for k, v in labels.items()]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

class MetricsRegistry:
    """Process-wide, thread-safe metric registry.

    `counter`/`gauge`/`histogram` are get-or-create: instrumentation sites
    call them on the hot path with just the name and get the same object
    back every time (a kind mismatch raises — two subsystems silently
    sharing one name as different types would corrupt both)."""

    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._collectors: List[Callable[[], dict]] = []
        self._lock = threading.RLock()

    # -- collectors -----------------------------------------------------
    def add_collector(self, fn: Callable[[], dict]) -> None:
        """Register a snapshot-shaped series source merged into every
        export (`snapshot`/`to_prometheus`/`to_json`).  `fn` returns
        ``{name: {type, help, series: [...]}}`` — the serve fleet uses
        this to federate worker registries onto the parent's /metrics
        as per-replica-labeled series."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def remove_collector(self, fn: Callable[[], dict]) -> None:
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}, "
                        f"requested {cls.kind}")
                return m
            m = cls(name, help=help, labelnames=labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        """Get-or-create a histogram.  `buckets=None` (hot-path callers)
        means "whatever the metric has" — defaults to
        :data:`DEFAULT_MS_BUCKETS` on first creation.  An EXPLICIT
        `buckets=` that conflicts with an already-registered histogram's
        buckets raises: two sites silently disagreeing on bucket bounds
        would make one of them misread every exposition."""
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, Histogram):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}, "
                        f"requested histogram")
                if buckets is not None and \
                        _normalize_buckets(name, buckets) != m.buckets:
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {m.buckets}, re-requested with "
                        f"{tuple(buckets)}")
                return m
            m = Histogram(name, help=help, labelnames=labelnames,
                          buckets=DEFAULT_MS_BUCKETS if buckets is None
                          else buckets)
            self._metrics[name] = m
            return m

    def get(self, name) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def __contains__(self, name) -> bool:
        return self.get(name) is not None

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def reset(self) -> None:
        """Drop every metric (tests; a long-lived process keeps its
        registry for the run's lifetime)."""
        with self._lock:
            self._metrics.clear()
            self._collectors.clear()

    # -- export ---------------------------------------------------------
    def snapshot(self) -> dict:
        """Plain-dict view: {name: {type, help, series: [...]}}; histogram
        series carry cumulative bucket counts + sum + count.  Collector
        series merge in after the local metrics (same name + same type
        extends the series list; a kind clash drops the collector's
        entry — never the local one)."""
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors)
        out = {}
        for m in metrics:
            series = []
            for labels, val in m._series():
                entry = {"labels": labels}
                if isinstance(val, dict):
                    entry.update(val)
                else:
                    entry["value"] = val
                series.append(entry)
            out[m.name] = {"type": m.kind, "help": m.help, "series": series}
        # collectors run OUTSIDE the registry lock (they take their own)
        for fn in collectors:
            try:
                extra = fn() or {}
            except Exception:
                _log.debug("metrics collector failed", exc_info=True)
                continue
            for name, fm in extra.items():
                series = [dict(s) for s in fm.get("series", ())]
                dst = out.get(name)
                if dst is None:
                    out[name] = {"type": fm.get("type", "gauge"),
                                 "help": fm.get("help", ""),
                                 "series": series}
                elif dst["type"] == fm.get("type"):
                    dst["series"].extend(series)
        return out

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps({"time": time.time(),
                           "metrics": self.snapshot()}, indent=indent)

    def to_prometheus(self) -> str:
        """Prometheus text exposition (version 0.0.4) — rendered from
        :meth:`snapshot`, so federated collector series are included."""
        lines = []
        for name, m in self.snapshot().items():
            if m.get("help"):
                lines.append(f"# HELP {name} {_escape_help(m['help'])}")
            lines.append(f"# TYPE {name} {m['type']}")
            for entry in m["series"]:
                labels = entry.get("labels") or {}
                if m["type"] == "histogram":
                    for le, c in (entry.get("buckets") or {}).items():
                        lines.append(
                            f"{name}_bucket"
                            f"{_labels_str(labels, f'le={json.dumps(le)}')}"
                            f" {c}")
                    ls = _labels_str(labels)
                    lines.append(
                        f"{name}_sum{ls} {_fmt_val(entry.get('sum', 0))}")
                    lines.append(
                        f"{name}_count{ls} {int(entry.get('count', 0))}")
                else:
                    lines.append(
                        f"{name}{_labels_str(labels)} "
                        f"{_fmt_val(entry.get('value', 0.0))}")
        return "\n".join(lines) + "\n"


def _fmt_val(v: float) -> str:
    v = float(v)
    if math.isnan(v):
        return "NaN"             # repr() would emit 'nan'/'inf', which
    if math.isinf(v):            # no Prometheus parser accepts
        return "+Inf" if v > 0 else "-Inf"
    return str(int(v)) if v.is_integer() and abs(v) < 1e15 else repr(v)


def json_safe(obj):
    """Replace non-finite floats with their string names so the output is
    strict RFC 8259 JSON.  Python's json emits bare ``NaN``/``Infinity``
    tokens by default — and the rows that carry them (NaN-loss probes,
    anomaly events, crash bundles) are exactly the ones downstream jq /
    JSON.parse / Go pipelines must be able to read."""
    if isinstance(obj, float):
        if math.isnan(obj):
            return "NaN"
        if math.isinf(obj):
            return "Infinity" if obj > 0 else "-Infinity"
        return obj
    if isinstance(obj, dict):
        return {k: json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_safe(v) for v in obj]
    return obj


# ---------------------------------------------------------------------------
# run journal
# ---------------------------------------------------------------------------

class RunJournal:
    """Append-only JSONL event log for one training run.

    Each row: ``{"seq": n, "ts": unix_s, "event": name, "step": id, ...}``.
    ``seq`` is strictly monotonic per journal; ``step`` is the training-step
    id the event belongs to — events recorded without one inherit the last
    seen step, so checkpoint/worker/fault rows correlate with the
    `step_dispatched` row (and the `profiler.step_annotation` span of the
    same id in the XPlane trace) that preceded them."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        self._lock = threading.Lock()
        self._seq = 0
        self._last_step = 0
        self._closed = False
        try:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            # line-buffered append: rows survive a crash up to the last line
            self._f = open(self.path, "a", buffering=1)
        except OSError as e:
            # an unwritable journal path must degrade to a disabled journal,
            # not abort the training run that asked for observability
            self._f = None
            self._closed = True
            _log.warning("run journal disabled: cannot open %s (%s)",
                         self.path, e)

    @property
    def disabled(self) -> bool:
        """True when the journal could not open its file (or was closed);
        `record` is a silent no-op in that state."""
        return self._closed

    def record(self, event: str, step: Optional[int] = None,
               **fields) -> None:
        with self._lock:
            if self._closed:
                return
            if step is not None:
                self._last_step = int(step)
            self._seq += 1
            row = {"seq": self._seq, "ts": round(time.time(), 6),
                   "event": event, "step": self._last_step}
            row.update(fields)
            try:
                self._f.write(json.dumps(json_safe(row), default=str,
                                         allow_nan=False) + "\n")
            except (OSError, ValueError, TypeError):
                pass  # a full disk must not take the training loop down

    def close(self) -> None:
        with self._lock:
            if not self._closed:
                self._closed = True
                try:
                    if self._f is not None:
                        self._f.close()
                except OSError:
                    pass

    @staticmethod
    def read(path: str) -> List[dict]:
        """Parse a journal file back into rows (tests, tools)."""
        rows = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    rows.append(json.loads(line))
        return rows

    @staticmethod
    def tail(path: str, n: int = 500) -> List[dict]:
        """The last `n` rows — bounded excerpts (incident capsules) from
        journals that may have grown for hours.  Reads at most ~256 KiB
        per requested row from the file's end, not the whole file."""
        budget = max(4096, 256 * 1024)
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - budget))
            chunk = f.read().decode("utf-8", errors="replace")
        lines = chunk.splitlines()
        if size > budget and lines:
            lines = lines[1:]   # first line is likely truncated
        rows = []
        for line in lines[-n:]:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except ValueError:
                continue
        return rows

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ---------------------------------------------------------------------------
# memory monitor
# ---------------------------------------------------------------------------

def _host_rss_bytes() -> Optional[int]:
    try:  # /proc is authoritative on linux; statm field 2 = resident pages
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return None


class MemoryMonitor:
    """Samples device + host memory into registry gauges.

    Per sample: ``device_live_bytes{device=}`` (sum of `jax.live_arrays()`
    shard bytes addressable on each local device — the framework's own
    footprint), ``device_memory_in_use_bytes{device=}`` from
    ``device.memory_stats()`` where the backend provides it (TPU does; the
    allocator's view, including non-jax buffers), and ``host_rss_bytes``.
    `start()` runs `sample_once` on a daemon thread every `interval`
    seconds (``MXTPU_MEMMON_INTERVAL``); `sample_once` is also public for
    on-demand probes.  jax is imported lazily — constructing a monitor
    costs nothing until the first sample."""

    def __init__(self, interval: float = 10.0,
                 registry: Optional[MetricsRegistry] = None):
        self.interval = float(interval)
        self._registry = registry
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples = 0

    def _reg(self) -> MetricsRegistry:
        return self._registry if self._registry is not None else registry()

    def sample_once(self) -> dict:
        import jax
        reg = self._reg()
        live: Dict[str, int] = {}
        try:
            arrays = jax.live_arrays()
        except Exception:
            arrays = []
        for a in arrays:
            try:
                for sh in a.addressable_shards:
                    d = str(sh.device)
                    live[d] = live.get(d, 0) + int(sh.data.nbytes)
            except Exception:  # deleted mid-walk, or an exotic array type
                continue
        g_live = reg.gauge("device_live_bytes",
                           "Live jax array bytes per device",
                           labelnames=("device",))
        for dev, nbytes in live.items():
            g_live.set(nbytes, device=dev)
        stats: Dict[str, dict] = {}
        try:
            devices = jax.local_devices()
        except Exception:
            devices = []
        for d in devices:
            try:
                ms = d.memory_stats()
            except Exception:
                ms = None
            if ms and "bytes_in_use" in ms:
                stats[str(d)] = ms
                reg.gauge("device_memory_in_use_bytes",
                          "Allocator bytes_in_use per device "
                          "(device.memory_stats)",
                          labelnames=("device",)).set(
                              ms["bytes_in_use"], device=str(d))
        rss = _host_rss_bytes()
        if rss is not None:
            reg.gauge("host_rss_bytes",
                      "Host resident set size of this process").set(rss)
        self.samples += 1
        return {"live_bytes": live, "memory_stats": stats, "host_rss": rss}

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.sample_once()
            except Exception as e:  # monitoring must never kill the run
                _log.warning("memory monitor sample failed: %s", e)

    def start(self) -> "MemoryMonitor":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="mxtpu-memmon", daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
        self._thread = None


# ---------------------------------------------------------------------------
# HTTP exposition (stdlib only)
# ---------------------------------------------------------------------------

class MetricsServer:
    """Background ``http.server`` thread serving the registry:
    ``/metrics`` (Prometheus text), ``/metrics.json`` (JSON snapshot),
    ``/healthz`` (watchdog heartbeat ages + stall state as JSON — a
    liveness probe that answers "is the training loop still moving"
    without parsing the full exposition).
    Port 0 binds an ephemeral port (read it back from ``.port``).
    Binds loopback by default — exposing runtime internals on all
    interfaces is an explicit opt-in (``MXTPU_METRICS_HOST=0.0.0.0``)."""

    def __init__(self, port: int, host: str = "127.0.0.1",
                 registry: Optional[MetricsRegistry] = None):
        self._requested = (host, int(port))
        self._registry = registry
        self._httpd = None
        self._thread = None
        self.port: Optional[int] = None

    def start(self) -> "MetricsServer":
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        reg = self._registry if self._registry is not None else registry()

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib API name
                if self.path.split("?")[0] in ("/metrics.json", "/json"):
                    body = reg.to_json(indent=2).encode()
                    ctype = "application/json"
                elif self.path.split("?")[0] == "/healthz":
                    # lazy import: health imports telemetry at module load,
                    # so telemetry can only reach back at request time
                    from . import health as _health
                    body = json.dumps(
                        _health.healthz(), indent=2).encode()
                    ctype = "application/json"
                elif self.path.split("?")[0] in ("/", "/metrics"):
                    body = reg.to_prometheus().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                _log.debug("metrics server: " + fmt, *args)

        self._httpd = ThreadingHTTPServer(self._requested, Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="mxtpu-metrics-http",
            daemon=True)
        self._thread.start()
        _log.info("telemetry: serving /metrics on port %d", self.port)
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


# ---------------------------------------------------------------------------
# process-wide state + module-level facade
# ---------------------------------------------------------------------------

_registry = MetricsRegistry()
_enabled = False
_journal: Optional[RunJournal] = None
_server: Optional[MetricsServer] = None
_memmon: Optional[MemoryMonitor] = None
_state_lock = threading.Lock()
_event_taps: List[Callable[[dict], None]] = []
_atexit_registered = False


def registry() -> MetricsRegistry:
    """The process-wide default registry (always usable, enabled or not)."""
    return _registry


def counter(name, help="", labelnames=()) -> Counter:
    return _registry.counter(name, help, labelnames)


def gauge(name, help="", labelnames=()) -> Gauge:
    return _registry.gauge(name, help, labelnames)


def histogram(name, help="", labelnames=(), buckets=None) -> Histogram:
    return _registry.histogram(name, help, labelnames, buckets)


def snapshot() -> dict:
    return _registry.snapshot()


def to_prometheus() -> str:
    return _registry.to_prometheus()


def to_json(indent=None) -> str:
    return _registry.to_json(indent=indent)


def enabled() -> bool:
    """One global read — the no-op fast path every instrumentation site
    guards on."""
    return _enabled


def journal() -> Optional[RunJournal]:
    return _journal


def event(name: str, step: Optional[int] = None, **fields) -> None:
    """Record a journal event; no-op when telemetry is disabled
    (instrumentation sites call this unconditionally after their
    `enabled()` guard).  The event goes to the run journal (when one is
    attached) AND to any registered taps — the crash flight recorder
    (`mx.health`) rides a tap so it sees every event even when no journal
    file is open."""
    if not _enabled:
        return
    j = _journal
    if j is not None:
        j.record(name, step=step, **fields)
    if _event_taps:
        row = {"ts": round(time.time(), 6), "event": name, "step": step}
        row.update(fields)
        for tap in tuple(_event_taps):
            try:
                tap(row)
            except Exception:  # a broken tap must not take training down
                _log.debug("telemetry event tap failed", exc_info=True)


def add_event_tap(tap: Callable[[dict], None]) -> None:
    """Register a callable invoked with every `event()` row dict (after
    the journal write).  Taps must be fast and never raise; used by the
    `health` flight recorder."""
    if tap not in _event_taps:
        _event_taps.append(tap)


def remove_event_tap(tap: Callable[[dict], None]) -> None:
    try:
        _event_taps.remove(tap)
    except ValueError:
        pass


def enable(journal_path: Optional[str] = None,
           port: Optional[int] = None,
           memmon_interval: Optional[float] = None) -> None:
    """Turn the instrumentation on.

    `journal_path`: open a :class:`RunJournal` there (replacing any active
    one).  `port`: start the metrics HTTP server (default: the
    ``MXTPU_METRICS_PORT`` env var; 0 = ephemeral).  `memmon_interval`:
    start the :class:`MemoryMonitor` at that period in seconds (default:
    ``MXTPU_MEMMON_INTERVAL``; unset/<=0 = no background sampling).
    Idempotent: a second call merges — it can attach a journal or server
    to an already-enabled process."""
    global _enabled, _journal, _server, _memmon
    with _state_lock:
        if journal_path is not None:
            if _journal is not None:
                _journal.close()
            _journal = RunJournal(journal_path)
        if port is None:
            env_port = os.environ.get(ENV_PORT, "").strip()
            if env_port:
                try:
                    port = int(env_port)
                except ValueError:
                    _log.warning("ignoring non-integer %s=%r",
                                 ENV_PORT, env_port)
        if port is not None and _server is None:
            host = os.environ.get("MXTPU_METRICS_HOST", "127.0.0.1")
            try:
                _server = MetricsServer(port, host=host).start()
            except OSError as e:
                _log.warning("telemetry: metrics server failed to bind "
                             "port %s (%s); continuing without", port, e)
                _server = None
        if memmon_interval is None:
            env_iv = os.environ.get(ENV_MEMMON, "").strip()
            if env_iv:
                try:
                    memmon_interval = float(env_iv)
                except ValueError:
                    _log.warning("ignoring non-numeric %s=%r",
                                 ENV_MEMMON, env_iv)
        if memmon_interval is not None and memmon_interval > 0 \
                and _memmon is None:
            _memmon = MemoryMonitor(interval=memmon_interval).start()
        _enabled = True
        global _atexit_registered
        if not _atexit_registered:
            # join the monitor/server threads (and flush the journal) at
            # interpreter exit, so pytest/bench processes never tear down
            # with a daemon thread mid-sample on a dying jax runtime
            atexit.register(_atexit_shutdown)
            _atexit_registered = True


def disable() -> None:
    """Turn instrumentation off and release the journal/server/monitor.
    The registry keeps its values (still snapshottable post-run)."""
    global _enabled, _journal, _server, _memmon
    with _state_lock:
        _enabled = False
        if _memmon is not None:
            _memmon.stop()
            _memmon = None
        if _server is not None:
            _server.stop()
            _server = None
        if _journal is not None:
            _journal.close()
            _journal = None


def _atexit_shutdown() -> None:
    """Interpreter-exit hook (registered by the first `enable`): stop and
    JOIN the memory-monitor and HTTP-server threads and close the journal.
    Daemon threads otherwise die mid-sample when the interpreter tears
    down — under pytest that shows up as leaked threads between runs."""
    try:
        disable()
    except Exception:
        pass


def metrics_server() -> Optional[MetricsServer]:
    return _server


def memory_monitor() -> Optional[MemoryMonitor]:
    return _memmon


def serve_metrics(port: Optional[int] = None,
                  host: str = "127.0.0.1") -> MetricsServer:
    """Start (and return) a metrics HTTP server outside of `enable` —
    for embedding in an existing serving process."""
    if port is None:
        port = int(os.environ.get(ENV_PORT, "0") or 0)
    return MetricsServer(port, host=host).start()


# ---------------------------------------------------------------------------
# compile-cache hit/miss listener (fed by jax.monitoring)
# ---------------------------------------------------------------------------

_cc_listener_installed = False


def _on_jax_event(event_name, *args, **kwargs) -> None:
    """jax.monitoring event listener: count persistent-compile-cache
    traffic. Gated on `enabled()` so an armed listener in a non-telemetry
    run costs one string check."""
    if not _enabled or "/compilation_cache/" not in str(event_name):
        return
    if "cache_miss" in event_name:
        counter("compile_cache_misses",
                "Persistent compile cache misses (full XLA compile)").inc()
    elif "cache_hit" in event_name:
        counter("compile_cache_hits",
                "Persistent compile cache hits (compile skipped)").inc()


def install_compile_cache_listener() -> bool:
    """Register the jax.monitoring listener that feeds
    ``compile_cache_hits``/``compile_cache_misses`` (idempotent; called by
    `runtime.enable_compile_cache`). Returns whether a listener is
    installed."""
    global _cc_listener_installed
    if _cc_listener_installed:
        return True
    try:
        from jax import monitoring as _mon
        _mon.register_event_listener(_on_jax_event)
    except Exception as e:  # jax too old/new: counters stay at 0, loudly
        _log.warning("compile-cache telemetry unavailable (%s)", e)
        return False
    _cc_listener_installed = True
    return True


def _in_child_process() -> bool:
    """True inside a multiprocessing child (spawned DataLoader worker).
    Auto-enable must not run there: each worker would append to the
    parent's journal with its own seq counter (breaking the per-journal
    monotonic-seq contract), retry the metrics-port bind, and start a
    jax-importing memory monitor per short-lived worker."""
    try:
        import multiprocessing
        return multiprocessing.parent_process() is not None
    except Exception:
        return False


# auto-enable from the environment: MXTPU_TELEMETRY=1 (or any truthy value)
# enables instrumentation; a value that looks like a path additionally opens
# the run journal there (e.g. MXTPU_TELEMETRY=/logs/run.jsonl). Parent
# process only — workers stay dark (their metrics would be process-local
# and unreachable anyway; batches cross via queues, not registries).
_env = os.environ.get(ENV_ENABLE, "").strip()
if _env and _env.lower() not in ("0", "false", "no", "off") \
        and not _in_child_process():
    _is_path = os.sep in _env or _env.endswith(".jsonl")
    enable(journal_path=_env if _is_path else None)
del _env
