"""RecordIO file format (parity: `python/mxnet/recordio.py` over dmlc-core's
recordio + `tools/im2rec`). Pure-Python reimplementation of the same binary
format: records framed by a magic number + length, 4-byte aligned, with an
optional `IRHeader` (label/id) prefix for packed datasets.

The native data plane (`mxnet_tpu/_native/io.cc`) provides the fast path —
C++ record codec + background-thread read-ahead (parity: dmlc recordio and
`src/io/iter_prefetcher.h`); this module transparently uses it when the
library is built and falls back to pure Python otherwise. The format is
compatible both ways and with files produced by the reference's
`tools/im2rec`.
"""
from __future__ import annotations

import ctypes
import os
import struct
from collections import namedtuple
from typing import Optional

import numpy as _onp

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "MXPrefetchedRecordIO",
           "IRHeader", "pack", "unpack", "pack_img", "unpack_img"]

_MAGIC = 0xced7230a
_LFLAG_BITS = 29


class MXRecordIO:
    """Sequential record reader/writer (dmlc recordio framing)."""

    def __init__(self, uri: str, flag: str):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.open()

    def open(self):
        from . import _native
        native = _native.available()
        if self.flag == "w":
            self.writable = True
            if native:
                self.handle = _native.NativeRecordWriter(self.uri)
                self._native = True
            else:
                self.handle = open(self.uri, "wb")
                self._native = False
        elif self.flag == "r":
            self.writable = False
            if native:
                self.handle = _native.NativeRecordReader(self.uri)
                self._native = True
            else:
                self.handle = open(self.uri, "rb")
                self._native = False
        else:
            raise MXNetError("flag must be 'r' or 'w'")

    def close(self):
        if self.handle is not None:
            self.handle.close()
            self.handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def reset(self):
        self.close()
        self.open()

    def tell(self) -> int:
        return self.handle.tell()

    def seek(self, pos: int):
        assert not self.writable
        self.handle.seek(pos)

    def write(self, buf: bytes):
        self._write(buf)

    def _write(self, buf: bytes) -> int:
        """Append one record; returns its byte offset (for .idx files)."""
        assert self.writable
        if self._native:
            return self.handle.write(buf)
        pos = self.handle.tell()
        # dmlc framing: [magic][lrec][data][pad to 4B]
        lrec = len(buf)  # upper 3 bits: continuation flag (0 = complete)
        self.handle.write(struct.pack("<II", _MAGIC, lrec))
        self.handle.write(buf)
        pad = (4 - (len(buf) % 4)) % 4
        if pad:
            self.handle.write(b"\x00" * pad)
        return pos

    def read(self) -> Optional[bytes]:
        assert not self.writable
        if self._native:
            return self.handle.read()
        hdr = self.handle.read(8)
        if len(hdr) < 8:
            return None
        magic, lrec = struct.unpack("<II", hdr)
        if magic != _MAGIC:
            raise MXNetError("invalid record magic; corrupt file?")
        length = lrec & ((1 << _LFLAG_BITS) - 1)
        data = self.handle.read(length)
        pad = (4 - (length % 4)) % 4
        if pad:
            self.handle.read(pad)
        return data


class MXIndexedRecordIO(MXRecordIO):
    """Random-access records via a .idx sidecar (parity: recordio.py:IndexedRecordIO)."""

    def __init__(self, idx_path: str, uri: str, flag: str, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if not self.writable and os.path.isfile(idx_path):
            with open(idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) >= 2:
                        k = key_type(parts[0])
                        self.idx[k] = int(parts[1])
                        self.keys.append(k)

    def close(self):
        if self.handle is not None and self.writable:
            with open(self.idx_path, "w") as f:
                for k in self.keys:
                    f.write(f"{k}\t{self.idx[k]}\n")
        super().close()

    def read_idx(self, idx):
        self.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx, buf: bytes):
        pos = self._write(buf)
        self.idx[idx] = pos
        self.keys.append(idx)


IRHeader = namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header: IRHeader, s: bytes) -> bytes:
    label = header.label
    if isinstance(label, (list, tuple, _onp.ndarray)) or \
            (hasattr(label, "size") and getattr(label, "size", 1) > 1):
        label = _onp.asarray(label, dtype=_onp.float32)
        header = header._replace(flag=label.size, label=0.0)
        return struct.pack(_IR_FORMAT, *header) + label.tobytes() + s
    return struct.pack(_IR_FORMAT, header.flag, float(label), header.id,
                       header.id2) + s


def unpack(s: bytes):
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = _onp.frombuffer(s[:header.flag * 4], dtype=_onp.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


class MXPrefetchedRecordIO:
    """Sequential reader with background read-ahead.

    Uses the C++ threaded prefetcher (`_native/io.cc` Prefetcher) when
    available; otherwise a Python thread + bounded queue (parity:
    `src/io/iter_prefetcher.h`). Iterate to get raw record bytes.
    """

    def __init__(self, uri: str, capacity: int = 16):
        from . import _native
        self.uri = uri
        self.capacity = capacity
        if _native.available():
            self._impl = _native.NativePrefetchReader(uri, capacity)
            self._queue = None
        else:
            import queue as _q
            import threading as _t
            self._impl = None
            self._queue = _q.Queue(maxsize=capacity)
            self._reader = MXRecordIO(uri, "r")
            self._exhausted = False
            self._stop = _t.Event()

            # worker errors (corrupt record, I/O failure) travel through
            # the queue as tagged entries and re-raise in the consumer —
            # a bare `self._reader.read()` raise used to kill the thread
            # silently and leave the consumer blocked on get() forever.
            # Every put is stop-aware so close() can always reclaim a
            # worker blocked on a full queue (the old thread leaked).
            def _put(entry) -> bool:
                while not self._stop.is_set():
                    try:
                        self._queue.put(entry, timeout=0.05)
                        return True
                    except _q.Full:
                        continue
                return False

            def worker():
                try:
                    while not self._stop.is_set():
                        rec = self._reader.read()
                        if rec is None:
                            _put(("end", None))
                            return
                        if not _put(("item", rec)):
                            return
                except BaseException as e:  # noqa: BLE001 — consumer's
                    _put(("error", e))      # to re-raise, not ours
            self._thread = _t.Thread(target=worker, daemon=True,
                                     name="mxtpu-recordio-prefetch")
            self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        if self._impl is not None:
            return next(self._impl)
        if self._exhausted or self._stop.is_set():
            raise StopIteration
        import queue as _q
        while True:
            try:
                kind, payload = self._queue.get(timeout=0.05)
                break
            except _q.Empty:
                # close() from another thread wakes this consumer
                # instead of deadlocking it on a dead producer
                if self._stop.is_set():
                    raise StopIteration from None
        if kind == "item":
            return payload
        self._exhausted = True
        self.close()
        if kind == "error":
            raise payload
        raise StopIteration

    def close(self, timeout: float = 5.0):
        if self._impl is not None:
            self._impl.close()
        elif self._queue is not None:
            # stop -> drain (wakes a put blocked on a full queue) ->
            # join -> re-drain (the woken producer may deposit one last
            # record between the first drain and its exit)
            import queue as _q
            import threading as _t
            self._stop.set()
            for _ in range(2):
                try:
                    while True:
                        self._queue.get_nowait()
                except _q.Empty:
                    pass
                t = self._thread
                if t is not _t.current_thread() and t.is_alive():
                    t.join(timeout)
            self._reader.close()


def pack_img(header: IRHeader, img, quality=95, img_fmt=".jpg"):
    raise MXNetError("pack_img requires an image codec; encode with PIL and "
                     "use pack() directly")


def unpack_img(s: bytes, iscolor=1):
    header, img_bytes = unpack(s)
    from .image import imdecode
    return header, imdecode(img_bytes)
