"""RecordIO file format (parity: `python/mxnet/recordio.py` over dmlc-core's
recordio + `tools/im2rec`). Pure-Python reimplementation of the same binary
format: records framed by a magic number + length, 4-byte aligned, with an
optional `IRHeader` (label/id) prefix for packed datasets.

A C++ accelerated indexer/reader is planned under `src/` (native data plane);
the format here is compatible with files produced by the reference's
`tools/im2rec`.
"""
from __future__ import annotations

import ctypes
import os
import struct
from collections import namedtuple
from typing import Optional

import numpy as _onp

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xced7230a
_LFLAG_BITS = 29


class MXRecordIO:
    """Sequential record reader/writer (dmlc recordio framing)."""

    def __init__(self, uri: str, flag: str):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.handle = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.handle = open(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError("flag must be 'r' or 'w'")

    def close(self):
        if self.handle is not None:
            self.handle.close()
            self.handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def reset(self):
        self.close()
        self.open()

    def tell(self) -> int:
        return self.handle.tell()

    def seek(self, pos: int):
        assert not self.writable
        self.handle.seek(pos)

    def write(self, buf: bytes):
        assert self.writable
        # dmlc framing: [magic][lrec][data][pad to 4B]
        lrec = len(buf)  # upper 3 bits: continuation flag (0 = complete)
        self.handle.write(struct.pack("<II", _MAGIC, lrec))
        self.handle.write(buf)
        pad = (4 - (len(buf) % 4)) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def read(self) -> Optional[bytes]:
        assert not self.writable
        hdr = self.handle.read(8)
        if len(hdr) < 8:
            return None
        magic, lrec = struct.unpack("<II", hdr)
        if magic != _MAGIC:
            raise MXNetError("invalid record magic; corrupt file?")
        length = lrec & ((1 << _LFLAG_BITS) - 1)
        data = self.handle.read(length)
        pad = (4 - (length % 4)) % 4
        if pad:
            self.handle.read(pad)
        return data


class MXIndexedRecordIO(MXRecordIO):
    """Random-access records via a .idx sidecar (parity: recordio.py:IndexedRecordIO)."""

    def __init__(self, idx_path: str, uri: str, flag: str, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if not self.writable and os.path.isfile(idx_path):
            with open(idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) >= 2:
                        k = key_type(parts[0])
                        self.idx[k] = int(parts[1])
                        self.keys.append(k)

    def close(self):
        if self.handle is not None and self.writable:
            with open(self.idx_path, "w") as f:
                for k in self.keys:
                    f.write(f"{k}\t{self.idx[k]}\n")
        super().close()

    def read_idx(self, idx):
        self.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx, buf: bytes):
        pos = self.tell()
        self.write(buf)
        self.idx[idx] = pos
        self.keys.append(idx)


IRHeader = namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header: IRHeader, s: bytes) -> bytes:
    label = header.label
    if isinstance(label, (list, tuple, _onp.ndarray)) or \
            (hasattr(label, "size") and getattr(label, "size", 1) > 1):
        label = _onp.asarray(label, dtype=_onp.float32)
        header = header._replace(flag=label.size, label=0.0)
        return struct.pack(_IR_FORMAT, *header) + label.tobytes() + s
    return struct.pack(_IR_FORMAT, header.flag, float(label), header.id,
                       header.id2) + s


def unpack(s: bytes):
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = _onp.frombuffer(s[:header.flag * 4], dtype=_onp.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header: IRHeader, img, quality=95, img_fmt=".jpg"):
    raise MXNetError("pack_img requires an image codec; encode with PIL and "
                     "use pack() directly")


def unpack_img(s: bytes, iscolor=1):
    header, img_bytes = unpack(s)
    from .image import imdecode
    return header, imdecode(img_bytes)
