"""Builtin-op lookup by registered name (parity:
`python/mxnet/numpy_op_signature.py` `_get_builtin_op`).

The reference maps registry names like ``_np_sum`` / ``_npx_relu`` (and
submodule-prefixed ones like ``_np_random_uniform``) back to the live
front-end callables so tests can drive ops through their registered
identity.  Here the front ends ARE the registry, so the lookup is a
prefix strip + attribute walk over `mx.np` / `mx.npx`.
"""
from __future__ import annotations

__all__ = ["_get_builtin_op"]

_SUBMODULES = ("random", "linalg", "fft")


def _get_builtin_op(op_name: str):
    from . import numpy as mx_np
    from . import numpy_extension as mx_npx
    if op_name.startswith("_np_"):
        root, rest = mx_np, op_name[len("_np_"):]
    elif op_name.startswith("_npx_"):
        root, rest = mx_npx, op_name[len("_npx_"):]
    else:
        return None
    for sub in _SUBMODULES:
        if rest.startswith(sub + "_"):
            root = getattr(root, sub, None)
            rest = rest[len(sub) + 1:]
            break
    return getattr(root, rest, None) if root is not None else None
