"""`mx.attribute` (parity: `python/mxnet/attribute.py`): scoped symbol
attributes (AttrScope)."""
import threading

__all__ = ["AttrScope", "current"]


class AttrScope:
    _state = threading.local()

    def __init__(self, **kwargs):
        self._attr = kwargs
        self._old = None

    def get(self, attr=None):
        out = dict(self._attr)
        if attr:
            out.update(attr)
        return out

    def __enter__(self):
        if not hasattr(AttrScope._state, "stack"):
            AttrScope._state.stack = [AttrScope()]
        parent = AttrScope._state.stack[-1]
        merged = dict(parent._attr)
        merged.update(self._attr)
        self._attr = merged
        AttrScope._state.stack.append(self)
        return self

    def __exit__(self, *exc):
        AttrScope._state.stack.pop()
        return False


def current():
    if not hasattr(AttrScope._state, "stack"):
        AttrScope._state.stack = [AttrScope()]
    return AttrScope._state.stack[-1]
