"""Distributed tracing + FLOP-accounted performance attribution.

Two instruments, one module (docs/observability.md, "Tracing &
performance attribution"):

* :class:`Tracer` — lightweight spans (trace_id / span_id / parent_id, a
  per-tracer thread-local current-span stack, explicit cross-thread
  context handoff via :meth:`Tracer.current_context`).  Finished spans
  go to the `telemetry.RunJournal` as ``span`` events (when a journal is
  attached) and accumulate in a bounded ring exportable as
  Chrome/Perfetto ``trace_event`` JSON (:func:`export_chrome` — open the
  file in https://ui.perfetto.dev or chrome://tracing).  Instrumentation
  sites live in the serve scheduler (the full request lifecycle:
  queue → admit → prefill chunks → decode steps → stream → finish),
  the serve fleet/router (``serve.route`` per dispatch,
  ``serve.failover`` per replica death, ``serve.shed`` per rejection —
  phase spans carry a ``replica`` tag so `tools/diagnose.py --trace`
  can roll a fleet up per replica),
  `ShardedTrainStep` (dispatch → compile → device execute → retire,
  correlated with journal step ids), `DevicePrefetcher` /
  `data.DataPipeline`, `CheckpointManager`, and the elastic reform path.

* :class:`CostAccountant` — a per-executable registry of XLA's own cost
  model: every ``.lower().compile()`` site hands its compiled object to
  :func:`record_executable`, which captures ``cost_analysis()`` +
  ``memory_analysis()`` into a feature vector (flops, bytes accessed,
  argument/output/temp bytes).  At step retire the cost flops combine
  with measured wall time into the always-on ``mfu_estimate`` /
  ``step_flops`` / ``hbm_bytes_est`` gauges, and each ``step_retired``
  journal row carries the feature vector — the labeled
  (cost-features, measured-ms) corpus a learned performance model
  (arxiv 2008.01040) trains on.

MFU semantics: on TPU the estimate is real attribution (XLA-counted
flops / wall / device peak).  On CPU the flop count is still exact for
the compiled program, but the peak is the **projected** peak of the
configured device kind (``MXTPU_MFU_DEVICE_KIND``, default ``v5e``) —
a trajectory proxy for `bench.py`, explicitly NOT a CPU utilization
number (the entry carries ``projected=True``).

Gating contract (the `telemetry.enabled()` idiom): span creation sites
guard on one module-level bool (:func:`enabled` — ``MXTPU_TRACE``), so
a run without tracing pays one boolean read and ZERO allocations per
step.  Cost capture happens once per compile (never on the hot path)
and is always on — it is how `bench.py` gets a defensible MFU proxy
without any env vars set.
"""
from __future__ import annotations

import atexit
import collections
import itertools
import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import telemetry as _tele

__all__ = [
    "Span", "SpanContext", "Tracer", "CostAccountant", "ClockSync",
    "enabled", "enable", "disable", "get_tracer", "tracers", "span",
    "trace_dir", "export_chrome", "chrome_events", "reset",
    "span_to_wire", "note_remote_process", "remote_processes",
    "account", "record_executable", "cost_features_of", "estimate_mfu",
    "peak_flops", "projected_peak_flops", "note_step_cost",
    "ENV_TRACE", "ENV_TRACE_DIR", "ENV_MFU_KIND", "ENV_PEAK_TFLOPS",
]

_log = logging.getLogger(__name__)

ENV_TRACE = "MXTPU_TRACE"
ENV_TRACE_DIR = "MXTPU_TRACE_DIR"
ENV_MFU_KIND = "MXTPU_MFU_DEVICE_KIND"
ENV_PEAK_TFLOPS = "MXTPU_PEAK_TFLOPS"

# spans kept per tracer for export (oldest dropped); a multi-hour run
# with tracing left on must stay bounded in host memory
DEFAULT_SPAN_CAP = 200_000

# ts anchor: chrome trace_event wants wall-clock microseconds, span
# timing wants a monotonic clock — record the pair once and convert
_EPOCH_WALL = time.time()
_EPOCH_PERF = time.perf_counter()

# span-id allocation is salted by pid so spans SHIPPED from a worker
# process into the parent's trace tree (Tracer.ingest) can never
# collide with the parent's own ids — parent_id links must stay
# unambiguous within one trace
_SPAN_ID_BASE = (os.getpid() & 0xFFFFF) << 32


def _wall_us(t_perf: float) -> float:
    return (_EPOCH_WALL + (t_perf - _EPOCH_PERF)) * 1e6


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

class SpanContext:
    """The portable identity of a span: what another thread needs to
    parent its own spans under it (`Tracer.current_context` →
    ``span(..., parent=ctx)``)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: int):
        self.trace_id = trace_id
        self.span_id = int(span_id)

    def __repr__(self):
        return f"SpanContext({self.trace_id}, {self.span_id})"


class Span:
    """One timed operation.  Usable as a context manager (lexical spans)
    or via explicit :meth:`finish` (request-lifecycle spans that outlive
    any single call frame)."""

    __slots__ = ("tracer", "name", "trace_id", "span_id", "parent_id",
                 "t0", "t1", "tags", "track", "pid", "_on_stack")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: int, parent_id: Optional[int],
                 track: Optional[str], tags: Dict[str, object],
                 t0: Optional[float] = None):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.track = track
        self.tags = tags
        self.t0 = time.perf_counter() if t0 is None else t0
        self.t1: Optional[float] = None
        self.pid: Optional[int] = None  # None = this process; set on ingest
        self._on_stack = False

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_tag(self, key: str, value) -> "Span":
        self.tags[key] = value
        return self

    @property
    def duration_ms(self) -> Optional[float]:
        if self.t1 is None:
            return None
        return (self.t1 - self.t0) * 1e3

    def finish(self, t1: Optional[float] = None, **tags) -> "Span":
        """Close the span (idempotent).  Extra `tags` merge in; manual
        spans pass nothing, post-hoc recorders pass an explicit `t1`."""
        if self.t1 is not None:
            return self
        if tags:
            self.tags.update(tags)
        self.t1 = time.perf_counter() if t1 is None else t1
        self.tracer._finish(self)
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.tags.setdefault("error", exc_type.__name__)
        if self._on_stack:
            self.tracer._pop(self)
        self.finish()
        return False

    def __repr__(self):
        state = "open" if self.t1 is None else f"{self.duration_ms:.3f}ms"
        return (f"Span({self.name}, trace={self.trace_id}, "
                f"id={self.span_id}, parent={self.parent_id}, {state})")


class Tracer:
    """One span namespace (e.g. ``serve``, ``train``, ``data``).

    Each tracer owns its OWN trace-id space and its OWN thread-local
    current-span stack, so a serving engine and a training step tracing
    concurrently in one process can never contaminate each other's
    traces (the trace_id carries the tracer name).  Root spans (no
    parent on the stack, no explicit parent) open a fresh trace_id;
    children inherit the parent's."""

    def __init__(self, name: str, span_cap: int = DEFAULT_SPAN_CAP):
        self.name = name
        self._span_ids = itertools.count(_SPAN_ID_BASE + 1)
        self._trace_ids = itertools.count(1)
        self._local = threading.local()
        self._lock = threading.Lock()
        # deque(maxlen): O(1) eviction at the cap — a list.pop(0) would
        # shift 200k entries under the lock on every finish once full
        self._spans: "collections.deque[Span]" = collections.deque(
            maxlen=int(span_cap))
        self._span_cap = int(span_cap)
        self.dropped = 0

    # -- stack ----------------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current(self) -> Optional[Span]:
        """The innermost open span on THIS thread (or None)."""
        st = self._stack()
        return st[-1] if st else None

    def current_context(self) -> Optional[SpanContext]:
        """Cross-thread handoff: capture on the owning thread, pass the
        context to the worker, parent its spans with ``parent=ctx``."""
        cur = self.current()
        return cur.context() if cur is not None else None

    def _new_trace_id(self) -> str:
        return f"{self.name}-{os.getpid():x}-{next(self._trace_ids):x}"

    def _ids_for(self, parent) -> Tuple[str, Optional[int]]:
        """(trace_id, parent_id) from an explicit parent (Span /
        SpanContext), the thread-local stack, or a fresh root."""
        if parent is not None:  # Span and SpanContext share the fields
            return parent.trace_id, parent.span_id
        cur = self.current()
        if cur is not None:
            return cur.trace_id, cur.span_id
        return self._new_trace_id(), None

    # -- span creation --------------------------------------------------
    def span(self, name: str, parent=None, track: Optional[str] = None,
             **tags) -> Span:
        """Lexical span: ``with tracer.span("phase"): ...`` — pushed on
        the thread-local stack, so nested ``span()`` calls on the same
        thread parent automatically."""
        s = self.start_span(name, parent=parent, track=track, **tags)
        s._on_stack = True
        self._stack().append(s)
        return s

    def start_span(self, name: str, parent=None,
                   track: Optional[str] = None, **tags) -> Span:
        """Manual span: NOT pushed on the stack (finish() explicitly).
        For operations that outlive the creating call frame — a serve
        request, an in-flight train step."""
        trace_id, parent_id = self._ids_for(parent)
        return Span(self, name, trace_id, next(self._span_ids),
                    parent_id, track, dict(tags))

    def record_span(self, name: str, t0: float, t1: float, parent=None,
                    track: Optional[str] = None, **tags) -> Span:
        """Post-hoc span from already-measured perf_counter endpoints
        (per-slot serve phases reconstructed after the fused step ran)."""
        trace_id, parent_id = self._ids_for(parent)
        s = Span(self, name, trace_id, next(self._span_ids), parent_id,
                 track, dict(tags), t0=t0)
        s.finish(t1=t1)
        return s

    def _pop(self, span: Span) -> None:
        st = self._stack()
        if st and st[-1] is span:
            st.pop()
        elif span in st:            # exited out of order: drop through it
            st.remove(span)

    def _finish(self, span: Span) -> None:
        with self._lock:
            if len(self._spans) == self._span_cap:
                self.dropped += 1      # deque maxlen evicts the oldest
            self._spans.append(span)
        if _tele.enabled():
            # a `step` tag intentionally lands as the journal row's step
            # id, correlating the span with step_dispatched/retired rows
            _tele.event("span", span=span.name, tracer=self.name,
                        trace_id=span.trace_id, span_id=span.span_id,
                        parent_id=span.parent_id,
                        dur_ms=round(span.duration_ms, 3),
                        **{k: v for k, v in span.tags.items()
                           if k not in ("span", "tracer", "trace_id",
                                        "span_id", "parent_id", "dur_ms")})

    # -- introspection / export -----------------------------------------
    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
        self.dropped = 0

    def drain(self) -> List[Span]:
        """Pop every finished span out of the ring (worker processes
        drain on each heartbeat and ship the batch to the parent, so
        the same span is never sent twice)."""
        with self._lock:
            out = list(self._spans)
            self._spans.clear()
        return out

    def ingest(self, rows: List[dict], offset: float = 0.0,
               pid: Optional[int] = None,
               replica: Optional[str] = None) -> int:
        """Adopt finished spans shipped from another process
        (:func:`span_to_wire` dicts).  `offset` is the remote clock's
        perf_counter offset relative to ours (``ClockSync.offset``):
        remote timestamps are rebased by subtracting it, so the adopted
        spans land on THIS process's timeline.  Keeps the remote
        trace/span/parent ids verbatim — that is what stitches the
        cross-process tree together."""
        n = 0
        for row in rows:
            try:
                tags = dict(row.get("tags") or {})
                if replica is not None:
                    tags.setdefault("replica", replica)
                s = Span(self, str(row["name"]), str(row["trace_id"]),
                         int(row["span_id"]),
                         (int(row["parent_id"])
                          if row.get("parent_id") is not None else None),
                         row.get("track"), tags,
                         t0=float(row["t0"]) - offset)
                s.pid = int(pid) if pid is not None else None
                s.finish(t1=float(row["t1"]) - offset)
                n += 1
            except (AttributeError, KeyError, TypeError, ValueError):
                continue   # one malformed row must not drop the batch
        return n


def span_to_wire(s: Span) -> dict:
    """One finished span as a JSON-safe dict for the events channel
    (the inverse of :meth:`Tracer.ingest`).  Timestamps stay in the
    SENDER's perf_counter domain — the receiver rebases with its
    ClockSync offset for this peer."""
    return {"name": s.name, "trace_id": s.trace_id,
            "span_id": s.span_id, "parent_id": s.parent_id,
            "track": s.track, "t0": s.t0, "t1": s.t1,
            "tags": _tele.json_safe(s.tags)}


class ClockSync:
    """NTP-style offset estimator between this process's perf_counter
    and a peer's (docs/observability.md, "Fleet observability").

    Each :meth:`update` sample is one request/response round trip:
    ``offset = remote_ts - (t_send + t_recv) / 2`` — the RTT-halving
    assumption (symmetric paths).  The estimate served is the offset of
    the MINIMUM-RTT sample in a sliding window: low-RTT exchanges bound
    the asymmetry error tightest, and the window lets the estimate
    track drift as old samples age out.  ``rebase`` maps a remote
    timestamp onto the local timeline."""

    __slots__ = ("_window", "offset", "rtt", "samples")

    def __init__(self, window: int = 8):
        self._window: "collections.deque[Tuple[float, float]]" = \
            collections.deque(maxlen=int(window))
        self.offset = 0.0
        self.rtt: Optional[float] = None
        self.samples = 0

    def seed(self, offset: float) -> None:
        """Coarse one-way estimate (the hello handshake timestamp,
        unknown RTT).  Only used until the first real round-trip
        sample — a one-way sample has no RTT bound, so it must never
        outcompete measured ones in the min-RTT selection."""
        if self.samples == 0:
            self.offset = float(offset)

    def update(self, t_send: float, remote_ts: float,
               t_recv: float) -> float:
        rtt = max(0.0, float(t_recv) - float(t_send))
        off = float(remote_ts) - (float(t_send) + float(t_recv)) / 2.0
        self._window.append((rtt, off))
        self.samples += 1
        self.rtt, self.offset = min(self._window, key=lambda s: s[0])
        return self.offset

    def rebase(self, remote_t: float) -> float:
        """A remote perf_counter timestamp on the local timeline."""
        return float(remote_t) - self.offset

    def __repr__(self):
        rtt = "?" if self.rtt is None else f"{self.rtt * 1e3:.3f}ms"
        return (f"ClockSync(offset={self.offset * 1e3:.3f}ms, "
                f"rtt={rtt}, samples={self.samples})")


# ---------------------------------------------------------------------------
# module-level tracer registry + enable gate
# ---------------------------------------------------------------------------

_enabled = False
_trace_dir: Optional[str] = None
_tracers: Dict[str, Tracer] = {}
_remote_procs: Dict[int, str] = {}
_reg_lock = threading.Lock()
_atexit_registered = False


def note_remote_process(pid: Optional[int], name: str) -> None:
    """Name a remote pid whose spans this process ingests — becomes a
    ``process_name`` metadata row in the Perfetto export, so worker
    tracks render under "worker d1" instead of a bare pid."""
    if pid is not None:
        with _reg_lock:
            _remote_procs[int(pid)] = str(name)


def remote_processes() -> Dict[int, str]:
    with _reg_lock:
        return dict(_remote_procs)


def enabled() -> bool:
    """One global read — the zero-cost fast path every span site guards
    on (`MXTPU_TRACE`)."""
    return _enabled


def get_tracer(name: str) -> Tracer:
    """Get-or-create the named tracer (instrumentation sites call this
    once and cache, or call per use — it is a dict lookup)."""
    t = _tracers.get(name)
    if t is None:
        with _reg_lock:
            t = _tracers.get(name)
            if t is None:
                t = _tracers[name] = Tracer(name)
    return t


def tracers() -> Dict[str, Tracer]:
    return dict(_tracers)


def span(name: str, tracer: str = "run", **tags) -> Span:
    """Module facade: a lexical span on the named tracer."""
    return get_tracer(tracer).span(name, **tags)


def trace_dir() -> Optional[str]:
    return _trace_dir


def enable(dir: Optional[str] = None) -> None:
    """Turn span collection on; `dir` (or ``MXTPU_TRACE_DIR``) is where
    :func:`export_chrome` writes by default, and where the atexit hook
    auto-exports when the env enabled tracing."""
    global _enabled, _trace_dir, _atexit_registered
    if dir is not None:
        _trace_dir = os.path.abspath(dir)
    elif _trace_dir is None:
        env_dir = os.environ.get(ENV_TRACE_DIR, "").strip()
        if env_dir:
            _trace_dir = os.path.abspath(env_dir)
    _enabled = True
    if not _atexit_registered:
        atexit.register(_atexit_export)
        _atexit_registered = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    """Drop every tracer and collected span (tests)."""
    global _trace_dir
    with _reg_lock:
        _tracers.clear()
        _remote_procs.clear()
    _trace_dir = None


def _atexit_export() -> None:
    if not _enabled or _trace_dir is None:
        return
    try:
        if any(t.spans() for t in _tracers.values()):
            export_chrome()
    except Exception:   # export-at-exit must never mask the real exit
        _log.debug("tracing atexit export failed", exc_info=True)


# ---------------------------------------------------------------------------
# Chrome/Perfetto trace_event export
# ---------------------------------------------------------------------------

def chrome_events(include: Optional[List[str]] = None,
                  since: Optional[float] = None) -> List[dict]:
    """All finished spans as Chrome ``trace_event`` dicts.
    ``since`` (a ``time.perf_counter`` instant) keeps only spans that
    were still open at or after it — bounded exports for incident
    capsules.

    Every span becomes a complete ``"ph": "X"`` event.  Tracks: spans
    carry either an explicit ``track`` (serve requests get one per
    request, so concurrent requests render as separate Perfetto rows
    instead of interleaving on one thread track) or the OS thread id
    they ran on; each (process, track) pair gets a synthetic tid plus
    an ``"M"`` thread_name metadata event naming it.  Spans ingested
    from worker processes keep their origin pid, and every remote pid
    named via :func:`note_remote_process` gets a ``process_name``
    metadata row — one export, one Perfetto tree per request, one
    process group per replica."""
    local_pid = os.getpid()
    events: List[dict] = []
    track_tids: Dict[Tuple[int, str], int] = {}
    next_tid = itertools.count(1)

    def tid_for(pid: int, track: str) -> int:
        t = track_tids.get((pid, track))
        if t is None:
            t = track_tids[(pid, track)] = next(next_tid)
        return t

    names = include if include is not None else sorted(_tracers)
    for tname in names:
        tracer = _tracers.get(tname)
        if tracer is None:
            continue
        for s in tracer.spans():
            if s.t1 is None:
                continue
            if since is not None and s.t1 < since:
                continue
            spid = s.pid if s.pid is not None else local_pid
            track = s.track if s.track is not None else f"{tname}"
            args = {"trace_id": s.trace_id, "span_id": s.span_id}
            if s.parent_id is not None:
                args["parent_id"] = s.parent_id
            args.update(_tele.json_safe(s.tags))
            events.append({
                "name": s.name, "ph": "X", "cat": tname,
                "ts": round(_wall_us(s.t0), 3),
                "dur": round((s.t1 - s.t0) * 1e6, 3),
                "pid": spid, "tid": tid_for(spid, track), "args": args,
            })
    meta = [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
             "args": {"name": track}}
            for (pid, track), tid in sorted(track_tids.items(),
                                            key=lambda kv: kv[1])]
    remote = remote_processes()
    seen_pids = {pid for pid, _ in track_tids}
    if remote and seen_pids - {local_pid}:
        # merged multi-process export: name every process group
        meta += [{"name": "process_name", "ph": "M", "pid": local_pid,
                  "args": {"name": f"parent {local_pid}"}}]
        meta += [{"name": "process_name", "ph": "M", "pid": pid,
                  "args": {"name": pname}}
                 for pid, pname in sorted(remote.items())
                 if pid in seen_pids]
    # stable render order: metadata first, then spans by start time
    events.sort(key=lambda e: e["ts"])
    return meta + events


def export_chrome(path: Optional[str] = None,
                  since: Optional[float] = None) -> str:
    """Write the collected spans as a Chrome/Perfetto-loadable JSON
    trace; returns the path (default:
    ``<trace_dir>/trace_<pid>.json``).  ``since`` bounds the export to
    spans still open at/after that ``perf_counter`` instant (capsules)."""
    if path is None:
        d = _trace_dir or os.getcwd()
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"trace_{os.getpid()}.json")
    else:
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
    doc = {"traceEvents": chrome_events(since=since),
           "displayTimeUnit": "ms",
           "otherData": {"exporter": "mxnet_tpu.tracing",
                         "pid": os.getpid()}}
    with open(path, "w") as f:
        json.dump(doc, f)
    if _tele.enabled():
        _tele.event("trace_export", path=path,
                    spans=len(doc["traceEvents"]))
    return path


# ---------------------------------------------------------------------------
# cost accounting
# ---------------------------------------------------------------------------

# bf16 peak matmul flops by TPU device kind (the bench.py table, shared
# so the MFU gauge and the bench agree on the denominator)
_PEAK_FLOPS = (
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5p", 459e12), ("v5", 459e12),
    ("v4", 275e12), ("v6", 918e12), ("trillium", 918e12),
)
_DEFAULT_PEAK = 197e12


def peak_flops(device_kind: str) -> float:
    """Peak bf16 FLOP/s for a device-kind string (conservative default
    for unknown kinds); ``MXTPU_PEAK_TFLOPS`` overrides everything."""
    env = os.environ.get(ENV_PEAK_TFLOPS, "").strip()
    if env:
        try:
            return float(env) * 1e12
        except ValueError:
            _log.warning("ignoring non-numeric %s=%r", ENV_PEAK_TFLOPS, env)
    kind = (device_kind or "").lower()
    for key, val in _PEAK_FLOPS:
        if key in kind:
            return val
    return _DEFAULT_PEAK


def projected_peak_flops() -> Tuple[float, str]:
    """(peak_flops, kind) for MFU **projection** on a non-TPU backend:
    the device kind the run is being sized for (``MXTPU_MFU_DEVICE_KIND``,
    default ``v5e``)."""
    kind = os.environ.get(ENV_MFU_KIND, "v5e").strip() or "v5e"
    return peak_flops(kind), kind


def estimate_mfu(flops, measured_s: float, device=None) -> Optional[dict]:
    """MFU of `flops` executed in `measured_s` wall seconds on `device`
    (default: the first jax device).  TPU: real peak for the attached
    kind; anything else: the PROJECTED peak of the configured kind
    (``MXTPU_MFU_DEVICE_KIND``) with ``projected=True`` — a trajectory
    proxy, never a CPU utilization claim."""
    if not flops or measured_s is None or measured_s <= 0:
        return None
    if device is None:
        try:
            import jax
            device = jax.devices()[0]
        except Exception:
            device = None
    platform = getattr(device, "platform", "").lower()
    if platform == "tpu":
        peak = peak_flops(getattr(device, "device_kind", ""))
        kind = getattr(device, "device_kind", "tpu")
        projected = False
    else:
        peak, kind = projected_peak_flops()
        projected = True
    achieved = float(flops) / measured_s
    return {"mfu_estimate": achieved / peak,
            "achieved_flops_per_s": achieved,
            "peak_flops": peak, "projected": projected,
            "device_kind": kind}


def cost_features_of(compiled) -> Optional[dict]:
    """Normalize one compiled executable's ``cost_analysis()`` +
    ``memory_analysis()`` into a flat feature dict (the per-op feature
    vector shape the learned performance model consumes).  Returns None
    when the runtime exposes neither (old jaxlib, exotic backend) —
    callers treat that as "no attribution", never an error."""
    cost = None
    try:
        cost = compiled.cost_analysis()
    except Exception:
        cost = None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    feats: Dict[str, float] = {}
    if isinstance(cost, dict):
        for key, out in (("flops", "flops"),
                         ("bytes accessed", "bytes_accessed"),
                         ("transcendentals", "transcendentals"),
                         ("optimal_seconds", "optimal_seconds")):
            v = cost.get(key)
            if v is not None:
                try:
                    feats[out] = float(v)
                except (TypeError, ValueError):
                    pass
    try:
        mem = compiled.memory_analysis()
    except Exception:
        mem = None
    if mem is not None:
        for attr, out in (
                ("argument_size_in_bytes", "argument_bytes"),
                ("output_size_in_bytes", "output_bytes"),
                ("temp_size_in_bytes", "temp_bytes"),
                ("alias_size_in_bytes", "alias_bytes"),
                ("generated_code_size_in_bytes", "generated_code_bytes")):
            v = getattr(mem, attr, None)
            if v is not None:
                feats[out] = float(v)
        # estimated peak live HBM for one execution: arguments + outputs
        # + XLA temp buffers, minus donated aliases counted twice
        feats["hbm_bytes_est"] = (
            feats.get("argument_bytes", 0.0)
            + feats.get("output_bytes", 0.0)
            + feats.get("temp_bytes", 0.0)
            - feats.get("alias_bytes", 0.0))
    return feats or None


class CostAccountant:
    """Registry of per-executable cost features keyed by a stable name
    (``train_step@<id>``, ``serve_step_c8@<id>``, ``autotune/<op>`` ...).

    `record` is called once per compile — every ``.lower().compile()``
    site in the framework feeds it — so lookups at step retire are one
    dict read.  `mfu` combines an entry's flops with a measured wall
    time and the device peak (projected peak on non-TPU backends)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, dict] = {}

    def record(self, key: str, compiled, **meta) -> Optional[dict]:
        feats = cost_features_of(compiled)
        if feats is None:
            return None
        return self.record_features(key, feats, **meta)

    def record_features(self, key: str, features: dict,
                        **meta) -> dict:
        """Register a pre-computed feature dict (the autotuner's
        analytic roofline for opaque kernel thunks; everything else goes
        through `record`)."""
        entry = {"key": key, "features": dict(features),
                 "meta": dict(meta)}
        with self._lock:
            self._entries[key] = entry
        if _tele.enabled():
            _tele.event("cost_analysis", key=key,
                        flops=features.get("flops"),
                        bytes_accessed=features.get("bytes_accessed"),
                        hbm_bytes_est=features.get("hbm_bytes_est"),
                        **meta)
        return entry

    def get(self, key: str) -> Optional[dict]:
        with self._lock:
            return self._entries.get(key)

    def features(self, key: str) -> Optional[dict]:
        e = self.get(key)
        return dict(e["features"]) if e else None

    def entries(self) -> Dict[str, dict]:
        with self._lock:
            return dict(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def discard(self, key: str) -> None:
        """Drop one entry (a reshard invalidates the old topology's
        cost features; the next compile re-records)."""
        with self._lock:
            self._entries.pop(key, None)

    def mfu(self, key: str, measured_s: float,
            device=None) -> Optional[dict]:
        """MFU estimate for one execution of `key` taking `measured_s`
        wall seconds: ``{"mfu_estimate", "achieved_flops_per_s",
        "peak_flops", "projected", "device_kind"}`` (None when the key
        has no flops or the measurement is degenerate)."""
        e = self.get(key)
        if e is None:
            return None
        return estimate_mfu(e["features"].get("flops"), measured_s,
                            device=device)


_account = CostAccountant()


def account() -> CostAccountant:
    """The process-wide cost registry."""
    return _account


def record_executable(key: str, compiled, **meta) -> Optional[dict]:
    """Facade over ``account().record`` — what the compile sites call.
    Never raises: attribution must not take a compile down."""
    try:
        return _account.record(key, compiled, **meta)
    except Exception:
        _log.debug("cost capture failed for %s", key, exc_info=True)
        return None


def note_step_cost(key: str, measured_s: float,
                   device=None) -> Optional[dict]:
    """Combine one retired execution's measured wall time with its
    executable's recorded cost: updates the always-on ``mfu_estimate`` /
    ``step_flops`` / ``hbm_bytes_est`` gauges (when telemetry is
    enabled) and returns the cost-feature row for the caller to embed
    in its journal record.  One dict lookup + arithmetic — cheap enough
    for every retire."""
    e = _account.get(key)
    if e is None:
        return None
    feats = e["features"]
    mfu = _account.mfu(key, measured_s, device=device)
    row = {"measured_ms": round(measured_s * 1e3, 3)}
    if feats.get("flops"):
        row["flops"] = feats["flops"]
    if feats.get("bytes_accessed"):
        row["bytes_accessed"] = feats["bytes_accessed"]
    if feats.get("hbm_bytes_est"):
        row["hbm_bytes_est"] = feats["hbm_bytes_est"]
    if mfu is not None:
        # full precision: a tiny CPU proxy model's MFU is ~1e-9 and must
        # stay NONZERO (it is a trajectory number, not a pretty one)
        row["mfu_estimate"] = mfu["mfu_estimate"]
        row["mfu_projected"] = mfu["projected"]
    if _tele.enabled():
        # per-program label: a process serving AND training must not
        # have the two executables overwrite each other's gauges
        program = e["meta"].get("kind", "unknown")
        if mfu is not None:
            _tele.gauge(
                "mfu_estimate",
                "Model-flops utilization of the last retired step "
                "(XLA cost_analysis flops / wall / device peak; "
                "PROJECTED peak on non-TPU backends)",
                labelnames=("program",)).set(mfu["mfu_estimate"],
                                             program=program)
        if feats.get("flops"):
            _tele.gauge(
                "step_flops",
                "XLA-counted flops of the executing step program",
                labelnames=("program",)).set(feats["flops"],
                                             program=program)
        if feats.get("hbm_bytes_est"):
            _tele.gauge(
                "hbm_bytes_est",
                "Estimated peak HBM bytes of the executing step "
                "program (args + outputs + temps - aliases)",
                labelnames=("program",)).set(feats["hbm_bytes_est"],
                                             program=program)
    return row


# auto-enable from the environment: MXTPU_TRACE=1 (or a path value,
# which doubles as the trace dir).  Same child-process rule as
# telemetry: spawned workers stay dark.
_env = os.environ.get(ENV_TRACE, "").strip()
if _env and _env.lower() not in ("0", "false", "no", "off") \
        and not _tele._in_child_process():
    _is_path = os.sep in _env
    enable(dir=_env if _is_path else None)
del _env
