"""`mx.image` — image ops (parity: `python/mxnet/image/` + `src/operator/image/`).

Decode uses PIL if present (no OpenCV in this environment); the tensor-space
transforms (resize/crop/normalize/flip) are pure XLA ops and run on device.
Layout: HWC uint8/float like the reference's image namespace.
"""
from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as _onp

from ..base import MXNetError
from ..device import current_device
from ..ndarray.ndarray import ndarray, apply_op, from_jax
from .. import random as _rng

__all__ = ["imdecode", "imresize", "resize_short", "fixed_crop", "center_crop",
           "random_crop", "random_size_crop", "color_normalize",
           "HorizontalFlipAug", "CastAug", "ColorNormalizeAug", "ResizeAug",
           "ForceResizeAug", "CenterCropAug", "RandomCropAug",
           "RandomSizedCropAug", "BrightnessJitterAug", "ContrastJitterAug",
           "SaturationJitterAug", "HueJitterAug", "ColorJitterAug",
           "LightingAug", "RandomGrayAug", "RandomOrderAug", "SequentialAug",
           "CreateAugmenter", "ImageIter"]


def imdecode(buf, to_rgb=1, flag=1):
    try:
        import io as _io

        from PIL import Image
    except ImportError as e:
        raise MXNetError("imdecode requires PIL (no OpenCV in TPU build)") from e
    img = Image.open(_io.BytesIO(buf))
    if flag == 0:
        img = img.convert("L")
    else:
        img = img.convert("RGB")
    arr = _onp.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return from_jax(jnp.asarray(arr), current_device())


def imresize(src: ndarray, w: int, h: int, interp=1):
    method = {0: "nearest", 1: "bilinear", 2: "cubic"}.get(interp, "bilinear")

    def fn(x):
        out = jax.image.resize(x.astype(jnp.float32), (h, w, x.shape[2]),
                               method=method)
        return out.astype(x.dtype) if jnp.issubdtype(x.dtype, jnp.integer) \
            else out
    return apply_op(fn, (src,), {}, name="imresize")


def resize_short(src: ndarray, size: int, interp=2):
    h, w = src.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src: ndarray, x0: int, y0: int, w: int, h: int,
               size=None, interp=2):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def center_crop(src: ndarray, size: Tuple[int, int], interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = max(0, (w - new_w) // 2)
    y0 = max(0, (h - new_h) // 2)
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def random_crop(src: ndarray, size: Tuple[int, int], interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = int(_onp.random.randint(0, max(1, w - new_w + 1)))
    y0 = int(_onp.random.randint(0, max(1, h - new_h + 1)))
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src: ndarray, mean, std=None):
    def fn(x):
        y = x.astype(jnp.float32) - jnp.asarray(mean, jnp.float32)
        if std is not None:
            y = y / jnp.asarray(std, jnp.float32)
        return y
    return apply_op(fn, (src,), {}, name="color_normalize")


class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _onp.random.rand() < self.p:
            return apply_op(lambda x: jnp.flip(x, axis=1), (src,), {},
                            name="hflip")
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean, self.std = mean, std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


def random_size_crop(src: ndarray, size: Tuple[int, int], area,
                     ratio: Tuple[float, float], interp=2):
    """Random crop with area/aspect jitter then resize (parity:
    `python/mxnet/image/image.py` random_size_crop)."""
    h, w = src.shape[0], src.shape[1]
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = float(_onp.random.uniform(area[0], area[1])) * h * w
        log_ratio = (_onp.log(ratio[0]), _onp.log(ratio[1]))
        aspect = float(_onp.exp(_onp.random.uniform(*log_ratio)))
        new_w = int(round(_onp.sqrt(target_area * aspect)))
        new_h = int(round(_onp.sqrt(target_area / aspect)))
        if new_w <= w and new_h <= h:
            x0 = int(_onp.random.randint(0, w - new_w + 1))
            y0 = int(_onp.random.randint(0, h - new_h + 1))
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__()
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__()
        self.size, self.area, self.ratio, self.interp = size, area, ratio, \
            interp

    def __call__(self, src):
        out = random_size_crop(src, self.size, self.area, self.ratio,
                               self.interp)
        return out[0] if isinstance(out, tuple) else out


class BrightnessJitterAug(Augmenter):
    """Scale pixel values by 1 ± U(-brightness, brightness)."""

    def __init__(self, brightness):
        super().__init__()
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + float(_onp.random.uniform(-self.brightness,
                                                self.brightness))
        return apply_op(lambda x: x * alpha, (src,), {}, name="brightness")


class ContrastJitterAug(Augmenter):
    """Blend with the mean gray value (ITU-R BT.601 coefficients, as the
    reference's contrast_aug)."""

    def __init__(self, contrast):
        super().__init__()
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + float(_onp.random.uniform(-self.contrast,
                                                self.contrast))
        coef = jnp.asarray([0.299, 0.587, 0.114])

        def fn(x):
            gray = (x * coef).sum(axis=-1, keepdims=True)
            mean = gray.mean()
            return x * alpha + mean * (1.0 - alpha)
        return apply_op(fn, (src,), {}, name="contrast")


class SaturationJitterAug(Augmenter):
    """Blend with the per-pixel gray image."""

    def __init__(self, saturation):
        super().__init__()
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + float(_onp.random.uniform(-self.saturation,
                                                self.saturation))
        coef = jnp.asarray([0.299, 0.587, 0.114])

        def fn(x):
            gray = (x * coef).sum(axis=-1, keepdims=True)
            return x * alpha + gray * (1.0 - alpha)
        return apply_op(fn, (src,), {}, name="saturation")


class HueJitterAug(Augmenter):
    """Rotate hue via the YIQ linear approximation (reference hue_aug)."""

    def __init__(self, hue):
        super().__init__()
        self.hue = hue

    def __call__(self, src):
        alpha = float(_onp.random.uniform(-self.hue, self.hue))
        u = _onp.cos(alpha * _onp.pi)
        w_ = _onp.sin(alpha * _onp.pi)
        bt = _onp.array([[1.0, 0.0, 0.0],
                         [0.0, u, -w_],
                         [0.0, w_, u]])
        tyiq = _onp.array([[0.299, 0.587, 0.114],
                           [0.596, -0.274, -0.321],
                           [0.211, -0.523, 0.311]])
        ityiq = _onp.array([[1.0, 0.9563, 0.6210],
                            [1.0, -0.2721, -0.6474],
                            [1.0, -1.107, 1.7046]])
        t = jnp.asarray(_onp.dot(_onp.dot(ityiq, bt), tyiq).T)
        return apply_op(lambda x: jnp.dot(x, t), (src,), {}, name="hue")


class ColorJitterAug(Augmenter):
    """Random order of brightness/contrast/saturation jitter."""

    def __init__(self, brightness=0.0, contrast=0.0, saturation=0.0):
        super().__init__()
        self.augs = []
        if brightness:
            self.augs.append(BrightnessJitterAug(brightness))
        if contrast:
            self.augs.append(ContrastJitterAug(contrast))
        if saturation:
            self.augs.append(SaturationJitterAug(saturation))

    def __call__(self, src):
        order = _onp.random.permutation(len(self.augs))
        for i in order:
            src = self.augs[i](src)
        return src


class LightingAug(Augmenter):
    """PCA-based lighting noise (AlexNet-style)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__()
        self.alphastd = alphastd
        self.eigval = _onp.asarray(eigval)
        self.eigvec = _onp.asarray(eigvec)

    def __call__(self, src):
        alpha = _onp.random.normal(0, self.alphastd, size=(3,))
        rgb = jnp.asarray(_onp.dot(self.eigvec * alpha, self.eigval))
        return apply_op(lambda x: x + rgb, (src,), {}, name="lighting")


class RandomGrayAug(Augmenter):
    def __init__(self, p):
        super().__init__()
        self.p = p
        self._mat = jnp.asarray([[0.21, 0.21, 0.21],
                                 [0.72, 0.72, 0.72],
                                 [0.07, 0.07, 0.07]])

    def __call__(self, src):
        if _onp.random.uniform() < self.p:
            mat = self._mat
            return apply_op(lambda x: jnp.dot(x, mat), (src,), {},
                            name="gray")
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def __call__(self, src):
        for i in _onp.random.permutation(len(self.ts)):
            src = self.ts[i](src)
        return src


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmentation pipeline (parity:
    `python/mxnet/image/image.py` CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3 / 4.0, 4 / 3.0), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    jitter = []
    if brightness:
        jitter.append(BrightnessJitterAug(brightness))
    if contrast:
        jitter.append(ContrastJitterAug(contrast))
    if saturation:
        jitter.append(SaturationJitterAug(saturation))
    if jitter:
        auglist.append(RandomOrderAug(jitter))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = _onp.array([55.46, 4.794, 1.148])
        eigvec = _onp.array([[-0.5675, 0.7192, 0.4009],
                             [-0.5808, -0.0045, -0.8140],
                             [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = _onp.array([123.68, 116.28, 103.53])
    if std is True:
        std = _onp.array([58.395, 57.12, 57.375])
    if mean is not None and not isinstance(mean, bool):
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter:
    """Image data iterator over an indexed RecordIO pack or an image list
    (parity: `python/mxnet/image/image.py` ImageIter). Yields `DataBatch`
    with NCHW float data."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imgidx=None, imglist=None, path_root="",
                 shuffle=False, aug_list=None, label_width=1, **kwargs):
        from ..io import DataBatch, DataDesc
        from .. import recordio as _recordio
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self._DataBatch = DataBatch
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **kwargs)
        self.shuffle = shuffle
        self._rec = None
        if path_imgrec is not None:
            idx_path = path_imgidx or os.path.splitext(path_imgrec)[0] + \
                ".idx"
            self._rec = _recordio.MXIndexedRecordIO(idx_path, path_imgrec,
                                                    "r")
            self._keys = list(self._rec.keys)
        elif imglist is not None:
            self._list = [(float(e[0]) if label_width == 1
                           else _onp.asarray(e[:-1], dtype=_onp.float32),
                           os.path.join(path_root, e[-1]))
                          for e in imglist]
            self._keys = list(range(len(self._list)))
        else:
            raise MXNetError("ImageIter needs path_imgrec or imglist")
        self._order = list(self._keys)
        self.reset()

    def reset(self):
        if self.shuffle:
            _onp.random.shuffle(self._order)
        self._cursor = 0

    def _read_sample(self, key):
        from .. import recordio as _recordio
        if self._rec is not None:
            header, img_bytes = _recordio.unpack(self._rec.read_idx(key))
            label = header.label
            img = imdecode(img_bytes)
        else:
            label, path = self._list[key]
            with open(path, "rb") as f:
                img = imdecode(f.read())
        for aug in self.auglist:
            img = aug(img)
        return img, label

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        if self._cursor >= len(self._order):
            self.reset()
            raise StopIteration
        from ..numpy import stack as _stack, array as _array
        imgs, labels = [], []
        while len(imgs) < self.batch_size and \
                self._cursor < len(self._order):
            img, label = self._read_sample(self._order[self._cursor])
            self._cursor += 1
            imgs.append(img.transpose(2, 0, 1))
            labels.append(label)
        # pad the final partial batch by repeating the last sample
        pad = self.batch_size - len(imgs)
        for _ in range(pad):
            imgs.append(imgs[-1])
            labels.append(labels[-1])
        data = _stack(imgs)
        label = _array(_onp.asarray(labels, dtype=_onp.float32))
        return self._DataBatch([data], [label], pad=pad)
