"""`mx.image` — image ops (parity: `python/mxnet/image/` + `src/operator/image/`).

Decode uses PIL if present (no OpenCV in this environment); the tensor-space
transforms (resize/crop/normalize/flip) are pure XLA ops and run on device.
Layout: HWC uint8/float like the reference's image namespace.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as _onp

from ..base import MXNetError
from ..device import current_device
from ..ndarray.ndarray import ndarray, apply_op, from_jax
from .. import random as _rng

__all__ = ["imdecode", "imresize", "resize_short", "fixed_crop", "center_crop",
           "random_crop", "color_normalize", "HorizontalFlipAug", "CastAug",
           "ColorNormalizeAug", "ResizeAug", "CenterCropAug", "RandomCropAug"]


def imdecode(buf, to_rgb=1, flag=1):
    try:
        import io as _io

        from PIL import Image
    except ImportError as e:
        raise MXNetError("imdecode requires PIL (no OpenCV in TPU build)") from e
    img = Image.open(_io.BytesIO(buf))
    if flag == 0:
        img = img.convert("L")
    else:
        img = img.convert("RGB")
    arr = _onp.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return from_jax(jnp.asarray(arr), current_device())


def imresize(src: ndarray, w: int, h: int, interp=1):
    method = {0: "nearest", 1: "bilinear", 2: "cubic"}.get(interp, "bilinear")

    def fn(x):
        out = jax.image.resize(x.astype(jnp.float32), (h, w, x.shape[2]),
                               method=method)
        return out.astype(x.dtype) if jnp.issubdtype(x.dtype, jnp.integer) \
            else out
    return apply_op(fn, (src,), {}, name="imresize")


def resize_short(src: ndarray, size: int, interp=2):
    h, w = src.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src: ndarray, x0: int, y0: int, w: int, h: int,
               size=None, interp=2):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def center_crop(src: ndarray, size: Tuple[int, int], interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = max(0, (w - new_w) // 2)
    y0 = max(0, (h - new_h) // 2)
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def random_crop(src: ndarray, size: Tuple[int, int], interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = int(_onp.random.randint(0, max(1, w - new_w + 1)))
    y0 = int(_onp.random.randint(0, max(1, h - new_h + 1)))
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src: ndarray, mean, std=None):
    def fn(x):
        y = x.astype(jnp.float32) - jnp.asarray(mean, jnp.float32)
        if std is not None:
            y = y / jnp.asarray(std, jnp.float32)
        return y
    return apply_op(fn, (src,), {}, name="color_normalize")


class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _onp.random.rand() < self.p:
            return apply_op(lambda x: jnp.flip(x, axis=1), (src,), {},
                            name="hflip")
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean, self.std = mean, std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)
