"""`npx.image` / `nd.image` operator namespace (parity:
`src/operator/image/image_random.cc` + `resize.cc`/`crop.cc` ops surfaced
as `_image_*`; python wrappers `python/mxnet/ndarray/image.py`).

Thin op-style adapters over `mxnet_tpu.image`'s functions/augmenters with
the reference's names and argument shapes (HWC or NHWC input)."""
from __future__ import annotations

import numpy as _onp

from ..ndarray.ndarray import apply_op
from .. import numpy as _np

__all__ = ["resize", "crop", "random_crop", "random_resized_crop",
           "to_tensor", "normalize", "flip_left_right", "flip_top_bottom",
           "random_flip_left_right", "random_flip_top_bottom",
           "random_brightness", "random_contrast", "random_saturation",
           "random_hue", "random_color_jitter", "random_lighting"]


def _hwc(call, data, *args, **kwargs):
    """Apply an HWC function over HWC or NHWC input."""
    if data.ndim == 4:
        outs = [call(data[i], *args, **kwargs) for i in range(data.shape[0])]
        return _np.stack(outs, axis=0)
    return call(data, *args, **kwargs)


def resize(data, size=(224, 224), keep_ratio=False, interp=1):
    from . import imresize, resize_short
    if isinstance(size, int):
        size = (size, size)

    def one(img):
        if keep_ratio:
            return resize_short(img, min(size), interp)
        return imresize(img, size[0], size[1], interp)
    return _hwc(one, data)


def crop(data, x, y, width, height):
    from . import fixed_crop
    return _hwc(lambda img: fixed_crop(img, x, y, width, height), data)


def random_crop(data, xrange=(0.0, 1.0), yrange=(0.0, 1.0),
                wrange=(0.0, 1.0), hrange=(0.0, 1.0), size=None, interp=1):
    """Random crop; the crop extent is sampled from wrange/hrange
    fractions of the source (reference `_image_random_crop` semantics)
    unless an explicit pixel `size` is given."""
    from . import random_crop as _rc

    def one(img):
        h, w = img.shape[0], img.shape[1]
        if size is not None:
            sz = (size, size) if isinstance(size, int) else size
        else:
            cw = int(w * _onp.random.uniform(*wrange))
            ch = int(h * _onp.random.uniform(*hrange))
            sz = (max(cw, 1), max(ch, 1))
        out = _rc(img, sz, interp)
        return out[0] if isinstance(out, tuple) else out
    return _hwc(one, data)


def random_resized_crop(data, xrange=(0.0, 1.0), yrange=(0.0, 1.0),
                        area=(0.08, 1.0), ratio=(3 / 4.0, 4 / 3.0),
                        size=None, interp=1):
    from . import random_size_crop

    def one(img):
        h, w = img.shape[0], img.shape[1]
        sz = size or (w, h)
        out = random_size_crop(img, sz if not isinstance(sz, int)
                               else (sz, sz), area, ratio, interp)
        return out[0] if isinstance(out, tuple) else out
    return _hwc(one, data)


def to_tensor(data):
    """HWC uint8/float [0,255] -> CHW float32 [0,1] (`_image_to_tensor`)."""
    def fn(x):
        import jax.numpy as jnp
        y = x.astype(jnp.float32) / 255.0
        perm = (2, 0, 1) if x.ndim == 3 else (0, 3, 1, 2)
        return jnp.transpose(y, perm)
    return apply_op(fn, (data,), {}, name="image_to_tensor")


def normalize(data, mean=0.0, std=1.0):
    """CHW/NCHW channel normalize (`_image_normalize`)."""
    def fn(x):
        import jax.numpy as jnp
        m = jnp.asarray(mean, jnp.float32)
        s = jnp.asarray(std, jnp.float32)
        shape = (-1, 1, 1) if x.ndim == 3 else (1, -1, 1, 1)
        return (x - m.reshape(shape)) / s.reshape(shape)
    return apply_op(fn, (data,), {}, name="image_normalize")


def flip_left_right(data):
    axis = 1 if data.ndim == 3 else 2
    return _np.flip(data, axis=axis)


def flip_top_bottom(data):
    axis = 0 if data.ndim == 3 else 1
    return _np.flip(data, axis=axis)


def random_flip_left_right(data, p=0.5):
    return flip_left_right(data) if _onp.random.random() < p else data


def random_flip_top_bottom(data, p=0.5):
    return flip_top_bottom(data) if _onp.random.random() < p else data


def random_brightness(data, min_factor, max_factor):
    alpha = float(_onp.random.uniform(min_factor, max_factor))
    return apply_op(lambda x: x * alpha, (data,), {},
                    name="image_random_brightness")


def random_contrast(data, min_factor, max_factor):
    alpha = float(_onp.random.uniform(min_factor, max_factor))
    import jax.numpy as jnp
    coef = jnp.asarray([0.299, 0.587, 0.114])

    def fn(x):
        gray = (x * coef).sum(axis=-1, keepdims=True)
        # per-image mean (batched NHWC keeps each image's own statistic)
        axes = tuple(range(x.ndim - 3, x.ndim))
        mean = gray.mean(axis=axes, keepdims=True)
        return x * alpha + mean * (1.0 - alpha)
    return apply_op(fn, (data,), {}, name="image_random_contrast")


def random_saturation(data, min_factor, max_factor):
    alpha = float(_onp.random.uniform(min_factor, max_factor))
    import jax.numpy as jnp
    coef = jnp.asarray([0.299, 0.587, 0.114])

    def fn(x):
        gray = (x * coef).sum(axis=-1, keepdims=True)
        return x * alpha + gray * (1.0 - alpha)
    return apply_op(fn, (data,), {}, name="image_random_saturation")


def random_hue(data, min_factor, max_factor):
    # draw the uniform factor and apply the YIQ hue rotation directly
    delta = float(_onp.random.uniform(min_factor, max_factor)) - 1.0
    import jax.numpy as jnp
    u = _onp.cos(delta * _onp.pi)
    w_ = _onp.sin(delta * _onp.pi)
    bt = _onp.array([[1.0, 0.0, 0.0], [0.0, u, -w_], [0.0, w_, u]])
    tyiq = _onp.array([[0.299, 0.587, 0.114],
                       [0.596, -0.274, -0.321],
                       [0.211, -0.523, 0.311]])
    ityiq = _onp.array([[1.0, 0.9563, 0.6210],
                        [1.0, -0.2721, -0.6474],
                        [1.0, -1.107, 1.7046]])
    t = jnp.asarray(_onp.dot(_onp.dot(ityiq, bt), tyiq).T)
    return apply_op(lambda x: jnp.dot(x, t), (data,), {},
                    name="image_random_hue")


def random_color_jitter(data, brightness=0.0, contrast=0.0,
                        saturation=0.0, hue=0.0):
    out = data
    if brightness:
        out = random_brightness(out, 1 - brightness, 1 + brightness)
    if contrast:
        out = random_contrast(out, 1 - contrast, 1 + contrast)
    if saturation:
        out = random_saturation(out, 1 - saturation, 1 + saturation)
    if hue:
        out = random_hue(out, 1 - hue, 1 + hue)
    return out


def random_lighting(data, alpha_std=0.05):
    from . import LightingAug
    from ..gluon.data.vision.transforms import RandomLighting
    aug = LightingAug(alpha_std, RandomLighting._EIGVAL,
                      RandomLighting._EIGVEC)
    return aug(data)
