"""Gluon utilities (parity: `python/mxnet/gluon/utils.py`)."""
from __future__ import annotations

from typing import List

from ..base import MXNetError
from ..device import Device
from ..ndarray.ndarray import ndarray

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download", "replace_file",
]


def split_data(data: ndarray, num_slice: int, batch_axis=0, even_split=True):
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(f"cannot evenly split axis of size {size} into "
                         f"{num_slice}")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list=None, device_list=None, batch_axis=0,
                   even_split=True):
    """Parity: split a batch across devices. Under GSPMD a single sharded
    array replaces per-device copies, but the API is preserved for ported
    training loops."""
    devices = device_list or ctx_list
    from .. import numpy as mnp
    if not isinstance(data, ndarray):
        data = mnp.array(data)
    if len(devices) == 1:
        return [data.to_device(devices[0])]
    slices = split_data(data, len(devices), batch_axis, even_split)
    return [s.to_device(d) for s, d in zip(slices, devices)]


def clip_global_norm(arrays: List[ndarray], max_norm: float,
                     check_isfinite=True):
    """Parity: gluon/utils.py clip_global_norm."""
    import math

    from .. import numpy as mnp
    total = 0.0
    for a in arrays:
        n = float((a * a).sum().asnumpy())
        total += n
    norm = math.sqrt(total)
    if check_isfinite and not math.isfinite(norm):
        import warnings
        warnings.warn("nan or inf in clip_global_norm")
        return norm
    scale = min(1.0, max_norm / (norm + 1e-8))
    if scale < 1.0:
        for a in arrays:
            a *= scale
    return norm


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    raise MXNetError("network egress is unavailable in this environment; "
                     "place files locally and pass the path instead")


def replace_file(src, dst):
    """Atomic rename (parity: `gluon/utils.py:210` — there a fallback for
    pre-3.3 Pythons; `os.replace` is atomic on every platform we run)."""
    import os
    os.replace(src, dst)
