"""Gluon utilities (parity: `python/mxnet/gluon/utils.py`)."""
from __future__ import annotations

from typing import List

from ..base import MXNetError
from ..device import Device
from ..ndarray.ndarray import ndarray

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download", "replace_file",
]


def split_data(data: ndarray, num_slice: int, batch_axis=0, even_split=True):
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        # ValueError, as the reference raises (gluon/utils.py:66)
        raise ValueError(f"cannot evenly split axis of size {size} into "
                         f"{num_slice}")
    # uneven split follows numpy.array_split (the reference's contract,
    # pinned by test_split_data): the first size % num_slice slices get
    # one extra row — NOT a short tail slice
    step, extra = divmod(size, num_slice)
    slices = []
    begin = 0
    for i in range(num_slice):
        end = begin + step + (1 if i < extra else 0)
        slices.append(data.slice_axis(batch_axis, begin, end))
        begin = end
    return slices


def split_and_load(data, ctx_list=None, device_list=None, batch_axis=0,
                   even_split=True):
    """Parity: split a batch across devices. Under GSPMD a single sharded
    array replaces per-device copies, but the API is preserved for ported
    training loops."""
    devices = device_list or ctx_list
    from .. import numpy as mnp
    if not isinstance(data, ndarray):
        data = mnp.array(data)
    if len(devices) == 1:
        return [data.to_device(devices[0])]
    slices = split_data(data, len(devices), batch_axis, even_split)
    return [s.to_device(d) for s, d in zip(slices, devices)]


def clip_global_norm(arrays: List[ndarray], max_norm: float,
                     check_isfinite=True):
    """Parity: gluon/utils.py clip_global_norm."""
    import math

    from .. import numpy as mnp
    total = 0.0
    for a in arrays:
        n = float((a * a).sum().asnumpy())
        total += n
    norm = math.sqrt(total)
    if check_isfinite and not math.isfinite(norm):
        import warnings
        warnings.warn("nan or inf in clip_global_norm")
        return norm
    scale = min(1.0, max_norm / (norm + 1e-8))
    if scale < 1.0:
        for a in arrays:
            a *= scale
    return norm


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    """Fetch `url` to `path`, verifying `sha1_hash` when given (parity:
    `gluon/utils.py` download).  Writes to a temp file and renames
    atomically; retries transient failures.  `file://` URLs work fully
    offline — they are how the model store and its tests exercise this
    machinery on a zero-egress box; http(s) uses urllib and simply fails
    where there is no route out."""
    import os
    import shutil
    import urllib.parse
    import urllib.request

    fname = urllib.parse.urlparse(url).path.split("/")[-1]
    if path is None:
        path = fname
    elif os.path.isdir(path):
        path = os.path.join(path, fname)
    path = os.path.expanduser(path)
    if os.path.exists(path) and not overwrite and \
            (sha1_hash is None or check_sha1(path, sha1_hash)):
        return path
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    last_err = None
    for attempt in range(max(1, retries)):
        tmp = f"{path}.{os.getpid()}.part"
        try:
            if not verify_ssl:
                import ssl
                ctx = ssl.create_default_context()
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
                opener = urllib.request.build_opener(
                    urllib.request.HTTPSHandler(context=ctx))
            else:
                opener = urllib.request.build_opener()
            with opener.open(url) as r, open(tmp, "wb") as f:
                shutil.copyfileobj(r, f)
            if sha1_hash and not check_sha1(tmp, sha1_hash):
                raise MXNetError(
                    f"downloaded file {fname} checksum mismatch "
                    f"(expected sha1 {sha1_hash}); the remote file may "
                    "be corrupted or outdated")
            replace_file(tmp, path)
            return path
        except MXNetError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise            # checksum failures don't retry
        except Exception as e:  # noqa: BLE001 — urllib raises many types
            last_err = e
            try:
                os.remove(tmp)
            except OSError:
                pass
    raise MXNetError(f"failed to download {url} after {retries} "
                     f"attempts: {last_err}")


def replace_file(src, dst):
    """Atomic rename (parity: `gluon/utils.py:210` — there a fallback for
    pre-3.3 Pythons; `os.replace` is atomic on every platform we run)."""
    import os
    os.replace(src, dst)
