"""Gluon losses (parity: `python/mxnet/gluon/loss.py`)."""
from __future__ import annotations

import jax.numpy as jnp

from ..base import MXNetError
from .. import numpy as _np
from .. import numpy_extension as npx
from ..ndarray.ndarray import ndarray, apply_op
from .block import HybridBlock

__all__ = [
    "Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
    "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss", "KLDivLoss",
    "CTCLoss", "HuberLoss", "HingeLoss", "SquaredHingeLoss", "LogisticLoss",
    "TripletLoss", "PoissonNLLLoss", "CosineEmbeddingLoss", "SDMLLoss",
]


def _reshape_like(pred, label):
    if label.shape != pred.shape:
        return label.reshape(pred.shape)
    return label


def _apply_weighting(loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = loss * sample_weight
    if weight is not None:
        loss = loss * weight
    return loss


class Loss(HybridBlock):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def _mean_nonbatch(self, loss):
        axes = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss

    def __repr__(self):
        return f"{type(self).__name__}(batch_axis={self._batch_axis}, w={self._weight})"


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = _np.square(label - pred)
        loss = _apply_weighting(loss, self._weight / 2, sample_weight)
        return self._mean_nonbatch(loss)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = _np.abs(label - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def forward(self, pred, label, sample_weight=None, pos_weight=None):
        label = _reshape_like(pred, label)
        if not self._from_sigmoid:
            if pos_weight is None:
                loss = npx.relu(pred) - pred * label + \
                    npx.activation(-_np.abs(pred), "softrelu")
            else:
                log_weight = 1 + (pos_weight - 1) * label
                loss = pred - pred * label + log_weight * (
                    npx.activation(-_np.abs(pred), "softrelu") +
                    npx.relu(-pred))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(_np.log(pred + eps) * label +
                         _np.log(1 - pred + eps) * (1 - label))
            else:
                loss = -(_np.log(pred + eps) * label * pos_weight +
                         _np.log(1 - pred + eps) * (1 - label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Parity: loss.py SoftmaxCrossEntropyLoss (+ `SoftmaxOutput` op,
    `src/operator/softmax_output.cc:166`)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def forward(self, pred, label, sample_weight=None):
        if self._from_logits:
            if self._sparse_label:
                loss = -npx.pick(pred, label, axis=self._axis)
            else:
                label = _reshape_like(pred, label)
                loss = -(pred * label).sum(axis=self._axis)
        elif self._sparse_label and self._axis in (-1, pred.ndim - 1):
            # fused streaming CE: on TPU the Pallas kernel never
            # materialises the fp32 (N, V) log-probs
            # (`ops/pallas/softmax_xent.py`; ref `softmax_output.cc`)
            loss = npx.softmax_cross_entropy(pred, label)
        else:
            logp = npx.log_softmax(pred, axis=self._axis)
            if self._sparse_label:
                loss = -npx.pick(logp, label, axis=self._axis)
            else:
                label = _reshape_like(logp, label)
                loss = -(logp * label).sum(axis=self._axis)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def forward(self, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = npx.log_softmax(pred, axis=self._axis)
        loss = label * (_np.log(label + 1e-12) - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


class CTCLoss(Loss):
    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        if layout not in ("NTC", "TNC"):
            raise MXNetError(f"bad layout {layout}")
        batch_axis = label_layout.find("N")
        super().__init__(weight, batch_axis, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def forward(self, pred, label, pred_lengths=None, label_lengths=None,
                sample_weight=None):
        if self._layout == "NTC":
            pred = pred.swapaxes(0, 1)
        if self._batch_axis == 1:
            label = label.swapaxes(0, 1)
        loss = npx.ctc_loss(pred, label, pred_lengths, label_lengths,
                            use_data_lengths=pred_lengths is not None,
                            use_label_lengths=label_lengths is not None,
                            blank_label="last")
        return _apply_weighting(loss, self._weight, sample_weight)


class HuberLoss(Loss):
    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = _np.abs(label - pred)
        loss = _np.where(loss > self._rho,
                         loss - 0.5 * self._rho,
                         (0.5 / self._rho) * _np.square(loss))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = npx.relu(self._margin - pred * label)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = _np.square(npx.relu(self._margin - pred * label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = npx.relu(pred) - pred * label + \
            npx.activation(-_np.abs(pred), "softrelu")
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(pred, positive)
        negative = _reshape_like(pred, negative)
        loss = (_np.square(pred - positive) - _np.square(pred - negative))
        loss = loss.sum(axis=tuple(range(1, loss.ndim))) if loss.ndim > 1 \
            else loss
        loss = npx.relu(loss + self._margin)
        return _apply_weighting(loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def forward(self, pred, target, sample_weight=None, epsilon=1e-08):
        target = _reshape_like(pred, target)
        if self._from_logits:
            loss = _np.exp(pred) - target * pred
        else:
            loss = pred - target * _np.log(pred + epsilon)
        if self._compute_full:
            stirling = target * _np.log(target + 1e-12) - target + \
                0.5 * _np.log(2 * 3.141592653589793 * (target + 1e-12))
            stirling = _np.where(target <= 1, _np.zeros_like(stirling),
                                 stirling)
            loss = loss + stirling
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, input1, input2, label, sample_weight=None):
        def cos_sim(a, b):
            num = (a * b).sum(axis=-1)
            den = _np.sqrt(_np.square(a).sum(axis=-1)) * \
                _np.sqrt(_np.square(b).sum(axis=-1))
            return num / (den + 1e-12)
        sim = cos_sim(input1, input2)
        label = label.reshape(sim.shape)
        loss = _np.where(label == 1, 1 - sim,
                         npx.relu(sim - self._margin))
        return _apply_weighting(loss, self._weight, sample_weight)


class SDMLLoss(Loss):
    """Smoothed Deep Metric Learning loss (parity: `gluon/loss.py`
    SDMLLoss; Bonadiman et al. 2019): aligned pairs (x1[i], x2[i]) are
    positives, every other row in the minibatch is a smoothed negative.
    Per-row KL between the smoothed one-hot target and the softmax over
    negative squared euclidean distances (reference scaling)."""

    def __init__(self, smoothing_parameter=0.3, weight=1.0, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self.smoothing_parameter = smoothing_parameter
        self._target_cache = {}

    @staticmethod
    def _distances(x1, x2):
        a = _np.expand_dims(x1, 1)
        b = _np.expand_dims(x2, 0)
        return _np.square(a - b).sum(axis=2)

    def _smoothed_targets(self, n):
        # keyed by (n, smoothing) so annealing the public attribute is
        # honored instead of serving stale targets
        sp = self.smoothing_parameter
        key = (n, sp)
        if key not in self._target_cache:
            import numpy as onp
            eye = onp.eye(n)
            smooth = sp / (n - 1)
            t = eye * (1.0 - sp) + (1 - eye) * smooth
            # closed-form row entropy (all rows identical): no device sync
            ent = (1 - sp) * onp.log(max(1 - sp, 1e-12)) + \
                (n - 1) * smooth * onp.log(max(smooth, 1e-12))
            self._target_cache[key] = (_np.array(t.astype(onp.float32)),
                                       float(ent))
        return self._target_cache[key]

    def forward(self, x1, x2, sample_weight=None):
        n = x1.shape[0]
        if n < 2:
            raise MXNetError(
                "SDMLLoss needs batch size >= 2: the other rows of the "
                "minibatch are the negative examples")
        target, ent = self._smoothed_targets(n)
        # reference formulation: KL(target || softmax(-distances)) per
        # row, one direction, scaled so the per-sample magnitude matches
        # `kl_loss(log_pred, labels) * batch_size` upstream
        logp = npx.log_softmax(-self._distances(x1, x2), axis=-1)
        kl = ent - (target * logp).sum(axis=-1)
        return _apply_weighting(kl, self._weight, sample_weight)
