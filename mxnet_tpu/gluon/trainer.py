"""Gluon `Trainer` (parity: `python/mxnet/gluon/trainer.py`).

The reference's step pipeline — per-parameter `kvstore.pushpull` of gradients
then per-parameter fused optimizer kernels (`trainer.py:341,392-417,451`) —
collapses on TPU into ONE jitted pytree update per step (all parameters, all
optimizer states, donated buffers), the XLA analog of multi-tensor fused
optimizers. Data-parallel gradient averaging is GSPMD's job (psum inserted by
XLA when batch-sharded); the KVStore path is kept for API parity and for
`update_on_kvstore=True` semantics (server-side updater).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Union

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import ndarray, from_jax
from .. import optimizer as opt
from ..kvstore import KVStore
from ..ops.fused_optim import HpScalarCache, tree_apply_update
from .parameter import Parameter

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore=None,
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict,)):
            param_dict = dict(params)
        elif isinstance(params, (list, tuple)):
            param_dict = {getattr(p, "name", str(i)): p
                          for i, p in enumerate(params)}
        else:
            raise MXNetError("params must be dict or list of Parameter")
        for p in param_dict.values():
            if not isinstance(p, Parameter):
                raise MXNetError(f"expected Parameter, got {type(p)}")
        self._param_dict = param_dict
        self._params = [p for p in param_dict.values()
                        if p.grad_req != "null"]
        # keyed by the caller's dict keys (collect_params structure
        # names): unique by construction and IMMUTABLE for this
        # trainer's lifetime — p.name can be re-stamped by a later
        # collect_params on a sub-block, which must not re-key updates
        self._param_names = [k for k, p in param_dict.items()
                             if p.grad_req != "null"]

        optimizer_params = optimizer_params or {}
        self._optimizer = opt.create(optimizer, param_idx2name={
            i: n for i, n in enumerate(self._param_names)},
            **optimizer_params)
        self._states = {}
        self._kvstore = None
        self._kv_initialized = False
        self._update_on_kvstore = update_on_kvstore
        self._kvstore_arg = kvstore
        self._compression_params = compression_params
        self._scale = 1.0
        self._fused_cache = None

    # -- properties ----------------------------------------------------------
    @property
    def optimizer(self):
        return self._optimizer

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    # -- kvstore init ---------------------------------------------------------
    def _init_kvstore(self):
        if self._kv_initialized:
            return
        arg = self._kvstore_arg
        if arg is None or arg is False:
            self._kvstore = None
            self._update_on_kvstore = False
        else:
            kv = arg if isinstance(arg, KVStore) else \
                __import__("mxnet_tpu.kvstore", fromlist=["create"]).create(
                    arg if isinstance(arg, str) else "device")
            self._kvstore = kv
            if self._compression_params:
                kv.set_gradient_compression(self._compression_params)
            if self._update_on_kvstore is None:
                self._update_on_kvstore = False
            if self._update_on_kvstore:
                self._optimizer.rescale_grad = self._scale
                kv.set_optimizer(self._optimizer)
            for i, p in enumerate(self._params):
                kv.init(i, p.data())
        self._kv_initialized = True

    def _ensure_states(self):
        for n, p in zip(self._param_names, self._params):
            if n not in self._states:
                self._states[n] = \
                    self._optimizer.create_state_multi_precision(
                        n, p.data())

    # -- main API -------------------------------------------------------------
    def step(self, batch_size, ignore_stale_grad=False):
        """Grad-allreduce + optimizer update (parity: trainer.py:341).

        With AMP fp16 (`amp.init_trainer(trainer)`): gradients are checked
        for inf/nan BEFORE the update — an overflowed step is skipped
        entirely and the loss scale halves; clean steps divide the scale
        back out (reference: `amp/loss_scaler.py` + trainer patching)."""
        self._init_kvstore()
        scaler = getattr(self, "_amp_loss_scaler", None)
        divisor = 1.0
        if scaler is not None and getattr(scaler, "active", True):
            divisor = scaler.loss_scale      # the scale this loss used
            overflow = scaler.has_overflow(
                [p for p in self._params if p.grad_req != "null"])
            scaler.update_scale(overflow)
            if overflow:
                return      # skip: stale weights beat poisoned weights
        self._optimizer.rescale_grad = self._scale / batch_size / divisor
        try:
            if self._kvstore is not None and not self._update_on_kvstore:
                # with update_on_kvstore the push inside update() both
                # aggregates and applies the optimizer — pushing here too
                # would apply the update twice
                self.allreduce_grads()
            self.update(batch_size, ignore_stale_grad=ignore_stale_grad,
                        _already_reduced=True)
        finally:
            self._optimizer.rescale_grad = self._scale / batch_size

    def allreduce_grads(self):
        """Parity: trainer.py:370. Single-process: kvstore aggregation.
        All parameters go in ONE multi-key call so a dist store pays one
        host collective per step, not one per parameter."""
        self._init_kvstore()
        if self._kvstore is None:
            return
        idx, grads = [], []
        for i, p in enumerate(self._params):
            if getattr(p.grad(), "stype", "default") == "row_sparse":
                raise MXNetError(
                    f"parameter {p.name}: row_sparse gradients are only "
                    "supported with local updates (kvstore=None); the "
                    "kvstore aggregation path would densify them. Use "
                    "Trainer(..., kvstore=None) or Embedding("
                    "sparse_grad=False).")
            if p.grad_req != "null":
                idx.append(i)
                grads.append(p.grad())
        if not idx:
            return
        if self._update_on_kvstore:
            self._kvstore.push(idx, grads)
        else:
            self._kvstore.pushpull(idx, grads, out=grads)

    def update(self, batch_size, ignore_stale_grad=False,
               _already_reduced=False):
        self._init_kvstore()
        if not _already_reduced:
            self._optimizer.rescale_grad = self._scale / batch_size
        if self._update_on_kvstore and self._kvstore is not None:
            # server-side update: push grads, pull fresh weights
            for i, p in enumerate(self._params):
                if getattr(p.grad(), "stype", "default") == "row_sparse":
                    raise MXNetError(
                        f"parameter {p.name}: row_sparse gradients are not "
                        "supported with update_on_kvstore; use local "
                        "updates (kvstore=None).")
                self._kvstore.push(i, p.grad())
                self._kvstore.pull(i, out=p.data())
            return
        self._ensure_states()
        any_sparse = any(
            getattr(p.grad(), "stype", "default") == "row_sparse"
            for p in self._params)
        if getattr(self._optimizer, "fused_safe", True) and \
                not self._optimizer.multi_precision and \
                not any_sparse and \
                self._uniform_mults():
            self._fused_update()
        else:
            for n, p in zip(self._param_names, self._params):
                self._optimizer.update_multi_precision(
                    n, p.data(), p.grad(), self._states[n])

    def _uniform_mults(self):
        o = self._optimizer
        if o.lr_mult or o.wd_mult or o.param_dict:
            return False  # optimizer-level multipliers need per-param rates
        return all(p.lr_mult == 1.0 and p.wd_mult == 1.0
                   for p in self._params)

    # -- fused pytree update ---------------------------------------------------
    def _fused_update(self):
        o = self._optimizer
        o.num_update += 1
        t = o.num_update
        names = self._param_names
        for n in names:
            o._index_update_count[n] = t

        params_tree = {n: p.data()._data for n, p in zip(names, self._params)}
        grads_tree = {n: p.grad()._data for n, p in zip(names, self._params)}

        from ..optimizer.optimizer import _state_values, _state_writeback
        states_tree = {n: _state_values(self._states[n]) for n in names}

        hp = self._cached_hp(t)

        # fused multi-tensor kernel route (MXTPU_PALLAS, ops/pallas/
        # fused_optimizer): same-dtype parameter chunks, one Pallas
        # launch each; otherwise the jitted whole-tree XLA update
        from ..ops.pallas import fused_optimizer as _fopt
        if _fopt.kernel_route(o):
            new_params, new_states = _fopt.tree_update(
                o, params_tree, grads_tree, states_tree, hp)
        else:
            new_params, new_states = tree_apply_update(
                _RuleAdapter(o), params_tree, grads_tree, states_tree, hp)
        for n, p in zip(names, self._params):
            p.data()._data = new_params[n]
            _state_writeback(self._states[n], new_states[n])

    _hp_cache = None

    def _cached_hp(self, t):
        """Device-resident hyperparameter scalars for the fused update,
        re-uploaded only when the host values change (async-pipeline
        satellite: lr/wd/rescale/clip are constant across steps, so the
        steady-state step enqueues one `t` upload instead of four).
        Shares `HpScalarCache` with `ShardedTrainStep._hp`."""
        if self._hp_cache is None:
            self._hp_cache = HpScalarCache()
        hp = self._hp_cache.get(self._optimizer)
        hp["t"] = jnp.asarray(t, jnp.float32)
        return hp

    # -- checkpointing ---------------------------------------------------------
    def save_states(self, fname):
        """Parity: trainer.py:510."""
        from ..optimizer.updater import Updater
        u = Updater(self._optimizer)
        u.states = dict(self._states)
        with open(fname, "wb") as f:
            f.write(u.get_states(dump_optimizer=True))

    def load_states(self, fname):
        """Parity: trainer.py:537."""
        from ..optimizer.updater import Updater
        self._init_kvstore()
        u = Updater(self._optimizer)
        with open(fname, "rb") as f:
            u.set_states(f.read())
        self._states = dict(u.states)
        if u.optimizer is not self._optimizer:
            # adopt the saved optimizer's step counters (num_update drives
            # lr schedules and bias correction)
            self._optimizer.num_update = u.optimizer.num_update
            self._optimizer._index_update_count = \
                dict(u.optimizer._index_update_count)


class _RuleAdapter:
    """Hashable wrapper so jit caches on the optimizer identity + class."""

    def __init__(self, optimizer):
        self.optimizer = optimizer

    def __call__(self, p, g, s, hp):
        return self.optimizer._rule(p, g, s, hp)

    def __hash__(self):
        return hash((type(self.optimizer), id(self.optimizer)))

    def __eq__(self, other):
        return isinstance(other, _RuleAdapter) and \
            other.optimizer is self.optimizer
