"""Uniform distribution (parity:
`python/mxnet/gluon/probability/distributions/uniform.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....random import next_key
from . import constraint
from .distribution import Distribution
from .utils import _j, _w, sample_n_shape_converter

__all__ = ["Uniform"]


class Uniform(Distribution):
    has_grad = True
    arg_constraints = {"low": constraint.dependent,
                       "high": constraint.dependent}

    def __init__(self, low=0.0, high=1.0, validate_args=None):
        self.low = _j(low)
        self.high = _j(high)
        super().__init__(event_dim=0, validate_args=validate_args)

    @property
    def support(self):
        return constraint.Interval(self.low, self.high)

    @property
    def _batch(self):
        return jnp.broadcast_shapes(jnp.shape(self.low), jnp.shape(self.high))

    def sample(self, size=None):
        shape = sample_n_shape_converter(size) + self._batch
        dtype = jnp.result_type(self.low, self.high, jnp.float32)
        u = jax.random.uniform(next_key(), shape, dtype)
        return _w(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = self._validate_sample(_j(value))
        inside = (v >= self.low) & (v <= self.high)
        return _w(jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf))

    def cdf(self, value):
        v = _j(value)
        return _w(jnp.clip((v - self.low) / (self.high - self.low), 0.0, 1.0))

    def icdf(self, value):
        return _w(self.low + (self.high - self.low) * _j(value))

    def _mean(self):
        return jnp.broadcast_to((self.low + self.high) / 2, self._batch)

    def _variance(self):
        return jnp.broadcast_to((self.high - self.low) ** 2 / 12, self._batch)

    def entropy(self):
        return _w(jnp.broadcast_to(jnp.log(self.high - self.low), self._batch))

    def broadcast_to(self, batch_shape):
        new = Uniform.__new__(Uniform)
        new.low = jnp.broadcast_to(self.low, batch_shape)
        new.high = jnp.broadcast_to(self.high, batch_shape)
        Distribution.__init__(new, event_dim=0)
        return new
