"""Bernoulli distribution (parity:
`python/mxnet/gluon/probability/distributions/bernoulli.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ....base import MXNetError
from ....random import next_key
from . import constraint
from .exp_family import ExponentialFamily
from .utils import (_j, _w, cached_property, logit2prob, prob2logit,
                    sample_n_shape_converter)

__all__ = ["Bernoulli"]


class Bernoulli(ExponentialFamily):
    has_enumerate_support = True
    arg_constraints = {"prob": constraint.unit_interval,
                       "logit": constraint.real}
    support = constraint.boolean

    def __init__(self, prob=None, logit=None, validate_args=None):
        if (prob is None) == (logit is None):
            raise MXNetError("Exactly one of `prob`, `logit` is required")
        self._prob = _j(prob)
        self._logit = _j(logit)
        super().__init__(event_dim=0, validate_args=validate_args)

    @cached_property
    def prob(self):
        return self._prob if self._prob is not None \
            else logit2prob(self._logit, True)

    @cached_property
    def logit(self):
        return self._logit if self._logit is not None \
            else prob2logit(self._prob, True)

    @property
    def _batch(self):
        p = self._prob if self._prob is not None else self._logit
        return jnp.shape(p)

    def sample(self, size=None):
        shape = sample_n_shape_converter(size) + self._batch
        p = jnp.broadcast_to(self.prob, shape)
        return _w(jax.random.bernoulli(next_key(), p, shape)
                  .astype(jnp.float32))

    def log_prob(self, value):
        v = self._validate_sample(_j(value))
        lg = self.logit
        # log p = v*logit - softplus(logit)
        return _w(v * lg - jnp.logaddexp(0.0, lg))

    def _mean(self):
        return jnp.broadcast_to(self.prob, self._batch)

    def _variance(self):
        return jnp.broadcast_to(self.prob * (1 - self.prob), self._batch)

    def entropy(self):
        lg = self.logit
        p = lax.logistic(lg)
        return _w(jnp.broadcast_to(
            jnp.logaddexp(0.0, lg) - p * lg, self._batch))

    def enumerate_support(self):
        vals = jnp.reshape(jnp.arange(2, dtype=jnp.float32),
                           (2,) + (1,) * len(self._batch))
        return _w(jnp.broadcast_to(vals, (2,) + self._batch))

    def broadcast_to(self, batch_shape):
        if self._logit is not None:
            return Bernoulli(logit=jnp.broadcast_to(self._logit, batch_shape))
        return Bernoulli(prob=jnp.broadcast_to(self._prob, batch_shape))

    _mean_carrier_measure = 0

    @property
    def _natural_params(self):
        return (self.logit,)

    def _log_normalizer(self, x):
        return jnp.logaddexp(0.0, x)
