"""Relaxed Bernoulli / binary Concrete distribution (parity:
`python/mxnet/gluon/probability/distributions/relaxed_bernoulli.py`).

Gumbel-sigmoid relaxation: fully reparameterized, so gradients flow through
samples — the discrete Bernoulli made trainable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ....base import MXNetError
from ....random import next_key
from . import constraint
from .distribution import Distribution
from .utils import (_j, _w, cached_property, logit2prob, prob2logit,
                    sample_n_shape_converter)

__all__ = ["RelaxedBernoulli"]


class RelaxedBernoulli(Distribution):
    has_grad = True
    arg_constraints = {"prob": constraint.unit_interval,
                       "logit": constraint.real}
    support = constraint.unit_interval

    def __init__(self, T=1.0, prob=None, logit=None, validate_args=None):
        if (prob is None) == (logit is None):
            raise MXNetError("Exactly one of `prob`, `logit` is required")
        self.T = _j(T)
        self._prob = _j(prob)
        self._logit = _j(logit)
        super().__init__(event_dim=0, validate_args=validate_args)

    @cached_property
    def prob(self):
        return self._prob if self._prob is not None \
            else logit2prob(self._logit, True)

    @cached_property
    def logit(self):
        return self._logit if self._logit is not None \
            else prob2logit(self._prob, True)

    @property
    def _batch(self):
        p = self._prob if self._prob is not None else self._logit
        return jnp.shape(p)

    def sample(self, size=None):
        shape = sample_n_shape_converter(size) + self._batch
        u = jax.random.uniform(
            next_key(), shape, jnp.float32,
            minval=jnp.finfo(jnp.float32).tiny)
        logistic = jnp.log(u) - jnp.log1p(-u)
        return _w(lax.logistic((self.logit + logistic) / self.T))

    def log_prob(self, value):
        v = self._validate_sample(_j(value))
        lg, T = self.logit, self.T
        diff = lg - T * (jnp.log(v) - jnp.log1p(-v))
        return _w(jnp.log(T) + diff - 2 * jnp.logaddexp(0.0, diff)
                  - jnp.log(v) - jnp.log1p(-v))
