"""Shared helpers for distributions (parity:
`python/mxnet/gluon/probability/distributions/utils.py`)."""
from __future__ import annotations

import functools

import jax.numpy as jnp
from jax import lax
from jax.scipy import special as jsp

from ....ndarray.ndarray import as_jax, from_jax, ndarray

__all__ = ["prob2logit", "logit2prob", "sum_right_most", "cached_property",
           "sample_n_shape_converter", "gammaln", "digamma", "erf", "erfinv"]

gammaln = jsp.gammaln
digamma = jsp.digamma
erf = jsp.erf
erfinv = jsp.erfinv


def _j(x):
    """Coerce distribution parameters/values to a jax array (or tracer)."""
    if x is None:
        return None
    x = as_jax(x)
    if isinstance(x, (int, float, bool, list, tuple)):
        x = jnp.asarray(x)
    return x


def _w(x):
    """Wrap a jax array back into the framework ndarray."""
    if isinstance(x, ndarray):
        return x
    return from_jax(jnp.asarray(x))


def prob2logit(prob, binary=True):
    """Convert probability to logit (log-odds for binary, log-prob otherwise)."""
    p = _j(prob)
    eps = jnp.finfo(jnp.result_type(p, jnp.float32)).tiny
    p = jnp.clip(p, eps, 1.0 - eps if binary else 1.0)
    if binary:
        return jnp.log(p) - jnp.log1p(-p)
    return jnp.log(p)


def logit2prob(logit, binary=True):
    lg = _j(logit)
    if binary:
        return lax.logistic(lg)
    return jnp.exp(lg - jsp.logsumexp(lg, axis=-1, keepdims=True))


def sum_right_most(x, ndim):
    """Sum over the rightmost `ndim` axes (event-dim reduction)."""
    if ndim == 0:
        return x
    return jnp.sum(x, axis=tuple(range(-ndim, 0)))


def sample_n_shape_converter(size):
    """Normalise a `size` argument into a tuple prefix shape."""
    if size is None:
        return ()
    if isinstance(size, int):
        return (size,)
    return tuple(size)


cached_property = functools.cached_property


# ---------------------------------------------------------------------------
# eager-autograd bridge
#
# Distribution internals compute in RAW jax (`_j` unwraps) — correct and
# fast under jit tracing (functional_call / ShardedTrainStep hand tracers
# straight through), but invisible to the EAGER tape: a Parameter passed
# as `loc` would get no gradient from log_prob/sample.  The reference's
# distributions are eagerly trainable (its ops all route the recorder),
# so this bridge closes the gap at ONE choke point: constructors capture
# their ORIGINAL (possibly tape-active) ndarray arguments, and wrapped
# methods rebuild the distribution from raw leaves INSIDE `apply_op`,
# making each call a single recorded differentiable op.
# ---------------------------------------------------------------------------

_EAGER_METHODS = ("log_prob", "prob", "sample", "sample_n", "cdf", "icdf",
                  "entropy")


def _capture_init(cls):
    orig_init = cls.__dict__["__init__"]

    @functools.wraps(orig_init)
    def wrapped_init(self, *a, **k):
        # outermost constructor wins: super().__init__ chains must not
        # overwrite the user-visible argument list
        if not hasattr(self, "_eager_args"):
            self._eager_args = (a, k)
        orig_init(self, *a, **k)

    cls.__init__ = wrapped_init


def _substitute(template, it):
    """Replace each ndarray in (args, kwargs) by the next raw leaf."""
    a, k = template
    sub_a = [next(it) if isinstance(v, ndarray) else v for v in a]
    sub_k = {key: (next(it) if isinstance(v, ndarray) else v)
             for key, v in k.items()}
    return sub_a, sub_k


def _leaves(template):
    a, k = template
    return [v for v in list(a) + list(k.values()) if isinstance(v, ndarray)]


def _raw(x):
    if isinstance(x, ndarray):
        return x._data
    if isinstance(x, (tuple, list)):
        return tuple(_raw(v) for v in x)
    return x


def _wrap_method(cls, mname):
    orig = cls.__dict__[mname]

    @functools.wraps(orig)
    def wrapped(self, *m_args, **m_kw):
        from .... import _tape
        init_t = getattr(self, "_eager_args", ((), {}))
        leaves = _leaves(init_t) + _leaves((m_args, m_kw))
        if not _tape.is_recording() or not leaves:
            return orig(self, *m_args, **m_kw)
        from ....ndarray.ndarray import apply_op

        def fn(*raw):
            it = iter(raw)
            sub_ia, sub_ik = _substitute(init_t, it)
            sub_ma, sub_mk = _substitute((m_args, m_kw), it)
            fresh = type(self)(*sub_ia, **sub_ik)
            return _raw(orig(fresh, *sub_ma, **sub_mk))

        return apply_op(fn, tuple(leaves), {},
                        name=f"{cls.__name__}.{mname}")

    setattr(cls, mname, wrapped)


def make_eager_differentiable(cls):
    """Apply the eager-autograd bridge to a Distribution class: wraps its
    own __init__ (argument capture) and its OWN public methods.  Only
    top-level ndarray arguments participate; nested containers and
    distribution-valued arguments (TransformedDistribution etc.) stay on
    the raw path — use the traced/jit route for those."""
    if "__init__" in cls.__dict__ and \
            not getattr(cls.__dict__["__init__"], "_eager_wrapped", False):
        _capture_init(cls)
        cls.__dict__["__init__"]._eager_wrapped = True
    for m in _EAGER_METHODS:
        fn = cls.__dict__.get(m)
        if callable(fn) and not getattr(fn, "_eager_wrapped", False):
            _wrap_method(cls, m)
            cls.__dict__[m]._eager_wrapped = True
    return cls
