"""Shared helpers for distributions (parity:
`python/mxnet/gluon/probability/distributions/utils.py`)."""
from __future__ import annotations

import functools

import jax.numpy as jnp
from jax import lax
from jax.scipy import special as jsp

from ....ndarray.ndarray import as_jax, from_jax, ndarray

__all__ = ["prob2logit", "logit2prob", "sum_right_most", "cached_property",
           "sample_n_shape_converter", "gammaln", "digamma", "erf", "erfinv"]

gammaln = jsp.gammaln
digamma = jsp.digamma
erf = jsp.erf
erfinv = jsp.erfinv


def _j(x):
    """Coerce distribution parameters/values to a jax array (or tracer)."""
    if x is None:
        return None
    x = as_jax(x)
    if isinstance(x, (int, float, bool, list, tuple)):
        x = jnp.asarray(x)
    return x


def _w(x):
    """Wrap a jax array back into the framework ndarray."""
    if isinstance(x, ndarray):
        return x
    return from_jax(jnp.asarray(x))


def prob2logit(prob, binary=True):
    """Convert probability to logit (log-odds for binary, log-prob otherwise)."""
    p = _j(prob)
    eps = jnp.finfo(jnp.result_type(p, jnp.float32)).tiny
    p = jnp.clip(p, eps, 1.0 - eps if binary else 1.0)
    if binary:
        return jnp.log(p) - jnp.log1p(-p)
    return jnp.log(p)


def logit2prob(logit, binary=True):
    lg = _j(logit)
    if binary:
        return lax.logistic(lg)
    return jnp.exp(lg - jsp.logsumexp(lg, axis=-1, keepdims=True))


def sum_right_most(x, ndim):
    """Sum over the rightmost `ndim` axes (event-dim reduction)."""
    if ndim == 0:
        return x
    return jnp.sum(x, axis=tuple(range(-ndim, 0)))


def sample_n_shape_converter(size):
    """Normalise a `size` argument into a tuple prefix shape."""
    if size is None:
        return ()
    if isinstance(size, int):
        return (size,)
    return tuple(size)


cached_property = functools.cached_property
