"""Weibull distribution (parity:
`python/mxnet/gluon/probability/distributions/weibull.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....random import next_key
from . import constraint
from .distribution import Distribution
from .utils import _j, _w, gammaln, sample_n_shape_converter

__all__ = ["Weibull"]

_EULER = 0.5772156649015329


class Weibull(Distribution):
    has_grad = True
    arg_constraints = {"concentration": constraint.positive,
                       "scale": constraint.positive}
    support = constraint.positive

    def __init__(self, concentration, scale=1.0, validate_args=None):
        self.concentration = _j(concentration)
        self.scale = _j(scale)
        super().__init__(event_dim=0, validate_args=validate_args)

    @property
    def _batch(self):
        return jnp.broadcast_shapes(jnp.shape(self.concentration),
                                    jnp.shape(self.scale))

    def sample(self, size=None):
        shape = sample_n_shape_converter(size) + self._batch
        dtype = jnp.result_type(self.concentration, self.scale, jnp.float32)
        e = jax.random.exponential(next_key(), shape, dtype)
        return _w(self.scale * e ** (1.0 / self.concentration))

    def log_prob(self, value):
        v = self._validate_sample(_j(value))
        k, lam = self.concentration, self.scale
        z = v / lam
        return _w(jnp.log(k / lam) + (k - 1) * jnp.log(z) - z ** k)

    def cdf(self, value):
        z = _j(value) / self.scale
        return _w(-jnp.expm1(-z ** self.concentration))

    def icdf(self, value):
        p = _j(value)
        return _w(self.scale *
                  (-jnp.log1p(-p)) ** (1.0 / self.concentration))

    def _mean(self):
        k = self.concentration
        return jnp.broadcast_to(
            self.scale * jnp.exp(gammaln(1 + 1.0 / k)), self._batch)

    def _variance(self):
        k = self.concentration
        m1 = jnp.exp(gammaln(1 + 1.0 / k))
        m2 = jnp.exp(gammaln(1 + 2.0 / k))
        return jnp.broadcast_to(self.scale ** 2 * (m2 - m1 ** 2), self._batch)

    def entropy(self):
        k = self.concentration
        return _w(jnp.broadcast_to(
            _EULER * (1 - 1.0 / k) + jnp.log(self.scale / k) + 1, self._batch))
