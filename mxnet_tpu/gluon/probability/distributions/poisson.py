"""Poisson distribution (parity:
`python/mxnet/gluon/probability/distributions/poisson.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import xlogy

from ....random import next_key
from . import constraint
from .exp_family import ExponentialFamily
from .utils import _j, _w, gammaln, sample_n_shape_converter

__all__ = ["Poisson"]


class Poisson(ExponentialFamily):
    arg_constraints = {"rate": constraint.positive}
    support = constraint.nonnegative_integer

    def __init__(self, rate=1.0, validate_args=None):
        self.rate = _j(rate)
        super().__init__(event_dim=0, validate_args=validate_args)

    @property
    def _batch(self):
        return jnp.shape(self.rate)

    def sample(self, size=None):
        shape = sample_n_shape_converter(size) + self._batch
        lam = jnp.broadcast_to(self.rate, shape).astype(jnp.float32)
        return _w(jax.random.poisson(next_key(), lam, shape)
                  .astype(jnp.float32))

    def log_prob(self, value):
        v = self._validate_sample(_j(value))
        return _w(xlogy(v, self.rate) - self.rate - gammaln(v + 1))

    def _mean(self):
        return jnp.broadcast_to(self.rate, self._batch)

    def _variance(self):
        return jnp.broadcast_to(self.rate, self._batch)

    def entropy(self):
        # H = λ(1 - log λ) + e^{-λ} Σ_k λ^k log(k!) / k!, truncated series
        # (accurate to float32 for the practical λ range; no closed form)
        lam = self.rate
        k = jnp.arange(1.0, 64.0)
        shape = (1,) * len(self._batch) + (-1,)
        k = jnp.reshape(k, shape)
        lam_b = jnp.asarray(lam)[..., None]
        terms = jnp.exp(k * jnp.log(lam_b) - gammaln(k + 1) - lam_b) \
            * gammaln(k + 1)
        return _w(lam * (1 - jnp.log(lam)) + terms.sum(-1))

    def broadcast_to(self, batch_shape):
        return Poisson(jnp.broadcast_to(self.rate, batch_shape))

    @property
    def _natural_params(self):
        return (jnp.log(self.rate),)

    def _log_normalizer(self, x):
        return jnp.exp(x)
