"""TransformedDistribution (parity:
`python/mxnet/gluon/probability/distributions/transformed_distribution.py`)."""
from __future__ import annotations

import jax.numpy as jnp

from .distribution import Distribution
from ..transformation.transformation import (ComposeTransformation,
                                             Transformation)
from .utils import _j, _w, sum_right_most

__all__ = ["TransformedDistribution"]


class TransformedDistribution(Distribution):
    r"""Distribution of y = f_k(...f_1(x)) for x ~ base_dist.

    log p(y) = log p_base(x) - Σ log|det J_{f_i}|, computed by walking the
    transform chain backwards — a pure jnp computation, so the density of
    arbitrarily transformed distributions remains jit- and grad-compatible.
    """

    def __init__(self, base_dist, transforms, validate_args=None):
        self._base_dist = base_dist
        if isinstance(transforms, Transformation):
            transforms = [transforms]
        self._transforms = list(transforms)
        event_dim = max(
            [base_dist.event_dim] + [t.event_dim for t in self._transforms])
        super().__init__(event_dim=event_dim, validate_args=validate_args)

    @property
    def has_grad(self):
        return self._base_dist.has_grad

    def sample(self, size=None):
        x = self._base_dist.sample(size)
        for t in self._transforms:
            x = t(x)
        return x

    def sample_n(self, n=None):
        x = self._base_dist.sample_n(n)
        for t in self._transforms:
            x = t(x)
        return x

    def log_prob(self, value):
        y = _j(value)
        lp = 0.0
        # walk the chain backwards accumulating inverse-jacobian terms
        for t in reversed(self._transforms):
            x = t._inverse_compute(y)
            ldj = t._log_det_jacobian(x, y)
            lp = lp - sum_right_most(ldj, self.event_dim - t.event_dim)
            y = x
        base_lp = _j(self._base_dist.log_prob(_w(y)))
        lp = lp + sum_right_most(base_lp,
                                 self.event_dim - self._base_dist.event_dim)
        return _w(lp)

    def cdf(self, value):
        y = _j(value)
        sign = 1
        for t in reversed(self._transforms):
            sign = sign * t.sign
            y = t._inverse_compute(y)
        base_cdf = _j(self._base_dist.cdf(_w(y)))
        return _w(jnp.where(jnp.asarray(sign) >= 0, base_cdf, 1 - base_cdf))

    def icdf(self, value):
        p = _j(value)
        sign = 1
        for t in self._transforms:
            sign = sign * t.sign
        p = jnp.where(jnp.asarray(sign) >= 0, p, 1 - p)
        x = _j(self._base_dist.icdf(_w(p)))
        for t in self._transforms:
            x = t._forward_compute(x)
        return _w(x)
