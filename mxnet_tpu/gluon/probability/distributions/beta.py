"""Beta distribution (parity:
`python/mxnet/gluon/probability/distributions/beta.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import betaln

from ....random import next_key
from . import constraint
from .exp_family import ExponentialFamily
from .utils import _j, _w, digamma, sample_n_shape_converter

__all__ = ["Beta"]


class Beta(ExponentialFamily):
    has_grad = True
    arg_constraints = {"alpha": constraint.positive,
                       "beta": constraint.positive}
    support = constraint.unit_interval

    def __init__(self, alpha, beta, validate_args=None):
        self.alpha = _j(alpha)
        self.beta = _j(beta)
        super().__init__(event_dim=0, validate_args=validate_args)

    @property
    def _batch(self):
        return jnp.broadcast_shapes(jnp.shape(self.alpha),
                                    jnp.shape(self.beta))

    def sample(self, size=None):
        shape = sample_n_shape_converter(size) + self._batch
        dtype = jnp.result_type(self.alpha, self.beta, jnp.float32)
        a = jnp.broadcast_to(self.alpha, shape).astype(dtype)
        b = jnp.broadcast_to(self.beta, shape).astype(dtype)
        return _w(jax.random.beta(next_key(), a, b))

    def log_prob(self, value):
        v = self._validate_sample(_j(value))
        a, b = self.alpha, self.beta
        return _w((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
                  - betaln(a, b))

    def _mean(self):
        return jnp.broadcast_to(
            self.alpha / (self.alpha + self.beta), self._batch)

    def _variance(self):
        a, b = self.alpha, self.beta
        tot = a + b
        return jnp.broadcast_to(a * b / (tot ** 2 * (tot + 1)), self._batch)

    def entropy(self):
        a, b = self.alpha, self.beta
        return _w(jnp.broadcast_to(
            betaln(a, b) - (a - 1) * digamma(a) - (b - 1) * digamma(b)
            + (a + b - 2) * digamma(a + b), self._batch))
