"""Multinomial distribution (parity:
`python/mxnet/gluon/probability/distributions/multinomial.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import xlogy

from ....random import next_key
from . import constraint
from .categorical import Categorical
from .distribution import Distribution
from .utils import _j, _w, gammaln, sample_n_shape_converter

__all__ = ["Multinomial"]


class Multinomial(Distribution):
    arg_constraints = {"prob": constraint.simplex, "logit": constraint.real}

    def __init__(self, num_events=None, prob=None, logit=None, total_count=1,
                 validate_args=None):
        self._categorical = Categorical(num_events, prob=prob, logit=logit)
        self.num_events = self._categorical.num_events
        self.total_count = int(total_count)
        super().__init__(event_dim=1, validate_args=validate_args)

    @property
    def prob(self):
        return self._categorical.prob

    @property
    def logit(self):
        return self._categorical.logit

    @property
    def _batch(self):
        return self._categorical._batch

    def sample(self, size=None):
        prefix = sample_n_shape_converter(size)
        shape = prefix + self._batch
        lg = jnp.broadcast_to(self._categorical.logit,
                              shape + (self.num_events,))
        # draw total_count categoricals at once, then histogram via one-hot sum
        idx = jax.random.categorical(
            next_key(), lg[..., None, :],
            axis=-1, shape=shape + (self.total_count,))
        onehot = jax.nn.one_hot(idx, self.num_events, dtype=jnp.float32)
        return _w(onehot.sum(-2))

    def log_prob(self, value):
        v = _j(value)
        n = v.sum(-1)
        log_coef = gammaln(n + 1) - jnp.sum(gammaln(v + 1), -1)
        return _w(log_coef + jnp.sum(xlogy(v, self.prob), -1))

    def _mean(self):
        return jnp.broadcast_to(self.total_count * self.prob,
                                self._batch + (self.num_events,))

    def _variance(self):
        p = self.prob
        return jnp.broadcast_to(self.total_count * p * (1 - p),
                                self._batch + (self.num_events,))
