"""Multivariate normal distribution (parity:
`python/mxnet/gluon/probability/distributions/multivariate_normal.py`).

Accepts exactly one of `cov`, `precision`, `scale_tril`; densities are
computed from the Cholesky factor (triangular solves — MXU-friendly, no
explicit inverse).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ....base import MXNetError
from ....random import next_key
from . import constraint
from .distribution import Distribution
from .utils import _j, _w, cached_property, sample_n_shape_converter

__all__ = ["MultivariateNormal"]


class MultivariateNormal(Distribution):
    has_grad = True
    arg_constraints = {"loc": constraint.real_vector,
                       "cov": constraint.positive_definite,
                       "precision": constraint.positive_definite,
                       "scale_tril": constraint.lower_cholesky}
    support = constraint.real_vector

    def __init__(self, loc, cov=None, precision=None, scale_tril=None,
                 validate_args=None):
        if sum(v is not None for v in (cov, precision, scale_tril)) != 1:
            raise MXNetError(
                "Exactly one of `cov`, `precision`, `scale_tril` is required")
        self.loc = _j(loc)
        self.cov = _j(cov)
        self.precision = _j(precision)
        self.scale_tril = _j(scale_tril)
        super().__init__(event_dim=1, validate_args=validate_args)

    @cached_property
    def _L(self):
        """Lower Cholesky factor of the covariance."""
        if self.scale_tril is not None:
            return self.scale_tril
        if self.cov is not None:
            return jnp.linalg.cholesky(self.cov)
        prec_chol = jnp.linalg.cholesky(self.precision)
        ident = jnp.eye(prec_chol.shape[-1], dtype=prec_chol.dtype)
        # cov = P^-1 = (L_p L_p^T)^-1; chol(cov) = L_p^-T (up to triangularity)
        inv = jax.scipy.linalg.solve_triangular(prec_chol, ident, lower=True)
        return jnp.linalg.cholesky(jnp.swapaxes(inv, -1, -2) @ inv)

    @property
    def _batch(self):
        return jnp.broadcast_shapes(jnp.shape(self.loc)[:-1],
                                    jnp.shape(self._L)[:-2])

    @property
    def _event(self):
        return jnp.shape(self.loc)[-1:]

    def sample(self, size=None):
        shape = sample_n_shape_converter(size) + self._batch + self._event
        dtype = jnp.result_type(self.loc, jnp.float32)
        eps = jax.random.normal(next_key(), shape, dtype)
        return _w(self.loc + jnp.einsum("...ij,...j->...i", self._L, eps))

    def log_prob(self, value):
        v = _j(value)
        diff = v - self.loc
        L = self._L
        # solve L z = diff; maha = |z|^2
        z = jax.scipy.linalg.solve_triangular(
            L, diff[..., None], lower=True)[..., 0]
        maha = jnp.sum(z ** 2, -1)
        half_log_det = jnp.sum(
            jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)), -1)
        k = v.shape[-1]
        return _w(-0.5 * (maha + k * math.log(2 * math.pi)) - half_log_det)

    def _mean(self):
        return jnp.broadcast_to(self.loc, self._batch + self._event)

    def _variance(self):
        L = self._L
        var = jnp.sum(L ** 2, -1)
        return jnp.broadcast_to(var, self._batch + self._event)

    def entropy(self):
        k = self._event[0]
        half_log_det = jnp.sum(
            jnp.log(jnp.diagonal(self._L, axis1=-2, axis2=-1)), -1)
        return _w(jnp.broadcast_to(
            0.5 * k * (1 + math.log(2 * math.pi)) + half_log_det,
            self._batch))
