"""Cauchy distribution (parity:
`python/mxnet/gluon/probability/distributions/cauchy.py`)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ....random import next_key
from . import constraint
from .distribution import Distribution
from .utils import _j, _w, sample_n_shape_converter

__all__ = ["Cauchy"]


class Cauchy(Distribution):
    has_grad = True
    arg_constraints = {"loc": constraint.real, "scale": constraint.positive}
    support = constraint.real

    def __init__(self, loc=0.0, scale=1.0, validate_args=None):
        self.loc = _j(loc)
        self.scale = _j(scale)
        super().__init__(event_dim=0, validate_args=validate_args)

    @property
    def _batch(self):
        return jnp.broadcast_shapes(jnp.shape(self.loc), jnp.shape(self.scale))

    def sample(self, size=None):
        shape = sample_n_shape_converter(size) + self._batch
        dtype = jnp.result_type(self.loc, self.scale, jnp.float32)
        eps = jax.random.cauchy(next_key(), shape, dtype)
        return _w(self.loc + self.scale * eps)

    def log_prob(self, value):
        v = self._validate_sample(_j(value))
        z = (v - self.loc) / self.scale
        return _w(-math.log(math.pi) - jnp.log(self.scale) - jnp.log1p(z ** 2))

    def cdf(self, value):
        v = _j(value)
        return _w(jnp.arctan((v - self.loc) / self.scale) / math.pi + 0.5)

    def icdf(self, value):
        p = _j(value)
        return _w(self.loc + self.scale * jnp.tan(math.pi * (p - 0.5)))

    def _mean(self):
        return jnp.full(self._batch, jnp.nan)

    def _variance(self):
        return jnp.full(self._batch, jnp.nan)

    def entropy(self):
        return _w(jnp.broadcast_to(
            math.log(4 * math.pi) + jnp.log(self.scale), self._batch))

    def broadcast_to(self, batch_shape):
        new = Cauchy.__new__(Cauchy)
        new.loc = jnp.broadcast_to(self.loc, batch_shape)
        new.scale = jnp.broadcast_to(self.scale, batch_shape)
        Distribution.__init__(new, event_dim=0)
        return new
