"""One-hot categorical distribution (parity:
`python/mxnet/gluon/probability/distributions/one_hot_categorical.py`)."""
from __future__ import annotations

import jax.numpy as jnp

from . import constraint
from .categorical import Categorical
from .distribution import Distribution
from .utils import _j, _w

__all__ = ["OneHotCategorical"]


class OneHotCategorical(Distribution):
    has_enumerate_support = True
    arg_constraints = {"prob": constraint.simplex, "logit": constraint.real}
    support = constraint.simplex

    def __init__(self, num_events=None, prob=None, logit=None,
                 validate_args=None):
        self._categorical = Categorical(num_events, prob=prob, logit=logit)
        self.num_events = self._categorical.num_events
        super().__init__(event_dim=1, validate_args=validate_args)

    @property
    def prob(self):
        return self._categorical.prob

    @property
    def logit(self):
        return self._categorical.logit

    @property
    def _batch(self):
        return self._categorical._batch

    def sample(self, size=None):
        idx = _j(self._categorical.sample(size)).astype(jnp.int32)
        return _w(jnp.eye(self.num_events, dtype=jnp.float32)[idx])

    def log_prob(self, value):
        v = _j(value)
        lg = self._categorical.logit
        return _w(jnp.sum(v * lg, -1))

    def _mean(self):
        return jnp.broadcast_to(self.prob,
                                self._batch + (self.num_events,))

    def _variance(self):
        p = self.prob
        return jnp.broadcast_to(p * (1 - p),
                                self._batch + (self.num_events,))

    def entropy(self):
        return self._categorical.entropy()

    def enumerate_support(self):
        n = self.num_events
        eye = jnp.eye(n, dtype=jnp.float32)
        eye = jnp.reshape(eye, (n,) + (1,) * len(self._batch) + (n,))
        return _w(jnp.broadcast_to(eye, (n,) + self._batch + (n,)))
