"""Base `Distribution` class (parity:
`python/mxnet/gluon/probability/distributions/distribution.py`).

Design: parameters are stored as jax arrays; every density method is a pure
jnp computation so distributions compose with `jax.jit`/`vmap`/`grad`.
Sampling threads PRNG keys from `mxnet_tpu.random.next_key()` which keeps the
stateful `mx.random.seed` reproducibility contract of the reference. Public
methods accept and return framework ndarrays.
"""
from __future__ import annotations

import jax.numpy as jnp

from ....base import MXNetError
from .utils import _j, _w, sample_n_shape_converter
from . import constraint as _c

__all__ = ["Distribution"]


class Distribution:
    """Base class for probability distributions.

    Attributes
    ----------
    has_grad : bool
        Whether `sample` is reparameterized (gradients flow to parameters).
    has_enumerate_support : bool
        Whether `enumerate_support` is implemented.
    event_dim : int
        Number of rightmost dimensions that form one event.
    arg_constraints : dict
        Map of parameter name -> Constraint.
    """

    has_grad = False
    has_enumerate_support = False
    arg_constraints: dict = {}
    _validate_args = False

    def __init__(self, event_dim=0, validate_args=None):
        self.event_dim = event_dim
        if validate_args is not None:
            self._validate_args = validate_args
        if self._validate_args:
            for name, constr in self.arg_constraints.items():
                if isinstance(constr, _c._Dependent):
                    continue
                val = getattr(self, name, None)
                if val is not None:
                    constr.validate(val, name)

    @staticmethod
    def set_default_validate_args(value: bool):
        Distribution._validate_args = bool(value)

    # -- support / validation ------------------------------------------------
    @property
    def support(self):
        raise NotImplementedError

    def _validate_sample(self, value):
        if self._validate_args:
            self.support.validate(value, "sample value")
        return value

    # -- core API ------------------------------------------------------------
    def sample(self, size=None):
        """Draw a (detached-by-default-in-reference, differentiable here if
        `has_grad`) sample of shape `size + batch_shape + event_shape`."""
        raise NotImplementedError

    def sample_n(self, n=None):
        """Draw `n` i.i.d. samples stacked along a new leading axis."""
        size = sample_n_shape_converter(n)
        return self.sample(size)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return _w(jnp.exp(_j(self.log_prob(value))))

    def cdf(self, value):
        raise NotImplementedError

    def icdf(self, value):
        raise NotImplementedError

    @property
    def mean(self):
        return _w(self._mean())

    @property
    def variance(self):
        return _w(self._variance())

    @property
    def stddev(self):
        return _w(jnp.sqrt(self._variance()))

    def _mean(self):
        raise NotImplementedError

    def _variance(self):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def perplexity(self):
        return _w(jnp.exp(_j(self.entropy())))

    def enumerate_support(self):
        raise MXNetError(
            f"{type(self).__name__} does not implement enumerate_support")

    def broadcast_to(self, batch_shape):
        """Return a copy with parameters broadcast to `batch_shape`."""
        raise NotImplementedError

    def __repr__(self):
        names = list(self.arg_constraints)
        args = ", ".join(
            f"{n}={getattr(self, n, None)!r}" for n in names
            if getattr(self, n, None) is not None)
        return f"{type(self).__name__}({args})"
