"""Exponential distribution (parity:
`python/mxnet/gluon/probability/distributions/exponential.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....random import next_key
from . import constraint
from .exp_family import ExponentialFamily
from .utils import _j, _w, sample_n_shape_converter

__all__ = ["Exponential"]


class Exponential(ExponentialFamily):
    has_grad = True
    arg_constraints = {"scale": constraint.positive}
    support = constraint.nonnegative

    def __init__(self, scale=1.0, validate_args=None):
        self.scale = _j(scale)
        super().__init__(event_dim=0, validate_args=validate_args)

    @property
    def _batch(self):
        return jnp.shape(self.scale)

    def sample(self, size=None):
        shape = sample_n_shape_converter(size) + self._batch
        dtype = jnp.result_type(self.scale, jnp.float32)
        e = jax.random.exponential(next_key(), shape, dtype)
        return _w(e * self.scale)

    def log_prob(self, value):
        v = self._validate_sample(_j(value))
        return _w(-v / self.scale - jnp.log(self.scale))

    def cdf(self, value):
        return _w(-jnp.expm1(-_j(value) / self.scale))

    def icdf(self, value):
        return _w(-self.scale * jnp.log1p(-_j(value)))

    def _mean(self):
        return self.scale + jnp.zeros(self._batch)

    def _variance(self):
        return self.scale ** 2 + jnp.zeros(self._batch)

    def entropy(self):
        return _w(1 + jnp.log(self.scale) + jnp.zeros(self._batch))

    def broadcast_to(self, batch_shape):
        new = Exponential.__new__(Exponential)
        new.scale = jnp.broadcast_to(self.scale, batch_shape)
        ExponentialFamily.__init__(new, event_dim=0)
        return new

    _mean_carrier_measure = 0

    @property
    def _natural_params(self):
        return (-1.0 / self.scale,)

    def _log_normalizer(self, x):
        return -jnp.log(-x)
